#!/bin/bash
# Round-5 opportunistic TPU bench hunt (VERDICT.md r4 directive 1).
# Loop until every pending scenario has a green line appended to
# BENCH_TPU_r05.jsonl or the deadline passes.  Each bench invocation
# fail-fasts (rc=2) when the tunnel is dead (require_devices, 1 probe
# x 45s, so a dead window costs <1 min per attempt).
#
# Priority order: the driver path (default counter) FIRST so
# BENCH_r05.json will parse, then host deep with 5 reps (target: every
# rep >= 1M), mixed at the new 2-4 timers (predicted p99 ~146 ms),
# post-batching spi, host_read, single-group WITH a tunnel-RTT probe
# recorded alongside (settles weather-vs-regression), then fill, then
# an XLA profile of mixed.
OUT=/root/repo/BENCH_TPU_r05.jsonl
DEADLINE=$(( $(date +%s) + ${HUNT_BUDGET_S:-41000} ))
STATE=/tmp/hunt_done_r05
touch $STATE

rtt_probe() {
  # Bounded tunnel-RTT probe: 20 tiny device round-trips, reports
  # ms stats.  Recorded alongside counter1 so single-group swings can
  # be attributed to tunnel weather vs regression (VERDICT r4 weak 6).
  timeout 180 python - <<'PY' 2>>/tmp/hunt_rtt.log
import json, os, time
os.environ.setdefault("JAX_PLATFORMS", "tpu")
from copycat_tpu.utils.platform import require_devices
require_devices(probes=1, timeout_s=45)
import jax, jax.numpy as jnp
x = jax.device_put(jnp.zeros((8,), jnp.int32))
f = jax.jit(lambda v: v + 1)
f(x).block_until_ready()  # compile outside the timed loop
samples = []
for _ in range(20):
    t0 = time.perf_counter()
    f(x).block_until_ready()
    samples.append((time.perf_counter() - t0) * 1e3)
samples.sort()
print(json.dumps({"metric": "tunnel_rtt_ms", "min": round(samples[0], 3),
                  "median": round(samples[10], 3), "max": round(samples[-1], 3)}))
PY
}

run() {
  name=$1; shift
  grep -qx "$name" $STATE && return 0
  echo "=== $(date -u +%H:%M:%S) $name ===" >&2
  line=$(env "$@" COPYCAT_DEVICE_PROBES=1 COPYCAT_BENCH_DEVICE_TIMEOUT=45 \
      timeout 1800 python /root/repo/bench.py 2>>/tmp/hunt_${name}.log | tail -1)
  if [ -n "$line" ] && echo "$line" | python3 -c 'import json,sys; d=json.loads(sys.stdin.read()); assert "metric" in d' 2>/dev/null; then
    echo "{\"scenario\": \"$name\", \"rc\": 0, \"window\": \"$(date -u +%FT%H:%MZ)\", \"result\": $line}" >> $OUT
    echo "$name" >> $STATE
    echo "    $name OK" >&2
  else
    echo "    $name failed/dead-tunnel" >&2
    return 1
  fi
}

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  # Driver path first: same invocation the driver makes for BENCH_r05.json.
  run counter COPYCAT_BENCH_SCENARIO=counter COPYCAT_BENCH_GROUPS=10000 COPYCAT_BENCH_REPEATS=3 || { sleep 240; continue; }
  run host5 COPYCAT_BENCH_SCENARIO=host COPYCAT_BENCH_GROUPS=10000 COPYCAT_BENCH_HOST_BURST=64 COPYCAT_BENCH_REPEATS=5
  run host_scan COPYCAT_BENCH_SCENARIO=host COPYCAT_BENCH_HOST_MODE=deepscan COPYCAT_BENCH_GROUPS=10000 COPYCAT_BENCH_HOST_BURST=64 COPYCAT_BENCH_REPEATS=5
  run session COPYCAT_BENCH_SCENARIO=session COPYCAT_BENCH_GROUPS=10000 COPYCAT_BENCH_HOST_BURST=64 COPYCAT_BENCH_REPEATS=3
  run session_scan COPYCAT_BENCH_SCENARIO=session COPYCAT_BENCH_SESSION_SCAN=1 COPYCAT_BENCH_GROUPS=10000 COPYCAT_BENCH_HOST_BURST=64 COPYCAT_BENCH_REPEATS=3
  run mixed COPYCAT_BENCH_SCENARIO=mixed COPYCAT_BENCH_GROUPS=100000 COPYCAT_BENCH_PEERS=5 COPYCAT_BENCH_REPEATS=3
  run spi COPYCAT_BENCH_SCENARIO=spi COPYCAT_BENCH_SPI_BURSTS=3
  run spi_w2 COPYCAT_BENCH_SCENARIO=spi COPYCAT_BENCH_SPI_BURSTS=3 COPYCAT_BENCH_SPI_WAVES=2
  run spi_shadow COPYCAT_BENCH_SCENARIO=spi COPYCAT_BENCH_SPI_BURSTS=3 COPYCAT_BENCH_SPI_PAYLOAD=str
  run host_read COPYCAT_BENCH_SCENARIO=host_read COPYCAT_BENCH_GROUPS=10000 COPYCAT_BENCH_HOST_BURST=64 COPYCAT_BENCH_REPEATS=3
  if ! grep -qx rtt $STATE; then
    r=$(rtt_probe | tail -1)
    if [ -n "$r" ]; then
      echo "{\"scenario\": \"rtt\", \"rc\": 0, \"window\": \"$(date -u +%FT%H:%MZ)\", \"result\": $r}" >> $OUT
      echo rtt >> $STATE
    fi
  fi
  run counter1 COPYCAT_BENCH_SCENARIO=counter COPYCAT_BENCH_GROUPS=1 COPYCAT_BENCH_REPEATS=3
  run lock COPYCAT_BENCH_SCENARIO=lock COPYCAT_BENCH_GROUPS=10000 COPYCAT_BENCH_REPEATS=3
  run map_read_atomic COPYCAT_BENCH_SCENARIO=map_read COPYCAT_BENCH_GROUPS=10000 COPYCAT_BENCH_READ_LEVEL=atomic COPYCAT_BENCH_REPEATS=3
  run election COPYCAT_BENCH_SCENARIO=election COPYCAT_BENCH_GROUPS=1000 COPYCAT_BENCH_REPEATS=3
  run host_read_atomic COPYCAT_BENCH_SCENARIO=host_read COPYCAT_BENCH_GROUPS=10000 COPYCAT_BENCH_HOST_BURST=64 COPYCAT_BENCH_READ_LEVEL=atomic COPYCAT_BENCH_REPEATS=3
  if [ "$(wc -l < $STATE)" -ge 16 ] && ! grep -qx profile $STATE; then
    echo "=== $(date -u +%H:%M:%S) profile ===" >&2
    if bash /root/repo/tpu_profile_mixed.sh /tmp/mixed_trace_r05 >/tmp/hunt_profile.log 2>&1; then
      echo profile >> $STATE
      echo "    profile OK (/tmp/hunt_profile.log)" >&2
    fi
  fi
  [ "$(wc -l < $STATE)" -ge 17 ] && { echo "hunt complete" >&2; break; }
  sleep 120
done
