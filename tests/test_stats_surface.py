"""The live stats surface: HTTP listener, Prometheus/JSON renderers,
and the ``copycat-tpu stats`` CLI verb against a running server."""

import asyncio
import json

import pytest

jax = pytest.importorskip("jax")

from copycat_tpu import cli  # noqa: E402
from copycat_tpu.atomic import DistributedAtomicLong  # noqa: E402
from copycat_tpu.io.local import LocalServerRegistry, LocalTransport  # noqa: E402
from copycat_tpu.io.transport import Address  # noqa: E402
from copycat_tpu.manager.atomix import AtomixClient, AtomixServer  # noqa: E402
from copycat_tpu.server.stats import fetch_stats  # noqa: E402
from copycat_tpu.utils import tracing  # noqa: E402

from helpers import async_test  # noqa: E402


async def _running_server():
    """One AtomixServer (local raft transport, REAL TCP stats port) plus
    a client that drove some public-API traffic through it."""
    registry = LocalServerRegistry()
    transport = LocalTransport(registry)
    addr = Address("127.0.0.1", 16123)
    server = AtomixServer(addr, [addr], transport, session_timeout=30.0,
                          stats_port=0)
    await server.open()
    client = AtomixClient([addr], transport, session_timeout=30.0)
    await client.open()
    counter = await client.get("hits", DistributedAtomicLong)
    # solo submit -> single lane; same-turn burst -> batch (fast lane)
    await counter.increment_and_get()
    await asyncio.gather(*(counter.increment_and_get() for _ in range(8)))
    return server, client


@async_test(timeout=120)
async def test_stats_listener_serves_snapshot_and_metrics():
    server, client = await _running_server()
    try:
        port = server.stats.port
        assert port > 0
        body = await fetch_stats(f"127.0.0.1:{port}", "/stats")
        snap = json.loads(body)
        # per-node raft gauges
        assert snap["node"] == "127.0.0.1:16123"
        assert snap["role"] == "leader"
        raft = snap["raft"]
        assert raft["raft_term"] >= 1
        assert raft["raft_is_leader"] == 1
        assert raft["raft_commit_lag"] == 0
        assert raft["raft_commit_index"] > 0
        assert raft["sessions_open"] >= 1
        # SPI lane counters: the burst rode the batch lanes
        assert raft.get("commands_single_lane", 0) >= 1
        lanes = (raft.get("commands_fast_lane", 0)
                 + raft.get("commands_general_lane", 0))
        assert lanes >= 8
        # transport frame accounting
        transport = snap["transport"]
        assert transport["frames_in"] > 0
        assert transport["bytes_out"] > 0
        # resource manager stats
        manager = snap["manager"]
        assert manager["resources"] == 1
        assert manager["instances"] == 1
        assert manager["executor"] == "cpu"
        # client-side latency percentiles exist for the same traffic
        lat = client.client.metrics.snapshot()["submit_latency_ms"]
        assert lat["count"] >= 2 and lat["p99"] > 0

        prom = (await fetch_stats(f"127.0.0.1:{port}", "/metrics")).decode()
        assert "# TYPE copycat_raft_term gauge" in prom
        assert "copycat_raft_is_leader 1" in prom
        assert "copycat_transport_frames_in" in prom
        assert "copycat_manager_resources" in prom

        unknown = json.loads(
            await fetch_stats(f"127.0.0.1:{port}", "/nope"))
        assert "/metrics" in unknown["routes"]
    finally:
        await client.close()
        await server.close()


@async_test(timeout=120)
async def test_traces_route_shows_spans():
    tracing.disable()
    tracing.TRACER.clear()
    server, client = await _running_server()
    try:
        tracing.enable()
        counter = await client.get("hits", DistributedAtomicLong)
        await asyncio.gather(*(counter.increment_and_get()
                               for _ in range(4)))
        tracing.disable()
        port = server.stats.port
        traces = json.loads(await fetch_stats(f"127.0.0.1:{port}",
                                              "/traces"))
        assert traces, "no traces served"
        names = {s["name"] for t in traces for s in t["spans"]}
        assert "client.submit" in names
        # new causal vocabulary (docs/OBSERVABILITY.md): the commit side
        # is the coarse group.commit on the single lane, or the
        # quorum.wait/apply split on the block lanes
        assert names & {"group.commit", "apply"}, names
        text = (await fetch_stats(f"127.0.0.1:{port}",
                                  "/traces.txt")).decode()
        assert "group.append" in text
        # the per-trace collection route serves this member's spans
        tid = traces[0]["trace"]
        local = json.loads(await fetch_stats(f"127.0.0.1:{port}",
                                             f"/traces/{tid}"))
        assert local["trace"] == tid and local["spans"], local
    finally:
        tracing.disable()
        tracing.TRACER.clear()
        await client.close()
        await server.close()


@async_test(timeout=120)
async def test_read_lane_family_on_stats_and_metrics():
    """Round-9 read-lane counters (query_windows / query_ops /
    query_gate_rounds_saved / per-consistency reads) land in the raft
    registry and render on both exposition surfaces."""
    server, client = await _running_server()
    try:
        counter = await client.get("hits", DistributedAtomicLong)
        await asyncio.gather(*(counter.get() for _ in range(6)))
        port = server.stats.port
        raft = json.loads(
            await fetch_stats(f"127.0.0.1:{port}", "/stats"))["raft"]
        assert raft["query_windows"] >= 1
        assert raft["query_ops"] >= 6
        assert raft["query_window_ops"]["count"] >= 1
        assert "query_gate_rounds_saved" in raft
        assert raft["query_reads{consistency=bounded_linearizable}"] >= 6
        prom = (await fetch_stats(f"127.0.0.1:{port}", "/metrics")).decode()
        assert "# TYPE copycat_query_windows counter" in prom
        assert "copycat_query_reads" in prom
    finally:
        await client.close()
        await server.close()


def test_cli_stats_what_all(capsys):
    """``copycat-tpu stats --what all`` renders every surface in one
    shot — the JSON snapshot (read-lane family included), the
    Prometheus text, and the flight ring."""
    async def run():
        server, client = await _running_server()
        port = server.stats.port
        try:
            counter = await client.get("hits", DistributedAtomicLong)
            await asyncio.gather(*(counter.get() for _ in range(4)))
            rc = await asyncio.to_thread(
                cli._stats, type("A", (), {"address": f"127.0.0.1:{port}",
                                           "what": "all"})())
            assert rc == 0
        finally:
            await client.close()
            await server.close()

    asyncio.run(asyncio.wait_for(run(), 110))
    out = capsys.readouterr().out
    assert "=== stats ===" in out
    assert '"query_windows"' in out
    # the apply.* family (parallel apply + cross-group fusion, ISSUE 11)
    # rides both renderings: the group-registry counters in the JSON
    # snapshot and the server-registry fusion series in the Prometheus
    # text (names dot->underscore sanitized)
    assert '"apply.parallel_spans"' in out
    assert '"apply.fused_dispatches"' in out
    # the edge.* family (subscriber registry + delta publication,
    # docs/EDGE_READS.md) rides the same surfaces
    assert '"edge.subscriptions"' in out
    assert '"edge.deltas_sent"' in out
    assert "=== metrics ===" in out
    assert "copycat_query_windows" in out
    assert "copycat_apply_fused_dispatches" in out
    assert "copycat_edge_subscriptions" in out
    assert "=== flight ===" in out


def test_cli_stats_verb(capsys):
    async def run():
        server, client = await _running_server()
        port = server.stats.port
        try:
            # the CLI verb's fetch+render path (the console script wraps
            # exactly this); to_thread because _stats owns its own
            # asyncio.run, like the real process would
            rc = await asyncio.to_thread(
                cli._stats, type("A", (), {"address": f"127.0.0.1:{port}",
                                           "what": "stats"})())
            assert rc == 0
            rc = await asyncio.to_thread(
                cli._stats, type("A", (), {"address": f"127.0.0.1:{port}",
                                           "what": "metrics"})())
            assert rc == 0
        finally:
            await client.close()
            await server.close()

    asyncio.run(asyncio.wait_for(run(), 110))
    out = capsys.readouterr().out
    assert '"raft_is_leader": 1' in out or '"raft_is_leader": 1.0' in out
    assert "copycat_raft_term" in out


@async_test(timeout=60)
async def test_failed_stats_bind_does_not_leak_the_server():
    """A stats port that cannot bind must close the already-opened raft
    server on the way out (Managed never marked the node open, so the
    caller's close() would be a no-op)."""
    registry = LocalServerRegistry()
    transport = LocalTransport(registry)
    addr = Address("127.0.0.1", 16124)
    blocker = AtomixServer(addr, [addr], transport, stats_port=0)
    await blocker.open()
    taken = blocker.stats.port
    try:
        dup = AtomixServer(Address("127.0.0.1", 16125),
                           [Address("127.0.0.1", 16125)],
                           LocalTransport(registry), stats_port=taken)
        with pytest.raises(OSError):
            await dup.open()
        assert dup.stats is None
        assert not dup.server.is_open
        # the raft address is free again: a fresh node can take it
        ok = AtomixServer(Address("127.0.0.1", 16125),
                          [Address("127.0.0.1", 16125)],
                          LocalTransport(registry))
        await ok.open()
        await ok.close()
    finally:
        await blocker.close()


def test_cli_stats_unreachable(capsys):
    rc = cli._stats(type("A", (), {"address": "127.0.0.1:1",
                                   "what": "stats"})())
    assert rc == 1
    assert "--stats-port" in capsys.readouterr().err


def test_watch_renderer_keeps_labeled_series_distinct():
    """The --watch delta view is label-aware (the multi-group fix): two
    series sharing a name but differing in labels (per-group `group=`
    series, the per-consistency read mix) render as separate lines with
    INDEPENDENT deltas, sorted with their family (a plain sort put
    `name{...}` after every unlabeled name — ASCII `{` > letters)."""
    snap = {"node": "n", "raft": {
        "raft_term{group=0}": 3, "raft_term{group=1}": 4,
        "query_reads{consistency=causal}": 5, "query_windows": 2}}
    prev = cli._flatten_numeric(snap)
    assert "raft.raft_term{group=0}" in prev
    assert "raft.raft_term{group=1}" in prev
    snap["raft"]["raft_term{group=1}"] = 6
    frame = cli._render_watch(snap, prev, 1.0)
    lines = [ln for ln in frame.splitlines() if "raft_term" in ln]
    assert len(lines) == 2
    g0 = next(ln for ln in lines if "{group=0}" in ln)
    g1 = next(ln for ln in lines if "{group=1}" in ln)
    assert "+2.0/s" in g1 and "/s" not in g0
    # family-sorted: the labeled read-mix series sits before
    # query_windows, not after it
    keys = [ln.split()[0] for ln in frame.splitlines() if "query" in ln]
    assert keys == ["raft.query_reads{consistency=causal}",
                    "raft.query_windows"]


def test_watch_renderer_orders_numeric_labels_numerically():
    """A wide multi-group watch stays in shard order: `group=2` sorts
    before `group=10` (numeric label comparison, not lexicographic), and
    the ordering is stable across delta frames."""
    snap = {"node": "n", "raft": {
        f"raft_term{{group={g}}}": 1 for g in (10, 2, 1, 0)}}
    prev = cli._flatten_numeric(snap)
    frame = cli._render_watch(snap, prev, 1.0)
    keys = [ln.split()[0] for ln in frame.splitlines()
            if "raft_term" in ln]
    assert keys == [f"raft.raft_term{{group={g}}}" for g in (0, 1, 2, 10)]
    # same order with no prev (first frame) — stable family sort
    frame0 = cli._render_watch(snap, None, 0.0)
    keys0 = [ln.split()[0] for ln in frame0.splitlines()
             if "raft_term" in ln]
    assert keys0 == keys


def test_watch_renderer_shows_apply_family_deltas():
    """`--watch` renders the apply.* family (parallel-apply spans on the
    group registries, fused-dispatch counters on the server registry)
    as plain numeric series with deltas — no special casing, but pinned
    here so the family can't silently fall off the watch surface."""
    snap = {"node": "n", "raft": {
        "apply.parallel_spans{group=0}": 4, "apply.fused_dispatches": 7}}
    prev = cli._flatten_numeric(snap)
    snap["raft"]["apply.fused_dispatches"] = 10
    frame = cli._render_watch(snap, prev, 1.0)
    assert "raft.apply.parallel_spans{group=0}" in frame
    fused = next(ln for ln in frame.splitlines()
                 if "apply.fused_dispatches" in ln)
    assert "+3.0/s" in fused


def test_watch_renderer_shows_nested_group_strings():
    """Per-group role/leader strings (nested sections) appear in the
    header instead of being dropped."""
    snap = {"node": "n", "role": "follower",
            "groups": {"0": {"role": "leader", "leader": "l:1",
                             "commit_index": 5}}}
    frame = cli._render_watch(snap, None, 0.0)
    assert "groups.0.role: leader" in frame
    assert "groups.0.leader: l:1" in frame
    assert "groups.0.commit_index" in frame
