"""Device-path cluster membership change (server join/leave).

The reference grows and shrinks a live cluster
(manager/src/test/java/io/atomix/AtomixServerTest.java testServerJoin /
testServerLeave — Raft membership change in the external Copycat core).
The device equivalent: per-group voter sets over the fixed ``P`` peer
lanes, changed by single-server OP_CFG_ADD/REMOVE entries through the
replicated log (``Config.dynamic_membership``). These tests drive the
full lifecycle — standby lanes, join, leave, leader self-removal — and
check the part that actually matters: THE QUORUM CHANGES (fault patterns
that stall the old config commit in the new one, and vice versa).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.models import RaftGroups  # noqa: E402
from copycat_tpu.ops import apply as ap  # noqa: E402
from copycat_tpu.ops.consensus import LEADER, Config  # noqa: E402

DYN = Config(dynamic_membership=True)


def make(groups=1, peers=5, voters=None, **kw):
    kw.setdefault("log_slots", 32)
    kw.setdefault("config", DYN)
    return RaftGroups(groups, peers, voters=voters, **kw)


def isolate(rg: RaftGroups, lanes) -> np.ndarray:
    """Full delivery except ``lanes``, which are cut from everyone."""
    dl = np.ones((rg.num_groups, rg.num_peers, rg.num_peers), bool)
    for lane in lanes:
        dl[:, lane, :] = False
        dl[:, :, lane] = False
    return dl


def commits_under(rg: RaftGroups, deliver, rounds=25) -> bool:
    """Submit one counter op and report whether it commits while the
    given delivery mask is in force."""
    tag = rg.submit(0, ap.OP_LONG_ADD, 1)
    for _ in range(rounds):
        rg.step_round(deliver=deliver)
        if tag in rg.results:
            return True
    # drain under full connectivity so the op doesn't leak into the next
    # phase of the test
    rg.run_until([tag], max_rounds=100)
    return False


def resolve(rg: RaftGroups, tag: int, max_rounds=100) -> int:
    rg.run_until([tag], max_rounds=max_rounds)
    return rg.results[tag]


def test_standby_lanes_never_lead():
    rg = make(groups=4, peers=5, voters=3)
    rg.wait_for_leaders()
    tags = [rg.submit(g, ap.OP_LONG_ADD, 1) for g in range(4)]
    for _ in range(40):
        rg.step_round()
        role = np.asarray(rg.state.role)
        assert not (role[:, 3:] == LEADER).any(), \
            "standby (non-voter) lane became leader"
    assert all(t in rg.results for t in tags)
    assert rg.voting_members(0) == [0, 1, 2]


def test_add_peer_grows_fault_tolerance():
    rg = make(peers=5, voters=3)
    rg.wait_for_leaders()

    # 3 voters {0,1,2}, quorum 2: cutting lanes 1 and 2 leaves one voter
    assert not commits_under(rg, isolate(rg, [1, 2]))

    # join lanes 3 and 4 (serialized by the one-in-flight append guard;
    # the second submit is simply rejected+requeued until the first
    # applies)
    t3 = rg.add_peer(0, 3)
    t4 = rg.add_peer(0, 4)
    rg.run_until([t3, t4], max_rounds=150)
    assert rg.voting_members(0) == [0, 1, 2, 3, 4]

    # 5 voters, quorum 3: the SAME fault now leaves {0,3,4} — commits
    assert commits_under(rg, isolate(rg, [1, 2]), rounds=60)


def test_remove_peer_shrinks_quorum():
    rg = make(peers=5)  # all 5 voting, quorum 3
    rg.wait_for_leaders()

    # cutting {1,3,4} leaves 2 of 5 — stalls
    assert not commits_under(rg, isolate(rg, [1, 3, 4]))

    t3 = rg.remove_peer(0, 3)
    t4 = rg.remove_peer(0, 4)
    rg.run_until([t3, t4], max_rounds=150)
    assert rg.voting_members(0) == [0, 1, 2]

    # same fault against 3 voters {0,1,2}, quorum 2: {0,2} — commits
    assert commits_under(rg, isolate(rg, [1, 3, 4]), rounds=60)

    # the departed lanes stay out: never lead again
    for _ in range(30):
        rg.step_round()
        role = np.asarray(rg.state.role)
        assert not (role[:, 3:] == LEADER).any()


def test_leader_self_removal_steps_down():
    rg = make(peers=3)
    rg.wait_for_leaders()
    old = rg.leader(0)
    tag = rg.remove_peer(0, old)
    resolve(rg, tag, max_rounds=150)
    # a new leader emerges among the remaining voters
    for _ in range(60):
        rg.step_round()
        new = rg.leader(0)
        if new >= 0 and new != old:
            break
    assert new >= 0 and new != old
    assert old not in rg.voting_members(0)
    # and the shrunk group still commits
    t = rg.submit(0, ap.OP_LONG_ADD, 7)
    assert resolve(rg, t) == 7


def test_remove_last_member_fails_fast():
    rg = make(peers=3, voters=1)  # single-voter group (lane 0)
    rg.wait_for_leaders()
    tag = rg.remove_peer(0, 0)
    for _ in range(30):
        rg.step_round()
        if tag in rg.results:
            break
    # refused outright (FAIL result) — NOT left retrying, which would
    # block every later op in the group's queue behind the FIFO gate
    assert rg.results.get(tag) == ap.FAIL
    assert rg.voting_members(0) == [0]
    # the group is still alive
    t = rg.submit(0, ap.OP_LONG_ADD, 3)
    assert resolve(rg, t) == 3


def test_removed_partitioned_lane_cannot_disrupt():
    """A lane removed WHILE partitioned never learns its removal: it
    holds an inflated term and campaigns forever, it gets no appends
    (non-member), so the ack path can't depose it either — without
    leader stickiness its RequestVote would depose the healthy leader
    every few rounds forever. With stickiness (voters ignore
    RequestVote while hearing a current leader, Raft thesis §4.2.3) the
    group must stay stable after the heal."""
    rg = make(peers=3)
    rg.wait_for_leaders()
    victim = (rg.leader(0) + 1) % 3  # a follower
    dl = isolate(rg, [victim])
    for _ in range(5):
        rg.step_round(deliver=dl)  # let the victim's term inflate
    t = rg.remove_peer(0, victim)
    for _ in range(100):
        rg.step_round(deliver=dl)
        if t in rg.results:
            break
    assert t in rg.results and victim not in rg.voting_members(0)

    # heal — the removed lane rejoins the network with a higher term
    depositions = 0
    prev = rg.leader(0)
    tags = []
    for r in range(80):
        if r % 4 == 0:
            tags.append(rg.submit(0, ap.OP_LONG_ADD, 1))
        rg.step_round()
        cur = rg.leader(0)
        if cur >= 0 and prev >= 0 and cur != prev:
            depositions += 1
        prev = cur if cur >= 0 else prev
    assert depositions <= 1, \
        f"removed lane depose-looped the leader ({depositions} changes)"
    rg.run_until(tags, max_rounds=100)


def test_exactly_once_counter_across_churn():
    """Counter increments interleaved with join/leave under nemesis:
    every committed increment applies exactly once, election safety
    holds (≤1 leader per (group, term)) across config changes."""
    rng = np.random.default_rng(7)
    rg = make(peers=5, voters=3, submit_slots=8)
    rg.wait_for_leaders()
    seen = {}  # (group, term) -> leader lane

    cfg_plan = [("add", 3), ("add", 4), ("remove", 1), ("remove", 3)]
    tags, cfg_tags = [], []
    prev_outside = set()
    for r in range(220):
        if r % 3 == 0:
            tags.append(rg.submit(0, ap.OP_LONG_ADD, 1))
        if r % 40 == 20 and cfg_plan:
            kind, lane = cfg_plan.pop(0)
            cfg_tags.append(rg.add_peer(0, lane) if kind == "add"
                            else rg.remove_peer(0, lane))
        deliver = None
        if 0 < (r % 30) < 8:  # nemesis window: cut one random lane
            deliver = isolate(rg, [int(rng.integers(0, 5))])
        rg.step_round(deliver=deliver)
        role = np.asarray(rg.state.role)
        term = np.asarray(rg.state.term)
        member = np.asarray(rg.state.member)
        outside = set()
        for g, p in zip(*np.nonzero(role == LEADER)):
            key = (int(g), int(term[g, p]))
            prev = seen.setdefault(key, int(p))
            assert prev == int(p), f"two leaders in term {key}"
            if not (member[g, p] >> p) & 1:
                # a leader that appended+applied its own removal in one
                # round steps down the NEXT round (it already tallies
                # commits under the new config meanwhile — Raft thesis
                # §4.2.2); it must never persist a second round
                outside.add((int(g), int(p)))
        assert not (outside & prev_outside), \
            f"self-removed leader persisted two rounds: {outside & prev_outside}"
        prev_outside = outside
    rg.run_until(tags + cfg_tags, max_rounds=200)
    assert rg.voting_members(0) == [0, 2, 4]
    # exactly-once: the final counter equals the number of increments
    t = rg.submit(0, ap.OP_LONG_ADD, 0)
    assert resolve(rg, t) == len(tags)


def test_added_lane_catches_up_via_snapshot_install():
    """A lane added AFTER the leader's ring has wrapped past genesis can
    never be served by AppendEntries (its needed prefix is gone): the
    stale→snapshot-install path must hand it the full state — including
    the membership view — and it must then count toward the new quorum."""
    rg = make(peers=5, voters=3, log_slots=16, submit_slots=8)
    rg.wait_for_leaders()
    # push well past L=16 entries so the ring has wrapped
    tags = [rg.submit(0, ap.OP_LONG_ADD, 1) for _ in range(40)]
    rg.run_until(tags, max_rounds=200)

    t = rg.add_peer(0, 3)
    resolve(rg, t, max_rounds=150)
    for _ in range(40):  # replication/install rounds
        rg.step_round()
    member = np.asarray(rg.state.member[0])
    applied = np.asarray(rg.state.applied_index[0])
    # the added lane holds the full applied state and the 4-voter config
    assert applied[3] == applied.max(), "added lane not caught up"
    assert member[3] == 0b01111, f"installed view wrong: {member[3]:b}"
    assert rg.value(0, peer=3) == 40

    # and it genuinely votes: with original voter 0 cut, the 4-voter
    # quorum (3) is reachable ONLY if the installed lane 3 acks —
    # {1,2} alone is 2 < 3
    assert commits_under(rg, isolate(rg, [0]), rounds=60)


def test_membership_sharded_over_mesh():
    """The dynamic-membership path (latest-config view scans, masked
    rank-select quorums, population_count) compiled and stepped over a
    multi-device mesh — join, leave, and leader self-removal all work
    with the group axis sharded (XLA inserts the collectives)."""
    from copycat_tpu.parallel import make_mesh

    mesh = make_mesh(groups=8)
    rg = make(groups=16, voters=3, mesh=mesh)
    rg.wait_for_leaders()
    t = rg.submit(3, ap.OP_LONG_ADD, 9)
    assert resolve(rg, t) == 9
    t3 = rg.add_peer(3, 3)
    t4 = rg.add_peer(3, 4)
    rg.run_until([t3, t4], max_rounds=200)
    assert rg.voting_members(3) == [0, 1, 2, 3, 4]
    tr = rg.remove_peer(3, rg.leader(3))
    rg.run_until([tr], max_rounds=200)
    assert len(rg.voting_members(3)) == 4
    t = rg.submit(3, ap.OP_LONG_ADD, 1)
    assert resolve(rg, t) == 10
    # untouched groups keep the initial 3-voter config
    assert rg.voting_members(0) == [0, 1, 2]


def test_api_validation():
    # raw config submits get add_peer/remove_peer's validation
    rg = make(peers=3)
    with pytest.raises(ValueError):
        rg.submit(0, ap.OP_CFG_ADD, 7)          # lane out of range
    static = RaftGroups(1, 3, log_slots=16, config=Config())
    with pytest.raises(ValueError):
        static.submit(0, ap.OP_CFG_ADD, 1)      # static engine
    with pytest.raises(ValueError):
        static.add_peer(0, 1)
    # voters == num_peers is the all-lanes default — fine without dyn
    RaftGroups(1, 3, log_slots=16, config=Config(), voters=3)
    with pytest.raises(ValueError):
        RaftGroups(1, 3, log_slots=16, config=Config(), voters=2)


def test_static_path_unchanged():
    """dynamic_membership=False keeps today's step semantics bit-for-bit:
    identical state evolution with member carried untouched."""
    a = RaftGroups(2, 3, log_slots=16, config=Config())
    b = RaftGroups(2, 3, log_slots=16, config=Config(dynamic_membership=True))
    for _ in range(40):
        a.step_round()
        b.step_round()
    for g in range(2):
        a.submit(g, ap.OP_LONG_ADD, 2)
        b.submit(g, ap.OP_LONG_ADD, 2)
    for _ in range(10):
        a.step_round()
        b.step_round()
    for la, lb in zip(jax.tree.leaves(a.state), jax.tree.leaves(b.state)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
