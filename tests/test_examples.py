"""The terminating examples run end-to-end in fresh interpreters.

Examples are the reference's user surface (`SURVEY.md` L6); running them
as subprocesses (like a user: fresh interpreter, fresh registry)
catches drift between the examples/docs and the library — the same
class-registration failure mode `test_standalone_server.py` guards on
the server side. All self-terminating examples run here; the serve-forever mains
(leader_election, atomic_value, group_membership, standalone_server) are
covered by the resource tests they demonstrate.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CASES = [
    # (example, argv, expected stdout fragment)
    ("custom_resource.py", [], "stock after release: 10"),
    ("bulk_counters.py", ["64", "8"], "linearizable reads/sec"),
    ("device_batch.py", [], "done"),
    ("session_client.py", ["32", "8"], "lock handed over to backup"),
]


@pytest.mark.parametrize("example,argv,expect",
                         CASES, ids=[c[0] for c in CASES])
def test_example_runs_clean(example, argv, expect):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", example), *argv],
        capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert expect in out.stdout, out.stdout[-2000:]
