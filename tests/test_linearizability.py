"""Linearizability verification (copycat_tpu.testing).

Unit-tests the Wing & Gong checker on hand-crafted histories, then runs
Jepsen-style nemesis schedules against the batched consensus engine and
checks the recorded histories — BASELINE.md config #5's verification layer
and the in-tree replacement for the reference's external atomix-jepsen
suite (SURVEY.md §4).
"""

import math

import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.models import RaftGroups  # noqa: E402
from copycat_tpu.ops import apply as ap  # noqa: E402
from copycat_tpu.testing import (  # noqa: E402
    HOp,
    HistoryRecorder,
    LockModel,
    MapModel,
    Nemesis,
    RegisterModel,
    check_linearizable,
)


# ---------------------------------------------------------------------------
# checker unit tests
# ---------------------------------------------------------------------------

def test_checker_rejects_stale_read():
    h = [HOp(1, ("set", 1), 0, invoke=0, complete=1),
         HOp(2, ("get",), 0, invoke=2, complete=3)]  # reads 0 AFTER set(1)
    assert not check_linearizable(h, RegisterModel).ok


def test_checker_accepts_concurrent_read():
    h = [HOp(1, ("set", 1), 0, invoke=0, complete=5),
         HOp(2, ("get",), 0, invoke=1, complete=2)]  # overlaps the set
    assert check_linearizable(h, RegisterModel).ok


def test_checker_incomplete_op_may_apply():
    # a crashed set(5) explains the later read of 5
    h = [HOp(1, ("set", 5), None, invoke=0, complete=math.inf),
         HOp(2, ("get",), 5, invoke=3, complete=4)]
    assert check_linearizable(h, RegisterModel).ok


def test_checker_incomplete_op_may_never_apply():
    h = [HOp(1, ("set", 5), None, invoke=0, complete=math.inf),
         HOp(2, ("get",), 0, invoke=3, complete=4)]
    assert check_linearizable(h, RegisterModel).ok


def test_checker_cas_chain():
    h = [HOp(1, ("set", 1), 0, 0, 1),
         HOp(2, ("cas", 1, 2), 1, 2, 3),
         HOp(3, ("cas", 1, 9), 0, 4, 5),
         HOp(4, ("get",), 2, 6, 7)]
    assert check_linearizable(h, RegisterModel).ok
    # two CAS(1→x) both succeeding from one set(1) is impossible
    h_bad = [HOp(1, ("set", 1), 0, 0, 1),
             HOp(2, ("cas", 1, 2), 1, 2, 3),
             HOp(3, ("cas", 1, 9), 1, 4, 5)]
    assert not check_linearizable(h_bad, RegisterModel).ok


def test_checker_lock_model():
    good = [HOp(1, ("acquire", 7), 1, 0, 1),
            HOp(2, ("acquire", 8), 0, 2, 3),
            HOp(3, ("release", 7), 1, 4, 5),
            HOp(4, ("acquire", 8), 1, 6, 7)]
    assert check_linearizable(good, LockModel).ok
    # two non-overlapping successful acquires without a release
    bad = [HOp(1, ("acquire", 7), 1, 0, 1),
           HOp(2, ("acquire", 8), 1, 2, 3)]
    assert not check_linearizable(bad, LockModel).ok


# ---------------------------------------------------------------------------
# engine histories under nemesis
# ---------------------------------------------------------------------------

def _drain(rec, rg, max_rounds=300):
    for _ in range(max_rounds):
        if not rec._pending:
            break
        rec.tick()


REGISTER_OPS = [
    (ap.OP_VALUE_SET, ("set",)),
    (ap.OP_VALUE_GET, ("get",)),
    (ap.OP_VALUE_CAS, ("cas",)),
    (ap.OP_LONG_ADD, ("add",)),
]


def test_register_histories_linearizable_under_nemesis():
    import numpy as np
    G = 4
    rg = RaftGroups(G, 3, log_slots=64)
    rg.wait_for_leaders()
    rec = HistoryRecorder(rg)
    nemesis = Nemesis(rg, seed=11, period=12)
    rng = np.random.default_rng(5)

    for round_no in range(180):
        nemesis.tick()
        if round_no % 2 == 0:
            g = int(rng.integers(G))
            kind = int(rng.integers(4))
            opcode, (name,) = REGISTER_OPS[kind]
            if name == "set":
                v = int(rng.integers(1, 50))
                rec.invoke(g, opcode, ("set", v), a=v)
            elif name == "get":
                rec.invoke(g, opcode, ("get",))
            elif name == "cas":
                e, u = int(rng.integers(0, 50)), int(rng.integers(1, 50))
                rec.invoke(g, opcode, ("cas", e, u), a=e, b=u)
            else:
                d = int(rng.integers(1, 5))
                rec.invoke(g, opcode, ("add", d), a=d)
        rec.tick()
    nemesis.heal()
    _drain(rec, rg)

    for g in range(G):
        hist = rec.history(g)
        assert len(hist) > 10
        res = check_linearizable(hist, RegisterModel)
        assert res.ok, f"group {g} history not linearizable: {hist}"


def test_map_histories_linearizable_under_nemesis():
    import numpy as np
    G = 2
    rg = RaftGroups(G, 3, log_slots=64)
    rg.wait_for_leaders()
    rec = HistoryRecorder(rg)
    nemesis = Nemesis(rg, seed=3, period=15)
    rng = np.random.default_rng(8)

    for round_no in range(150):
        nemesis.tick()
        if round_no % 3 == 0:
            g = int(rng.integers(G))
            k = int(rng.integers(1, 4))
            kind = int(rng.integers(3))
            if kind == 0:
                v = int(rng.integers(1, 100))
                rec.invoke(g, ap.OP_MAP_PUT, ("put", k, v), a=k, b=v)
            elif kind == 1:
                rec.invoke(g, ap.OP_MAP_GET, ("get", k), a=k)
            else:
                rec.invoke(g, ap.OP_MAP_REMOVE, ("remove", k), a=k)
        rec.tick()
    nemesis.heal()
    _drain(rec, rg)

    for g in range(G):
        hist = rec.history(g)
        assert len(hist) > 10
        assert check_linearizable(hist, MapModel).ok


def test_trylock_histories_linearizable_under_nemesis():
    import numpy as np
    rg = RaftGroups(1, 3, log_slots=64)
    rg.wait_for_leaders()
    rec = HistoryRecorder(rg)
    nemesis = Nemesis(rg, seed=7, period=10, faults=("heal", "loss"))
    rng = np.random.default_rng(2)
    held: set[int] = set()

    for round_no in range(120):
        nemesis.tick()
        if round_no % 4 == 0:
            who = int(rng.integers(1, 5))
            if who in held and rng.random() < 0.7:
                rec.invoke(0, ap.OP_LOCK_RELEASE, ("release", who), a=who)
                held.discard(who)
            else:
                # immediate try-lock only (b=0) — synchronous result
                rec.invoke(0, ap.OP_LOCK_ACQUIRE, ("acquire", who),
                           a=who, b=0)
                held.add(who)
        rec.tick()
    nemesis.heal()
    _drain(rec, rg)

    hist = rec.history(0)
    assert len(hist) > 10
    assert check_linearizable(hist, LockModel).ok


def test_atomic_lease_reads_linearizable_under_nemesis():
    """Half the reads ride the lease-gated ATOMIC query lane (no log
    append, served only when the leader holds a quorum-acked lease);
    interleaved with writes under partitions, every history must still
    linearize — the leader-lease soundness claim (round-3 directive #8,
    reference Consistency.java:157-176 BOUNDED_LINEARIZABLE)."""
    import numpy as np
    G = 4
    rg = RaftGroups(G, 3, log_slots=64)
    rg.wait_for_leaders()
    rec = HistoryRecorder(rg)
    nemesis = Nemesis(rg, seed=21, period=12)
    rng = np.random.default_rng(9)

    for round_no in range(180):
        nemesis.tick()
        if round_no % 2 == 0:
            g = int(rng.integers(G))
            kind = int(rng.integers(4))
            if kind == 0:
                v = int(rng.integers(1, 50))
                rec.invoke(g, ap.OP_VALUE_SET, ("set", v), a=v)
            elif kind == 1:
                d = int(rng.integers(1, 5))
                rec.invoke(g, ap.OP_LONG_ADD, ("add", d), a=d)
            else:
                # reads: half lease-lane ATOMIC, half through the log
                query = "atomic" if kind == 2 else None
                rec.invoke(g, ap.OP_VALUE_GET, ("get",), query=query)
        rec.tick()
    nemesis.heal()
    _drain(rec, rg)

    served = rg.metrics.counter("queries_served").value
    assert served > 0, "no read was ever lease-served"
    for g in range(G):
        hist = rec.history(g)
        assert len(hist) > 10
        res = check_linearizable(hist, RegisterModel)
        assert res.ok, f"group {g} lease-read history not linearizable"
