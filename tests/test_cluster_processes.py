"""Three-process Raft cluster over real TCP — the reference's deployment
shape (one server per machine), which no in-process test can cover:
server-to-server RPC crosses real sockets between separate interpreters,
and a server PROCESS dying mid-load exercises client re-route + failover
against genuinely independent peers.
"""

import asyncio
import os
import subprocess
import sys
import tempfile

import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.atomic import DistributedAtomicLong  # noqa: E402
from copycat_tpu.deploy.topology import allocate_ports  # noqa: E402
from copycat_tpu.io.tcp import TcpTransport  # noqa: E402
from copycat_tpu.io.transport import Address  # noqa: E402
from copycat_tpu.manager.atomix import AtomixClient  # noqa: E402

from helpers import async_test  # noqa: E402

# ephemeral ports via the bind-port-0 probe (deploy.topology): parallel
# CI runs and leftover listeners can no longer collide the way the old
# hardcoded 19361-19363 could
ADDRS = [f"127.0.0.1:{p}" for p in allocate_ports(3)]


def _spawn(idx: int, logf):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(__file__)))
    members = [ADDRS[idx]] + [a for i, a in enumerate(ADDRS) if i != idx]
    return subprocess.Popen(
        [sys.executable, "-c",
         f"from copycat_tpu.cli import server; server({members!r})"],
        env=env, stdout=logf, stderr=subprocess.STDOUT)


@async_test(timeout=300)
async def test_three_process_cluster_survives_server_kill():
    logs = [tempfile.NamedTemporaryFile("w+b", suffix=f".{i}.log",
                                        delete=False) for i in range(3)]
    procs = [_spawn(i, logs[i]) for i in range(3)]
    try:
        client = (AtomixClient.builder([Address.parse(a) for a in ADDRS])
                  .with_transport(TcpTransport()).build())
        for attempt in range(60):
            try:
                await asyncio.wait_for(client.open(), 15)
                break
            except Exception:
                dead = [i for i, p in enumerate(procs)
                        if p.poll() is not None]
                if len(dead) == 3:
                    logs[0].seek(0)
                    pytest.fail("all servers died: "
                                + logs[0].read().decode(
                                    errors="replace")[-600:])
                await asyncio.sleep(2)
        else:
            pytest.fail("client never connected to the cluster")

        counter = await client.get("hits", DistributedAtomicLong)
        for want in range(1, 6):
            got = await asyncio.wait_for(counter.increment_and_get(), 30)
            assert got == want

        # kill one server PROCESS mid-run: 2/3 keep quorum; if the victim
        # was the leader the client must re-route after failover
        procs[0].kill()
        procs[0].wait(timeout=10)
        deadline = asyncio.get_event_loop().time() + 90
        want = 6
        while want <= 10:
            try:
                got = await asyncio.wait_for(
                    counter.increment_and_get(), 20)
                assert got == want, (got, want)
                want += 1
            except AssertionError:
                raise
            except Exception:
                if asyncio.get_event_loop().time() > deadline:
                    raise
                await asyncio.sleep(1)  # failover window: retry
        await client.close()
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
