"""Sessioned bulk client: the unified client plane (VERDICT r4 #2).

The reference's client runtime gives every session FIFO sequencing,
exactly-once command application, response caching, event delivery and
liveness over ONE data path (Copycat client — SURVEY.md §2.3). These
tests pin that contract onto ``models.session_client.BulkSessionClient``
driving the deep (monotone-tag) pipeline — and, for the composability
claim, a classic engine too.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.models import BulkSessionClient, RaftGroups  # noqa: E402
from copycat_tpu.models.sessions import SessionExpiredError  # noqa: E402
from copycat_tpu.ops import apply as ap  # noqa: E402
from copycat_tpu.ops.consensus import Config  # noqa: E402


@pytest.fixture(scope="module")
def deep_rg():
    rg = RaftGroups(8, 3, log_slots=32, submit_slots=4, seed=11,
                    config=Config(monotone_tag_accept=True))
    rg.wait_for_leaders()
    return rg


@pytest.fixture(scope="module")
def client(deep_rg):
    return BulkSessionClient(deep_rg)


def test_exactly_once_fifo_and_result_cache(client):
    s = client.open_session()
    seqs = s.submit_batch([0] * 10, ap.OP_LONG_ADD, 1)
    extra = s.submit(0, ap.OP_VALUE_GET)
    n = client.flush()
    assert n == 11
    # FIFO: the GET queued after 10 increments sees all of them
    # (running totals 1..10 for the adds, then the read).
    adds = s.results_window(int(seqs[0]), 10)
    base = adds[0] - 1
    assert list(adds - base) == list(range(1, 11))
    assert s.result(extra) == base + 10
    # exactly-once read side: results re-correlate any number of times,
    # and a second flush with nothing pending applies nothing.
    before = s.result(extra)
    assert client.flush() == 0
    assert s.result(extra) == before
    check = s.submit(0, ap.OP_VALUE_GET)
    client.flush()
    assert s.result(check) == base + 10  # no hidden re-application


def test_sessions_interleave_on_one_group(client):
    s1 = client.open_session()
    s2 = client.open_session()
    g = 1
    a = s1.submit_batch([g] * 5, ap.OP_LONG_ADD, 10)
    b = s2.submit_batch([g] * 5, ap.OP_LONG_ADD, 1)
    client.flush()
    # both sessions' ops all applied exactly once: 5*10 + 5*1
    read = s1.submit(g, ap.OP_VALUE_GET)
    client.flush()
    assert s1.result(read) == 55
    # per-session FIFO: each session's own running results are ordered
    r1 = s1.results_window(int(a[0]), 5)
    r2 = s2.results_window(int(b[0]), 5)
    assert all(np.diff(r1) == 10)
    assert all(np.diff(r2) == 1)


def test_queries_and_atomic_reads(client):
    s = client.open_session()
    s.submit_batch([2, 2, 2], ap.OP_LONG_ADD, 7)
    client.flush()
    vals = s.query_batch([2] * 4, ap.OP_VALUE_GET, consistency="atomic")
    assert list(vals) == [21] * 4
    # the full SPI read vocabulary routes (round 9): sub-linearizable
    # levels serve from applied state, linearizable rides the lease gate
    for level in ("none", "causal", "process", "sequential",
                  "bounded_linearizable", "linearizable"):
        got = s.query_batch([2, 2], ap.OP_VALUE_GET, consistency=level)
        assert list(got) == [21, 21], (level, got)
    with pytest.raises(ValueError, match="unknown read consistency"):
        s.query_batch([2], ap.OP_VALUE_GET, consistency="nope")


def test_edge_cache_serves_causal_reads_locally(deep_rg, client):
    """The device-plane edge replica (docs/EDGE_READS.md): CAUSAL-level
    GETs of groups this client already read serve from its own
    committed post-apply state rows — zero engine rounds — and every
    write shape (ADD / SET / successful and failed CAS / GET_AND_SET)
    keeps the replica in lockstep with the engine's answer."""
    s = client.open_session()
    g = 5
    edge = client._edge
    assert edge is not None
    # cold read: drives the engine, marks interest
    s.submit(g, ap.OP_LONG_ADD, 4)
    client.flush()
    assert list(s.query_batch([g], ap.OP_VALUE_GET,
                              consistency="causal")) == [4]
    serves0 = edge._m_serves.value
    script = [
        (ap.OP_LONG_ADD, 3, 0, 7),          # add -> 7
        (ap.OP_VALUE_SET, 9, 0, 9),         # set -> 9
        (ap.OP_VALUE_CAS, 9, 12, 12),       # cas success -> 12
        (ap.OP_VALUE_CAS, 9, 99, 12),       # cas FAILURE -> still 12
        (ap.OP_VALUE_GET_AND_SET, 20, 0, 20),
    ]
    for opcode, a, b, expect in script:
        s.submit(g, opcode, a, b)
        client.flush()
        rounds_before = deep_rg.rounds
        local = s.query_batch([g] * 3, ap.OP_VALUE_GET,
                              consistency="causal")
        assert list(local) == [expect] * 3, (opcode, local)
        assert deep_rg.rounds == rounds_before, "local serve drove rounds"
        # the engine agrees (sequential drives the query lane)
        engine = s.query_batch([g], ap.OP_VALUE_GET,
                               consistency="sequential")
        assert list(engine) == [expect]
    assert edge._m_serves.value > serves0
    # sequential never serves from the cache
    assert edge._m_serves.value == serves0 + 3 * len(script)


def test_edge_cache_refuses_ttl_groups():
    """A TTL'd SET arms a device-side deadline the host cache cannot
    observe (the register later reads as unset) — the group becomes
    permanently uncacheable instead of serving the value past its
    expiry (found by review; the engine-side expiry is invisible to
    the result-row feed)."""
    from copycat_tpu.models.session_client import _EdgeValueCache
    from copycat_tpu.utils.metrics import MetricsRegistry

    cache = _EdgeValueCache(MetricsRegistry())
    cache.interest.update((0, 1))
    cache.observe(np.asarray([0, 1]),
                  np.asarray([ap.OP_VALUE_SET, ap.OP_VALUE_SET]),
                  np.asarray([5, 6]), np.asarray([0, 0]),
                  np.asarray([0, 30]),  # group 1 arms a TTL
                  np.asarray([0, 0]))
    assert cache.serve(np.asarray([0])).tolist() == [5]
    assert cache.serve(np.asarray([1])) is None
    # even a later plain write to the TTL'd group stays uncached
    cache.observe(np.asarray([1]), np.asarray([ap.OP_LONG_ADD]),
                  np.asarray([1]), np.asarray([0]), np.asarray([0]),
                  np.asarray([7]))
    assert cache.serve(np.asarray([1])) is None


def test_edge_cache_purged_on_abandoned_flush(monkeypatch):
    """An abandoned drive leaves its ops INDETERMINATE: the replica is
    purged so a later causal read cannot hide a write that may have
    applied (the correlate-a-fresh-read contract)."""
    from copycat_tpu.models.session_client import _EdgeValueCache
    from copycat_tpu.utils.metrics import MetricsRegistry

    cache = _EdgeValueCache(MetricsRegistry())
    cache.interest.add(0)
    cache.observe(np.asarray([0]), np.asarray([ap.OP_VALUE_SET]),
                  np.asarray([5]), np.asarray([0]), np.asarray([0]),
                  np.asarray([0]))
    assert cache.serve(np.asarray([0])).tolist() == [5]
    cache.purge()
    assert cache.serve(np.asarray([0])) is None
    assert cache._m_purges.value == 1


def test_edge_cache_knob_off(monkeypatch):
    monkeypatch.setenv("COPYCAT_EDGE_READS", "0")
    rg = RaftGroups(4, 3, log_slots=32, submit_slots=4, seed=12,
                    config=Config(monotone_tag_accept=True))
    rg.wait_for_leaders()
    c = BulkSessionClient(rg)
    assert c._edge is None
    s = c.open_session()
    s.submit(0, ap.OP_LONG_ADD, 2)
    c.flush()
    assert list(s.query_batch([0], ap.OP_VALUE_GET,
                              consistency="causal")) == [2]


def test_lock_events_and_expiry_fanout(deep_rg, client):
    """A dead session's lock is released THROUGH THE LOG on a monotone
    engine (cleanup rides the next flush), and the grant event reaches
    the surviving session's listener."""
    g = 3
    holder = client.open_session()
    waiter = client.open_session()
    got = []
    waiter.on_event(g, lambda ev: got.append(ev))
    t1 = holder.lock_acquire(g)
    client.flush()
    assert holder.result(t1) == 1            # granted immediately
    t2 = waiter.lock_acquire(g)
    client.flush()
    assert waiter.result(t2) == 2            # queued behind holder
    # holder dies silently: stop keep-aliving it. Expiry is measured in
    # engine rounds; burn rounds with the OTHER session's traffic.
    client._sessions.pop(holder.id)
    reg = deep_rg.sessions
    for _ in range(40):
        waiter.submit_batch([7] * 8, ap.OP_LONG_ADD, 1)
        client.flush()
        if not reg.pending_cleanup and holder.id not in reg._sessions:
            # expiry fired on an earlier flush and cleanup committed
            q = waiter.submit(g, ap.OP_LOCK_HOLDER)
            client.flush()
            if waiter.result(q) == waiter.id:
                break
    q = waiter.submit(g, ap.OP_LOCK_HOLDER)
    client.flush()
    assert waiter.result(q) == waiter.id, \
        "dead session's lock was not released to the waiter"
    assert any(ev.code == ap.EV_LOCK_GRANT and ev.target == waiter.id
               for ev in got), "grant event not delivered to listener"
    with pytest.raises(SessionExpiredError):
        holder.submit(g, ap.OP_VALUE_GET)


def test_graceful_close_releases_lock(deep_rg, client):
    g = 4
    a = client.open_session()
    b = client.open_session()
    a.lock_acquire(g)
    b.lock_acquire(g)
    client.flush()
    a.close()
    client.flush()                            # commits the release fan-out
    q = b.submit(g, ap.OP_LOCK_HOLDER)
    client.flush()
    assert b.result(q) == b.id


def test_classic_engine_compat():
    """The same client contract runs on a CLASSIC engine (no monotone
    gate): drive is the classic bulk path, cleanup rides the queue."""
    rg = RaftGroups(4, 3, log_slots=32, submit_slots=4, seed=3)
    rg.wait_for_leaders()
    client = BulkSessionClient(rg)
    s = client.open_session()
    seqs = s.submit_batch([0] * 6, ap.OP_LONG_ADD, 2)
    client.flush()
    assert list(s.results_window(int(seqs[0]), 6)) == [2, 4, 6, 8, 10, 12]
    # graceful close commits lock release through the queue-managed path
    t = s.lock_acquire(1)
    client.flush()
    assert s.result(t) == 1
    s.close()
    client.flush()
    s2 = client.open_session()
    t2 = s2.lock_acquire(1)
    client.flush()
    assert s2.result(t2) == 1, "closed session's lock not released"


def test_throughput_smoke(client):
    """Mechanical throughput check (CPU): the sessioned surface commits
    a 4k-op burst in one flush with per-op numpy cost only. The real
    ≥100k/s target is measured by the ``session`` bench scenario on
    TPU; this guards the mechanics (one drive per flush, vectorized
    correlation)."""
    s = client.open_session()
    rounds_before = client._rg.rounds
    g = np.arange(4096) % client._rg.num_groups
    seqs = s.submit_batch(g, ap.OP_LONG_ADD, 1)
    n = client.flush()
    assert n == 4096
    assert s.results_window(int(seqs[0]), 4096).min() >= 1
    # one pipelined drive: rounds grow like burst/S + settle, not per-op
    assert client._rg.rounds - rounds_before < 4096 // 2


def test_abandoned_flush_indeterminate_then_recover():
    """A flush abandoned mid-fault (liveness lost) marks its commands
    INDETERMINATE — they may or may not have applied — re-stages the
    idempotent cleanup ops, and after heal + recover() the client
    resumes with exactly-once preserved (each abandoned op applied at
    most once, verified by reading the counter)."""
    import jax.numpy as jnp

    from copycat_tpu.models.session_client import CommandIndeterminateError

    rg = RaftGroups(4, 3, log_slots=32, submit_slots=4, seed=21,
                    config=Config(monotone_tag_accept=True))
    rg.wait_for_leaders()
    client = BulkSessionClient(rg)
    s = client.open_session()
    base = s.submit(0, ap.OP_LONG_ADD, 1)
    client.flush()
    assert s.result(base) == 1

    # cut ALL delivery: nothing can commit; the drive must lose liveness
    rg.deliver = jnp.zeros((4, 3, 3), dtype=bool)
    seqs = s.submit_batch([0] * 4, ap.OP_LONG_ADD, 1)
    with pytest.raises(TimeoutError):
        client.flush(max_rounds=40)
    with pytest.raises(CommandIndeterminateError):
        s.result(int(seqs[0]))

    # heal + recover, then the session keeps working with fresh seqs
    rg.deliver = jnp.ones((4, 3, 3), dtype=bool)
    client.recover()
    q = s.submit(0, ap.OP_VALUE_GET)
    client.flush()
    val = s.result(q)
    # exactly-once bound: the 4 abandoned adds applied AT MOST once each
    assert 1 <= val <= 5, val
    # and new commands still apply exactly once
    t = s.submit(0, ap.OP_LONG_ADD, 10)
    client.flush()
    assert s.result(t) == val + 10
