"""Golden wire-schema test (satellite of the copycheck PR).

The wire format is positional: ``@serialize_with(id)`` + ``_fields``
order IS the encoding. This test freezes the *runtime* schema — the
actual registered classes, not the AST view (`tests/test_copycheck.py`
covers that one and proves both views agree) — against
``tests/golden/wire_schema.json``.

If it fails because you intentionally changed the protocol:

    copycat-tpu lint --update-golden

then commit the regenerated ``tests/golden/wire_schema.json`` so the
schema change is an explicit, reviewable diff.
"""

import json
import os

from copycat_tpu.io import serializer
from copycat_tpu.protocol import messages as msg

GOLDEN = os.path.join(os.path.dirname(__file__), "golden",
                      "wire_schema.json")
REGEN = ("schema drift — if intentional, run `copycat-tpu lint "
         "--update-golden` and commit tests/golden/wire_schema.json")


def _runtime_schema() -> dict:
    out = {}
    for type_id, cls in serializer._TYPE_REGISTRY.items():
        if cls.__module__ == msg.__name__ and issubclass(cls, msg.Message):
            out[str(type_id)] = [cls.__name__, list(cls._fields)]
    return out


def test_protocol_ids_unique_and_in_reserved_block():
    schema = _runtime_schema()
    assert schema, "no protocol messages registered?"
    for type_id in schema:
        assert 200 <= int(type_id) <= 229, (
            f"id {type_id} outside the protocol block 200-229 "
            f"(messages.py docstring)")


def test_runtime_schema_matches_golden():
    with open(GOLDEN, encoding="utf-8") as f:
        golden = json.load(f)
    current = _runtime_schema()
    assert current.keys() == golden.keys(), (
        f"type-id set drifted: only-in-code="
        f"{sorted(set(current) - set(golden), key=int)} only-in-golden="
        f"{sorted(set(golden) - set(current), key=int)}; {REGEN}")
    for type_id in sorted(golden, key=int):
        assert current[type_id] == golden[type_id], (
            f"id {type_id}: golden {golden[type_id]} != code "
            f"{current[type_id]} — field ORDER is the wire encoding; "
            f"{REGEN}")


def test_every_message_field_list_is_complete():
    """Responses must carry the uniform error surface the clients
    expect; requests carrying sessions must name session_id first-class
    (positional walk in the C codec)."""
    for cls_name, fields in _runtime_schema().values():
        cls = getattr(msg, cls_name)
        if issubclass(cls, msg.Response):
            assert "error" in fields, f"{cls_name} lacks `error`"
