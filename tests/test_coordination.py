"""Coordination tests (reference ``DistributedLockTest``,
``DistributedLeaderElectionTest`` incl. testNextElection,
``DistributedMembershipGroupTest``, ``DistributedTopicTest``,
``DistributedMessageBusTest.testSend``)."""

import asyncio

from copycat_tpu.coordination import (
    DistributedLeaderElection,
    DistributedLock,
    DistributedMembershipGroup,
    DistributedMessageBus,
    DistributedTopic,
)
from copycat_tpu.io.local import LocalTransport
from copycat_tpu.io.transport import Address

from atomix_fixtures import Stack
from helpers import async_test
from raft_fixtures import next_ports


@async_test(timeout=120)
async def test_lock_unlock():
    stack = await Stack().start(3)
    try:
        c1 = await stack.client()
        c2 = await stack.client()
        l1 = await c1.get("lock", DistributedLock)
        l2 = await c2.get("lock", DistributedLock)
        await l1.lock()
        # Second holder must wait.
        assert await l2.try_lock() is False
        waiter = asyncio.ensure_future(l2.lock())
        await asyncio.sleep(0.2)
        assert not waiter.done()
        await l1.unlock()
        await asyncio.wait_for(waiter, 5)  # grant flows via session event
        await l2.unlock()
        # Re-acquirable after release.
        assert await l1.try_lock() is True
        await l1.unlock()
    finally:
        await stack.close()


@async_test(timeout=120)
async def test_lock_timeout():
    stack = await Stack().start(3)
    try:
        c1 = await stack.client()
        c2 = await stack.client()
        l1 = await c1.get("tlock", DistributedLock)
        l2 = await c2.get("tlock", DistributedLock)
        await l1.lock()
        # Bounded wait times out through the replicated clock.
        assert await asyncio.wait_for(l2.try_lock(0.3), 10) is False
        await l1.unlock()
    finally:
        await stack.close()


@async_test(timeout=120)
async def test_lock_released_on_session_expiry():
    """Capability fix over the reference: holder crash releases the lock."""
    stack = await Stack().start(3, session_timeout=0.8)
    try:
        c1 = await stack.client(session_timeout=0.8)
        c2 = await stack.client(session_timeout=3.0)
        l1 = await c1.get("xlock", DistributedLock)
        l2 = await c2.get("xlock", DistributedLock)
        await l1.lock()
        waiter = asyncio.ensure_future(l2.lock())
        await asyncio.sleep(0.1)
        # Crash client 1 (no graceful close - keepalives just stop).
        c1.client._keepalive.cancel()
        c1.client._session.state = "expired"
        await asyncio.wait_for(waiter, 15)  # lock re-granted to client 2
        await l2.unlock()
    finally:
        await stack.close()


@async_test(timeout=120)
async def test_leader_election_and_failover():
    """Reference testElection + testNextElection."""
    stack = await Stack().start(3, session_timeout=0.8)
    try:
        c1 = await stack.client(session_timeout=0.8)
        c2 = await stack.client(session_timeout=3.0)
        e1 = await c1.get("election", DistributedLeaderElection)
        e2 = await c2.get("election", DistributedLeaderElection)

        elected1 = asyncio.Event()
        elected2 = asyncio.Event()
        epochs: dict = {}

        def on1(epoch):
            epochs[1] = epoch
            elected1.set()

        def on2(epoch):
            epochs[2] = epoch
            elected2.set()

        await e1.on_election(on1)
        await asyncio.wait_for(elected1.wait(), 5)
        assert await e1.is_leader(epochs[1]) is True

        await e2.on_election(on2)
        await asyncio.sleep(0.2)
        assert not elected2.is_set()  # second listener waits

        # Kill the leader's client; leadership must pass to listener 2.
        c1.client._keepalive.cancel()
        c1.client._session.state = "expired"
        await asyncio.wait_for(elected2.wait(), 15)
        assert await e2.is_leader(epochs[2]) is True
        # Old epoch is no longer valid (fencing).
        assert await e2.is_leader(epochs[1]) is False
    finally:
        await stack.close()


@async_test(timeout=120)
async def test_membership_group_join_leave_events():
    stack = await Stack().start(3)
    try:
        c1 = await stack.client()
        c2 = await stack.client()
        g1 = await c1.get("group", DistributedMembershipGroup)
        g2 = await c2.get("group", DistributedMembershipGroup)

        joins: list = []
        leaves: list = []
        joined = asyncio.Event()
        left = asyncio.Event()
        g1.on_join(lambda m: (joins.append(m.id), joined.set()))
        g1.on_leave(lambda m: (leaves.append(m), left.set()))

        me1 = await g1.join()
        me2 = await g2.join()
        await asyncio.wait_for(joined.wait(), 5)
        assert joins == [me2.id]
        assert {m.id for m in await g1.members()} == {me1.id, me2.id}

        await g2.leave()
        await asyncio.wait_for(left.wait(), 5)
        assert leaves == [me2.id]
    finally:
        await stack.close()


@async_test(timeout=120)
async def test_membership_group_remote_execute():
    """Remote execution via registered callback names (closure-free)."""
    stack = await Stack().start(3)
    try:
        c1 = await stack.client()
        c2 = await stack.client()
        g1 = await c1.get("exec-group", DistributedMembershipGroup)
        g2 = await c2.get("exec-group", DistributedMembershipGroup)

        ran = asyncio.Event()
        payloads: list = []
        g2.handler("record", lambda x: (payloads.append(x), ran.set()))

        await g1.join()
        me2 = await g2.join()
        assert await g1.member(me2.id).execute("record", "hello") is True
        await asyncio.wait_for(ran.wait(), 5)
        assert payloads == ["hello"]

        # Scheduled execution through the deterministic timer wheel.
        ran.clear()
        assert await g1.member(me2.id).schedule(0.3, "record", "later") is True
        await asyncio.wait_for(ran.wait(), 10)
        assert payloads == ["hello", "later"]
    finally:
        await stack.close()


@async_test(timeout=120)
async def test_topic_pub_sub():
    stack = await Stack().start(3)
    try:
        c1 = await stack.client()
        c2 = await stack.client()
        t1 = await c1.get("topic", DistributedTopic)
        t2 = await c2.get("topic", DistributedTopic)

        messages: list = []
        got = asyncio.Event()
        await t2.subscribe(lambda m: (messages.append(m), got.set()))
        await t1.sync().publish("news")
        await asyncio.wait_for(got.wait(), 5)
        assert messages == ["news"]

        # async_() mode: publish completes on COMMIT (SEQUENTIAL write,
        # reference DistributedTopic.async()); delivery still arrives
        got.clear()
        await t1.async_().publish("later")
        await asyncio.wait_for(got.wait(), 5)
        assert messages == ["news", "later"]
    finally:
        await stack.close()


@async_test(timeout=120)
async def test_message_bus_direct_send():
    """Reference DistributedMessageBusTest.testSend: registry via the log,
    payload over a direct connection."""
    stack = await Stack().start(3)
    try:
        c1 = await stack.client()
        c2 = await stack.client()
        b1 = await c1.get("bus", DistributedMessageBus)
        b2 = await c2.get("bus", DistributedMessageBus)
        addr1, addr2 = next_ports(2)
        await b1.open(addr1, LocalTransport(stack.registry))
        await b2.open(addr2, LocalTransport(stack.registry))

        received: list = []
        await b2.consumer("orders", lambda body: (received.append(body), "ack")[1])
        # Registry propagation reaches b1 via session events.
        for _ in range(100):
            if "orders" in b1._consumers:
                break
            await asyncio.sleep(0.05)
        producer = await b1.producer("orders")
        reply = await producer.send({"sku": 7})
        assert reply == "ack"
        assert received == [{"sku": 7}]
        await b1.close_bus()
        await b2.close_bus()
    finally:
        await stack.close()
