"""Differential proof for the batched device-native read plane.

The read pump (``RaftServer._run_read_window`` + ``RaftGroups.
drive_query_vector``) coalesces reads arriving across sessions into
per-consistency windows, pays each window's consistency gate ONCE, and
evaluates device-eligible reads as tensors through one ``query_step``
engine round. Its contract is BIT-IDENTICAL observable behavior to the
per-op query lane (``COPYCAT_SERVER_READ_PUMP=0``): same results, same
observed indices, same error surfaces — proven here by running the same
seeded mixed read/write script through both lanes and comparing
everything the client can see, plus gate-amortization accounting
(≤1 leadership-confirm round per linearizable window, witnessed by the
``query_gate_rounds_saved`` counter) and the engine-level vector read
drive against per-op ``serve_query``.
"""

import asyncio
import os
import random

import pytest

jax = pytest.importorskip("jax")

import numpy as np  # noqa: E402

from copycat_tpu.atomic import DistributedAtomicValue  # noqa: E402
from copycat_tpu.io.local import (  # noqa: E402
    LocalServerRegistry, LocalTransport)
from copycat_tpu.manager.atomix import AtomixClient, AtomixServer  # noqa: E402
from copycat_tpu.manager.device_executor import DeviceEngineConfig  # noqa: E402
from copycat_tpu.models import RaftGroups  # noqa: E402
from copycat_tpu.ops import apply as ap  # noqa: E402
from copycat_tpu.resource.consistency import Consistency  # noqa: E402

from helpers import async_test  # noqa: E402
from raft_fixtures import next_ports  # noqa: E402

ENGINE = DeviceEngineConfig(capacity=16, num_peers=3, log_slots=32)


async def _spi_cluster(registry, read_pump: bool):
    """One standalone server + client; the read pump forced on or off."""
    (addr,) = next_ports(1)
    server = AtomixServer(addr, [addr], LocalTransport(registry),
                          election_timeout=0.5, heartbeat_interval=0.1,
                          session_timeout=20.0, executor="tpu",
                          engine_config=ENGINE)
    server.server._read_pump = read_pump
    await server.open()
    client = AtomixClient([addr], LocalTransport(registry),
                          session_timeout=20.0)
    await client.open()
    return server, client


def _script(seed: int, n_rounds: int, wave: int):
    """Seeded read-dominated script over 4 values: each round is a
    write phase (set/cas/gas bursts) followed by a read phase of
    ``wave`` gets. Phases are awaited separately so every read phase
    observes a settled state — the histories of both lanes are then
    comparable value-for-value (concurrent read/write races have many
    valid linearizations and would compare noise, not the lanes).
    Value 3 carries a change listener (its writes take the generator
    path — the read window still serves its gets from the device)."""
    rng = random.Random(seed)
    rounds = []
    for _ in range(n_rounds):
        writes = [(rng.randrange(4), rng.randrange(3), rng.randrange(5),
                   rng.randrange(5)) for _ in range(wave // 4)]
        reads = [rng.randrange(4) for _ in range(wave)]
        rounds.append((writes, reads))
    return rounds


async def _run_script(client, rounds):
    """Execute the script; returns (results, indices, finals, events) —
    the client-observable history including the per-round high-water
    index the reads advanced."""
    values = [await client.get(f"v{i}", DistributedAtomicValue)
              for i in range(4)]
    # exercise every consistency routing: bounded (default), sequential,
    # full-quorum linearizable, bounded+listener
    values[1].with_consistency(Consistency.SEQUENTIAL)
    values[2]._read_cl = "linearizable"
    events: list = []
    listener = await values[3].on_change(lambda v: events.append(v))
    for i, v in enumerate(values):
        await v.set(i)  # deterministic non-None base; lands on device
    results = []
    indices = []
    for writes, reads in rounds:
        async def one_write(target, kind, a, b):
            v = values[target]
            try:
                if kind == 0:
                    await v.set(a)
                    return ("set", None)
                if kind == 1:
                    return ("cas", await v.compare_and_set(a, b))
                return ("gas", await v.get_and_set(a))
            except Exception as e:  # noqa: BLE001 — error surfaces compare
                return ("err", type(e).__name__, str(e))

        async def one_read(target):
            try:
                return ("get", await values[target].get())
            except Exception as e:  # noqa: BLE001
                return ("err", type(e).__name__, str(e))

        results.append(await asyncio.gather(
            *(one_write(*w) for w in writes)))
        results.append(await asyncio.gather(
            *(one_read(t) for t in reads)))
        indices.append(client.client.index)
    finals = [await v.get() for v in values]
    listener.close()
    await asyncio.sleep(0.05)
    return results, indices, finals, events


@async_test(timeout=300)
async def test_read_pump_bit_identical_to_per_op_path():
    """Same seeded script, two servers (read pump on / off): results,
    observed indices, event order and final state must be identical."""
    waves = _script(seed=7, n_rounds=5, wave=32)
    histories = []
    metrics = []
    for pump in (True, False):
        registry = LocalServerRegistry()
        server, client = await _spi_cluster(registry, read_pump=pump)
        try:
            histories.append(await _run_script(client, waves))
            snap = server.server.metrics.snapshot()
            metrics.append(snap)
        finally:
            await asyncio.wait_for(client.close(), 5)
            await asyncio.wait_for(server.close(), 5)
    on, off = histories
    assert on[0] == off[0], "read pump diverged from per-op results"
    assert on[1] == off[1], "read pump diverged in observed indices"
    assert on[2] == off[2], "read pump diverged in final state"
    assert on[3] == off[3], "read pump diverged in event order"
    # the script genuinely exercised the batched lane: windows flushed,
    # device rows evaluated, and the per-op lane stayed dark on writes
    snap_on, snap_off = metrics
    assert snap_on["query_windows"] > 0
    assert snap_on["query_ops_device_lane"] > 0
    assert snap_off["query_windows"] == 0, "pump-off must not window"


@async_test(timeout=300)
async def test_linearizable_window_pays_one_confirm_round():
    """N same-turn linearizable reads across sessions form ONE window:
    exactly one leadership-confirm round runs, and the
    query_gate_rounds_saved counter records the N-1 amortized rounds."""
    registry = LocalServerRegistry()
    server, client = await _spi_cluster(registry, read_pump=True)
    try:
        raft = server.server
        values = [await client.get(f"v{i}", DistributedAtomicValue)
                  for i in range(4)]
        for v in values:
            v._read_cl = "linearizable"
            await v.set(9)
        confirms = [0]
        real_confirm = raft._confirm_leadership

        async def counting_confirm():
            confirms[0] += 1
            return await real_confirm()

        raft._confirm_leadership = counting_confirm
        saved0 = raft.metrics.counter("query_gate_rounds_saved").value
        windows0 = raft.metrics.counter("query_windows").value
        n = 24
        got = await asyncio.gather(
            *(values[i % 4].get() for i in range(n)))
        assert got == [9] * n
        # client-side the 24 gets coalesce into one QueryBatchRequest,
        # server-side into one window: ≤1 confirm round for all of them
        assert confirms[0] == 1, f"window paid {confirms[0]} confirm rounds"
        assert raft.metrics.counter("query_windows").value == windows0 + 1
        assert raft.metrics.counter(
            "query_gate_rounds_saved").value - saved0 == n - 1
    finally:
        await asyncio.wait_for(client.close(), 5)
        await asyncio.wait_for(server.close(), 5)


@async_test(timeout=300)
async def test_cross_session_reads_share_one_window():
    """Reads from DIFFERENT client sessions arriving in one event-loop
    turn share a single read window (the pump's advantage over the
    per-request QueryBatch gate)."""
    registry = LocalServerRegistry()
    server, client_a = await _spi_cluster(registry, read_pump=True)
    client_b = AtomixClient([server.server.address],
                            LocalTransport(registry), session_timeout=20.0)
    await client_b.open()
    try:
        raft = server.server
        va = await client_a.get("shared", DistributedAtomicValue)
        vb = await client_b.get("shared", DistributedAtomicValue)
        await va.set(5)
        windows0 = raft.metrics.counter("query_windows").value
        got = await asyncio.gather(va.get(), vb.get(),
                                   va.get(), vb.get())
        assert got == [5, 5, 5, 5]
        flushed = raft.metrics.counter("query_windows").value - windows0
        assert flushed <= 2, (
            f"4 same-turn reads from 2 sessions flushed {flushed} windows")
    finally:
        await asyncio.wait_for(client_b.close(), 5)
        await asyncio.wait_for(client_a.close(), 5)
        await asyncio.wait_for(server.close(), 5)


@async_test(timeout=120)
async def test_read_pump_env_knob(monkeypatch):
    """COPYCAT_SERVER_READ_PUMP=0 keeps the per-op lane; default is on."""
    registry = LocalServerRegistry()
    monkeypatch.setenv("COPYCAT_SERVER_READ_PUMP", "0")
    (addr,) = next_ports(1)
    server = AtomixServer(addr, [addr], LocalTransport(registry),
                          session_timeout=20.0)
    assert server.server._read_pump is False
    monkeypatch.delenv("COPYCAT_SERVER_READ_PUMP")
    (addr2,) = next_ports(1)
    server2 = AtomixServer(addr2, [addr2], LocalTransport(registry),
                           session_timeout=20.0)
    assert server2.server._read_pump is True


def test_drive_query_vector_matches_per_op_serve():
    """Engine level: one vectorized query_step round returns exactly what
    per-op serve_query returns, for mixed groups and uneven per-group
    read counts (slot packing + pow2 width padding)."""
    rg = RaftGroups(8, 3, log_slots=32, submit_slots=4, seed=3)
    rg.wait_for_leaders()
    for g in range(8):
        rg.run_until([rg.submit(g, ap.OP_LONG_ADD, g + 1)])
    # uneven read multiplicity per group: group g read g+1 times
    groups = np.concatenate([np.full(g + 1, g) for g in range(8)])
    got = rg.drive_query_vector(groups, ap.OP_VALUE_GET)
    want = np.array([rg.serve_query(int(g), ap.OP_VALUE_GET)
                     for g in groups])
    assert (got == want).all(), (got, want)
    # atomic (lease-gated) rows serve too on a healthy engine
    got_atomic = rg.drive_query_vector(groups, ap.OP_VALUE_GET,
                                       atomic=True)
    assert (got_atomic == want).all()


def test_drive_query_vector_refuses_writes():
    rg = RaftGroups(2, 3, log_slots=32, submit_slots=4, seed=4)
    rg.wait_for_leaders()
    with pytest.raises(ValueError, match="not read-only"):
        rg.drive_query_vector([0], ap.OP_LONG_ADD, 1)


@async_test(timeout=300)
async def test_follower_reads_round_robin(monkeypatch):
    """SEQUENTIAL reads round-robin across the cluster (follower read
    scale-out) and still return the committed value — the server-side
    client-index wait keeps them at-or-after the client's own writes;
    lagging servers refuse and the client falls back to the leader.
    Edge reads are pinned OFF: this test exercises the server read
    lane the edge tier exists to bypass (docs/EDGE_READS.md)."""
    monkeypatch.setenv("COPYCAT_EDGE_READS", "0")
    registry = LocalServerRegistry()
    addrs = next_ports(3)
    servers = [
        AtomixServer(a, addrs, LocalTransport(registry, local_address=a),
                     election_timeout=0.3, heartbeat_interval=0.05,
                     session_timeout=20.0)
        for a in addrs
    ]
    await asyncio.gather(*(s.open() for s in servers))
    client = AtomixClient(addrs, LocalTransport(registry),
                          session_timeout=20.0)
    await client.open()
    try:
        assert client.client._follower_reads is True
        v = await client.get("v", DistributedAtomicValue)
        v.with_consistency(Consistency.SEQUENTIAL)
        await v.set(7)
        for _ in range(9):  # sequential singles: each advances the RR
            assert await v.get() == 7
        snap = client.client.metrics.snapshot()
        assert snap.get("client_reads_follower_lane", 0) >= 3, snap
        # every server saw read traffic (round-robin actually rotated)
        served = [s.server.metrics.counter(
            "query_reads", consistency="sequential").value
            for s in servers]
        assert sum(1 for n in served if n > 0) >= 2, served
    finally:
        await asyncio.wait_for(client.close(), 5)
        for s in servers:
            await asyncio.wait_for(s.close(), 10)


@async_test(timeout=120)
async def test_follower_reads_env_knob(monkeypatch):
    """COPYCAT_CLIENT_FOLLOWER_READS=0 restores leader-pinned reads."""
    from copycat_tpu.client.client import RaftClient
    from copycat_tpu.io.transport import Address

    monkeypatch.setenv("COPYCAT_CLIENT_FOLLOWER_READS", "0")
    registry = LocalServerRegistry()
    c = RaftClient([Address("127.0.0.1", 1)], LocalTransport(registry))
    assert c._follower_reads is False
    monkeypatch.delenv("COPYCAT_CLIENT_FOLLOWER_READS")
    c2 = RaftClient([Address("127.0.0.1", 1)], LocalTransport(registry))
    assert c2._follower_reads is True


@async_test(timeout=300)
async def test_read_pump_error_surfaces_match():
    """A read against a deleted resource raises the same ApplicationError
    through both lanes (the window's per-row error path)."""
    outcomes = []
    for pump in (True, False):
        registry = LocalServerRegistry()
        server, client = await _spi_cluster(registry, read_pump=pump)
        try:
            v = await client.get("doomed", DistributedAtomicValue)
            await v.set(1)
            instance_id = v.client.instance_id
            await v.delete()
            from copycat_tpu.atomic import commands as vc
            from copycat_tpu.manager.operations import InstanceQuery
            from copycat_tpu.resource.operations import ResourceQuery
            try:
                await client.client.submit(InstanceQuery(
                    instance_id, ResourceQuery(vc.Get(), "sequential")))
                outcomes.append(("ok",))
            except Exception as e:  # noqa: BLE001 — the surface under test
                outcomes.append((type(e).__name__, str(e)))
        finally:
            await asyncio.wait_for(client.close(), 5)
            await asyncio.wait_for(server.close(), 5)
    assert outcomes[0] == outcomes[1], outcomes
    assert outcomes[0][0] == "ApplicationError"
