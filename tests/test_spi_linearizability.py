"""Linearizability of the PUBLIC API under a leader kill.

The committed verdict (`LINEARIZABILITY.md`) checks device-engine
histories; this checks the full SPI stack the way Jepsen would check
the reference: concurrent ``AtomixClient`` sessions drive a shared
resource through ``atomix.get`` (real sessions, RPC, state-machine
multiplexing) while the LEADER server is killed mid-run, and the
client-observed invoke/complete history must satisfy the Wing & Gong
checker. Ops that error or time out are recorded with unknown
completion (the checker tries both "applied" and "never applied" — the
Jepsen-correct treatment of an ambiguous failure). Register histories
run against both executors; lock histories against the CPU stack.
(Reference obligation: `README.md:8` Jepsen claim through
`Atomix.java:205`'s public surface. The CPU-only tests need no jax.)

Soundness bounds baked into the harness: the workload phase is
hard-capped at a fraction of the session timeout, so a session can
never expire mid-history — an expiry performs *implicit* state changes
(e.g. LockState releases a dead holder's lock) that the history cannot
represent and the checker would misread as a violation.
"""

import asyncio
import random
import time

import pytest

# The CPU-stack tests need no jax themselves, but the checker lives in
# copycat_tpu.testing whose package __init__ imports the device-history
# recorder (jax) — so a jax-less environment can't collect this module
# either way; skip it cleanly there.
jax = pytest.importorskip("jax")

from copycat_tpu.atomic import DistributedAtomicValue
from copycat_tpu.coordination import DistributedLock
from copycat_tpu.io.local import LocalServerRegistry, LocalTransport
from copycat_tpu.manager.atomix import AtomixClient, AtomixServer
from copycat_tpu.server.raft import LEADER
from copycat_tpu.testing.linearize import (
    HOp,
    LockModel,
    RegisterModel,
    check_linearizable,
)

from helpers import async_test
from raft_fixtures import next_ports

OPS_PER_CLIENT = 24
CLIENTS = 3
VALUE_DOMAIN = 4     # small domain so cas sometimes succeeds
SESSION_TIMEOUT = 30.0
WORKLOAD_CAP_S = 10.0  # << SESSION_TIMEOUT: no expiry can land mid-history


async def _register_loop(cid: int, client, history: list, seq: list,
                         deadline: float) -> None:
    reg = await client.get("reg", DistributedAtomicValue)
    rng = random.Random(100 + cid)
    for _ in range(OPS_PER_CLIENT):
        if time.monotonic() > deadline:
            return
        kind = rng.randrange(3)
        if kind == 0:
            v = rng.randrange(1, VALUE_DOMAIN)
            op, coro = ("set", v), reg.set(v)
        elif kind == 1:
            op, coro = ("get",), reg.get()
        else:
            e = rng.randrange(0, VALUE_DOMAIN)
            u = rng.randrange(1, VALUE_DOMAIN)
            op, coro = ("cas", e, u), reg.compare_and_set(e, u)
        seq[0] += 1
        op_id, t0 = seq[0], time.monotonic()
        try:
            raw = await asyncio.wait_for(coro, 15)
        except (Exception, asyncio.TimeoutError):
            # ambiguous: may or may not have applied
            history.append(HOp(op_id=op_id, op=op, result=None, invoke=t0))
            continue
        if op[0] == "set":
            result = 0
        elif op[0] == "get":
            result = 0 if raw is None else int(raw)
        else:
            result = int(bool(raw))
        history.append(HOp(op_id=op_id, op=op, result=result, invoke=t0,
                           complete=time.monotonic()))
        await asyncio.sleep(0.01)  # pace: keep the workload spanning faults


async def _lock_loop(cid: int, client, history: list, seq: list,
                     deadline: float) -> None:
    """try_lock/unlock history for LockModel (who = client id).

    Never re-acquires while holding (the CPU LockState queues a holder's
    re-lock per the reference; the model treats re-acquire as idempotent
    — avoiding the case keeps one model valid for both executors). An
    unlock COMPLETION is recorded with unknown result: after a failover
    re-establishes the session, a leftover local ``holding`` flag can
    drive an unlock of a free lock, which the server accepts silently
    but the model scores 0 — unknown-result lets the checker consider
    both, which is always sound.
    """
    lock = await client.get("lk", DistributedLock)
    rng = random.Random(200 + cid)
    holding = False
    for _ in range(16):
        if time.monotonic() > deadline:
            return
        if holding and rng.random() < 0.7:
            op, coro = ("release", cid), lock.unlock()
        elif holding:
            await asyncio.sleep(0.02)
            continue
        else:
            op, coro = ("acquire", cid), lock.try_lock()
        seq[0] += 1
        op_id, t0 = seq[0], time.monotonic()
        try:
            raw = await asyncio.wait_for(coro, 15)
        except (Exception, asyncio.TimeoutError):
            history.append(HOp(op_id=op_id, op=op, result=None, invoke=t0))
            holding = False  # unknown; stop assuming we hold it
            continue
        if op[0] == "acquire":
            result = int(bool(raw))
            holding = bool(raw)
            history.append(HOp(op_id=op_id, op=op, result=result,
                               invoke=t0, complete=time.monotonic()))
        else:
            holding = False
            history.append(HOp(op_id=op_id, op=op, result=None, invoke=t0))
        await asyncio.sleep(0.01)


async def _run_stack(executor: str, loop_fn, fault: str = "kill"
                     ) -> "tuple[list[HOp], float]":
    """Boot 3 servers + CLIENTS clients, run ``loop_fn`` per client,
    inject the ``fault`` ("kill" = close the leader; "partition" =
    isolate the leader for ~2s of the workload, then heal; "loss" =
    10%/10% request/response loss for the whole run plus a leader
    partition) once a third of the target ops are in flight, return the
    recorded history and the fault time."""
    registry = LocalServerRegistry()
    addrs = next_ports(3)
    kwargs = {}
    if executor == "tpu":
        from copycat_tpu.manager.device_executor import DeviceEngineConfig
        kwargs = dict(engine_config=DeviceEngineConfig(
            capacity=8, num_peers=3, log_slots=32))
    servers = [
        AtomixServer(a, addrs, LocalTransport(registry, local_address=a),
                     election_timeout=0.2, heartbeat_interval=0.04,
                     session_timeout=SESSION_TIMEOUT, executor=executor,
                     **kwargs)
        for a in addrs
    ]
    nem = registry.attach_nemesis()
    if fault == "loss":
        nem.set_loss(request=0.10, response=0.10)
    await asyncio.gather(*(s.open() for s in servers))
    clients = []
    for _ in range(CLIENTS):
        c = AtomixClient(addrs, LocalTransport(registry),
                         session_timeout=SESSION_TIMEOUT)
        await c.open()
        clients.append(c)

    history: list[HOp] = []
    seq = [0]
    deadline = time.monotonic() + WORKLOAD_CAP_S
    tasks = [
        asyncio.ensure_future(loop_fn(i, c, history, seq, deadline))
        for i, c in enumerate(clients)
    ]

    # mid-run nemesis: kill the LEADER server (2/3 keep quorum; sessions
    # pinned to the victim must fail over). Trigger once a third of the
    # ops are in, so the kill provably lands mid-workload.
    while seq[0] < CLIENTS * 12 // 3 and time.monotonic() < deadline:
        await asyncio.sleep(0.02)
    if all(t.done() for t in tasks):
        # On a slow machine WORKLOAD_CAP_S can expire before the kill
        # threshold is reached — the workload simply finished; that is a
        # timing artifact, not a linearizability signal. Teardown with
        # the same guards as the normal path (an unguarded close against
        # already-dead peers can hang or raise, masking the skip).
        for c in clients:
            try:
                await asyncio.wait_for(c.close(), 5)
            except (Exception, asyncio.TimeoutError):
                pass
        for s in servers:
            try:
                await asyncio.wait_for(s.close(), 5)
            except (Exception, asyncio.TimeoutError):
                pass
        pytest.skip("workload finished before the nemesis threshold "
                    "(slow machine) — nothing to check")
    leader = next((s for s in servers if s.server.role == LEADER),
                  servers[0])
    if fault == "kill":
        await leader.close()
    else:
        # partition the leader from its peers (clients are anonymous and
        # reach both sides — the Jepsen client model); heal mid-workload
        # so the history records refusals/ambiguity AND recovery
        lead_addr = leader.server.address
        nem.partition([lead_addr], [a for a in addrs if a != lead_addr])
    kill_t = time.monotonic()
    if fault != "kill":
        await asyncio.sleep(2.0)
        nem.partition()  # heal the partition (loss, if any, stays on)

    await asyncio.wait_for(asyncio.gather(*tasks), 240)
    nem.heal()
    for c in clients:
        try:
            await asyncio.wait_for(c.close(), 5)
        except (Exception, asyncio.TimeoutError):
            pass
    for s in servers:
        if fault != "kill" or s is not leader:
            try:
                await asyncio.wait_for(s.close(), 10)
            except (Exception, asyncio.TimeoutError):
                pass
    return history, kill_t


def _check(history: list, kill_t: float, model) -> None:
    completed = [h for h in history if h.complete != float("inf")
                 or h.result is not None]
    assert len(completed) >= 12, \
        f"too few completed ops ({len(completed)}) — cluster never healed"
    post_kill = [h for h in history if h.result is not None
                 and h.invoke > kill_t]
    assert post_kill, "no op completed after the fault — failover dead"
    res = check_linearizable(history, model)
    assert res.ok, f"SPI history not linearizable: {res}"


@async_test(timeout=420)
async def test_spi_linearizable_under_leader_kill_cpu():
    _check(*await _run_stack("cpu", _register_loop), model=RegisterModel)


@async_test(timeout=420)
async def test_spi_linearizable_under_leader_kill_tpu():
    _check(*await _run_stack("tpu", _register_loop), model=RegisterModel)


@async_test(timeout=420)
async def test_spi_lock_histories_linearizable_under_leader_kill():
    _check(*await _run_stack("cpu", _lock_loop), model=LockModel)


@async_test(timeout=420)
async def test_spi_linearizable_under_leader_partition_cpu():
    """Round-5 extension (VERDICT r4 #3): the fault is a PARTITION, not
    a clean kill — the isolated leader stays up and dialable, its
    in-flight commands become ambiguous, and the majority side must
    elect and serve while stale-leader reads refuse."""
    _check(*await _run_stack("cpu", _register_loop, fault="partition"),
           model=RegisterModel)


@async_test(timeout=420)
async def test_spi_linearizable_under_partition_and_loss_cpu():
    """Partition + 10%/10% request/response loss for the whole run: lost
    responses make acked-but-unreported commands, the exactly-once
    session dedup's worst case."""
    _check(*await _run_stack("cpu", _register_loop, fault="loss"),
           model=RegisterModel)


@async_test(timeout=420)
async def test_spi_lock_histories_linearizable_under_partition():
    _check(*await _run_stack("cpu", _lock_loop, fault="partition"),
           model=LockModel)


async def _stale_leader_refuses(read_pump: bool) -> None:
    """Round-9 stale-read nemesis (read-pump extension): after a
    partition deposes the leader, the OLD leader's lease expires and a
    new leader commits fresh writes on the majority side. A
    linearizable/bounded read sent straight at the deposed leader must
    REFUSE (its leadership confirm cannot reach a quorum) rather than
    serve state that misses the committed write — with the batched read
    window and with the per-op lane alike."""
    from copycat_tpu.protocol import messages as msg
    from copycat_tpu.atomic import commands as vc
    from copycat_tpu.manager.operations import InstanceQuery
    from copycat_tpu.resource.operations import ResourceQuery

    registry = LocalServerRegistry()
    addrs = next_ports(3)
    servers = [
        AtomixServer(a, addrs, LocalTransport(registry, local_address=a),
                     election_timeout=0.2, heartbeat_interval=0.04,
                     session_timeout=SESSION_TIMEOUT, executor="cpu")
        for a in addrs
    ]
    nem = registry.attach_nemesis()
    await asyncio.gather(*(s.open() for s in servers))
    for s in servers:
        s.server._read_pump = read_pump
    client = AtomixClient(addrs, LocalTransport(registry),
                          session_timeout=SESSION_TIMEOUT)
    await client.open()
    probe = None
    try:
        reg = await client.get("reg", DistributedAtomicValue)
        await reg.set(1)
        instance_id = reg.client.instance_id
        old = next(s for s in servers if s.server.role == LEADER)
        old_term = old.server.term
        lead_addr = old.server.address
        nem.partition([lead_addr], [a for a in addrs if a != lead_addr])
        # wait until the majority side elected a successor
        successor = None
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            successor = next(
                (s for s in servers if s is not old
                 and s.server.role == LEADER
                 and s.server.term > old_term), None)
            if successor is not None:
                break
            await asyncio.sleep(0.05)
        if successor is None:
            pytest.fail("majority never elected a successor")
        # commit a write the deposed leader cannot have seen. Route the
        # client straight at the successor: the old leader is still
        # dialable and ACCEPTS commands it can never commit, so letting
        # the generic retry loop discover the new leader burns a full
        # per-try timeout per wrong dial (generic failover is covered by
        # the leader-kill/partition histories above — this test targets
        # the stale READ refusal).
        client.client._leader_hint = successor.server.address
        client.client._drop_connection()
        await asyncio.wait_for(reg.set(2), 120)
        # direct reads at the DEPOSED leader (anonymous connection — it
        # reaches both sides of the partition, the Jepsen client model)
        probe = LocalTransport(registry).client()
        conn = await probe.connect(lead_addr)
        for consistency in ("linearizable", "bounded_linearizable"):
            response = await asyncio.wait_for(conn.send(msg.QueryRequest(
                session_id=0, index=0, consistency=consistency,
                operation=InstanceQuery(
                    instance_id, ResourceQuery(vc.Get(), consistency)))),
                30)
            assert response.error in (msg.NOT_LEADER, msg.NO_LEADER), (
                f"deposed leader served a {consistency} read "
                f"(result={response.result!r}) that misses the committed "
                f"write")
        # the healed cluster serves the committed value linearizably
        nem.heal()
        reg._read_cl = "linearizable"
        assert await asyncio.wait_for(reg.get(), 60) == 2
    finally:
        nem.heal()
        if probe is not None:
            try:
                await asyncio.wait_for(probe.close(), 5)
            except (Exception, asyncio.TimeoutError):
                pass
        try:
            await asyncio.wait_for(client.close(), 5)
        except (Exception, asyncio.TimeoutError):
            pass
        for s in servers:
            try:
                await asyncio.wait_for(s.close(), 10)
            except (Exception, asyncio.TimeoutError):
                pass


@async_test(timeout=420)
async def test_stale_leader_refuses_reads_with_read_pump():
    await _stale_leader_refuses(read_pump=True)


@async_test(timeout=420)
async def test_stale_leader_refuses_reads_per_op_lane():
    await _stale_leader_refuses(read_pump=False)


@async_test(timeout=420)
async def test_spi_linearizable_under_leader_partition_tpu():
    """Partition nemesis against the DEVICE-executor stack: the engines
    replicate deterministically from each server's committed CPU log, so
    a partitioned server's engine simply lags and reconverges by replay
    — the history must stay linearizable through it."""
    _check(*await _run_stack("tpu", _register_loop, fault="partition"),
           model=RegisterModel)
