"""Linearizability of the PUBLIC API under a leader kill.

The committed verdict (`LINEARIZABILITY.md`) checks device-engine
histories; this checks the full SPI stack the way Jepsen would check
the reference: concurrent ``AtomixClient`` sessions drive ONE shared
``DistributedAtomicValue`` through ``atomix.get`` (real sessions, RPC,
state-machine multiplexing) while the LEADER server is killed mid-run,
and the client-observed invoke/complete history must satisfy the Wing &
Gong checker. Ops that error or time out are recorded with unknown
completion (the checker tries both "applied" and "never applied" — the
Jepsen-correct treatment of an ambiguous failure). Runs against both
executors (reference obligation: `README.md:8` Jepsen claim through
`Atomix.java:205`'s public surface).
"""

import asyncio
import random
import time

import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.atomic import DistributedAtomicValue  # noqa: E402
from copycat_tpu.io.local import LocalServerRegistry, LocalTransport  # noqa: E402
from copycat_tpu.manager.atomix import AtomixClient, AtomixServer  # noqa: E402
from copycat_tpu.manager.device_executor import DeviceEngineConfig  # noqa: E402
from copycat_tpu.server.raft import LEADER  # noqa: E402
from copycat_tpu.testing.linearize import (  # noqa: E402
    HOp,
    RegisterModel,
    check_linearizable,
)

from helpers import async_test  # noqa: E402
from raft_fixtures import next_ports  # noqa: E402

OPS_PER_CLIENT = 24
CLIENTS = 3
VALUE_DOMAIN = 4  # small domain so cas sometimes succeeds


async def _client_loop(cid: int, client, history: list[HOp],
                       seq: "list[int]") -> None:
    reg = await client.get("reg", DistributedAtomicValue)
    rng = random.Random(100 + cid)
    for _ in range(OPS_PER_CLIENT):
        kind = rng.randrange(3)
        if kind == 0:
            v = rng.randrange(1, VALUE_DOMAIN)
            op, coro = ("set", v), reg.set(v)
        elif kind == 1:
            op, coro = ("get",), reg.get()
        else:
            e = rng.randrange(0, VALUE_DOMAIN)
            u = rng.randrange(1, VALUE_DOMAIN)
            op, coro = ("cas", e, u), reg.compare_and_set(e, u)
        seq[0] += 1
        op_id, t0 = seq[0], time.monotonic()
        try:
            raw = await asyncio.wait_for(coro, 15)
        except (Exception, asyncio.TimeoutError):
            # ambiguous: may or may not have applied (HOp frozen; record
            # with unknown completion)
            history.append(HOp(op_id=op_id, op=op, result=None, invoke=t0))
            continue
        if op[0] == "set":
            result = 0
        elif op[0] == "get":
            result = 0 if raw is None else int(raw)
        else:
            result = int(bool(raw))
        history.append(HOp(op_id=op_id, op=op, result=result, invoke=t0,
                           complete=time.monotonic()))
        await asyncio.sleep(0.01)  # pace: keep the workload spanning faults


async def _run_stack(executor: str) -> "tuple[list[HOp], float]":
    registry = LocalServerRegistry()
    addrs = next_ports(3)
    kwargs = {}
    if executor == "tpu":
        kwargs = dict(engine_config=DeviceEngineConfig(
            capacity=8, num_peers=3, log_slots=32))
    servers = [
        AtomixServer(a, addrs, LocalTransport(registry),
                     election_timeout=0.2, heartbeat_interval=0.04,
                     session_timeout=3.0, executor=executor, **kwargs)
        for a in addrs
    ]
    await asyncio.gather(*(s.open() for s in servers))
    clients = []
    for _ in range(CLIENTS):
        c = AtomixClient(addrs, LocalTransport(registry),
                         session_timeout=3.0)
        await c.open()
        clients.append(c)

    history: list[HOp] = []
    seq = [0]
    tasks = [asyncio.ensure_future(_client_loop(i, c, history, seq))
             for i, c in enumerate(clients)]

    # mid-run nemesis: kill the LEADER server (2/3 keep quorum; sessions
    # pinned to the victim must fail over). Trigger once a third of the
    # ops have been invoked, so the kill provably lands mid-workload.
    while seq[0] < CLIENTS * OPS_PER_CLIENT // 3:
        await asyncio.sleep(0.02)
    assert not all(t.done() for t in tasks), "workload finished pre-kill"
    leader = next((s for s in servers if s.server.role == LEADER),
                  servers[0])
    await leader.close()
    kill_t = time.monotonic()

    await asyncio.wait_for(asyncio.gather(*tasks), 240)
    for c in clients:
        try:
            await asyncio.wait_for(c.close(), 5)
        except (Exception, asyncio.TimeoutError):
            pass
    for s in servers:
        if s is not leader:
            await s.close()
    return history, kill_t


def _check(history: list[HOp], kill_t: float) -> None:
    completed = [h for h in history if h.result is not None]
    assert len(completed) >= CLIENTS * OPS_PER_CLIENT // 2, \
        f"too few completed ops ({len(completed)}) — cluster never healed"
    post_kill = [h for h in completed if h.invoke > kill_t]
    assert post_kill, "no op completed after the leader kill — failover dead"
    res = check_linearizable(history, RegisterModel)
    assert res.ok, f"SPI history not linearizable: {res}"


@async_test(timeout=420)
async def test_spi_linearizable_under_leader_kill_cpu():
    _check(*await _run_stack("cpu"))


@async_test(timeout=420)
async def test_spi_linearizable_under_leader_kill_tpu():
    _check(*await _run_stack("tpu"))
