"""Differential proof for the dependency-classified parallel apply +
cross-group engine fusion (docs/SHARDING.md "Apply ordering").

The parallel plane generalizes the vector classifier from "contiguous
runs" to "dependency-classified windows": device-eligible entries on
disjoint resource keys join a staged run ACROSS interleaved ineligible
entries, per-key/per-session FIFO is preserved by the conflict gate
(a colliding entry forces the staged dispatch before it applies), and
staged runs from every Raft group fuse into ONE engine round per
server turn (``RaftServer.flush_fused``). Its contract is BIT-IDENTICAL
observable behavior to the contiguous/per-group plane on every knob
combination:

- ``COPYCAT_PARALLEL_APPLY=0`` restores the contiguous classifier;
- ``COPYCAT_APPLY_FUSE=0`` restores one dispatch per group per run.

These tests prove it by running one seeded interleaved-eligibility
script through all four knob planes and comparing everything the client
can see plus the committed per-group command streams, then racing the
parallel plane against partition + leader-deposition nemeses under
``COPYCAT_INVARIANTS=strict``. The mid-run engine-failure test covers
the explicit failed-pump branch of ``_finalize_vector_run`` (ISSUE 11
satellite: no ``raws[k]`` walk behind a short-circuit guard).
"""

import asyncio
import random

import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.atomic import (  # noqa: E402
    DistributedAtomicLong, DistributedAtomicValue)
from copycat_tpu.io.local import LocalServerRegistry, LocalTransport  # noqa: E402
from copycat_tpu.io.serializer import Serializer  # noqa: E402
from copycat_tpu.manager.atomix import AtomixClient, AtomixServer  # noqa: E402
from copycat_tpu.manager.device_executor import DeviceEngineConfig  # noqa: E402
from copycat_tpu.server.log import CommandEntry  # noqa: E402
from copycat_tpu.server.raft import LEADER  # noqa: E402

from helpers import async_test  # noqa: E402
from raft_fixtures import next_ports  # noqa: E402

ENGINE = DeviceEngineConfig(capacity=32, num_peers=3, log_slots=32)

#: (parallel_apply, apply_fuse) — plane 0 is today's default (both on),
#: plane 3 is the pre-PR contiguous/per-group plane.
PLANES = ((True, True), (True, False), (False, True), (False, False))


async def _cluster(registry, parallel: bool, fuse: bool, *,
                   members: int = 1, groups: int = 4,
                   election_timeout: float = 0.5, clients: int = 1):
    addrs = next_ports(members)
    servers = [AtomixServer(a, addrs, LocalTransport(registry),
                            election_timeout=election_timeout,
                            heartbeat_interval=election_timeout / 5,
                            session_timeout=30.0, executor="tpu",
                            engine_config=ENGINE, groups=groups)
               for a in addrs]
    for s in servers:
        s.server._parallel_apply = parallel
        s.server._apply_fuse = fuse
    await asyncio.gather(*(s.open() for s in servers))
    cs = [AtomixClient(addrs, LocalTransport(registry),
                       session_timeout=30.0) for _ in range(clients)]
    await asyncio.gather(*(c.open() for c in cs))
    return servers, (cs[0] if clients == 1 else cs)


def _script(seed: int, n_waves: int, wave: int):
    """Seeded interleaved-eligibility script over 6 plain values (the
    vector-eligible steady state, driven by 3 writer sessions) + 2
    LISTENED values driven by a 4th session (listeners force the
    generator path, so every wave interleaves eligible and ineligible
    entries from DIFFERENT sessions — the contiguity-collapsing shape
    the dependency classifier spans; same-session interleaving always
    conflicts, by the session-FIFO gate). Values hash-route across all
    4 groups, so the fused plane mixes groups in one round."""
    rng = random.Random(seed)
    waves = []
    for _ in range(n_waves):
        ops = []
        for _ in range(wave):
            if rng.random() < 0.25:           # ineligible, session 3
                target = 6 + rng.randrange(2)
            else:                             # eligible, sessions 0-2
                target = rng.randrange(6)
            kind = rng.randrange(4)
            ops.append((target, kind, rng.randrange(5), rng.randrange(5)))
        waves.append(ops)
    return waves


async def _run_script(clients, waves):
    """Execute the script; returns (results, events, finals) — the full
    client-observable history. Ops on value ``t`` ride session
    ``t % 3`` (plain values) or session 3 (listened values). Wave 2
    creates a late value mid-script (a catalog entry: ``apply_key``
    None, the whole-window barrier)."""
    values = [await clients[3 if i >= 6 else i % 3].get(
        f"pv{i}", DistributedAtomicValue) for i in range(8)]
    events: list[tuple[int, int]] = []
    listeners = [await values[t].on_change(
        lambda v, t=t: events.append((t, v))) for t in (6, 7)]
    for i, v in enumerate(values):
        await v.set(i)  # deterministic non-None base; lands on device
    results = []
    for w, ops in enumerate(waves):
        if w == 2:
            late = await clients[0].get("pv-late", DistributedAtomicValue)
            await late.set(99)
            values.append(late)

        async def one(target, kind, a, b):
            v = values[target]
            if kind == 0:
                await v.set(a)
                return ("set", None)
            if kind == 1:
                return ("cas", await v.compare_and_set(a, b))
            if kind == 2:
                return ("gas", await v.get_and_set(a))
            return ("get", await v.get())
        results.append(await asyncio.gather(*(one(*op) for op in ops)))
    finals = [await v.get() for v in values]
    for listener in listeners:
        listener.close()
    await asyncio.sleep(0.05)  # drain in-flight publishes
    return results, events, finals


def _command_streams(server) -> dict[int, list[bytes]]:
    """Per-group committed command content in log order — serialized
    operation bytes, the cross-plane comparable view."""
    ser = Serializer()
    out: dict[int, list[bytes]] = {}
    for grp in server.groups:
        stream = []
        for i in range(1, grp.commit_index + 1):
            e = grp.log.get(i)
            if isinstance(e, CommandEntry):
                stream.append(ser.write(e.operation))
        out[grp.group_id] = stream
    return out


@async_test(timeout=600)
async def test_parallel_apply_bit_identical_across_knob_planes():
    """Same seeded interleaved script, four knob planes: results,
    per-session event order, final state, and the committed per-group
    command streams must all be identical — COPYCAT_PARALLEL_APPLY=0
    and COPYCAT_APPLY_FUSE=0 each restore the pre-PR plane exactly."""
    waves = _script(seed=11, n_waves=5, wave=32)
    histories = []
    streams = []
    metrics = []
    for parallel, fuse in PLANES:
        registry = LocalServerRegistry()
        servers, clients = await _cluster(registry, parallel, fuse,
                                          clients=4)
        try:
            histories.append(await _run_script(clients, waves))
            streams.append(_command_streams(servers[0].server))
            snap = servers[0].server.stats_snapshot()
            flat = {}
            for grp in servers[0].server.groups:
                for name in ("apply.parallel_spans",
                             "apply.conflict_flushes", "vector_runs",
                             "vector_ops"):
                    flat[name] = flat.get(name, 0) + \
                        grp.metrics.counter(name).value
            flat["apply.fused_dispatches"] = servers[0].server._metrics \
                .counter("apply.fused_dispatches").value
            metrics.append(flat)
            assert "apply.fused_dispatches" in str(snap), \
                "apply.* family missing from the stats surface"
        finally:
            for c in clients:
                await asyncio.wait_for(c.close(), 5)
            for s in servers:
                await asyncio.wait_for(s.close(), 5)
    base = histories[0]
    for plane, hist in zip(PLANES, histories[1:], strict=False):
        assert hist[0] == base[0], f"results diverged vs plane {plane}"
        assert hist[1] == base[1], f"event order diverged vs plane {plane}"
        assert hist[2] == base[2], f"final state diverged vs plane {plane}"
    # Every plane routed work to every group (the fused plane had
    # cross-group rows to merge). Raw LOG bytes are deliberately not
    # compared across planes: held-commit ``clean()`` timing differs by
    # plane, so compaction legitimately retains different entry sets —
    # cross-MEMBER byte identity (the Raft safety property) is asserted
    # per plane in the nemesis differential below, and the client-
    # observable history above is the full cross-plane contract.
    for plane, stream in zip(PLANES, streams):
        assert all(stream[g] for g in stream), \
            f"plane {plane} left a group without committed commands"
    # the script genuinely exercised the planes it compares:
    on = metrics[0]           # (parallel=1, fuse=1)
    contiguous = metrics[2]   # (parallel=0, fuse=1)
    assert on["apply.parallel_spans"] > 0, \
        "parallel plane never spanned an ineligible entry"
    assert on["apply.fused_dispatches"] > 0, "fusion never dispatched"
    assert contiguous["apply.parallel_spans"] == 0, \
        "knobs-off plane must not classify dependency windows"
    assert on["vector_ops"] > 0 and contiguous["vector_ops"] > 0
    # spanning can only merge runs, never split them (run count is also
    # bounded by commit-window cuts, so equality is legitimate when the
    # windows were small)
    assert on["vector_runs"] <= contiguous["vector_runs"], (
        on["vector_runs"], contiguous["vector_runs"])


@async_test(timeout=600)
async def test_fused_dispatch_merges_groups_per_turn():
    """A concurrent burst across all 4 groups on the fused plane:
    staged runs from different groups land in shared engine rounds —
    the fused-dispatch count stays BELOW the per-group run count, and
    at least one dispatch carried rows from 2+ groups."""
    registry = LocalServerRegistry()
    servers, client = await _cluster(registry, parallel=True, fuse=True)
    try:
        counters = await asyncio.gather(
            *(client.get(f"fc{i}", DistributedAtomicLong)
              for i in range(16)))
        for _ in range(6):
            await asyncio.gather(*(c.add_and_get(1) for c in counters
                                   for _ in range(4)))
        server = servers[0].server
        fused = server._metrics.counter("apply.fused_dispatches").value
        runs = sum(g.metrics.counter("vector_runs").value
                   for g in server.groups)
        rows = server._metrics.histogram("apply.fused_rows")
        groups_hist = server._metrics.histogram("apply.fused_groups")
        assert fused > 0 and runs > 0
        assert fused <= runs, (fused, runs)
        assert groups_hist.max_value >= 2, (
            "no fused dispatch ever mixed rows from 2+ groups "
            f"(max {groups_hist.max_value})")
        assert rows.sum == sum(
            g.metrics.counter("vector_ops").value for g in server.groups)
        # exactly-once across the fused plane
        got = await asyncio.gather(*(c.get() for c in counters))
        assert got == [24] * 16, got
    finally:
        await asyncio.wait_for(client.close(), 5)
        for s in servers:
            await asyncio.wait_for(s.close(), 5)


@async_test(timeout=600)
async def test_mid_run_engine_failure_fails_rows_explicitly():
    """A mid-run engine failure (run_vector raises) must resolve every
    staged entry's future with the pump error — no hung futures, no
    ``raws`` indexing — and the engine must serve the NEXT burst
    normally with exactly-once bookkeeping intact."""
    registry = LocalServerRegistry()
    servers, client = await _cluster(registry, parallel=True, fuse=True)
    try:
        counter = await client.get("mc", DistributedAtomicLong)
        assert await counter.add_and_get(1) == 1  # settle on the device
        engine = servers[0].server.groups[0].state_machine.device_engine
        real = engine.run_vector

        def boom(*a, **k):
            raise RuntimeError("injected mid-run engine failure")

        engine.run_vector = boom
        try:
            results = await asyncio.gather(
                *(asyncio.wait_for(counter.add_and_get(1), 30)
                  for _ in range(8)),
                return_exceptions=True)
        finally:
            engine.run_vector = real
        failed = [r for r in results if isinstance(r, BaseException)]
        assert failed, "injected engine failure never surfaced"
        for r in failed:
            assert not isinstance(r, asyncio.TimeoutError), \
                "a failed pump hung its command future"
        acked = [r for r in results if not isinstance(r, BaseException)]
        # the failed rows never applied; the healthy burst lands on the
        # exact value the acked set implies
        value = await counter.add_and_get(1)
        assert value == 1 + len(acked) + 1, (value, len(acked))
    finally:
        await asyncio.wait_for(client.close(), 5)
        for s in servers:
            await asyncio.wait_for(s.close(), 5)


# ---------------------------------------------------------------------------
# nemesis under COPYCAT_INVARIANTS=strict (ISSUE 11 acceptance)
# ---------------------------------------------------------------------------


def _assert_members_bit_identical(servers) -> None:
    """Every member of every group holds bit-identical committed log
    bytes up to the shared commit boundary."""
    ser = Serializer()
    compared = 0
    for g in range(len(servers[0].server.groups)):
        grps = [s.server.groups[g] for s in servers]
        up_to = min(grp.commit_index for grp in grps)
        base = {i: ser.write(e) for i in range(1, up_to + 1)
                if (e := grps[0].log.get(i)) is not None}
        for other in grps[1:]:
            for i, data in base.items():
                e = other.log.get(i)
                if e is not None:
                    assert ser.write(e) == data, \
                        f"group {g} log divergence at {i}"
                    compared += 1
    assert compared > 0, "nothing compared — the workload never committed"


def _assert_no_invariant_violations(servers) -> None:
    for s in servers:
        for grp in s.server.groups:
            assert grp.metrics.counter(
                "repl.invariant_violations").value == 0, \
                f"{s.address} group {grp.group_id}: strict check fired"


@pytest.mark.parametrize("plane", ((True, True), (False, False)),
                         ids=("knobs-on", "knobs-off"))
def test_nemesis_partition_and_deposition_strict(plane, monkeypatch):
    """Partition a follower mid-storm, heal, then depose a leader-
    hosting member mid-storm — on BOTH knob planes, under the strict
    commit invariant: every acked op applies exactly once, survivors'
    per-group logs are bit-identical, and the strict check never
    fires. This is the acceptance differential: the knobs-off run IS
    the pre-PR plane, racing the same faults."""
    parallel, fuse = plane
    monkeypatch.setenv("COPYCAT_INVARIANTS", "strict")

    @async_test(timeout=600)
    async def run():
        registry = LocalServerRegistry()
        servers, client = await _cluster(
            registry, parallel, fuse, members=3, groups=2,
            election_timeout=0.25)
        live = [s for s in servers]
        try:
            for s in servers:
                assert s.server.groups[0]._strict_invariants
            counters = await asyncio.gather(
                *(client.get(f"nc{i}", DistributedAtomicLong)
                  for i in range(6)))
            listened = await client.get("nv", DistributedAtomicValue)
            await listened.set(0)
            seen: list = []
            listener = await listened.on_change(seen.append)
            acked = [0] * len(counters)
            unknown = [0] * len(counters)

            async def one(i: int) -> None:
                try:
                    await asyncio.wait_for(
                        counters[i].increment_and_get(), 30)
                    acked[i] += 1
                except Exception:
                    unknown[i] += 1

            async def storm(rounds: int) -> None:
                for r in range(rounds):
                    ops = [one(i) for i in range(len(counters))]
                    # interleave an ineligible (listened) write per round
                    ops.append(listened.set(r))
                    await asyncio.gather(*ops, return_exceptions=True)

            await storm(3)  # steady state
            # phase 1: partition a follower mid-storm
            nem = registry.attach_nemesis()
            task = asyncio.ensure_future(storm(5))
            await asyncio.sleep(0.05)
            leader0 = next(s for s in servers
                           if s.server.groups[0].role == LEADER)
            victim = next(s for s in servers if s is not leader0)
            rest = [s.address for s in servers if s is not victim]
            nem.partition([victim.address], rest)
            await asyncio.sleep(0.4)
            nem.heal()
            await asyncio.wait_for(task, 120)
            # phase 2: depose a leader-hosting member mid-storm
            task = asyncio.ensure_future(storm(5))
            await asyncio.sleep(0.05)
            depose = next(s for s in live if any(
                g.role == LEADER for g in s.server.groups))
            live.remove(depose)
            await asyncio.wait_for(depose.close(), 10)
            await asyncio.wait_for(task, 120)
            await storm(2)  # settle on the surviving quorum
            # exactly-once window through the public read API
            got = await asyncio.gather(*(c.get() for c in counters))
            for i, value in enumerate(got):
                assert acked[i] <= value <= acked[i] + unknown[i], (
                    f"counter {i}: {value} outside "
                    f"[{acked[i]}, {acked[i] + unknown[i]}]")
            assert sum(acked) >= 6 * 8, "the storms never committed work"
            # survivors converge, then byte-compare their group logs
            deadline = asyncio.get_running_loop().time() + 30
            while asyncio.get_running_loop().time() < deadline:
                if all(grp.last_applied >= min(
                        s.server.groups[grp.group_id].commit_index
                        for s in live)
                       for s in live for grp in s.server.groups):
                    break
                await asyncio.sleep(0.05)
            _assert_members_bit_identical(live)
            _assert_no_invariant_violations(live)
            listener.close()
        finally:
            nem = registry.attach_nemesis()
            nem.heal()
            try:
                await asyncio.wait_for(client.close(), 5)
            except Exception:
                pass
            for s in live:
                try:
                    await asyncio.wait_for(s.close(), 5)
                except Exception:
                    pass

    run()
