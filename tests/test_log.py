"""Log storage-level tests (server/log.py).

The reference Storage contract exposes three levels (SURVEY.md §2.3 storage
row); MAPPED is a distinct path — mmap-backed segments whose recovery trusts
a persisted watermark — not an alias of DISK's buffered+flushed files.
"""

import os

from copycat_tpu.server.log import (
    CommandEntry,
    Log,
    NoOpEntry,
    Storage,
    StorageLevel,
)


def _fill(log: Log, n: int, term: int = 1) -> None:
    for i in range(n):
        log.append(CommandEntry(term=term, timestamp=float(i),
                                session_id=7, seq=i, operation=f"op-{i}"))


def _segments(directory: str, ext: str) -> list[str]:
    return sorted(f for f in os.listdir(directory) if f.endswith("." + ext))


def test_disk_recover_roundtrip(tmp_path):
    storage = Storage(StorageLevel.DISK, str(tmp_path), max_entries_per_segment=4)
    log = storage.build_log()
    _fill(log, 10)
    log.close()
    assert len(_segments(str(tmp_path), "seg")) >= 3
    assert not _segments(str(tmp_path), "mseg")

    recovered = storage.build_log()
    assert recovered.last_index == 10
    assert recovered.get(3).operation == "op-2"


def test_mapped_recover_roundtrip(tmp_path):
    storage = Storage(StorageLevel.MAPPED, str(tmp_path), max_entries_per_segment=4)
    log = storage.build_log()
    _fill(log, 10)
    log.append(NoOpEntry(term=2, timestamp=10.0))
    log.close()
    # distinct on-disk format, rolled by entry count
    assert len(_segments(str(tmp_path), "mseg")) >= 3
    assert not _segments(str(tmp_path), "seg")

    recovered = storage.build_log()
    assert recovered.last_index == 11
    assert recovered.get(5).operation == "op-4"
    assert recovered.term_at(11) == 2
    assert recovered.term_at(4) == 1


def test_mapped_truncate_then_reopen(tmp_path):
    storage = Storage(StorageLevel.MAPPED, str(tmp_path), max_entries_per_segment=4)
    log = storage.build_log()
    _fill(log, 9)
    log.truncate(5)  # follower conflict resolution: drop [5..9]
    log.append(CommandEntry(term=3, timestamp=9.0, session_id=7, seq=99,
                            operation="new-5"))
    log.close()

    recovered = storage.build_log()
    assert recovered.last_index == 5
    assert recovered.get(5).operation == "new-5"
    assert recovered.get(5).term == 3
    assert recovered.get(4).operation == "op-3"


def test_mapped_watermark_bounds_torn_tail(tmp_path):
    """Garbage past the watermark (a torn post-crash frame) is not observed."""
    storage = Storage(StorageLevel.MAPPED, str(tmp_path), max_entries_per_segment=64)
    log = storage.build_log()
    _fill(log, 5)
    log.close()
    (path,) = (os.path.join(str(tmp_path), f)
               for f in _segments(str(tmp_path), "mseg"))
    with open(path, "r+b") as f:
        used = int.from_bytes(f.read(8), "little")
        f.seek(8 + used)
        f.write(b"\xde\xad\xbe\xef" * 8)  # torn bytes inside the capacity

    recovered = storage.build_log()
    assert recovered.last_index == 5
    assert recovered.get(5).operation == "op-4"


def test_mapped_oversize_frame_gets_own_segment(tmp_path):
    storage = Storage(StorageLevel.MAPPED, str(tmp_path), max_entries_per_segment=64)
    log = storage.build_log()
    big = "x" * (Log.MAPPED_SEGMENT_BYTES + 1024)
    log.append(CommandEntry(term=1, timestamp=0.0, session_id=1, seq=0,
                            operation="small"))
    log.append(CommandEntry(term=1, timestamp=1.0, session_id=1, seq=1,
                            operation=big))
    log.close()
    assert len(_segments(str(tmp_path), "mseg")) == 2

    recovered = storage.build_log()
    assert recovered.get(2).operation == big


def test_mapped_crc_bounds_reordered_writeback(tmp_path):
    """Kernel writeback may flush the watermark page before the tail frame's
    pages; recovery must CRC-reject the unwritten (zeroed) tail frame and
    keep everything before it."""
    storage = Storage(StorageLevel.MAPPED, str(tmp_path), max_entries_per_segment=64)
    log = storage.build_log()
    _fill(log, 6)
    log.close()
    (path,) = (os.path.join(str(tmp_path), f)
               for f in _segments(str(tmp_path), "mseg"))
    # Simulate the torn state: watermark says 6 frames are valid, but the
    # last frame — HEADER PAGE INCLUDED — never hit the disk. The all-zero
    # header must not validate (crc32(b"")==0 would, without the seed).
    with open(path, "r+b") as f:
        used = int.from_bytes(f.read(8), "little")
        f.seek(8 + used - (used // 6))       # start of the last frame
        f.write(b"\x00" * (used // 6))

    recovered = storage.build_log()
    assert recovered.last_index == 5          # torn frame 6 dropped
    assert recovered.get(5).operation == "op-4"

    # Payload-only tear (header survived, payload pages did not).
    storage2 = Storage(StorageLevel.MAPPED, str(tmp_path) + "2",
                       max_entries_per_segment=64)
    log2 = storage2.build_log()
    _fill(log2, 6)
    log2.close()
    (path2,) = (os.path.join(str(tmp_path) + "2", f)
                for f in _segments(str(tmp_path) + "2", "mseg"))
    with open(path2, "r+b") as f:
        used = int.from_bytes(f.read(8), "little")
        f.seek(8 + used - (used // 6) + 8)   # past the last frame's header
        f.write(b"\x00" * (used // 6 - 8))
    recovered2 = storage2.build_log()
    assert recovered2.last_index == 5
    assert recovered2.get(5).operation == "op-4"


def test_append_replicated_block_matches_per_entry():
    """The follower's block ingest must land the exact structure the
    per-entry append_replicated walk produced: same entries, same gap
    slots, same term boundaries (term_at over compacted slots)."""

    def entries():
        out = []
        for i, (index, term) in enumerate(
                [(1, 1), (2, 1), (4, 2), (5, 2), (8, 3)]):  # gaps at 3, 6-7
            e = CommandEntry(term=term, timestamp=float(i), session_id=1,
                             seq=i + 1, operation=f"op-{index}")
            e.index = index
            out.append(e)
        return out

    per_entry = Storage(StorageLevel.MEMORY).build_log()
    for e in entries():
        per_entry.append_replicated(e)
    block = Storage(StorageLevel.MEMORY).build_log()
    block.append_replicated_block(entries())

    assert block.last_index == per_entry.last_index == 8
    for i in range(1, 9):
        a, b = per_entry.get(i), block.get(i)
        assert (a is None) == (b is None), i
        if a is not None:
            assert (a.index, a.term, a.operation) == \
                (b.index, b.term, b.operation), i
        assert per_entry.term_at(i) == block.term_at(i), i


def test_append_replicated_block_continues_existing_log():
    log = Storage(StorageLevel.MEMORY).build_log()
    _fill(log, 3)
    tail = []
    for index in (5, 6):  # gap at 4 (compacted on the leader)
        e = NoOpEntry(term=2, timestamp=float(index))
        e.index = index
        tail.append(e)
    log.append_replicated_block(tail)
    assert log.last_index == 6
    assert log.get(4) is None
    assert log.term_at(6) == 2
    assert log.term_at(2) == 1
    log.append_replicated_block([])  # no-op, not an error


def test_append_replicated_block_persists(tmp_path):
    storage = Storage(StorageLevel.MAPPED, str(tmp_path),
                      max_entries_per_segment=4)
    log = storage.build_log()
    block = []
    for index in range(1, 11):
        e = CommandEntry(term=1, timestamp=float(index), session_id=1,
                         seq=index, operation=f"op-{index}")
        e.index = index
        block.append(e)
    log.append_replicated_block(block)
    log.close()
    recovered = storage.build_log()
    assert recovered.last_index == 10
    assert recovered.get(7).operation == "op-7"


def test_recover_reopens_last_segment_no_small_segment_buildup(tmp_path):
    """Repeated restarts must not roll one near-empty segment per run: the
    newest segment is reopened for continued appends (DISK via append mode,
    MAPPED via watermark-resumed mmap) when it still has entry budget."""
    for level, ext in ((StorageLevel.DISK, "seg"), (StorageLevel.MAPPED, "mseg")):
        directory = str(tmp_path / ext)
        os.makedirs(directory)
        storage = Storage(level, directory, max_entries_per_segment=8)
        log = storage.build_log()
        _fill(log, 3)
        log.close()
        before = len(_segments(directory, ext))
        for _ in range(3):  # restart + append, 3 times
            log = storage.build_log()
            _fill(log, 1, term=2)
            log.close()
        assert len(_segments(directory, ext)) == before, ext
        recovered = storage.build_log()
        assert recovered.last_index == 6, ext
        assert recovered.get(6).term == 2, ext
        assert recovered.get(2).operation == "op-1", ext
