"""Native wire codec (native/copycat_codec.c) vs the pure-Python reference.

The C extension must be BYTE-IDENTICAL to serializer.py on encode and
produce equal object graphs on decode, for every corner of the format:
primitives, containers (incl. the sorted-set determinism rule),
generic field-list messages, custom-serialized classes (fallback
hooks), class references, and >64-bit ints (graceful Fallback).
"""

import pytest

pytest.importorskip("jax")  # repo-wide platform pin in conftest

from copycat_tpu.atomic import commands as ac  # noqa: E402
from copycat_tpu.io.buffer import BufferInput, BufferOutput  # noqa: E402
from copycat_tpu.io.codec import codec  # noqa: E402
from copycat_tpu.io.serializer import Serializer  # noqa: E402
from copycat_tpu.io.transport import Address  # noqa: E402
from copycat_tpu.manager import operations as mo  # noqa: E402
from copycat_tpu.protocol import messages as pm  # noqa: E402

C = codec()
pytestmark = pytest.mark.skipif(C is None, reason="no native toolchain")

_ser = Serializer()


def _py_write(obj) -> bytes:
    buf = BufferOutput()
    _ser.write_object(obj, buf)
    return buf.to_bytes()


def _py_read(data: bytes):
    return _ser.read_object(BufferInput(data))


CORPUS = [
    None, True, False,
    0, 1, -1, 63, 64, -64, -65, 127, 128, -300, 2**31, -(2**31),
    2**62 - 1, -(2**62),
    0.0, -0.0, 3.141592653589793, float("inf"), float("-inf"),
    "", "ascii", "héllo ✓ ☃", "a" * 300,
    b"", b"bytes", bytearray(b"mutable"),
    [], [1, "two", None, [3.0]], (), (1,), ((2, 3), [4]),
    {}, {"k": 1, 2: "v", None: [True]},
    set(), {1, 2, 3}, {"a", b"b", 3}, frozenset({9, "z"}),
    mo.InstanceCommand(7, ac.Set(value=42, ttl=None)),
    mo.InstanceQuery(3, ac.Get()),
    mo.InstanceEvent(1, "changed"),
    mo.GetResource("res", ac.Set),           # class reference field
    mo.DeleteResource(11),
    pm.CommandBatchRequest(
        session_id=9,
        entries=[(1, mo.InstanceCommand(1, ac.Get())), (2, None)]),
    pm.RegisterResponse(error=None, error_detail=None, leader=None,
                        session_id=5, timeout=10.0, members=["a:1"]),
    Address("host", 8080),                   # custom write/read (fallback)
    [Address("h", 1), mo.InstanceCommand(2, ac.CompareAndSet(
        expect=1, update=2, ttl=None))],     # fallback nested in fast path
]


@pytest.mark.parametrize("obj", CORPUS, ids=lambda o: repr(o)[:40])
def test_encode_byte_identical(obj):
    assert C.encode(obj) == _py_write(obj)


@pytest.mark.parametrize("obj", CORPUS, ids=lambda o: repr(o)[:40])
def test_decode_cross_paths_equal(obj):
    wire = _py_write(obj)
    via_c = C.decode(wire)
    via_py = _py_read(wire)
    # object graphs may lack __eq__ (Message classes) — compare by
    # re-encoding, which is a faithful structural fingerprint
    assert _py_write(via_c) == _py_write(via_py) == wire


def test_set_encoding_is_deterministic():
    # same set, different construction order -> same bytes (the sorted
    # per-item-encoding rule)
    a = C.encode({3, 1, 2, "x"})
    b = C.encode({"x", 2, 1, 3})
    assert a == b == _py_write({1, 2, 3, "x"})


def test_bigint_falls_back_not_corrupts():
    big = 2**70
    with pytest.raises(C.Fallback):
        C.encode(big)
    # the public API falls back silently and round-trips
    assert _ser.read(_ser.write(big)) == big
    assert _ser.read(_ser.write(-big)) == -big
    # and decode of a Python-encoded bigint falls back too
    with pytest.raises(C.Fallback):
        C.decode(_py_write(big))


def test_unregistered_type_raises_fallback():
    class Unregistered:
        pass

    with pytest.raises(C.Fallback):
        C.encode(Unregistered())


def test_truncated_input_raises_eof():
    wire = C.encode([1, 2, 3])
    with pytest.raises(EOFError):
        C.decode(wire[:-1])


def test_trailing_bytes_rejected():
    with pytest.raises(C.Fallback):
        C.decode(C.encode(1) + b"\x00")


def test_serializer_write_read_use_native_and_match():
    msg = pm.CommandBatchRequest(
        session_id=1,
        entries=[(i, mo.InstanceCommand(i, ac.Set(value=i, ttl=None)))
                 for i in range(50)])
    wire = _ser.write(msg)
    assert wire == _py_write(msg)          # native path, same bytes
    back = _ser.read(wire)
    assert _py_write(back) == wire


def test_full_registry_roundtrip_default_instances():
    """Every registered type must survive encode->decode on BOTH paths
    (constructible ones with default args)."""
    from copycat_tpu.io.serializer import _TYPE_REGISTRY
    # import the catalogs so the registry is fully populated
    import copycat_tpu.collections.commands  # noqa: F401
    import copycat_tpu.coordination.commands  # noqa: F401
    import copycat_tpu.resource.operations  # noqa: F401
    import copycat_tpu.server.log  # noqa: F401

    checked = 0
    for type_id, cls in sorted(_TYPE_REGISTRY.items()):
        if not hasattr(cls, "write_object"):
            continue  # registered only for class-reference serialization
        try:
            obj = cls()
        except Exception:
            continue  # needs constructor args; covered by CORPUS cases
        wire_py = _py_write(obj)
        assert C.encode(obj) == wire_py, (type_id, cls)
        assert _py_write(C.decode(wire_py)) == wire_py, (type_id, cls)
        checked += 1
    assert checked >= 40  # the catalogs are actually populated


def test_fuzz_decode_garbage_never_crashes():
    """The C decoder parses UNTRUSTED wire bytes: any garbage must raise
    a Python exception (EOFError / Fallback / UnicodeDecodeError /
    MemoryError...), never crash the process."""
    import random

    rng = random.Random(0xC0DEC)
    for trial in range(3000):
        size = rng.randrange(0, 64)
        data = bytes(rng.randrange(256) for _ in range(size))
        try:
            C.decode(data)
        except Exception:
            pass  # any Python-level failure is fine


def test_fuzz_truncations_of_valid_wire():
    """Every prefix of a real message must fail cleanly, not crash."""
    msg = pm.CommandBatchRequest(
        session_id=3,
        entries=[(i, mo.InstanceCommand(i, ac.Set(value=i, ttl=None)))
                 for i in range(8)])
    wire = C.encode(msg)
    for cut in range(len(wire)):
        try:
            C.decode(wire[:cut])
        except Exception:
            pass


def _random_graph(rng, depth=0):
    kinds = ["int", "str", "bytes", "float", "none", "bool"]
    if depth < 3:
        kinds += ["list", "tuple", "dict", "set", "msg"]
    k = rng.choice(kinds)
    if k == "int":
        return rng.randrange(-2**62, 2**62)
    if k == "str":
        return "".join(chr(rng.randrange(32, 0x2FF))
                       for _ in range(rng.randrange(8)))
    if k == "bytes":
        return bytes(rng.randrange(256) for _ in range(rng.randrange(8)))
    if k == "float":
        return rng.uniform(-1e9, 1e9)
    if k == "none":
        return None
    if k == "bool":
        return rng.random() < 0.5
    if k == "list":
        return [_random_graph(rng, depth + 1)
                for _ in range(rng.randrange(4))]
    if k == "tuple":
        return tuple(_random_graph(rng, depth + 1)
                     for _ in range(rng.randrange(4)))
    if k == "dict":
        return {rng.randrange(1000): _random_graph(rng, depth + 1)
                for _ in range(rng.randrange(4))}
    if k == "set":
        return {rng.randrange(1000) for _ in range(rng.randrange(4))}
    return mo.InstanceCommand(rng.randrange(100),
                              ac.Set(value=rng.randrange(1000), ttl=None))


def test_fuzz_random_graphs_roundtrip_both_paths():
    import random

    rng = random.Random(7)
    for trial in range(300):
        obj = _random_graph(rng)
        wire = _py_write(obj)
        assert C.encode(obj) == wire, repr(obj)[:80]
        assert _py_write(C.decode(wire)) == wire, repr(obj)[:80]


# ---------------------------------------------------------------------------
# frame-burst walk (decode_frames / encode_frames): the TCP wire framing
# [u32 len][u8 kind][u64 corr][payload] walked in one C call per read
# burst — must match io/tcp.py's Python struct walk byte-for-byte.

import struct  # noqa: E402

_FRAME = struct.Struct(">IBQ")


def _py_frame(kind: int, corr: int, obj) -> bytes:
    payload = _py_write(obj)
    return _FRAME.pack(len(payload), kind, corr) + payload


def test_encode_frames_byte_identical_to_python_framing():
    burst = [(0, 1, mo.InstanceCommand(1, ac.Get())),
             (1, 2, [1, "two", None]),
             (2, 2**40, "TypeError: boom")]
    assert C.encode_frames(burst) == b"".join(
        _py_frame(k, co, o) for k, co, o in burst)


def test_decode_frames_walks_whole_burst():
    burst = [(0, i, mo.InstanceCommand(i, ac.Set(value=i, ttl=None)))
             for i in range(20)]
    wire = C.encode_frames(burst)
    frames, consumed = C.decode_frames(wire)
    assert consumed == len(wire)
    assert [(k, co) for k, co, _ in frames] == [(0, i) for i in range(20)]
    for (_, _, got), (_, _, sent) in zip(frames, burst):
        assert _py_write(got) == _py_write(sent)


def test_decode_frames_stops_at_torn_frame():
    whole = _py_frame(1, 7, "complete")
    torn = _py_frame(0, 8, ["partial", "frame"])
    for cut in range(1, len(torn)):
        frames, consumed = C.decode_frames(whole + torn[:cut])
        assert consumed == len(whole)
        assert len(frames) == 1 and frames[0][:2] == (1, 7)


def test_decode_frames_inexpressible_payload_raises_fallback():
    # a >64-bit int inside one frame aborts the WHOLE burst with
    # Fallback — io/tcp.py then re-walks it frame-by-frame in Python
    wire = _py_frame(1, 1, 1) + _py_frame(1, 2, 2**70)
    with pytest.raises(C.Fallback):
        C.decode_frames(wire)


def test_fuzz_decode_frames_garbage_never_crashes():
    import random

    rng = random.Random(0xF4A3E)
    real = C.encode_frames([(0, 5, mo.InstanceCommand(5, ac.Get()))])
    for trial in range(2000):
        if rng.random() < 0.5:
            data = bytes(rng.randrange(256)
                         for _ in range(rng.randrange(0, 48)))
        else:  # bit-flipped real frames: valid headers, corrupt payloads
            data = bytearray(real)
            for _ in range(rng.randrange(1, 4)):
                data[rng.randrange(len(data))] ^= 1 << rng.randrange(8)
            data = bytes(data)
        try:
            C.decode_frames(data)
        except Exception:
            pass  # any Python-level failure is fine; crashing is not


def test_frame_walk_fuzz_roundtrip_random_bursts():
    import random

    rng = random.Random(31)
    for trial in range(100):
        burst = [(rng.randrange(3), rng.randrange(2**63),
                  _random_graph(rng)) for _ in range(rng.randrange(1, 8))]
        wire = C.encode_frames(burst)
        assert wire == b"".join(_py_frame(*f) for f in burst)
        frames, consumed = C.decode_frames(wire)
        assert consumed == len(wire) and len(frames) == len(burst)
        for (k, co, got), (k0, co0, sent) in zip(frames, burst):
            assert (k, co) == (k0, co0)
            assert _py_write(got) == _py_write(sent)


def test_deep_nesting_falls_back_never_segfaults():
    """Unbounded recursion in the C walkers was a crash vector (found by
    fuzzing: 200k-deep nesting segfaulted; crafted deep WIRE bytes could
    crash decode from untrusted input). Past MAX_DEPTH both sides raise
    Fallback; the public Serializer then surfaces Python's clean
    RecursionError."""
    obj = 0
    for _ in range(5000):
        obj = [obj]
    with pytest.raises(C.Fallback):
        C.encode(obj)
    # zigzag(T_LIST)=14, zigzag(len=1)=2: a 5000-deep crafted wire graph
    wire = bytes([14, 2]) * 5000 + bytes([0])
    with pytest.raises(C.Fallback):
        C.decode(wire)
    with pytest.raises(RecursionError):
        _ser.write(obj)
    # shallow graphs still take the C fast path untouched
    assert C.decode(C.encode([[[1]]])) == [[[1]]]
