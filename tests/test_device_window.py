"""The shared device round pump (VERDICT r3 #3).

Round 3's SPI device plane committed one op per engine round-trip
(submit → run_until([tag]) → 2 settle rounds), so the public resource API
reached the device at per-op latency. The DeviceWindow batches many
handler chains into shared rounds: K independent one-op handlers must
cost ~one chain's rounds, not K chains'.

Reference obligation: the public API *is* the data path
(``Atomix.java:205``, ``AtomixReplica.java:374``).
"""

import asyncio

import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.atomic import DistributedAtomicLong  # noqa: E402
from copycat_tpu.collections import DistributedMap  # noqa: E402
from copycat_tpu.io.local import LocalServerRegistry, LocalTransport  # noqa: E402
from copycat_tpu.manager.atomix import AtomixClient, AtomixServer  # noqa: E402
from copycat_tpu.manager.device_executor import (  # noqa: E402
    DeviceEngine,
    DeviceEngineConfig,
    DeviceJob,
)
from copycat_tpu.ops.apply import OP_LONG_ADD  # noqa: E402

from helpers import async_test  # noqa: E402
from raft_fixtures import next_ports  # noqa: E402

ENGINE = DeviceEngineConfig(capacity=64, num_peers=3, log_slots=32)


def _one_add(engine: DeviceEngine, group: int, amount: int) -> DeviceJob:
    def chain():
        result = yield ("cmd", OP_LONG_ADD, amount, 0, 0)
        return result

    return DeviceJob(engine, group, False, chain())


def test_window_shares_rounds_across_groups():
    engine = DeviceEngine(ENGINE)
    warm_groups = engine._ensure()
    r0 = warm_groups.rounds

    window = engine.begin_window()
    results = {}
    for g in range(32):
        window.add_job(_one_add(engine, g, g + 1),
                       on_done=lambda res, exc, _g=g: results.__setitem__(_g, res))
    window.close()

    rounds = engine._groups.rounds - r0
    assert results == {g: g + 1 for g in range(32)}
    # 32 independent one-op chains through the per-op path would cost
    # >= 32 rounds (3x that with settles); shared rounds must stay flat.
    assert rounds <= 8, f"window used {rounds} rounds for 32 one-op chains"


def test_window_serializes_same_group_chains_in_order():
    engine = DeviceEngine(ENGINE)
    engine._ensure()

    window = engine.begin_window()
    results = []
    for i in range(5):
        window.add_job(_one_add(engine, 0, 10),
                       on_done=lambda res, exc: results.append(res))
    window.close()
    # same group: strict FIFO -> a running counter, not interleaved adds
    assert results == [10, 20, 30, 40, 50]


def test_window_finalizes_in_add_order():
    engine = DeviceEngine(ENGINE)
    engine._ensure()
    window = engine.begin_window()
    done = []
    window.add_job(_one_add(engine, 1, 1),
                   on_done=lambda res, exc: done.append("job"))
    window.add_ready(lambda res, exc: done.append("ready"))
    window.close()
    assert done == ["job", "ready"]


def test_window_surfaces_chain_exceptions_to_on_done():
    engine = DeviceEngine(ENGINE)
    engine._ensure()

    def boom():
        yield ("cmd", OP_LONG_ADD, 1, 0, 0)
        raise ValueError("chain failed")

    window = engine.begin_window()
    seen = {}
    window.add_job(DeviceJob(engine, 2, False, boom()),
                   on_done=lambda res, exc: seen.update(res=res, exc=exc))
    window.close()
    assert isinstance(seen["exc"], ValueError)


@async_test(timeout=300)
async def test_spi_batching_end_to_end():
    """Pipelined increments over many device-backed resources through the
    public API share engine rounds (single server: the deferred commit
    advance batches concurrent appends into one apply window)."""
    registry = LocalServerRegistry()
    addrs = next_ports(1)
    server = AtomixServer(addrs[0], addrs, LocalTransport(registry),
                          election_timeout=0.2, heartbeat_interval=0.04,
                          session_timeout=10.0, executor="tpu",
                          engine_config=ENGINE)
    await server.open()
    client = AtomixClient(addrs, LocalTransport(registry),
                          session_timeout=10.0)
    await client.open()
    try:
        n = 24
        counters = await asyncio.gather(
            *(client.get(f"ctr{i}", DistributedAtomicLong) for i in range(n)))
        engine = server.server.state_machine.device_engine
        r0 = engine._groups.rounds

        reps = 4
        for _ in range(reps):
            got = await asyncio.gather(
                *(c.increment_and_get() for c in counters))
        assert got == [reps] * n

        rounds = engine._groups.rounds - r0
        # per-op cost would be >= 3 rounds x n x reps = 288; batching must
        # beat one round per op even with imperfect arrival batching
        assert rounds < 3 * n * reps / 2, f"{rounds} rounds for {n*reps} ops"

        # capacity is no longer 16: all 24 resources went on-device
        assert engine._next_group >= n
    finally:
        await asyncio.wait_for(client.close(), 5)
        await asyncio.wait_for(server.close(), 5)


@async_test(timeout=300)
async def test_ttl_under_window_still_fires(monkeypatch):
    """Timer-fired device chains (map TTL eviction) spawned mid-window run
    at their log-ordered slot."""
    registry = LocalServerRegistry()
    addrs = next_ports(1)
    server = AtomixServer(addrs[0], addrs, LocalTransport(registry),
                          election_timeout=0.2, heartbeat_interval=0.04,
                          session_timeout=10.0, executor="tpu",
                          engine_config=ENGINE)
    await server.open()
    client = AtomixClient(addrs, LocalTransport(registry),
                          session_timeout=10.0)
    await client.open()
    try:
        m = await client.get("ttlmap", DistributedMap)
        await m.put(1, 100, ttl=0.3)
        assert await m.get(1) == 100
        await asyncio.sleep(0.9)
        # later ops advance the log clock past the deadline
        await m.put(2, 200)
        assert await m.get(1) is None
        assert await m.get(2) == 200
    finally:
        await asyncio.wait_for(client.close(), 5)
        await asyncio.wait_for(server.close(), 5)
