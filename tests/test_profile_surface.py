"""The continuous profiling plane against live servers
(docs/OBSERVABILITY.md "Profiling"): hold attribution naming the
blocking frame, the ``/profile`` routes, the ``COPYCAT_PROFILE=0``
off-plane differential, and the nemesis ground truth — an injected
synchronous hold named by BOTH the ``loop_stall`` finding and the
merged cluster profile, over the real wire."""

import asyncio
import json
import threading

import pytest

jax = pytest.importorskip("jax")

from copycat_tpu import cli  # noqa: E402
from copycat_tpu.server.log import Storage, StorageLevel  # noqa: E402
from copycat_tpu.server.stats import StatsListener, fetch_stats  # noqa: E402
from copycat_tpu.testing.nemesis import LoopHoldNemesis  # noqa: E402
from copycat_tpu.utils import profiler  # noqa: E402
from copycat_tpu.utils.timeseries import assemble_timeline  # noqa: E402

from helpers import arun  # noqa: E402
from raft_fixtures import Put, create_cluster  # noqa: E402


def _ns(**kw):
    return type("A", (), kw)()


@pytest.fixture(autouse=True)
def _fresh_profiler():
    """Crash-nemesis tests elsewhere leak a refcounted profiler into
    the process ON PURPOSE (SIGKILL semantics: ``_cancel_timers``
    never releases) — start every test here from the unpatched shape
    so knob monkeypatching and thread-count deltas mean something."""
    with profiler._ACQUIRE_LOCK:
        leaked, profiler.PROFILER = profiler.PROFILER, None
    if leaked is not None:
        leaked.stop()
    yield


def _sampler_threads() -> int:
    return sum(1 for t in threading.enumerate()
               if t.name == "copycat-profiler")


# ---------------------------------------------------------------------------
# the profiler itself: sampling + hold attribution, no cluster needed
# ---------------------------------------------------------------------------


def test_hold_attribution_names_the_blocking_frame(monkeypatch):
    """A synchronous callback over the threshold records a hold whose
    folded stack ends in the CALLBACK's own frame (a sample lands
    inside any 60ms block at 97 Hz), notes fire, and release restores
    the unpatched loop."""
    monkeypatch.setenv("COPYCAT_PROFILE_HZ", "97")
    monkeypatch.setenv("COPYCAT_PROFILE_HOLD_MS", "20")
    import asyncio.events as aio_events

    unpatched = aio_events.Handle._run
    notes = []
    prof = profiler.acquire()
    assert prof is not None and prof.running
    # registering a view late still creates the gauge keys + notes
    from copycat_tpu.utils.metrics import MetricsRegistry
    reg = MetricsRegistry()
    prof.register_view(reg, lambda kind, **f: notes.append((kind, f)))

    def sync_block():
        import time
        time.sleep(0.06)

    async def run():
        asyncio.get_running_loop().call_soon(sync_block)
        await asyncio.sleep(0.25)

    asyncio.run(run())
    payload = prof.payload()
    assert payload["counters"]["samples"] > 0
    assert payload["counters"]["holds"] >= 1
    hold = max(payload["holds"], key=lambda h: h["ms"])
    assert hold["ms"] >= 20
    assert hold["frame"].endswith(".sync_block")
    assert hold["stack"].split(";")[-1] == hold["frame"]
    assert any(k == "loop_stall" and f["frame"].endswith(".sync_block")
               for k, f in notes)
    # gauges refreshed by the hold path
    snap = reg.snapshot()
    assert snap["profile.holds"] >= 1
    assert snap["profile.hold_max_ms"] >= 20
    # text rendering is pure collapsed lines
    line = prof.render_text(top=1).strip()
    assert line.rsplit(" ", 1)[1].isdigit()
    profiler.release(prof, reg)
    assert profiler.PROFILER is None
    assert aio_events.Handle._run is unpatched


def test_frame_table_merge_and_diff():
    """The pure aggregation side: self/total percentages (total
    deduped per stack, so recursion can't exceed 100%), the member-
    prefixed cluster merge with incomplete-never-dropped semantics,
    and the self% diff against a saved baseline."""
    stacks = [("main;a.f;b.g", 6), ("main;a.f", 3), ("main;c.h;a.f", 1)]
    table = profiler.frame_table(stacks, top=10, skip=1)
    by_frame = {r["frame"]: r for r in table}
    assert by_frame["a.f"]["self"] == 4      # leaf in rows 2 + 3
    assert by_frame["a.f"]["total"] == 10    # appears in every stack
    assert by_frame["a.f"]["total_pct"] == 100.0
    assert by_frame["b.g"]["self"] == 6
    # merge: member prefixes, unreachable + knob-off reasons, holds
    pay = {"node": "m1", "stacks": [{"stack": "main;a.f", "count": 2}],
           "holds": [{"t": 1.0, "ms": 50.0, "frame": "a.f",
                      "stack": "main;a.f"}]}
    merged = profiler.assemble_profile(
        {"m1:1": pay, "m2:2": {"error": "unknown path /profile"}},
        failed_members=["m3:3"])
    assert merged["incomplete"] is True
    assert any("m3:3 unreachable" in w for w in merged["incomplete_why"])
    assert any("m2:2" in w and "COPYCAT_PROFILE=0" in w
               for w in merged["incomplete_why"])
    assert merged["stacks"] == [{"stack": "m1;main;a.f", "count": 2}]
    assert merged["contributed"] == {"m1": 2, "m2:2": 0}
    assert merged["holds"][0]["member"] == "m1"
    text = profiler.render_profile(merged, top=5)
    assert "INCOMPLETE" in text and "a.f" in text
    # diff: per-frame self% move vs the saved artifact shape
    base = {"stacks": [{"stack": "m1;main;a.f", "count": 1},
                       {"stack": "m1;main;b.g", "count": 1}]}
    rows = profiler.diff_profiles(merged, base, top=10)
    moves = {r["frame"]: r["delta_pct"] for r in rows}
    assert moves["a.f"] == 50.0   # 100% now vs 50% in the baseline
    assert moves["b.g"] == -50.0


# ---------------------------------------------------------------------------
# the exposition: /profile routes + the off-knob A/B differential
# ---------------------------------------------------------------------------


def test_profile_route_serves_windowed_stacks(monkeypatch):
    monkeypatch.setenv("COPYCAT_PROFILE_HZ", "53")

    async def run():
        cluster = await create_cluster(1)
        try:
            server = cluster.servers[0]
            assert server.profiler is not None
            client = await cluster.client()
            await client.submit(Put(key="k", value=1))
            await asyncio.sleep(0.25)
            listener = await StatsListener(server, port=0).open()
            try:
                addr = f"127.0.0.1:{listener.port}"
                p = json.loads(await fetch_stats(addr, "/profile"))
                assert p["node"] == str(server.address)
                assert p["stacks"] and p["window_samples"] > 0
                # every folded stack leads with a thread name
                assert all(";" in r["stack"] for r in p["stacks"])
                topped = json.loads(await fetch_stats(
                    addr, "/profile?top=1"))
                assert len(topped["stacks"]) == 1
                assert topped["stacks"][0] == p["stacks"][0]
                # ?since= windows on wall time (the /series model);
                # a future cutoff leaves nothing
                future = json.loads(await fetch_stats(
                    addr, f"/profile?since={p['now'] + 60}"))
                assert future["stacks"] == []
                # malformed query degrades, never 500s
                degraded = json.loads(await fetch_stats(
                    addr, "/profile?since=nope&top=x"))
                assert degraded["stacks"]
                text = (await fetch_stats(addr, "/profile.txt")).decode()
                first = text.splitlines()[0]
                assert first.rsplit(" ", 1)[1].isdigit()
                unknown = json.loads(await fetch_stats(addr, "/nope"))
                assert "/profile" in unknown["routes"]
                assert "/profile.txt" in unknown["routes"]
            finally:
                await listener.close()
        finally:
            await cluster.close()

    arun(run(), timeout=120)


def test_profile_off_knob_removes_the_plane(monkeypatch):
    """COPYCAT_PROFILE=0 differential: no sampler thread, no /profile
    route, no profile.* registry keys, no loop_stall detector — the
    registry key set, route listing and thread set match the
    pre-profiler process exactly (the bit-identity A/B the plane is
    gated on)."""

    async def snapshot_keys():
        samplers_before = _sampler_threads()
        cluster = await create_cluster(1)
        try:
            server = cluster.servers[0]
            client = await cluster.client()
            await client.submit(Put(key="k", value=1))
            server.health.tick()
            listener = await StatsListener(server, port=0).open()
            try:
                addr = f"127.0.0.1:{listener.port}"
                profile_body = json.loads(
                    await fetch_stats(addr, "/profile"))
                unknown = json.loads(await fetch_stats(addr, "/nope"))
                snap = server.stats_snapshot()["raft"]
                detectors = set(server.health.tick()["detectors"])
                # sampler threads created by THIS boot (delta, so a
                # leak from an unrelated earlier test can't bleed in)
                new_samplers = _sampler_threads() - samplers_before
                return (server.profiler, profile_body,
                        unknown["routes"], set(snap), detectors,
                        new_samplers)
            finally:
                await listener.close()
        finally:
            await cluster.close()

    monkeypatch.setenv("COPYCAT_PROFILE", "0")
    prof_off, body_off, routes_off, keys_off, det_off, threads_off = \
        arun(snapshot_keys(), timeout=120)
    assert prof_off is None
    assert threads_off == 0
    # /profile is ABSENT, not empty: the unknown-route error, unlisted
    assert "error" in body_off and "/profile" not in routes_off
    assert not any(k.startswith("profile.") for k in keys_off)

    monkeypatch.setenv("COPYCAT_PROFILE", "1")
    prof_on, body_on, routes_on, keys_on, det_on, threads_on = arun(
        snapshot_keys(), timeout=120)
    assert prof_on is not None
    assert threads_on == 1
    assert "stacks" in body_on and "/profile" in routes_on
    # the on-plane adds EXACTLY the profile.* family and the
    # loop_stall detector gauge; everything else is bit-identical
    assert keys_on - keys_off == {
        "profile.samples", "profile.holds", "profile.hold_max_ms",
        "profile.hold_ms", "profile.overhead_ms",
        "health.detector_status{detector=loop_stall}"}
    assert det_on - det_off == {"loop_stall"}


# ---------------------------------------------------------------------------
# the acceptance ground truth: nemesis hold -> finding + merged flame
# ---------------------------------------------------------------------------


def test_nemesis_loop_hold_named_by_finding_and_merged_profile(
        monkeypatch, tmp_path, capsys):
    """The acceptance differential, over the real wire: an injected
    synchronous blocking call on a 3-member cluster is named — by
    frame — in the ``loop_stall`` health finding, in the merged
    cluster profile's top frames AND heaviest hold, and as a timeline
    event mark."""
    monkeypatch.setenv("COPYCAT_PROFILE_HZ", "97")
    monkeypatch.setenv("COPYCAT_PROFILE_HOLD_MS", "30")

    async def run():
        cluster = await create_cluster(
            3, storage_factory=lambda i: Storage(
                StorageLevel.DISK, str(tmp_path / str(i)),
                max_entries_per_segment=64))
        listeners = []
        try:
            client = await cluster.client()
            await client.submit(Put(key="k", value=1))
            for s in cluster.servers:
                listeners.append(await StatsListener(s, port=0).open())
            addrs = [f"127.0.0.1:{ln.port}" for ln in listeners]
            # the injection: a NAMED module-level synchronous call on
            # the shared loop (97 Hz puts ~11 samples inside each
            # 120ms hold, so attribution reads a real sampled stack)
            nemesis = LoopHoldNemesis(cluster.servers[0], delay_s=0.12)
            for _ in range(3):
                nemesis.inject()
                await asyncio.sleep(0.15)
            # the finding: two ticks (delta detectors need history)
            leader = cluster.leader
            leader.health.tick()
            await asyncio.sleep(0.05)
            verdict = leader.health.tick()
            stall = verdict["detectors"]["loop_stall"]["groups"][
                "server"]
            assert stall["status"] in ("warn", "critical")
            assert "nemesis._nemesis_synchronous_hold" in \
                stall["reason"]
            # the merged cluster profile, over the wire via the CLI
            rc = await asyncio.to_thread(cli._profile, _ns(
                addresses=addrs, last=None, top=10, json=True,
                diff=None, device=None))
            assert rc == 0
            profile = json.loads(capsys.readouterr().out)
            assert profile["incomplete"] is False
            assert len(profile["members"]) == 3
            assert all(profile["contributed"][m] > 0
                       for m in profile["members"])
            # ...the heaviest hold names the injected frame...
            assert profile["holds"][0]["frame"] == \
                "nemesis._nemesis_synchronous_hold"
            # ...and so do the top folded frames of the merged flame
            table = profiler.frame_table(
                [(s["stack"], s["count"]) for s in profile["stacks"]],
                top=10, skip=2)
            assert "nemesis._nemesis_synchronous_hold" in \
                [r["frame"] for r in table]
            # the stall notes land durably (black-box on this tier)
            # and the timeline renders them as event marks
            members, failed = await cli.collect_timeline(addrs)
            assert not failed
            timeline = assemble_timeline(members, failed_members=failed,
                                         last_s=60)
            stalls = [e for e in timeline["events"]
                      if e["kind"] == "loop_stall"]
            assert stalls
            assert any("_nemesis_synchronous_hold" in e["detail"]
                       for e in stalls)
        finally:
            for ln in listeners:
                await ln.close()
            await cluster.close()

    arun(run(), timeout=180)
