"""Fuzz the Wing & Gong checker against brute-force exhaustive search.

The checker is the trust anchor behind LINEARIZABILITY.md and the verdict
runner, so it gets its own oracle: for random tiny histories (≤6 ops,
random overlap windows, random results — most of them NOT linearizable),
a brute-force reference decides linearizability by trying EVERY
permutation of completed ops (with every subset/interleaving of
incomplete ones) against the sequential model and the real-time partial
order. The two verdicts must agree on every history.
"""

import itertools
import math
import random

import pytest

# deliberately NO jax gate: the checker, the models and this oracle are
# pure stdlib — the trust anchor must run everywhere
from copycat_tpu.testing.linearize import (
    HOp,
    LockModel,
    MapModel,
    RegisterModel,
    check_linearizable,
    check_linearizable_windowed,
    check_map_linearizable,
)


def _random_op(rng: random.Random, model=RegisterModel) -> tuple:
    if model is MapModel:
        kind = rng.choice(("put", "get", "remove", "contains", "size"))
        if kind == "put":
            return ("put", rng.randint(1, 2), rng.randint(1, 3))
        if kind == "size":
            return ("size",)
        return (kind, rng.randint(1, 2))
    if model is LockModel:
        return (rng.choice(("acquire", "release")), rng.randint(1, 2))
    kind = rng.choice(("set", "get", "cas", "add"))
    if kind == "set":
        return ("set", rng.randint(1, 3))
    if kind == "get":
        return ("get",)
    if kind == "cas":
        return ("cas", rng.randint(0, 3), rng.randint(1, 3))
    return ("add", rng.randint(1, 2))


def brute_force(history, model) -> bool:
    """Exhaustive reference: a history is linearizable iff SOME total
    order of (all completed ops + any subset of incomplete ops) respects
    the real-time partial order and replays through the model with
    matching results."""
    completed = [h for h in history if h.complete != math.inf]
    incomplete = [h for h in history if h.complete == math.inf]
    for r in range(len(incomplete) + 1):
        for subset in itertools.combinations(incomplete, r):
            ops = completed + list(subset)
            for perm in itertools.permutations(ops):
                # real-time: a must precede b if a completed before b invoked
                ok = True
                for i, a in enumerate(perm):
                    for b in perm[i + 1:]:
                        if b.complete < a.invoke:
                            ok = False
                            break
                    if not ok:
                        break
                if not ok:
                    continue
                state = model.init
                good = True
                for h in perm:
                    state, res = model.apply(state, h.op)
                    if h.result is not None and res != h.result:
                        good = False
                        break
                if good:
                    return True
    return False


def _random_history(rng: random.Random, model=RegisterModel) -> list:
    n = rng.randint(2, 6)
    hist = []
    for i in range(n):
        op = _random_op(rng, model)
        invoke = rng.randint(0, 6)
        if rng.random() < 0.15:
            complete, result = math.inf, None
        else:
            complete = invoke + rng.randint(0, 4)
            # results drawn from a small range: many histories will be
            # UNlinearizable, exercising the reject path hard
            result = rng.randint(0, 4)
        hist.append(HOp(op_id=i, op=op, result=result, invoke=invoke,
                        complete=complete))
    return hist


def _valid_history(rng: random.Random, model=RegisterModel) -> list:
    """A history produced by an actual sequential execution with TRUE
    model results, then with invocation windows randomly WIDENED — still
    linearizable by construction (the original order remains a valid
    witness), but with real concurrency for the search to untangle."""
    n = rng.randint(2, 6)
    state = model.init
    hist = []
    t = 0
    for i in range(n):
        op = _random_op(rng, model)
        state, res = model.apply(state, op)
        invoke = max(0, t - rng.randint(0, 3))   # widen backwards
        complete = t + rng.randint(0, 3)         # widen forwards
        if rng.random() < 0.1:
            complete, res = math.inf, None       # crashed client
        hist.append(HOp(op_id=i, op=op, result=res, invoke=invoke,
                        complete=complete))
        t += 1
    return hist


@pytest.mark.parametrize("model", [RegisterModel, MapModel, LockModel],
                         ids=["register", "map", "lock"])
def test_checker_matches_brute_force(model):
    rng = random.Random(97)
    agree_yes = agree_no = 0
    for k in range(400):
        hist = (_valid_history(rng, model) if k % 2 == 0
                else _random_history(rng, model))
        expected = brute_force(hist, model)
        got = check_linearizable(hist, model).ok
        assert got == expected, f"checker={got} brute={expected}: {hist}"
        agree_yes += expected
        agree_no += not expected
    # the fuzz must genuinely exercise both verdicts
    assert agree_yes > 40 and agree_no > 40, (agree_yes, agree_no)


@pytest.mark.parametrize("model", [RegisterModel, MapModel, LockModel],
                         ids=["register", "map", "lock"])
def test_windowed_checker_matches_brute_force(model):
    """The quiescent-cut windowed search must give the monolithic verdict
    on every history (it is the verdict runner's checker now)."""
    rng = random.Random(131)
    agree_yes = agree_no = 0
    for k in range(400):
        hist = (_valid_history(rng, model) if k % 2 == 0
                else _random_history(rng, model))
        expected = brute_force(hist, model)
        got = check_linearizable_windowed(hist, model).ok
        assert got == expected, f"windowed={got} brute={expected}: {hist}"
        agree_yes += expected
        agree_no += not expected
    assert agree_yes > 40 and agree_no > 40, (agree_yes, agree_no)


def test_map_per_key_checker_matches_brute_force():
    """Per-key decomposition (Herlihy & Wing locality) must agree with
    the whole-map brute force, including the size-op fallback path."""
    rng = random.Random(173)
    agree_yes = agree_no = 0
    for k in range(400):
        hist = (_valid_history(rng, MapModel) if k % 2 == 0
                else _random_history(rng, MapModel))
        expected = brute_force(hist, MapModel)
        got = check_map_linearizable(hist).ok
        assert got == expected, f"per-key={got} brute={expected}: {hist}"
        agree_yes += expected
        agree_no += not expected
    assert agree_yes > 40 and agree_no > 40, (agree_yes, agree_no)


def test_fully_chained_history_has_no_recursion_limit():
    """Overlap chains (complete == next invoke) admit NO quiescent cut,
    so one segment carries every op; the iterative search must handle
    thousands of ops — the recursive version hit Python's stack limit at
    ~1k and turned deep verdict groups into spurious 'undecided'."""
    hist = []
    state = RegisterModel.init
    for i in range(3000):
        op = ("add", 1)
        state, res = RegisterModel.apply(state, op)
        hist.append(HOp(op_id=i, op=op, result=res, invoke=i,
                        complete=i + 1))
    res = check_linearizable_windowed(hist, RegisterModel)
    assert res.ok
    assert res.nodes <= 3000, res.nodes


def test_windowed_checker_tractable_on_deep_histories():
    """A 2,000-op low-concurrency history (the verdict's new per-group
    depth) must check in ~linear nodes — the monolithic search's windows
    would compound instead."""
    rng = random.Random(7)
    model = RegisterModel
    state = model.init
    hist = []
    t = 0
    for i in range(2000):
        op = _random_op(rng, model)
        state, res = model.apply(state, op)
        invoke = max(0, t - rng.randint(0, 2))
        complete = t + rng.randint(0, 2)
        hist.append(HOp(op_id=i, op=op, result=res, invoke=invoke,
                        complete=complete))
        t += rng.randint(1, 2)
    res = check_linearizable_windowed(hist, model)
    assert res.ok
    assert res.nodes < 40_000, res.nodes  # ~linear, not exponential
