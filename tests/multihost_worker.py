"""SPMD worker for the multi-host test: both processes run THIS program
(the lockstep contract), each serving clients for its own 8 groups of a
16-group cluster sharded over 2 processes × 4 virtual CPU devices.
Launched by tests/test_multihost.py; prints one RESULT line for the
parent to assert on."""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from copycat_tpu.ops import apply as ap  # noqa: E402
from copycat_tpu.parallel import multihost  # noqa: E402


def main() -> None:
    coord, nproc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    multihost.initialize(coord, num_processes=nproc, process_id=pid)
    assert jax.process_count() == nproc
    rg = multihost.MultiHostRaftGroups(groups_per_process=8, num_peers=3,
                                       log_slots=32)
    rg.wait_for_leaders()

    # wave 1: one counter add per local group, distinct deltas
    tags = [rg.submit(g, ap.OP_LONG_ADD, g + 1) for g in range(8)]
    rg.run_until(tags, max_rounds=150)
    r1 = [rg.results[t] for t in tags]

    # wave 2: bulk path on the same groups (prefix sums prove FIFO)
    tags2 = rg.submit_batch(np.arange(8), ap.OP_LONG_ADD, 1).tolist()
    rg.run_until(tags2, max_rounds=150)
    r2 = [rg.results[t] for t in tags2]

    # partition phase: cut peer lane 2 everywhere for a while with ops
    # in flight — commits continue on {0,1} quorums, and any op lost to
    # a deposed leader is re-submitted by the per-process retry protocol
    cut = np.ones((8, 3, 3), bool)
    cut[:, 2, :] = False
    cut[:, :, 2] = False
    t_part = [rg.submit(g, ap.OP_LONG_ADD, 10) for g in range(8)]
    for _ in range(12):  # FIXED count — a local break would diverge lockstep
        rg.step_round(deliver=cut)
    rg.run_until(t_part, max_rounds=150)  # heal + lockstep drain
    r3 = [rg.results[t] for t in t_part]

    # fast query lane (runs in lockstep every round on every process)
    qt = rg.submit_query(0, ap.OP_VALUE_GET)
    rg.run_until([qt], max_rounds=100)
    # lockstep ad-hoc read + local membership view
    v1 = rg.serve_query(1, ap.OP_VALUE_GET)

    print("RESULT " + json.dumps(
        {"pid": pid, "r1": r1, "r2": r2, "r3": r3, "q": rg.results[qt],
         "v1": v1, "members0": rg.voting_members(0),
         "leader0": rg.leader(0)}), flush=True)


if __name__ == "__main__":
    main()
