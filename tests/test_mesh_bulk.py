"""Deep bulk plane over a sharded mesh (round 4).

The scaling artifact's claim — the client data path runs over
group-sharded engines with ZERO cross-device collectives — needs an
automated guard, not just the hand-run `parallel/scaling` script: a
wrong PartitionSpec or an accumulator formulation that reshards (the
round-4 census caught the `.at[]` scatter compiling to all-gathers of
the [G,B] buffers) would otherwise ship silently.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.models import BulkDriver, RaftGroups  # noqa: E402
from copycat_tpu.ops import apply as ap  # noqa: E402
from copycat_tpu.ops.consensus import Config  # noqa: E402
from copycat_tpu.parallel.mesh import make_mesh  # noqa: E402
from copycat_tpu.parallel.scaling import _census_text, _deep_census  # noqa: E402


def _mesh_engine(n_groups=48, seed=51):
    mesh = make_mesh()  # all 8 virtual devices, 1D groups axis
    rg = RaftGroups(n_groups, 3, log_slots=32, submit_slots=4, seed=seed,
                    mesh=mesh, config=Config(monotone_tag_accept=True))
    rg.wait_for_leaders()
    return rg


def test_deep_drive_on_sharded_mesh_fifo_and_reads():
    rg = _mesh_engine()
    driver = BulkDriver(rg)
    # uneven per-group counts exercise the padded [G,B] accumulators
    g = np.concatenate([np.full((i % 7) + 1, i) for i in range(48)])
    res = driver.drive(g, ap.OP_LONG_ADD, 1)
    off = 0
    for i in range(48):
        cnt = (i % 7) + 1
        assert (res.results[off:off + cnt] == np.arange(1, cnt + 1)).all()
        off += cnt
    # second drive continues streams across the mesh
    res2 = driver.drive(np.arange(48), ap.OP_LONG_ADD, 1)
    assert (res2.results == (np.arange(48) % 7) + 2).all()
    # and the query lane serves ATOMIC lease reads over the mesh
    got = driver.drive_queries(np.arange(48), ap.OP_VALUE_GET,
                               consistency="atomic")
    assert (got == (np.arange(48) % 7) + 2).all()


def test_deep_step_census_zero_collectives_on_mesh():
    devices = jax.devices("cpu")
    config = Config(append_window=8, applies_per_round=8,
                    monotone_tag_accept=True)
    assert _deep_census(2, devices, config) == {}
    assert _deep_census(8, devices, config) == {}


def test_deep_scan_census_zero_collectives_on_mesh():
    """The round-5 fused scan program is a DISTINCT compiled module; its
    zero-collective property must be verified, not inherited."""
    from copycat_tpu.parallel.scaling import _deep_scan_census

    devices = jax.devices("cpu")
    config = Config(append_window=8, applies_per_round=8,
                    monotone_tag_accept=True)
    assert _deep_scan_census(2, devices, config) == {}
    assert _deep_scan_census(8, devices, config) == {}


def test_query_step_census_zero_collectives_on_mesh():
    """The round-9 read plane: the ``query_step`` program (the batched
    read pump's device leg) is leader-lane selects + one fused apply
    pass per group — group-local by construction — and must compile to
    zero cross-device collectives like the step."""
    from copycat_tpu.parallel.scaling import _query_census

    devices = jax.devices("cpu")
    assert _query_census(2, devices) == {}
    assert _query_census(8, devices) == {}


def test_census_positive_control():
    """The census must be able to SEE collectives — a broken tally that
    always returns {} would turn the scaling artifact into a false
    pass (this exact bug appeared and was caught in round-4 review:
    an over-escaped regex matched nothing)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh()
    x = jax.device_put(np.ones(64, np.float32),
                       NamedSharding(mesh, P("groups")))
    txt = jax.jit(lambda v: v.sum()).lower(x).compile().as_text()
    census = _census_text(txt)
    assert census, f"cross-shard sum must census >=1 collective: {txt[:200]}"
