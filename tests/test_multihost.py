"""Multi-host execution: TWO real processes, one global mesh.

The reference's multi-server story runs one JVM per machine over TCP
(AtomixClientServerTest's 5-server clusters); the TPU-native equivalent
is one SPMD program over a process-spanning ``jax.sharding.Mesh`` with
``jax.distributed`` wiring the processes. This test launches two actual
Python processes over a loopback coordinator (4 virtual CPU devices
each), shards a 16-group cluster across them, and asserts both halves
elect, commit, keep FIFO order, and serve the query lane — i.e. the
full host runtime works when each process can only address half the
batch.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

pytest.importorskip("jax")

WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
DEEP_WORKER = os.path.join(os.path.dirname(__file__),
                           "multihost_deep_worker.py")


def _run_workers(worker: str) -> dict:
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # the worker pins its own platform
    procs = [
        subprocess.Popen(
            [sys.executable, worker, coord, "2", str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, text=True)
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-4000:]}"
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT "):
                d = json.loads(line[len("RESULT "):])
                results[d["pid"]] = d
    assert set(results) == {0, 1}, f"missing worker results: {outs}"
    return results


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_cluster():
    results = _run_workers(WORKER)
    for pid, d in results.items():
        # wave 1 deltas g+1 from zero -> g+1; wave 2 +1; partition wave +10
        assert d["r1"] == [g + 1 for g in range(8)], (pid, d)
        assert d["r2"] == [g + 2 for g in range(8)], (pid, d)
        assert d["r3"] == [g + 12 for g in range(8)], (pid, d)
        assert d["q"] == 12, (pid, d)   # group 0 after the partition wave
        assert d["v1"] == 13, (pid, d)  # group 1: 3 + 10
        assert d["members0"] == [0, 1, 2], (pid, d)
        assert 0 <= d["leader0"] < 3


def test_two_process_deep_sessioned_drive():
    """The unified plane multihost (VERDICT r4 #2): a monotone-tag
    engine sharded over 2 processes, driven through the SESSIONED bulk
    client (deep pipelined drive) with asymmetric per-process loads —
    including one wave where process 1 submits nothing and must pad the
    collective drive with empty windows."""
    results = _run_workers(DEEP_WORKER)
    for pid, d in results.items():
        assert d["fifo_ok"], (pid, d)
        assert d["v0"] == d["expect0"], (pid, d)
