"""Cluster-wide causal tracing (docs/OBSERVABILITY.md): wire
bit-identity when disabled, cross-member span propagation, assembly +
critical-path semantics, and the partition/incomplete contract.

The load-bearing contracts:

- **Tracing off is invisible**: every RPC frame is byte-identical to
  the pre-tracing wire (the committed golden bytes in
  ``tests/golden/wire_frames.json`` were captured from the plane BEFORE
  the trace fields existed — optional trailing fields omit a ``None``
  entirely), and member logs never carry trace state.
- **Tracing on is causal**: a proxied write records phases on every
  member it crossed, all under the client's id, and the assembly's
  critical path accounts for the full end-to-end wall time.
- **Partitions mark assemblies incomplete, never dropped.**
"""

from __future__ import annotations

import asyncio
import json
import pathlib
import zlib

import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.client.client import PinnedConnectionStrategy, RaftClient  # noqa: E402
from copycat_tpu.io.buffer import BufferInput, BufferOutput  # noqa: E402
from copycat_tpu.io import codec as codec_mod  # noqa: E402
from copycat_tpu.io.local import LocalTransport  # noqa: E402
from copycat_tpu.io.serializer import Serializer  # noqa: E402
from copycat_tpu.io.transport import Address  # noqa: E402
from copycat_tpu.protocol import messages as msg  # noqa: E402
from copycat_tpu.server.log import CommandEntry  # noqa: E402
from copycat_tpu.server.raft import LEADER  # noqa: E402
from copycat_tpu.utils import tracing  # noqa: E402
from copycat_tpu.utils.tracing import (  # noqa: E402
    assemble_trace,
    render_waterfall,
)

from helpers import async_test  # noqa: E402
from raft_fixtures import Put  # noqa: E402
from test_sharding import (  # noqa: E402
    NotifyKey,
    close_all,
    sharded_cluster,
)

GOLDEN = pathlib.Path(__file__).parent / "golden" / "wire_frames.json"


@pytest.fixture(autouse=True)
def _clean_tracer():
    tracing.disable()
    tracing.TRACER.clear()
    yield
    tracing.disable()
    tracing.TRACER.clear()


# ---------------------------------------------------------------------------
# the tracing-off wire differential: byte identity with the pre-tracing
# plane, via golden frames captured before the trace fields existed
# ---------------------------------------------------------------------------


def _golden_samples() -> dict:
    addr = Address("local", 5001)
    entry = CommandEntry(3, 1700000000.5, 41, 7, {"k": "v", "n": 9})
    entry.index = 12
    return {
        "vote_request": msg.VoteRequest(
            term=5, candidate=addr, last_log_index=10, last_log_term=4,
            group=None),
        "vote_request_g2": msg.VoteRequest(
            term=5, candidate=addr, last_log_index=10, last_log_term=4,
            group=2),
        "append_heartbeat": msg.AppendRequest(
            term=3, leader=addr, prev_index=12, prev_term=3, entries=[],
            commit_index=12, global_index=None, fill_to=None, group=None),
        "append_window": msg.AppendRequest(
            term=3, leader=addr, prev_index=11, prev_term=3,
            entries=[entry], commit_index=11, global_index=8, fill_to=12,
            group=1),
        "install": msg.InstallRequest(
            term=3, leader=addr, index=5, snap_term=2, total=4, offset=0,
            data=b"abcd", done=False, group=None),
        "proxy_request": msg.ProxyRequest(
            group=1, kind="commands", payload=(41, [(7, {"k": "v"})])),
        "proxy_response": msg.ProxyResponse(
            error=None, error_detail=None, leader=None,
            result=[(7, 12, "ok", None, None)]),
        "publish": msg.PublishRequest(
            session_id=41, event_index=3, prev_event_index=2,
            events=[("poked", "x")], group=None),
        "publish_g1": msg.PublishRequest(
            session_id=41, event_index=3, prev_event_index=2,
            events=[("poked", "x")], group=1),
        "command_untraced": msg.CommandRequest(
            session_id=41, seq=7, operation={"op": 1}, trace=None),
        "command_batch_untraced": msg.CommandBatchRequest(
            session_id=41, entries=[(7, {"op": 1}), (8, {"op": 2})],
            trace=None),
        "keepalive": msg.KeepAliveRequest(
            session_id=41, command_seq=6, event_index=2),
        "query": msg.QueryRequest(
            session_id=41, index=9, operation={"q": 1},
            consistency="linearizable"),
    }


def test_untraced_frames_bit_identical_to_pre_tracing_golden():
    """Every RPC with tracing off serializes to EXACTLY the bytes the
    pre-tracing plane produced (the golden hex was captured from the
    tree before ProxyRequest/ProxyResponse/AppendRequest/PublishRequest
    grew their optional trailing ``trace`` field) — on the pure-Python
    walk AND, when built, the C codec."""
    golden = json.loads(GOLDEN.read_text())
    s = Serializer()
    c = codec_mod.codec()
    for name, obj in _golden_samples().items():
        buf = BufferOutput()
        s.write_object(obj, buf)
        py = buf.to_bytes()
        assert py.hex() == golden[name], \
            f"{name}: python frame drifted from the pre-tracing wire"
        if c is not None:
            assert c.encode(obj).hex() == golden[name], \
                f"{name}: C frame drifted from the pre-tracing wire"


def test_optional_trace_field_round_trips_on_both_codecs():
    addr = Address("local", 5001)
    entry = CommandEntry(3, 1700000000.5, 41, 7, {"k": "v"})
    entry.index = 12
    traced = [
        msg.ProxyRequest(group=1, kind="commands",
                         payload=(41, [(7, {"k": "v"})]), trace=99),
        msg.ProxyResponse(result=[(7, 12, "ok", None, None)], trace=99),
        msg.AppendRequest(term=3, leader=addr, prev_index=11, prev_term=3,
                          entries=[entry], commit_index=11, global_index=8,
                          fill_to=12, group=1, trace=(99, 12)),
        msg.PublishRequest(session_id=41, event_index=3,
                           prev_event_index=2, events=[("poked", "x")],
                           group=None, trace=99),
    ]
    s = Serializer()
    c = codec_mod.codec()
    for obj in traced:
        buf = BufferOutput()
        s.write_object(obj, buf)
        py = buf.to_bytes()
        back = s.read_object(BufferInput(py))
        want = obj.trace
        assert back.trace == want, type(obj).__name__
        if c is not None:
            assert c.encode(obj) == py, type(obj).__name__
            assert c.decode(py).trace == want, type(obj).__name__
        # the untraced twin omits the field: strictly shorter frame,
        # and decoding it yields trace=None
        obj.trace = None
        buf2 = BufferOutput()
        s.write_object(obj, buf2)
        untraced = buf2.to_bytes()
        assert len(untraced) < len(py)
        assert s.read_object(BufferInput(untraced)).trace is None
        if c is not None:
            assert c.decode(untraced).trace is None


# ---------------------------------------------------------------------------
# assembly semantics (pure units)
# ---------------------------------------------------------------------------


def _span(name, member, wall, ms, trace=1, **meta):
    return {"trace": trace, "name": name, "member": member, "wall": wall,
            "duration_ms": ms, **meta}


def test_assembly_critical_path_sums_to_e2e():
    spans = [
        _span("client.submit", "client", 100.0, 10.0),
        _span("ingress.queue", "m1", 100.001, 1.0, group=0),
        _span("proxy.hop", "m1", 100.002, 7.0, group=0),
        _span("group.append", "m2", 100.003, 1.0, group=0),
        _span("quorum.wait", "m2", 100.004, 4.0, group=0),
        _span("apply", "m2", 100.008, 0.5, group=0),
    ]
    asm = assemble_trace(1, {"ring": spans})
    assert asm["incomplete"] is False, asm["incomplete_why"]
    assert asm["members"] == ["client", "m1", "m2"]
    assert asm["e2e_ms"] == pytest.approx(10.0, abs=0.01)
    # innermost-cover: segments partition the whole interval exactly
    assert asm["critical_path_ms"] == pytest.approx(asm["e2e_ms"],
                                                    abs=0.01)
    names = [c["name"] for c in asm["critical_path"]]
    assert "quorum.wait" in names and "client.submit" in names
    text = render_waterfall(asm)
    assert "INCOMPLETE" not in text
    assert "critical path" in text


def test_assembly_marks_unserved_dispatch_incomplete():
    """The partition signature: a sub-block dispatched (ingress.queue /
    a failed proxy.hop) with no group-side span for that group."""
    spans = [
        _span("client.submit", "client", 100.0, 5.0),
        _span("ingress.queue", "m1", 100.001, 0.5, group=1),
        _span("proxy.hop", "m1", 100.002, 2.0, group=1,
              error="unreachable"),
    ]
    asm = assemble_trace(1, {"ring": spans})
    assert asm["incomplete"] is True
    assert any("group 1" in why for why in asm["incomplete_why"])
    # the spans that DID land are all there, rendered with a banner
    assert len(asm["spans"]) == 3
    assert "INCOMPLETE" in render_waterfall(asm)


def test_assembly_errored_hop_with_successful_retry_is_complete():
    """A transient mid-trace failure (leader election) records an
    errored proxy.hop attempt, but the RETRY served the group — the
    assembly is complete; the failed attempt stays on the timeline."""
    spans = [
        _span("client.submit", "client", 100.0, 8.0),
        _span("ingress.queue", "m1", 100.001, 0.2, group=0),
        _span("proxy.hop", "m1", 100.001, 1.0, group=0,
              error="unreachable"),
        _span("proxy.hop", "m1", 100.003, 3.0, group=0),
        _span("group.append", "m2", 100.004, 0.5, group=0),
        _span("quorum.wait", "m2", 100.0045, 2.0, group=0),
    ]
    asm = assemble_trace(1, {"ring": spans})
    assert asm["incomplete"] is False, asm["incomplete_why"]
    assert len(asm["spans"]) == 6  # the errored attempt is rendered


def test_assembly_marks_failed_member_fetch_incomplete_and_dedups():
    span = _span("group.append", "m2", 100.0, 1.0, group=0)
    asm = assemble_trace(
        1, {"a": [span], "b": [dict(span)]},  # same ring seen twice
        failed_members=["host:9"])
    assert asm["incomplete"] is True
    assert any("host:9" in why for why in asm["incomplete_why"])
    assert len(asm["spans"]) == 1  # deduplicated


def test_assembly_of_nothing_is_incomplete_not_dropped():
    asm = assemble_trace(7, {}, failed_members=["host:1"])
    assert asm["incomplete"] is True
    assert asm["spans"] == [] and asm["critical_path_ms"] == 0.0


# ---------------------------------------------------------------------------
# the cross-member waterfall end to end (in-process sharded cluster:
# the shared ring's member tags stand in for per-member fetches)
# ---------------------------------------------------------------------------


@async_test(timeout=120)
async def test_proxied_write_produces_cross_member_waterfall():
    registry, servers = await sharded_cluster(n=3, groups=2)
    # pin the client to a member that leads NEITHER group, so every
    # sub-block pays the proxy hop (seed-spread: member g%N leads
    # group g, so the third member leads nothing at boot)
    ingress = next(s for s in servers
                   if all(g.role != LEADER for g in s.groups))
    client = RaftClient([s.address for s in servers],
                        LocalTransport(registry), session_timeout=30.0,
                        connection_strategy=PinnedConnectionStrategy(
                            ingress.address))
    try:
        await client.open()
        tracing.enable()
        # one event-loop turn, keys covering both groups -> ONE batch
        cover: dict[int, str] = {}
        i = 0
        while len(cover) < 2:
            k = f"w{i}"
            cover.setdefault(zlib.crc32(k.encode()) % 2, k)
            i += 1
        await asyncio.gather(*(
            client.submit_command_nowait(
                Put(key=k, value=1))
            for k in cover.values()))
        tracing.disable()
        traces = tracing.TRACER.traces()
        tid = next(t for t, spans in traces.items()
                   if any(s.name == "client.submit" for s in spans))
        asm = assemble_trace(tid, {"ring": traces[tid]})
        assert asm["incomplete"] is False, asm["incomplete_why"]
        server_members = [m for m in asm["members"] if m != "client"]
        assert len(server_members) >= 2, asm["members"]
        phases = {s["name"] for s in asm["spans"]}
        assert {"client.submit", "ingress.queue", "proxy.hop",
                "group.append", "quorum.wait", "apply",
                "respond"} <= phases, phases
        # acceptance bar: the critical path accounts for the measured
        # end-to-end latency within 10%
        assert abs(asm["critical_path_ms"] - asm["e2e_ms"]) \
            <= 0.1 * asm["e2e_ms"], asm
        # phase histograms fed on the members that did the work
        leader0 = next(s for s in servers
                       if s.groups[0].role == LEADER)
        lat = leader0.groups[0].metrics.histogram("latency.append_ms")
        assert lat.count > 0
        assert ingress._metrics.histogram(
            "latency.ingress_queue_ms").count >= 2
        assert ingress._metrics.histogram(
            "latency.proxy_hop_ms").count >= 2
    finally:
        await close_all(servers, client)


@async_test(timeout=120)
async def test_traced_event_delivery_rides_the_publish_frame():
    """A traced command whose apply publishes session events yields
    event.push (server, under the SAME id via the entry marks) and
    client.event (client receipt) spans."""
    registry, servers = await sharded_cluster(n=3, groups=2)
    client = RaftClient([s.address for s in servers],
                        LocalTransport(registry), session_timeout=30.0)
    try:
        await client.open()
        got: list = []
        client.session().on_event("poked", got.append)
        tracing.enable()
        await client.submit(NotifyKey(key="evt-k", payload="p"))
        tracing.disable()
        # poll for the SPANS, not just the delivery: the client observes
        # the event inside _on_publish BEFORE the server's flush
        # coroutine resumes with the ack and records event.push —
        # asserting at first delivery races that resumption
        def span_names() -> set:
            return {s.name for spans in tracing.TRACER.traces().values()
                    for s in spans}

        deadline = asyncio.get_running_loop().time() + 10
        while asyncio.get_running_loop().time() < deadline and not (
                got and {"event.push", "client.event"} <= span_names()):
            await asyncio.sleep(0.02)
        assert got, "event never delivered"
        names = span_names()
        assert "event.push" in names, names
        assert "client.event" in names, names
    finally:
        await close_all(servers, client)


# ---------------------------------------------------------------------------
# nemesis: partition between ingress and owning leader mid-trace
# ---------------------------------------------------------------------------


def test_partition_mid_trace_yields_incomplete_assembly(monkeypatch):
    """ISSUE 9 satellite: a partition between the ingress and the
    owning group's leader mid-trace yields an ``incomplete=true``
    assembly carrying the spans that DID land (ingress.queue + the
    failed proxy.hop), under COPYCAT_INVARIANTS=strict."""
    monkeypatch.setenv("COPYCAT_INVARIANTS", "strict")

    @async_test(timeout=240)
    async def run():
        registry, servers = await sharded_cluster(
            n=3, groups=2, session_timeout=3.0)
        ingress = next(s for s in servers
                       if all(g.role != LEADER for g in s.groups))
        client = RaftClient(
            [s.address for s in servers], LocalTransport(registry),
            session_timeout=3.0,
            connection_strategy=PinnedConnectionStrategy(ingress.address))
        try:
            await client.open()
            # a key owned by group 0, whose leader we cut off from the
            # ingress (clients bypass partitions by design, so the
            # session connection itself stays up)
            key = next(f"p{i}" for i in range(64)
                       if zlib.crc32(f"p{i}".encode()) % 2 == 0)
            leader0 = next(s for s in servers
                           if s.groups[0].role == LEADER)
            nem = registry.attach_nemesis()
            nem.partition([ingress.address],
                          [s.address for s in servers if s is not ingress])
            tracing.enable()
            fut = client.submit_command_nowait(
                Put(key=key, value=1))
            # let the ingress dispatch, try the hop, and fail it (the
            # per-try budget is the 3 s session timeout)
            await asyncio.sleep(5.0)
            tracing.disable()
            traces = tracing.TRACER.traces()
            # the trace that dispatched toward group 0 from the ingress
            tid = next(
                t for t, spans in traces.items()
                if any(s.name == "ingress.queue"
                       and (s.meta or {}).get("member")
                       == str(ingress.address) for s in spans))
            asm = assemble_trace(tid, {"ring": traces[tid]})
            assert asm["incomplete"] is True, asm
            assert any("group 0" in why for why in asm["incomplete_why"])
            landed = {s["name"] for s in asm["spans"]}
            assert "ingress.queue" in landed, landed
            # the partitioned leader recorded nothing under this id
            assert not any(
                s["name"] in ("group.append", "quorum.wait", "apply")
                and s.get("member") == str(leader0.address)
                for s in asm["spans"]), asm["spans"]
            # rendered, never dropped
            assert "INCOMPLETE" in render_waterfall(asm)
            nem.heal()
            # after the heal the in-flight write resolves one way or
            # the other (the 3 s session may legitimately have expired
            # at the group leaders while keep-alives could not fan out
            # through the partitioned ingress) — it must not hang
            try:
                await asyncio.wait_for(asyncio.shield(fut), 60)
            except (msg.ProtocolError, Exception):  # noqa: BLE001
                pass
            # strict tripwire stayed silent on every member and group
            for s in servers:
                for g in s.groups:
                    assert g.metrics.counter(
                        "repl.invariant_violations").value == 0
        finally:
            if registry.nemesis is not None:
                registry.nemesis.heal()
            await close_all(servers, client)

    run()


# ---------------------------------------------------------------------------
# member logs stay trace-free: the traced run's replicated state is
# bit-identical across members and equal to the untraced run's stream
# ---------------------------------------------------------------------------


@async_test(timeout=240)
async def test_traced_and_untraced_runs_produce_identical_logs():
    from test_sharding import _command_stream

    async def drive(traced: bool):
        registry, servers = await sharded_cluster(n=3, groups=2)
        client = RaftClient([s.address for s in servers],
                            LocalTransport(registry),
                            session_timeout=30.0)
        try:
            await client.open()
            if traced:
                tracing.enable()
            for i in range(12):
                await client.submit(
                    Put(key=f"d{i}", value=i))
            tracing.disable()
            # convergence across members, per group
            deadline = asyncio.get_running_loop().time() + 20
            while asyncio.get_running_loop().time() < deadline:
                if all(
                        s.groups[g].last_applied
                        == servers[0].groups[g].last_applied
                        and s.groups[g].log.last_index
                        == servers[0].groups[g].log.last_index
                        for s in servers for g in range(2)):
                    break
                await asyncio.sleep(0.02)
            ser = Serializer()
            slots = []
            for g in range(2):
                last = servers[0].groups[g].log.last_index
                for i in range(1, last + 1):
                    copies = {ser.write(e) for e in
                              (s.groups[g].log.get(i) for s in servers)
                              if e is not None}
                    assert len(copies) <= 1, \
                        f"group {g} slot {i} diverged"
                slots.append([_command_stream(s.groups[g])
                              for s in servers])
            return slots
        finally:
            await close_all(servers, client)

    untraced = await drive(traced=False)
    tracing.TRACER.clear()
    traced = await drive(traced=True)
    for g in range(2):
        # within each run: identical across members; across runs: the
        # same command stream — tracing left no residue in the log
        assert untraced[g][0] == untraced[g][1] == untraced[g][2]
        assert traced[g][0] == traced[g][1] == traced[g][2]
        assert untraced[g][0] == traced[g][0]


# ---------------------------------------------------------------------------
# the collection route + CLI rendering
# ---------------------------------------------------------------------------


@async_test(timeout=120)
async def test_stats_listener_serves_per_trace_spans():
    from copycat_tpu.server.stats import StatsListener, fetch_stats

    registry, servers = await sharded_cluster(n=3, groups=2)
    client = RaftClient([s.address for s in servers],
                        LocalTransport(registry), session_timeout=30.0)
    listener = StatsListener(servers[0], port=0)
    try:
        await client.open()
        await listener.open()
        tracing.enable()
        await client.submit(
            Put(key="t0", value=1))
        tracing.disable()
        addr = f"127.0.0.1:{listener.port}"
        slowest = json.loads(await fetch_stats(addr, "/traces"))
        assert slowest, "no traces on /traces"
        tid = slowest[0]["trace"]
        local = json.loads(await fetch_stats(addr, f"/traces/{tid}"))
        assert local["trace"] == tid
        assert local["member"] == str(servers[0].address)
        assert local["spans"], local
        assert all("wall" in s for s in local["spans"])
        # unknown id: empty spans, not an error (assembler marks it)
        empty = json.loads(await fetch_stats(addr, "/traces/999999"))
        assert empty["spans"] == []
    finally:
        await listener.close()
        await close_all(servers, client)


def test_traces_watch_renders_slowest_with_new_markers():
    from copycat_tpu.cli import _render_traces_watch

    body = json.dumps([
        {"trace": 2, "total_ms": 9.0, "spans": [
            {"trace": 2, "name": "group.append", "member": "m1",
             "group": 0, "duration_ms": 1.0, "wall": 1.0},
            {"trace": 2, "name": "quorum.wait", "member": "m1",
             "group": 0, "duration_ms": 8.0, "wall": 2.0}]},
        {"trace": 1, "total_ms": 3.0, "spans": [
            {"trace": 1, "name": "client.submit", "duration_ms": 3.0,
             "wall": 1.0}]},
    ]).encode()
    frame, ids = _render_traces_watch(body, None, slowest=8)
    assert ids == {1, 2}
    assert "trace 2" in frame and "quorum.wait{group=0,member=m1}" in frame
    assert "NEW" not in frame  # first poll: no delta baseline yet
    frame2, ids2 = _render_traces_watch(body, {2}, slowest=8)
    assert "NEW" in frame2  # trace 1 appeared since the last poll
    assert ids2 == {1, 2}
