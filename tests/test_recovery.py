"""Crash-recovery plane (docs/DURABILITY.md): snapshot capture/restore,
restart-recovery differentials under the crash/torn-write nemesis family,
snapshot-install streaming, and boot-time edge cases.

The headline differential: a member killed mid-append and rebooted from
snapshot + log tail must be bit-identical (log, state machine state,
session table) to a never-crashed member — with COPYCAT_SNAPSHOTS=0
restoring the replay-only path bit-identically (the recovery A/B knob).
"""

import asyncio
import os
import shutil

import pytest

from copycat_tpu.io.local import LocalTransport
from copycat_tpu.server.log import Storage, StorageLevel
from copycat_tpu.server.raft import LEADER, RaftServer
from copycat_tpu.server.snapshot import SnapshotStore, frame, unframe
from copycat_tpu.testing.nemesis import StorageNemesis, crash_server

from raft_fixtures import (
    Get,
    KVStateMachine,
    Put,
    PutTtl,
    create_cluster,
    server_fingerprint,
)

LEVELS = [StorageLevel.MAPPED, StorageLevel.DISK]


def _storage(level, directory):
    return Storage(level, str(directory), max_entries_per_segment=16)


def _reboot(cluster, index, level, directory, *, env=None,
            members=None) -> RaftServer:
    """A fresh RaftServer on a crashed member's storage + address."""
    old = cluster.servers[index]
    server = RaftServer(
        old.address,
        members or [s.address for s in cluster.servers],
        LocalTransport(cluster.registry, local_address=old.address),
        KVStateMachine(),
        storage=_storage(level, directory),
        election_timeout=old.election_timeout,
        heartbeat_interval=old.heartbeat_interval,
        session_timeout=old.session_timeout,
    )
    cluster.servers[index] = server
    return server


async def _converged(cluster, timeout: float = 10.0):
    """Wait until every open member applied the leader's full log."""
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        leader = cluster.leader
        if leader is not None:
            lagging = [
                s for s in cluster.servers
                if s.is_open and s.last_applied < leader.last_applied]
            if not lagging and leader.commit_index == leader.log.last_index:
                return leader
        await asyncio.sleep(0.02)
    raise TimeoutError("cluster did not converge")


def _assert_bit_identical(a: RaftServer, b: RaftServer) -> None:
    from copycat_tpu.io.serializer import Serializer
    from copycat_tpu.server.log import KeepAliveEntry, NoOpEntry

    start = max(a.log.first_index, b.log.first_index)
    fa = server_fingerprint(a, from_index=start)
    fb = server_fingerprint(b, from_index=start)
    # Log: bit-identical entry bytes, EXCEPT that a slot compacted on one
    # side may hold a cleaned/superseded entry on the other (a leader
    # legitimately omits compacted entries when re-replicating; their
    # effects are replicated via machine + session state, compared
    # strictly below).
    ser = Serializer()
    assert a.log.last_index == b.log.last_index
    for i in range(start, a.log.last_index + 1):
        ea, eb = a.log.get(i), b.log.get(i)
        if ea is None and eb is None:
            continue
        if ea is None or eb is None:
            present, holder = (eb, b) if ea is None else (ea, a)
            assert holder.log.is_cleaned(i) or isinstance(
                present, (KeepAliveEntry, NoOpEntry)), (
                i, type(present).__name__)
            continue
        assert ser.write(ea) == ser.write(eb), i
    assert fa["machine"] == fb["machine"]
    assert fa["sessions"] == fb["sessions"]
    assert fa["last_applied"] == fb["last_applied"]


# ---------------------------------------------------------------------------
# the restart-recovery differential (snapshots ON and OFF, both levels)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("level", LEVELS, ids=lambda lv: lv.value)
@pytest.mark.parametrize("snapshots", ["1", "0"], ids=["snap", "replay"])
@pytest.mark.parametrize(
    "fault", [None, "torn_tail", "partial_frame", "dropped_fsync"],
    ids=["clean", "torn_tail", "partial_frame", "dropped_fsync"])
def test_restart_recovery_differential(tmp_path, monkeypatch, level,
                                       snapshots, fault):
    """Kill a follower mid-append, tear what the crash left behind,
    reboot it from snapshot + log tail (or full replay with
    COPYCAT_SNAPSHOTS=0): once re-converged it must be bit-identical to a
    member that never crashed."""
    monkeypatch.setenv("COPYCAT_SNAPSHOTS", snapshots)
    monkeypatch.setenv("COPYCAT_SNAPSHOT_ENTRIES", "20")
    monkeypatch.setenv("COPYCAT_SNAPSHOT_RETAIN", "4")
    dirs = [tmp_path / f"m{i}" for i in range(3)]

    async def run() -> None:
        cluster = await create_cluster(
            3, storage_factory=lambda i: _storage(level, dirs[i]))
        try:
            client = await cluster.client(session_timeout=30)
            for i in range(30):
                await client.submit(Put(key=f"k{i % 7}", value=i))
            leader = cluster.leader
            victim = next(s for s in cluster.servers if s is not leader)
            vic = cluster.servers.index(victim)

            # kill mid-append: a burst is in flight when the process dies
            burst = [
                asyncio.ensure_future(
                    client.submit(Put(key=f"burst{i}", value=i)))
                for i in range(8)]
            await asyncio.sleep(0)
            await crash_server(victim)
            await asyncio.gather(*burst)  # quorum of 2 still commits

            if fault is not None:
                StorageNemesis(str(dirs[vic])).inject(fault)

            for i in range(20):
                await client.submit(Put(key=f"post{i % 5}", value=i))

            reborn = _reboot(cluster, vic, level, dirs[vic])
            if snapshots == "1":
                # boot must start from the snapshot, not index 1
                assert reborn.last_applied > 0
            await reborn.open()
            leader = await _converged(cluster)
            healthy = next(
                s for s in cluster.servers
                if s is not reborn and s is not leader)
            _assert_bit_identical(reborn, healthy)
            _assert_bit_identical(reborn, leader)
            # and the recovered member still serves reads through the API
            v = await client.submit(Get(key="post4"))
            assert v == 19
        finally:
            await cluster.close()

    asyncio.run(run())


@pytest.mark.parametrize("level", LEVELS, ids=lambda lv: lv.value)
def test_recovery_with_ttl_timers(tmp_path, monkeypatch, level):
    """Pending log-time TTLs ride the snapshot image: a recovered member
    expires keys at the same log time a never-crashed member does."""
    monkeypatch.setenv("COPYCAT_SNAPSHOTS", "1")
    monkeypatch.setenv("COPYCAT_SNAPSHOT_ENTRIES", "10")
    monkeypatch.setenv("COPYCAT_SNAPSHOT_RETAIN", "0")
    dirs = [tmp_path / f"m{i}" for i in range(3)]

    async def run() -> None:
        cluster = await create_cluster(
            3, storage_factory=lambda i: _storage(level, dirs[i]))
        try:
            client = await cluster.client(session_timeout=30)
            await client.submit(PutTtl(key="ephemeral", value=1, ttl=0.6))
            for i in range(15):
                await client.submit(Put(key=f"k{i}", value=i))
            leader = cluster.leader
            victim = next(s for s in cluster.servers if s is not leader)
            vic = cluster.servers.index(victim)
            assert victim._snap_index > 0
            # the snapshot image carries the pending deadline
            await crash_server(victim)
            reborn = _reboot(cluster, vic, level, dirs[vic])
            assert "ephemeral" in reborn.state_machine.data
            assert "ephemeral" in reborn.state_machine.ttl_deadlines
            await reborn.open()
            await _converged(cluster)
            await asyncio.sleep(0.8)
            for _ in range(100):
                if "ephemeral" not in reborn.state_machine.data:
                    break
                await asyncio.sleep(0.05)
            healthy = next(
                s for s in cluster.servers if s is not reborn)
            assert "ephemeral" not in healthy.state_machine.data
            assert "ephemeral" not in reborn.state_machine.data
        finally:
            await cluster.close()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# snapshot-install streaming
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("pipeline", ["1", "0"], ids=["pipelined", "stopwait"])
def test_install_streaming_catches_up_wiped_follower(tmp_path, monkeypatch,
                                                     pipeline):
    """A follower with total data loss reboots empty while the leader's
    log is prefix-truncated: the append stream cannot serve it, so the
    leader streams the snapshot (chunked, through the replication plane)
    and resumes appends where the snapshot ends — on BOTH replication
    lanes."""
    monkeypatch.setenv("COPYCAT_REPL_PIPELINE", pipeline)
    monkeypatch.setenv("COPYCAT_SNAPSHOTS", "1")
    monkeypatch.setenv("COPYCAT_SNAPSHOT_ENTRIES", "25")
    monkeypatch.setenv("COPYCAT_SNAPSHOT_RETAIN", "2")
    monkeypatch.setenv("COPYCAT_SNAP_CHUNK", "4096")  # force several chunks
    dirs = [tmp_path / f"m{i}" for i in range(3)]
    level = StorageLevel.MAPPED

    async def run() -> None:
        cluster = await create_cluster(
            3, storage_factory=lambda i: _storage(level, dirs[i]))
        try:
            client = await cluster.client(session_timeout=30)
            leader = cluster.leader
            victim = next(s for s in cluster.servers if s is not leader)
            vic = cluster.servers.index(victim)
            await crash_server(victim)
            # big values so the snapshot spans multiple install chunks
            for i in range(120):
                await client.submit(
                    Put(key=f"k{i % 9}", value="v" * 200 + str(i)))
            leader = cluster.leader
            assert leader.log.prefix_index > 0
            shutil.rmtree(dirs[vic])
            os.makedirs(dirs[vic])
            reborn = _reboot(cluster, vic, level, dirs[vic])
            await reborn.open()
            await _converged(cluster)
            _assert_bit_identical(reborn, leader)
            snap = leader.metrics.snapshot()
            assert snap["snap.installs_sent"] >= 1
            assert snap["snap.install_chunks_sent"] >= 2
            rsnap = reborn.metrics.snapshot()
            assert rsnap["snap.installs_received"] >= 1
            assert rsnap["snap.install_chunks_received"] >= 2
        finally:
            await cluster.close()

    asyncio.run(run())


def test_snapshots_off_keeps_full_log_no_installs(tmp_path, monkeypatch):
    """COPYCAT_SNAPSHOTS=0 restores the replay-only plane bit-identically:
    no snapshot files, no prefix truncation, recovery replays from the
    log alone, and no install traffic ever flows."""
    monkeypatch.setenv("COPYCAT_SNAPSHOTS", "0")
    monkeypatch.setenv("COPYCAT_SNAPSHOT_ENTRIES", "10")
    dirs = [tmp_path / f"m{i}" for i in range(3)]
    level = StorageLevel.DISK

    async def run() -> None:
        cluster = await create_cluster(
            3, storage_factory=lambda i: _storage(level, dirs[i]))
        try:
            client = await cluster.client(session_timeout=30)
            for i in range(60):
                await client.submit(Put(key=f"k{i % 5}", value=i))
            leader = cluster.leader
            assert leader.log.prefix_index == 0
            assert leader.log.first_index == 1
            assert not [f for f in os.listdir(dirs[0]) if f.endswith(".snap")]
            victim = next(s for s in cluster.servers if s is not leader)
            vic = cluster.servers.index(victim)
            await crash_server(victim)
            reborn = _reboot(cluster, vic, level, dirs[vic])
            assert reborn.last_applied == 0  # full replay, by design
            await reborn.open()
            leader = await _converged(cluster)
            _assert_bit_identical(reborn, leader)
            snap = leader.metrics.snapshot()
            assert snap.get("snap.installs_sent", 0) == 0
            assert snap.get("snap.snapshots_taken", 0) == 0
        finally:
            await cluster.close()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# boot-time recovery edges
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("level", LEVELS, ids=lambda lv: lv.value)
def test_corrupt_meta_falls_back_to_zero_state(tmp_path, monkeypatch, level):
    monkeypatch.setenv("COPYCAT_SNAPSHOTS", "1")
    dirs = [tmp_path / "m0"]

    async def run() -> None:
        cluster = await create_cluster(
            1, storage_factory=lambda i: _storage(level, dirs[i]))
        try:
            client = await cluster.client(session_timeout=30)
            await client.submit(Put(key="a", value=1))
            server = cluster.servers[0]
            assert server.term > 0
            await crash_server(server)
            assert StorageNemesis(str(dirs[0])).torn_meta() is not None
            reborn = _reboot(cluster, 0, level, dirs[0])
            # boot survived; vote state fell back to zero, loudly counted
            assert reborn.term == 0
            assert reborn.voted_for is None
            assert reborn.metrics.snapshot()["snap.meta_fallbacks"] == 1
            await reborn.open()
            await _converged(cluster)
            assert reborn.state_machine.data["a"] == 1
        finally:
            await cluster.close()

    asyncio.run(run())


def test_corrupt_snapshot_falls_back_to_older_then_replay(tmp_path,
                                                          monkeypatch):
    """A bad-CRC newest snapshot is skipped (never restored, never fatal):
    recovery uses the previous snapshot; with every snapshot corrupt it
    falls back to full replay."""
    monkeypatch.setenv("COPYCAT_SNAPSHOTS", "1")
    monkeypatch.setenv("COPYCAT_SNAPSHOT_ENTRIES", "10")
    monkeypatch.setenv("COPYCAT_SNAPSHOT_RETAIN", "1000")  # keep the log
    d = tmp_path / "m0"
    level = StorageLevel.DISK

    async def run() -> None:
        cluster = await create_cluster(
            1, storage_factory=lambda i: _storage(level, d))
        try:
            client = await cluster.client(session_timeout=30)
            for i in range(25):
                await client.submit(Put(key=f"k{i % 3}", value=i))
            server = cluster.servers[0]
            store = server._snapshots
            assert len(store.indexes()) == 2
            newest = store.indexes()[-1]
            await crash_server(server)

            nem = StorageNemesis(str(d))
            assert nem.corrupt_snapshot() is not None
            reborn = _reboot(cluster, 0, level, d)
            # restored from the OLDER snapshot (newest skipped on CRC)
            assert 0 < reborn.last_applied < newest
            assert reborn._snapshots.bad_skipped == 1
            reborn.log.close()

            # corrupt EVERY snapshot: full replay is the final fallback
            for fname in os.listdir(d):
                if fname.endswith(".snap"):
                    path = os.path.join(str(d), fname)
                    with open(path, "r+b") as f:
                        f.seek(24)
                        chunk = f.read(8)
                        f.seek(24)
                        f.write(bytes(b ^ 0xFF for b in chunk))
            reborn2 = _reboot(cluster, 0, level, d)
            assert reborn2.last_applied == 0
            await reborn2.open()
            await _converged(cluster)
            assert reborn2.state_machine.data["k0"] == 24
        finally:
            await cluster.close()

    asyncio.run(run())


@pytest.mark.parametrize("level", LEVELS, ids=lambda lv: lv.value)
def test_torn_tail_past_snapshot_index(tmp_path, monkeypatch, level):
    """A torn log tail PAST the snapshot boundary: recovery restores the
    snapshot, replays the surviving tail frames, and drops only the torn
    ones — then re-fetches them from the leader."""
    monkeypatch.setenv("COPYCAT_SNAPSHOTS", "1")
    monkeypatch.setenv("COPYCAT_SNAPSHOT_ENTRIES", "15")
    monkeypatch.setenv("COPYCAT_SNAPSHOT_RETAIN", "0")
    dirs = [tmp_path / f"m{i}" for i in range(3)]

    async def run() -> None:
        cluster = await create_cluster(
            3, storage_factory=lambda i: _storage(level, dirs[i]))
        try:
            client = await cluster.client(session_timeout=30)
            for i in range(40):
                await client.submit(Put(key=f"k{i % 7}", value=i))
            leader = cluster.leader
            victim = next(s for s in cluster.servers if s is not leader)
            vic = cluster.servers.index(victim)
            snap_index = victim._snap_index
            assert snap_index > 0
            await crash_server(victim)
            StorageNemesis(str(dirs[vic])).partial_frame()
            reborn = _reboot(cluster, vic, level, dirs[vic])
            assert reborn.last_applied >= snap_index
            assert reborn.log.last_index >= snap_index
            await reborn.open()
            leader = await _converged(cluster)
            _assert_bit_identical(reborn, leader)
        finally:
            await cluster.close()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# device-backed manager: snapshot via the checkpoint pytree format
# ---------------------------------------------------------------------------


def test_manager_tpu_snapshot_restores_device_values(tmp_path, monkeypatch):
    """A ResourceManager on the TPU executor snapshots its whole catalog:
    device-resident registers ride one ``models/checkpoint.py`` field-path
    blob, and a rebooted server serves the same values without replaying
    history."""
    monkeypatch.setenv("COPYCAT_SNAPSHOTS", "1")
    monkeypatch.setenv("COPYCAT_SNAPSHOT_ENTRIES", "8")
    monkeypatch.setenv("COPYCAT_SNAPSHOT_RETAIN", "0")
    from copycat_tpu.atomic import DistributedAtomicValue
    from copycat_tpu.io.local import LocalServerRegistry
    from copycat_tpu.manager.atomix import AtomixClient, AtomixServer
    from copycat_tpu.manager.device_executor import DeviceEngineConfig

    from raft_fixtures import next_ports

    d = tmp_path / "m0"

    async def run() -> None:
        registry = LocalServerRegistry()
        (addr,) = next_ports(1)

        def build_server() -> AtomixServer:
            return AtomixServer(
                addr, [addr], LocalTransport(registry, local_address=addr),
                storage=_storage(StorageLevel.DISK, d),
                election_timeout=0.2, heartbeat_interval=0.04,
                session_timeout=10.0, executor="tpu",
                engine_config=DeviceEngineConfig(capacity=4))

        server = build_server()
        await server.open()
        client = AtomixClient([addr], LocalTransport(registry),
                              session_timeout=10.0)
        await client.open()
        try:
            value = await client.get("reg", DistributedAtomicValue)
            for i in range(12):
                await value.set(100 + i)
            raft = server.server
            assert raft._snap_index > 0  # the manager snapshot happened
            await client.close()
            await crash_server(raft)

            reborn = build_server()
            # restored from the snapshot image, not from index 1
            assert reborn.server.last_applied >= raft._snap_index
            manager = reborn.server.state_machine
            assert manager.keys == {"reg": min(manager.keys.values())} \
                or "reg" in manager.keys
            await reborn.open()
            client2 = AtomixClient([addr], LocalTransport(registry),
                                   session_timeout=10.0)
            await client2.open()
            try:
                value2 = await client2.get("reg", DistributedAtomicValue)
                assert await value2.get() == 111
                await value2.set(7)
                assert await value2.get() == 7
            finally:
                await client2.close()
            await reborn.close()
        finally:
            try:
                await server.close()
            except Exception:
                pass

    asyncio.run(run())


def test_manager_tpu_snapshot_restores_device_map_and_set(tmp_path,
                                                          monkeypatch):
    """The device map and set machines' ``snapshot_state``/
    ``restore_state`` hooks (docs/DURABILITY.md): a manager hosting them
    no longer opts the whole server into replay-only recovery. The
    differential: a server that crashed after the snapshot serves the
    SAME answers as the never-crashed one for device-resident int
    entries, host-shadowed string entries, sizes and membership — and
    it provably restored from the image (``last_applied`` at or past
    the snapshot index before any replay)."""
    monkeypatch.setenv("COPYCAT_SNAPSHOTS", "1")
    monkeypatch.setenv("COPYCAT_SNAPSHOT_ENTRIES", "8")
    monkeypatch.setenv("COPYCAT_SNAPSHOT_RETAIN", "0")
    from copycat_tpu.collections import DistributedMap, DistributedSet
    from copycat_tpu.io.local import LocalServerRegistry
    from copycat_tpu.manager.atomix import AtomixClient, AtomixServer
    from copycat_tpu.manager.device_executor import DeviceEngineConfig

    from raft_fixtures import next_ports

    d = tmp_path / "m0"

    async def probe(client) -> dict:
        m = await client.get("m", DistributedMap)
        s = await client.get("s", DistributedSet)
        return {
            "dev_keys": [await m.get(k) for k in range(1, 7)],
            "shadow": await m.get("name"),
            "absent": await m.get(99),
            "m_size": await m.size(),
            "s_members": [await s.contains(v) for v in (5, 6, 7, "x")],
            "s_size": await s.size(),
        }

    async def run() -> None:
        registry = LocalServerRegistry()
        (addr,) = next_ports(1)

        def build_server() -> AtomixServer:
            return AtomixServer(
                addr, [addr], LocalTransport(registry, local_address=addr),
                storage=_storage(StorageLevel.DISK, d),
                election_timeout=0.2, heartbeat_interval=0.04,
                session_timeout=10.0, executor="tpu",
                engine_config=DeviceEngineConfig(capacity=4))

        server = build_server()
        await server.open()
        client = AtomixClient([addr], LocalTransport(registry),
                              session_timeout=10.0)
        await client.open()
        try:
            m = await client.get("m", DistributedMap)
            s = await client.get("s", DistributedSet)
            for k in range(1, 7):
                await m.put(k, k * 10)          # device probe table
            await m.put("name", "shadowed")     # host shadow
            await m.remove(3)
            for v in (5, 6, 7):
                await s.add(v)                  # device probe table
            await s.add("x")                    # host shadow
            await s.remove(6)
            raft = server.server
            assert raft._snap_index > 0, \
                "map/set hooks must not opt the manager out of snapshots"
            before = await probe(client)
            await client.close()
            await crash_server(raft)

            reborn = build_server()
            assert reborn.server.last_applied >= raft._snap_index
            await reborn.open()
            client2 = AtomixClient([addr], LocalTransport(registry),
                                   session_timeout=10.0)
            await client2.open()
            try:
                assert await probe(client2) == before
                # the restored machines keep working (device + shadow)
                m2 = await client2.get("m", DistributedMap)
                assert await m2.put(1, 11) == 10
                assert await m2.get(1) == 11
                s2 = await client2.get("s", DistributedSet)
                assert await s2.add(7) is False  # still a member
            finally:
                await client2.close()
            await reborn.close()
        finally:
            try:
                await server.close()
            except Exception:
                pass

    asyncio.run(run())


def test_device_map_set_ttl_still_opts_out(monkeypatch):
    """An armed per-key TTL timer holds commit references that cannot
    round-trip a snapshot: the map/set machines must keep opting out
    (NotImplemented) exactly like the value machine's documented rule."""
    from copycat_tpu.manager.device_executor import (
        DeviceMapState,
        DeviceSetState,
        _Held,
    )
    from copycat_tpu.server.state_machine import Commit

    for cls in (DeviceMapState, DeviceSetState):
        machine = cls.__new__(cls)  # no engine needed for the hook
        machine._held = {}
        assert machine.snapshot_state() == {"held": []}
        held = _Held(Commit(0, None, 0.0, None, None), value=1)
        machine._held[1] = held
        assert machine.snapshot_state() is not NotImplemented
        held.timer = object()  # armed TTL
        assert machine.snapshot_state() is NotImplemented


# ---------------------------------------------------------------------------
# snapshot store + log prefix units
# ---------------------------------------------------------------------------


def test_snapshot_store_frame_roundtrip_and_bad_crc(tmp_path):
    store = SnapshotStore(str(tmp_path), "s")
    store.save(10, b"ten")
    store.save(20, b"twenty")
    assert store.indexes() == [10, 20]
    assert store.newest() == (20, b"twenty")
    # corrupt the newest: falls back to 10, counts the skip
    path = os.path.join(str(tmp_path), "s-%016d.snap" % 20)
    with open(path, "r+b") as f:
        f.seek(-2, os.SEEK_END)
        f.write(b"\xff\xff")
    assert store.newest() == (10, b"ten")
    assert store.bad_skipped == 1
    # an all-zero file must not validate (seeded CRC)
    with open(path, "wb") as f:
        f.write(b"\x00" * 64)
    assert store.newest() == (10, b"ten")
    assert store.gc(keep=1) == 1
    assert store.indexes() == [20]  # gc keeps newest by name; it's corrupt
    assert store.newest() is None


def test_snapshot_frame_unframe():
    assert unframe(frame(b"payload")) == b"payload"
    assert unframe(frame(b"")) == b""
    assert unframe(b"") is None
    assert unframe(b"CCSNAP1\n") is None
    data = bytearray(frame(b"payload"))
    data[-1] ^= 0x01
    assert unframe(bytes(data)) is None


def test_meta_write_is_atomic(tmp_path):
    """_persist_meta must leave either the old or the new complete file —
    interrupting the write path never yields a half-written meta."""

    async def run() -> None:
        cluster = await create_cluster(
            1, storage_factory=lambda i: _storage(
                StorageLevel.DISK, tmp_path / "m0"))
        try:
            server = cluster.servers[0]
            meta = server._meta_path
            assert os.path.exists(meta)
            # no .tmp sibling survives a completed write
            assert not os.path.exists(meta + ".tmp")
            import json
            with open(meta) as f:
                parsed = json.load(f)
            assert parsed["term"] == server.term
        finally:
            await cluster.close()

    asyncio.run(run())
