"""Async test harness.

The reference's tests use ConcurrentUnit's ``resume()``/``await()`` pattern
(SURVEY.md §4); with asyncio we simply run each test body as a coroutine with a
hard timeout so a hung cluster fails rather than wedging the suite.
"""

from __future__ import annotations

import asyncio
import functools
from typing import Any, Awaitable, Callable


def arun(coro: Awaitable[Any], timeout: float = 60.0) -> Any:
    async def wrapped() -> Any:
        return await asyncio.wait_for(coro, timeout)

    return asyncio.run(wrapped())


def async_test(fn: Callable[..., Awaitable[None]] | None = None, *, timeout: float = 60.0):
    """Decorator turning ``async def test_*`` into a sync pytest test."""

    def deco(f: Callable[..., Awaitable[None]]):
        @functools.wraps(f)
        def sync(*args: Any, **kwargs: Any) -> None:
            arun(f(*args, **kwargs), timeout=timeout)

        return sync

    if fn is not None:
        return deco(fn)
    return deco
