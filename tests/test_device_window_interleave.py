"""Adversarial interleavings inside one device apply window.

Single-member clusters defer commit advance to the end of the event-loop
turn, so same-turn submits apply as ONE DeviceWindow batch — these tests
force the trickiest orderings deterministically: deletes barriering
in-flight chains of the same group, lock handoff with both commands in
one window, listener registration ordered against a concurrent set, and
a batched mixed-resource storm.
"""

import asyncio

import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.atomic import DistributedAtomicValue  # noqa: E402
from copycat_tpu.collections import DistributedMap  # noqa: E402
from copycat_tpu.coordination import DistributedLock  # noqa: E402
from copycat_tpu.io.local import LocalServerRegistry, LocalTransport  # noqa: E402
from copycat_tpu.manager.atomix import AtomixClient, AtomixServer  # noqa: E402
from copycat_tpu.manager.device_executor import DeviceEngineConfig  # noqa: E402

from helpers import async_test  # noqa: E402
from raft_fixtures import next_ports  # noqa: E402

ENGINE = DeviceEngineConfig(capacity=32, num_peers=3, log_slots=32)


async def _node(n_clients: int = 1):
    registry = LocalServerRegistry()
    addrs = next_ports(1)
    server = AtomixServer(addrs[0], addrs, LocalTransport(registry),
                          election_timeout=0.2, heartbeat_interval=0.04,
                          session_timeout=10.0, executor="tpu",
                          engine_config=ENGINE)
    await server.open()
    clients = []
    for _ in range(n_clients):
        c = AtomixClient(addrs, LocalTransport(registry),
                         session_timeout=10.0)
        await c.open()
        clients.append(c)
    return server, clients


async def _teardown(nodes):
    for node in nodes:
        try:
            await asyncio.wait_for(node.close(), 5)
        except (Exception, asyncio.TimeoutError):
            pass


@async_test(timeout=180)
async def test_delete_mid_burst_barriers_then_group_reuses_clean(deleted="m1"):
    server, (client,) = await _node()
    try:
        m = await client.get("m1", DistributedMap)
        await asyncio.gather(*(m.put(i, i * 10) for i in range(6)))
        # same-turn: more puts racing the delete — the delete's run_excl
        # barriers the window so in-flight chains settle first
        results = await asyncio.gather(
            m.put(100, 1), m.put(101, 2), m.delete(),
            return_exceptions=True)
        # recreate under the same key: the recycled device group must be
        # clean (delete reset the device table before release)
        m2 = await client.get("m1", DistributedMap)
        assert await m2.size() == 0
        await m2.put(7, 70)
        assert await m2.get(7) == 70
    finally:
        await _teardown([client, server])


@async_test(timeout=180)
async def test_lock_handoff_within_one_window():
    server, (c1, c2) = await _node(2)
    try:
        l1 = await c1.get("lk", DistributedLock)
        l2 = await c2.get("lk", DistributedLock)
        await l1.lock()
        waiter = asyncio.ensure_future(l2.lock())
        await asyncio.sleep(0.2)
        assert not waiter.done()
        # unlock and a fresh contender race in the same turn: the grant
        # event (buffered during chain drive, replayed in log order) must
        # reach the FIFO-first waiter
        await l1.unlock()
        await asyncio.wait_for(waiter, 15)
        await l2.unlock()
        # lock still functional afterwards
        await l1.lock()
        await l1.unlock()
    finally:
        await _teardown([c1, c2, server])


@async_test(timeout=180)
async def test_listener_ordered_against_same_window_set():
    server, (c1, c2) = await _node(2)
    try:
        v1 = await c1.get("val", DistributedAtomicValue)
        v2 = await c2.get("val", DistributedAtomicValue)
        seen: list = []
        # listen (c1) lands in the log BEFORE the set (c2) or after — the
        # window must keep whichever order the log chose for host state
        # AND event delivery alike; after settling, a second set must
        # always notify
        await v1.on_change(seen.append)
        await v2.set(1)
        for _ in range(50):
            if seen:
                break
            await asyncio.sleep(0.05)
        assert seen and seen[-1] == 1, seen
        await v2.set(2)
        for _ in range(50):
            if seen[-1] == 2:
                break
            await asyncio.sleep(0.05)
        assert seen[-1] == 2, seen
    finally:
        await _teardown([c1, c2, server])


@async_test(timeout=240)
async def test_mixed_resource_storm_in_shared_windows():
    """Many resource types, many concurrent ops per turn, several turns:
    everything must commit with per-resource FIFO results intact."""
    server, (client,) = await _node()
    try:
        from copycat_tpu.atomic import DistributedAtomicLong
        from copycat_tpu.collections import DistributedQueue, DistributedSet

        counters = await asyncio.gather(
            *(client.get(f"n{i}", DistributedAtomicLong) for i in range(8)))
        maps = await asyncio.gather(
            *(client.get(f"mp{i}", DistributedMap) for i in range(4)))
        sets_ = await asyncio.gather(
            *(client.get(f"st{i}", DistributedSet) for i in range(4)))
        queues = await asyncio.gather(
            *(client.get(f"q{i}", DistributedQueue) for i in range(4)))

        for rep in range(3):
            ops = []
            ops += [c.increment_and_get() for c in counters]
            ops += [m.put(rep, rep * 7) for m in maps]
            ops += [s.add(rep) for s in sets_]
            ops += [q.offer(rep) for q in queues]
            await asyncio.wait_for(asyncio.gather(*ops), 60)

        got = await asyncio.gather(*(c.get() for c in counters))
        assert got == [3] * 8
        for m in maps:
            assert await m.size() == 3
        for s in sets_:
            assert await s.size() == 3
        for q in queues:
            assert [await q.poll() for _ in range(3)] == [0, 1, 2]  # FIFO
    finally:
        await _teardown([client, server])
