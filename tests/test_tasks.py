"""utils/tasks.spawn lifecycle tests (satellite of the copycheck PR).

``spawn`` is the tree's ONE blessed background-task spawn point (the
``orphan-task`` rule enforces it), so its contract needs direct
coverage: strong-ref until done, unexpected exceptions logged and
discarded, cancellation silent, names attributed.
"""

import asyncio
import gc
import logging

import pytest

from copycat_tpu.utils import tasks
from copycat_tpu.utils.tasks import spawn


def _run(coro):
    return asyncio.run(coro)


def test_spawn_returns_task_and_result_flows():
    async def main():
        task = spawn(asyncio.sleep(0, result=42), name="answer")
        assert isinstance(task, asyncio.Task)
        assert task.get_name() == "answer"
        assert task in tasks._BACKGROUND  # strong ref while in flight
        assert await task == 42
        await asyncio.sleep(0)  # let the done callback run
        assert task not in tasks._BACKGROUND

    _run(main())


def test_spawn_error_path_logs_and_discards(caplog):
    async def boom():
        raise RuntimeError("kaboom")

    async def main():
        with caplog.at_level(logging.ERROR, logger="copycat_tpu.utils.tasks"):
            task = spawn(boom(), name="doomed")
            # unobserved failure: nobody awaits the task
            for _ in range(3):
                await asyncio.sleep(0)
            assert task.done()
            assert task not in tasks._BACKGROUND  # discarded after done

    _run(main())
    messages = [r.getMessage() for r in caplog.records]
    assert any("doomed" in m and "kaboom" in m for m in messages), messages


def test_spawn_cancelled_task_is_silent(caplog):
    async def forever():
        await asyncio.Event().wait()

    async def main():
        with caplog.at_level(logging.ERROR, logger="copycat_tpu.utils.tasks"):
            task = spawn(forever(), name="cancelled")
            await asyncio.sleep(0)
            task.cancel()
            with pytest.raises(asyncio.CancelledError):
                await task
            await asyncio.sleep(0)
            assert task not in tasks._BACKGROUND

    _run(main())
    assert caplog.records == [], [r.getMessage() for r in caplog.records]


def test_spawn_survives_gc_without_external_reference():
    """The weakref hazard spawn exists to close: a fire-and-forget task
    must run to completion even when the caller drops its handle and a
    collection happens mid-flight."""
    results: list[int] = []

    async def work():
        await asyncio.sleep(0)
        gc.collect()  # would reap a weakly-held task here
        await asyncio.sleep(0)
        results.append(7)

    async def main():
        spawn(work())  # handle dropped immediately
        gc.collect()
        for _ in range(5):
            await asyncio.sleep(0)

    _run(main())
    assert results == [7]


def test_spawn_requires_running_loop():
    coro = asyncio.sleep(0)
    try:
        with pytest.raises(RuntimeError):
            spawn(coro)
    finally:
        coro.close()  # avoid the never-awaited warning
