"""Retrospective telemetry units (``utils/timeseries.py``): the
delta-encoded ring's bounds and encoding, payload windowing, the
cluster-timeline assembly/rendering, the ``top`` frame, and the
onset detection behind ``doctor --last N``.

Pure-python on synthetic payloads — no jax, no sockets; the live-server
side lives in ``test_series_surface.py``.
"""

from copycat_tpu.utils.timeseries import (
    DEFAULT_TIMELINE_PREFIXES,
    SeriesStore,
    assemble_timeline,
    flatten_registry,
    render_timeline,
    render_top,
    resample,
    series_onsets,
    series_sort_key,
    sparkline,
)


# ---------------------------------------------------------------------------
# ordering + flattening primitives
# ---------------------------------------------------------------------------


def test_series_sort_key_groups_labeled_with_family():
    keys = ["raft_term", "raft_commit_index{group=0}", "zzz",
            "raft_commit_index{group=1}", "raft_commit_index"]
    ordered = sorted(keys, key=series_sort_key)
    # the labeled variants sort WITH the unlabeled family head, not
    # after every other name (ASCII '{' > letters)
    assert ordered == ["raft_commit_index", "raft_commit_index{group=0}",
                       "raft_commit_index{group=1}", "raft_term", "zzz"]


def test_series_sort_key_numeric_label_values():
    keys = [f"c{{group={g}}}" for g in (10, 2, 1)]
    assert sorted(keys, key=series_sort_key) == [
        "c{group=1}", "c{group=2}", "c{group=10}"]
    # non-numeric values still order, lexicographically
    assert sorted(["c{peer=b}", "c{peer=a}"], key=series_sort_key) == [
        "c{peer=a}", "c{peer=b}"]


def test_flatten_registry_histograms_and_hints():
    snap = {
        "ops": 7,
        "depth": 3.5,
        "flag": True,
        "lat": {"count": 9, "mean": 1.0, "p50": 0.8, "p99": 2.0,
                "max": 3.0},
        "_gauge_keys": ["depth"],
        "uptime_s": 123.0,
        "weird": {"not": "a-histogram"},
    }
    values, gauge_keys = flatten_registry(snap)
    assert values["ops"] == 7 and values["flag"] == 1
    assert values["lat.p50"] == 0.8 and values["lat.p99"] == 2.0
    assert values["lat.count"] == 9
    # p50/p99 sample like gauges; .count delta-encodes like a counter
    assert gauge_keys == {"depth", "lat.p50", "lat.p99"}
    assert "uptime_s" not in values and "_gauge_keys" not in values
    assert "weird" not in values


# ---------------------------------------------------------------------------
# the ring: delta encoding, bounds, queries
# ---------------------------------------------------------------------------


def _store(window=4):
    return SeriesStore(node="n1", role="member", interval_s=1.0,
                       window=window)


def test_counters_delta_encode_and_gauges_sample():
    s = _store()
    base = 100.0
    for i in range(3):
        s.ingest({"ops": 10 * (i + 1), "depth": float(i),
                  "_gauge_keys": ["depth"]}, t=base + i)
    rows = s.rows()
    # first sight of a counter contributes 0 (history starts now)
    assert [r[1]["ops"] for r in rows] == [0, 10, 10]
    assert [r[1]["depth"] for r in rows] == [0.0, 1.0, 2.0]


def test_ring_eviction_bounds_memory():
    s = _store(window=4)
    for i in range(10):
        s.ingest({"ops": i}, t=1000.0 + i)
    rows = s.rows()
    assert len(rows) == 4  # never more than the window
    assert rows[0][0] == 1006.0  # oldest-first eviction
    assert s.samples_taken == 10 and s.evictions == 6
    p = s.payload()
    assert p["samples_taken"] == 10 and p["evictions"] == 6
    assert len(p["samples"]) == 4


def test_payload_since_and_names_filters():
    s = _store(window=8)
    for i in range(5):
        s.ingest({"raft_commit_index": i, "other": i,
                  "_gauge_keys": ["raft_commit_index", "other"]},
                 t=2000.0 + i)
    p = s.payload(since=2002.0)
    assert [r["t"] for r in p["samples"]] == [2003.0, 2004.0]
    p = s.payload(names=["raft_commit"])
    assert all(set(r["values"]) == {"raft_commit_index"}
               for r in p["samples"])
    # prefix match covers labeled variants too
    s.ingest({"raft_commit_index{group=1}": 9,
              "_gauge_keys": ["raft_commit_index{group=1}"]}, t=2005.0)
    p = s.payload(since=2004.5, names=["raft_commit_index"])
    assert set(p["samples"][-1]["values"]) == {"raft_commit_index{group=1}"}


def test_maybe_sample_respects_interval_and_bad_snapshots():
    s = SeriesStore(node="n", role="member", interval_s=1000.0, window=4)
    assert s.maybe_sample(lambda: {"ops": 1}) is True
    # next sample not due for 1000s — snap_fn must not even be called
    assert s.maybe_sample(lambda: 1 / 0) is False
    assert s.samples_taken == 1
    # a due sample whose snapshot raises is swallowed (observability
    # must never wound the host), not retained
    s2 = SeriesStore(node="n", role="member", interval_s=0.05, window=4)
    assert s2.maybe_sample(lambda: 1 / 0) is False
    assert s2.samples_taken == 0


def test_render_text_sparklines():
    s = _store(window=8)
    for i in range(4):
        s.ingest({"g": float(i), "_gauge_keys": ["g"]}, t=3000.0 + i)
    text = s.render_text()
    assert "member n1: 4 sample(s)" in text
    assert "g" in text and "min 0 max 3" in text
    assert SeriesStore(node="x", interval_s=1, window=2) \
        .render_text().endswith("(no samples retained)\n")


# ---------------------------------------------------------------------------
# grid primitives
# ---------------------------------------------------------------------------


def test_sparkline_scaling_and_gaps():
    assert sparkline([]) == ""
    assert sparkline([5, 5, 5]) == "▁▁▁"  # flat renders at the floor
    line = sparkline([0, None, 10])
    assert line[0] == "▁" and line[1] == " " and line[2] == "█"


def test_resample_means_and_gaps():
    samples = [{"t": t, "values": {"k": v}}
               for t, v in ((0.5, 2.0), (0.6, 4.0), (3.5, 9.0))]
    out = resample(samples, "k", 0.0, 4.0, 4)
    assert out == [3.0, None, None, 9.0]  # mean per bucket, None gaps
    assert resample(samples, "k", 4.0, 0.0, 4) == []


# ---------------------------------------------------------------------------
# timeline assembly
# ---------------------------------------------------------------------------


def _member_payload(node, t0, commits, events=(), role="member"):
    samples = [{"t": t0 + i, "values": {"raft_commit_index": c}}
               for i, c in enumerate(commits)]
    return {
        "series": {"node": node, "role": role, "interval_s": 1.0,
                   "window": 300, "now": t0 + len(commits),
                   "samples": samples},
        "flight": {"events": list(events)},
        "health": {"status": "ok", "node": node, "role": role},
    }


def test_assemble_timeline_merges_and_marks_incomplete():
    t0 = 1000.0
    m1 = _member_payload("n1", t0, [1, 2, 3, 4],
                         events=[{"t": t0 + 1, "kind": "fault",
                                  "fault": "partition"}])
    m2 = {"series": None, "flight": None,
          "health": {"status": "warn", "node": "n2"}}
    tl = assemble_timeline({"a:1": m1, "a:2": m2},
                           failed_members=["a:3"], last_s=60)
    assert tl["incomplete"] is True
    assert "member a:3 unreachable" in tl["incomplete_why"]
    assert any("n2 serves no /series" in w for w in tl["incomplete_why"])
    # every member renders — the series-less and the unreachable never
    # drop the reachable one's data
    assert tl["members"] == ["n1", "n2"]
    assert tl["series"]["n1"]["raft_commit_index"]
    assert tl["series"]["n2"] == {}
    assert [e["kind"] for e in tl["events"]] == ["fault"]
    text = render_timeline(tl)
    assert "!! INCOMPLETE" in text
    assert "n1 [member]" in text and "fault" in text


def test_timeline_derives_election_events_from_series():
    t0 = 2000.0
    payload = _member_payload("n1", t0, [1, 2, 3, 4])
    payload["series"]["samples"][2]["values"][
        "raft_elections_started"] = 2
    tl = assemble_timeline({"a:1": payload}, last_s=60)
    ev = [e for e in tl["events"] if e["kind"] == "election"]
    assert len(ev) == 1 and ev[0]["t"] == t0 + 2
    assert ev[0]["detail"] == "+2 election(s)"


def test_timeline_orders_fault_before_election_per_member():
    """The nemesis differential's pure core: a fault mark at T and an
    election spike at T+dt merge time-ordered and member-attributed on
    every member."""
    t0 = 3000.0
    members = {}
    for i in range(3):
        node = f"n{i}"
        payload = _member_payload(node, t0, [5, 5, 5, 5])
        payload["flight"]["events"] = [
            {"t": t0 + 1, "kind": "fault", "fault": "partition"}]
        payload["series"]["samples"][3]["values"][
            "raft_elections_started"] = 1
        members[f"a:{i}"] = payload
    tl = assemble_timeline(members, last_s=60)
    assert tl["incomplete"] is False
    for i in range(3):
        node = f"n{i}"
        mine = [e for e in tl["events"] if e["member"] == node]
        kinds = [e["kind"] for e in mine]
        assert kinds == ["fault", "election"], kinds
        assert mine[0]["t"] < mine[1]["t"]
    # and the global merge is time-sorted
    ts = [e["t"] for e in tl["events"]]
    assert ts == sorted(ts)


def test_timeline_keeps_recovered_events_outside_window():
    t0 = 5000.0
    payload = _member_payload("n1", t0, [1, 2])
    payload["flight"] = {
        "events": [],
        "blackbox": {"events": [
            {"t": t0 - 900.0, "kind": "fault", "fault": "kill",
             "recovered": True}]}}
    tl = assemble_timeline({"a:1": payload}, last_s=30)
    assert any(e["kind"] == "fault" and e["recovered"]
               for e in tl["events"])


def test_timeline_default_prefixes_filter_series():
    t0 = 6000.0
    payload = _member_payload("n1", t0, [1, 2, 3])
    for row in payload["series"]["samples"]:
        row["values"]["transport_bytes_out"] = 1
    tl = assemble_timeline({"a:1": payload}, last_s=60)
    assert set(tl["series"]["n1"]) == {"raft_commit_index"}
    tl = assemble_timeline({"a:1": payload}, last_s=60,
                           names=["transport_"])
    assert set(tl["series"]["n1"]) == {"transport_bytes_out"}
    assert "raft_commit_index" in DEFAULT_TIMELINE_PREFIXES


# ---------------------------------------------------------------------------
# the `top` frame
# ---------------------------------------------------------------------------


def _top_member(commit, leader=True, groups=None):
    stats = {"node": "n1", "role": "leader" if leader else "follower",
             "term": 3,
             "raft": {"raft_commit_index": commit,
                      "repl.windows_inflight": 2,
                      "commands_fast_lane": commit * 2,
                      "commands_general_lane": 0,
                      "commands_single_lane": 0}}
    if groups is not None:
        stats["groups"] = groups
    return {"stats": stats, "health": {"status": "ok"}}


def test_render_top_rates_need_two_frames():
    frame1, state = render_top({"a:1": _top_member(100)}, [], None, 0.0)
    assert "-" in frame1  # no rate on the first frame
    frame2, _ = render_top({"a:1": _top_member(150)}, [], state, 2.0)
    assert "25.0/s" in frame2
    assert "100/0/0%" in frame2  # lane mix: all fast-lane
    assert "worst health: OK" in frame2


def test_render_top_unreachable_and_verdict():
    frame, _ = render_top({"a:1": _top_member(1)}, ["a:2", "a:3"],
                          None, 0.0)
    rows = [ln for ln in frame.splitlines() if ln.endswith("UNREACHABLE")]
    assert len(rows) == 2
    assert "1/3 member(s) up" in frame
    assert "worst health: UNREACHABLE" in frame
    bad = _top_member(1)
    bad["health"]["status"] = "critical"
    frame, _ = render_top({"a:1": bad}, ["a:2"], None, 0.0)
    assert "worst health: CRITICAL" in frame


def test_render_top_multi_group_rows():
    groups = {"0": {"role": "leader", "term": 2, "commit_index": 10,
                    "log_last_index": 12},
              "1": {"role": "follower", "term": 2, "commit_index": 5,
                    "log_last_index": 5}}
    frame, _ = render_top({"a:1": _top_member(15, groups=groups)},
                          [], None, 0.0)
    assert "1/2 led" in frame
    assert "group 0: leader" in frame and "lag 2" in frame


# ---------------------------------------------------------------------------
# onset detection (doctor --last N)
# ---------------------------------------------------------------------------


def _series_of(key, values, t0=1000.0):
    return {"now": t0 + len(values),
            "samples": [{"t": t0 + i, "values": {key: v}}
                        for i, v in enumerate(values)]}


def test_series_onsets_finds_the_breach_start():
    payload = _series_of("raft_commit_lag", [0, 0, 0, 0, 0, 0, 9, 12])
    onsets = series_onsets(payload, ["raft_commit_lag"])
    assert len(onsets) == 1
    o = onsets[0]
    assert o["key"] == "raft_commit_lag" and o["value"] == 9
    assert o["t"] == 1006.0 and o["median"] == 0
    assert o["from_window_start"] is False


def test_series_onsets_always_breaching_flags_window_start():
    payload = _series_of("raft_commit_lag", [9, 9, 10, 11])
    onsets = series_onsets(payload, ["raft_commit_lag"], factor=3.0)
    # median 9.5-ish -> threshold ~28: no onset inside the window
    # unless the first sample itself breaches factor x median
    payload = _series_of("latency.p99", [50, 50, 50, 50])
    assert series_onsets(payload, ["latency."]) == []
    # a series above threshold from sample 0 reports window-start
    payload = _series_of("x", [5, 0, 0, 0, 0, 0, 0, 0])
    onsets = series_onsets(payload, ["x"])
    assert onsets and onsets[0]["from_window_start"] is True


def test_series_onsets_prefix_filter_and_cap():
    t0 = 1000.0
    values = {f"k{i}": 0 for i in range(12)}
    samples = [{"t": t0 + j, "values": dict(values)} for j in range(6)]
    for i in range(12):
        samples[-1]["values"][f"k{i}"] = 5
    payload = {"now": t0 + 6, "samples": samples}
    onsets = series_onsets(payload, ["k"], cap=8)
    assert len(onsets) == 8  # capped
    assert series_onsets(payload, ["nope"]) == []
    assert series_onsets({}, ["k"]) == []
