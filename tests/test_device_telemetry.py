"""Device-plane flight recorder: telemetry ground truth, invariant
monitors, fault correlation (docs/OBSERVABILITY.md § device plane).

Ground-truth obligations (ISSUE 3): a steady-state run shows ZERO
elections/leader-changes after warmup; a nemesis partition run shows
elections > 0 and leaderless rounds > 0 that disappear after heal; the
invariant monitor flags a deliberately corrupted snapshot and stays
silent on a healthy one; and the telemetry-off step is bit-identical to
the telemetry-on step's state evolution (the block is pure output).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from copycat_tpu.models import RaftGroups  # noqa: E402
from copycat_tpu.models.telemetry import (  # noqa: E402
    DeviceTelemetryHub,
    InvariantViolation,
    POOL_NAMES,
)
from copycat_tpu.ops import apply as ap  # noqa: E402
from copycat_tpu.ops.consensus import (  # noqa: E402
    Config,
    DeviceTelemetry,
    full_delivery,
    init_state,
    make_submits,
    step,
)
from copycat_tpu.testing.nemesis import Nemesis  # noqa: E402
from copycat_tpu.utils.metrics import merge_snapshots  # noqa: E402

TEL_CFG = Config(telemetry=True)


def make(groups=8, **kw):
    kw.setdefault("log_slots", 32)
    kw.setdefault("config", TEL_CFG)
    return RaftGroups(groups, 3, **kw)


def counter_value(rg, name, **labels):
    return rg.telemetry.registry.counter(name, **labels).value


# ---------------------------------------------------------------------------
# the knob: off is bit-identical, on is pure output
# ---------------------------------------------------------------------------


def test_telemetry_off_state_bit_identical():
    """Same seeds, same submits: the telemetry-on and telemetry-off
    programs must produce bit-identical STATE every round (the block
    derives from existing intermediates — no extra RNG, no writes)."""
    from functools import partial

    G, P, L = 4, 3, 16
    key = jax.random.PRNGKey(7)
    key, ik = jax.random.split(key)
    on, off = Config(telemetry=True), Config()
    s_on = init_state(G, P, L, ik, on)
    s_off = init_state(G, P, L, ik, off)
    sub = make_submits(G, 4)
    ones = jnp.ones((G, 4), jnp.int32)
    sub = sub._replace(opcode=ones * ap.OP_LONG_ADD, a=ones, tag=ones,
                       valid=ones.astype(bool))
    dl = full_delivery(G, P)
    f_on = jax.jit(partial(step, config=on))
    f_off = jax.jit(partial(step, config=off))
    for _ in range(15):
        key, k = jax.random.split(key)
        s_on, out_on = f_on(s_on, sub, dl, k)
        s_off, out_off = f_off(s_off, sub, dl, k)
    assert out_off.telemetry is None
    assert out_on.telemetry is not None
    for name, a, b in zip(s_on._fields, s_on, s_off):
        if name == "resources":
            for rn, ra, rb in zip(a._fields, a, b):
                assert (np.asarray(ra) == np.asarray(rb)).all(), rn
        else:
            assert (np.asarray(a) == np.asarray(b)).all(), name


# ---------------------------------------------------------------------------
# ground truth: steady state vs nemesis
# ---------------------------------------------------------------------------


def test_steady_state_zero_elections_after_warmup():
    rg = make(groups=8)
    rg.wait_for_leaders()
    rg.run(5)  # settle any residual churn
    e0 = counter_value(rg, "device.elections_started")
    c0 = counter_value(rg, "device.leader_changes")
    l0 = counter_value(rg, "device.leaderless_rounds")
    commit0 = counter_value(rg, "device.commit_advance")
    tags = [rg.submit(g, ap.OP_LONG_ADD, 1) for g in range(8)]
    rg.run_until(tags)
    rg.run(10)
    assert counter_value(rg, "device.elections_started") == e0
    assert counter_value(rg, "device.leader_changes") == c0
    assert counter_value(rg, "device.leaderless_rounds") == l0
    # real work flowed and was attributed to the right pool
    assert counter_value(rg, "device.commit_advance") > commit0
    assert counter_value(rg, "device.applies", pool="value") >= 8
    assert rg.telemetry.monitor.violations == 0


def test_nemesis_partition_shows_elections_then_heals():
    rg = make(groups=16)
    rg.wait_for_leaders()
    rg.run(5)
    nem = Nemesis(rg, seed=3, period=10, faults=("partition",))
    e0 = counter_value(rg, "device.elections_started")
    l0 = counter_value(rg, "device.leaderless_rounds")
    for _ in range(30):
        nem.tick()
        rg.step_round()
    e_fault = counter_value(rg, "device.elections_started")
    l_fault = counter_value(rg, "device.leaderless_rounds")
    assert e_fault > e0, "partitions must force elections"
    assert l_fault > l0, "partitions must produce leaderless rounds"
    # heal → settle → a quiet window records NO new churn
    nem.heal()
    rg.run(40)
    e1 = counter_value(rg, "device.elections_started")
    l1 = counter_value(rg, "device.leaderless_rounds")
    rg.run(20)
    assert counter_value(rg, "device.elections_started") == e1
    assert counter_value(rg, "device.leaderless_rounds") == l1
    # the whole storm ran under the online monitor without a violation
    assert rg.telemetry.monitor.violations == 0

    # fault correlation: the flight ring holds the injected partition
    # events AND telemetry events recording the churn they caused
    kinds = [ev["kind"] for ev in rg.telemetry.flight.events()]
    assert "fault" in kinds and "telemetry" in kinds
    faults = [ev for ev in rg.telemetry.flight.events()
              if ev["kind"] == "fault"]
    assert any(ev["fault"] == "partition" for ev in faults)
    assert faults[-1]["fault"] == "heal"
    text = rg.telemetry.flight.render_text()
    assert "partition" in text


def test_events_drained_counted():
    """A queued-lock grant pushes a session event through the outbox;
    the drain shows up in device.events_drained."""
    rg = make(groups=2)
    rg.wait_for_leaders()
    t1 = rg.submit(0, ap.OP_LOCK_ACQUIRE, 1, -1)
    t2 = rg.submit(0, ap.OP_LOCK_ACQUIRE, 2, -1)
    rg.run_until([t1, t2])
    t3 = rg.submit(0, ap.OP_LOCK_RELEASE, 1)
    rg.run_until([t3])
    rg.run(5)
    assert counter_value(rg, "device.events_drained") >= 1
    assert counter_value(rg, "device.applies", pool="lock") >= 3


# ---------------------------------------------------------------------------
# fused + deep planes: telemetry rides the amortized fetches
# ---------------------------------------------------------------------------


def test_step_rounds_fused_ingests_every_round():
    rg = make(groups=4)
    rg.wait_for_leaders()
    r0 = counter_value(rg, "device.rounds")
    rg.step_rounds(5)
    assert counter_value(rg, "device.rounds") == r0 + 5
    assert rg.telemetry._rounds == rg.rounds


def test_deep_drive_telemetry_one_fetch():
    from copycat_tpu.models.bulk import BulkDriver

    rg = RaftGroups(4, 3, log_slots=32, submit_slots=4,
                    config=Config(monotone_tag_accept=True, telemetry=True))
    rg.wait_for_leaders()
    r0 = counter_value(rg, "device.rounds")
    drv = BulkDriver(rg)
    res = drv.drive(np.repeat(np.arange(4), 6), ap.OP_LONG_ADD, 1)
    assert (res.results == np.tile(np.arange(1, 7), 4)).all()
    assert counter_value(rg, "device.rounds") == r0 + res.rounds
    assert counter_value(rg, "device.applies", pool="value") >= 24
    # scan mode (whole blind phase as one program): stacked telemetry
    scan = BulkDriver(rg, deep_scan=True)
    r1 = counter_value(rg, "device.rounds")
    res2 = scan.drive(np.repeat(np.arange(4), 5), ap.OP_LONG_ADD, 1)
    assert counter_value(rg, "device.rounds") == r1 + res2.rounds
    assert rg.telemetry.monitor.violations == 0


# ---------------------------------------------------------------------------
# invariant monitor: silent on healthy, loud on corruption
# ---------------------------------------------------------------------------


def _tel(G=4, commit=0, term=1, lane=0, leaderless=0, changes=0):
    z = np.zeros(G, np.int32)
    return DeviceTelemetry(
        elections_started=z,
        leader_changes=np.full(G, changes, np.int32), term_bumps=z,
        leaderless=np.full(G, leaderless, np.int32),
        commit_advance=z, commit_max=np.full(G, commit, np.int32),
        term_max=np.full(G, term, np.int32),
        leader_lane=np.full(G, lane, np.int32),
        leader_term=np.full(G, term, np.int32),
        applies=np.zeros((G, len(POOL_NAMES)), np.int32),
        ring_occ_max=z, submit_rejections=z, vote_splits=z,
        events_drained=z, events_dropped=z)


def test_monitor_silent_on_healthy_sequence():
    hub = DeviceTelemetryHub(4, mode="observe")
    for r, commit in enumerate((1, 2, 2, 5)):
        hub.ingest(_tel(commit=commit, term=1 + r // 2), r)
    assert hub.monitor.violations == 0


def test_monitor_flags_corrupted_snapshot():
    hub = DeviceTelemetryHub(4, mode="observe")
    hub.ingest(_tel(commit=5), 0)
    hub.ingest(_tel(commit=3), 1)       # commit regressed: corruption
    assert hub.monitor.violations >= 1
    assert hub.registry.counter("device.invariant_violations",
                                kind="commit_monotone").value >= 1
    kinds = [ev.get("check") for ev in hub.flight.events()
             if ev["kind"] == "violation"]
    assert "commit_monotone" in kinds


def test_monitor_flags_term_regression_and_split_brain():
    hub = DeviceTelemetryHub(4, mode="observe")
    hub.ingest(_tel(commit=1, term=5, lane=1, changes=1), 0)
    # a zombie VIEW regression without an election is legitimate
    # (higher-term leader stepped down, stale leader still visible)
    hub.ingest(_tel(commit=1, term=3, lane=1), 1)
    assert hub.registry.counter("device.invariant_violations",
                                kind="term_monotone").value == 0
    # but a fresh ELECTION at a non-increasing term is a safety breach
    hub.ingest(_tel(commit=1, term=4, lane=2, changes=1), 2)
    assert hub.registry.counter("device.invariant_violations",
                                kind="term_monotone").value >= 1
    v0 = hub.monitor.violations
    hub.ingest(_tel(commit=1, term=5, lane=2), 3)   # 2nd leader, term 5
    assert hub.registry.counter("device.invariant_violations",
                                kind="leader_per_term").value >= 1
    assert hub.monitor.violations > v0


def test_monitor_strict_raises():
    hub = DeviceTelemetryHub(4, mode="strict")
    hub.ingest(_tel(commit=5), 0)
    with pytest.raises(InvariantViolation, match="commit"):
        hub.ingest(_tel(commit=3), 1)


def test_monitor_leaderless_bound():
    hub = DeviceTelemetryHub(4, mode="observe")
    hub.monitor.leaderless_max = 0.5
    hub.ingest(_tel(leaderless=1), 0)   # 4/4 leaderless > 0.5
    assert hub.registry.counter("device.invariant_violations",
                                kind="leaderless_bound").value == 1


def test_strict_mode_raises_through_the_engine_path():
    rg = make(groups=4)
    rg.wait_for_leaders()
    rg.telemetry.monitor.mode = "strict"
    # fabricate a corruption baseline: pretend we saw commits far ahead
    rg.telemetry.monitor._last_commit[:] = 10_000
    rg.telemetry.monitor._commit_total = 40_000
    with pytest.raises(InvariantViolation):
        rg.step_round()


def test_env_opt_in_enables_telemetry(monkeypatch):
    monkeypatch.setenv("COPYCAT_INVARIANTS", "strict")
    rg = RaftGroups(2, 3, log_slots=32)
    assert rg.config.telemetry
    assert rg.telemetry is not None
    assert rg.telemetry.monitor.mode == "strict"
    monkeypatch.setenv("COPYCAT_INVARIANTS", "off")
    rg2 = RaftGroups(2, 3, log_slots=32)
    assert not rg2.config.telemetry and rg2.telemetry is None


# ---------------------------------------------------------------------------
# exposition: snapshots, shard merge, stats routes, CLI watch rendering
# ---------------------------------------------------------------------------


def test_device_snapshot_and_shard_merge():
    rg = make(groups=8)
    rg.wait_for_leaders()
    rg.run(5)
    snap = rg.device_snapshot()
    assert snap["device.rounds"] == rg.rounds
    assert "device.elections_started" in snap
    assert "device.leaderless_groups" in snap.get("_gauge_keys", [])
    # single-host merged view is the local view
    assert rg.merged_device_snapshot() == snap
    # per-shard attribution folds back to the totals via merge_snapshots
    shards = rg.telemetry.shard_snapshots(4)
    assert len(shards) == 4 and sum(s["groups"] for s in shards) == 8
    merged = merge_snapshots(
        [{k: v for k, v in s.items() if k.startswith("device.")}
         for s in shards])
    per_group = rg.telemetry.per_group_totals()
    assert merged["device.elections_started"] == int(
        per_group["elections_started"].sum())
    assert merged["device.commit_advance"] == int(
        per_group["commit_advance"].sum())


def test_stats_listener_flight_route():
    from types import SimpleNamespace

    from copycat_tpu.server.stats import StatsListener

    hub = DeviceTelemetryHub(2, mode="observe")
    hub.flight.record("fault", 3, fault="partition")
    raft = SimpleNamespace(state_machine=SimpleNamespace(
        _engine=SimpleNamespace(_groups=SimpleNamespace(telemetry=hub))))
    listener = StatsListener(raft)
    body, ctype = listener._route("/flight")
    assert ctype == "application/json"
    import json
    events = json.loads(body)["events"]
    assert events and events[0]["kind"] == "fault"
    body, _ = listener._route("/flight.txt")
    assert b"partition" in body
    # no engine → a clear "disabled" note, not a 500
    bare = StatsListener(SimpleNamespace(state_machine=object()))
    body, _ = bare._route("/flight")
    assert b"disabled" in body
    # /flight is advertised on unknown-path responses
    body, _ = listener._route("/nope")
    assert b"/flight" in body


def test_cli_watch_rendering():
    from copycat_tpu.cli import _flatten_numeric, _render_watch

    snap = {"node": "127.0.0.1:5001", "role": "leader",
            "raft": {"ops": 10, "lat": {"count": 4, "mean": 1.5,
                                        "p50": 1.0, "p99": 3.0, "max": 3.0},
                     "_gauge_keys": ["raft_term"], "raft_term": 7},
            "manager": {"device": {"device.rounds": 5}}}
    flat = _flatten_numeric(snap)
    assert flat["raft.ops"] == 10
    assert flat["raft.lat.p99"] == 3.0
    assert flat["manager.device.device.rounds"] == 5
    assert "raft._gauge_keys" not in flat
    prev = dict(flat, **{"raft.ops": 0})
    frame = _render_watch(snap, prev, 2.0)
    assert "node: 127.0.0.1:5001" in frame
    assert "+5.0/s" in frame  # (10 - 0) / 2s
