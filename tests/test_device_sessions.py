"""Device-path sessions: keep-alives + deterministic expiry fan-out.

Round-2 VERDICT directive #3: a crashed device-path client must not wedge
a lock or a leadership slot — session death must release through the log,
totally ordered with concurrent grants (the reference's session story,
``ResourceManager.java:238-266``, ``LeaderElectionState.close:36-49``;
the CPU path's release-on-death fix, ``coordination/state.py``).
"""

import pytest

from copycat_tpu.models.device_resources import DeviceElection, DeviceLock
from copycat_tpu.models.raft_groups import RaftGroups
from copycat_tpu.models.sessions import SessionExpiredError
from copycat_tpu.ops.apply import OP_LOCK_ACQUIRE


def _groups(timeout_rounds: int = 25) -> RaftGroups:
    groups = RaftGroups(4, 3, log_slots=32, submit_slots=4, seed=7)
    groups.sessions.timeout_rounds = timeout_rounds
    groups.wait_for_leaders()
    return groups


def test_crashed_holder_releases_lock_to_next_waiter():
    groups = _groups()
    s1 = groups.sessions.open_session()
    s2 = groups.sessions.open_session()
    holder = DeviceLock(groups, 0, session=s1)
    waiter = DeviceLock(groups, 0, session=s2)

    holder.lock()
    assert not waiter.try_lock()  # held

    # s1 "crashes": it never keep-alives again. waiter.lock() drives the
    # batch; s1 expires mid-wait, the registry fans OP_LOCK_CANCEL +
    # OP_LOCK_RELEASE through the log, and the queued waiter is granted.
    waiter.lock()
    assert s1.expired

    # the zombie's facade is fenced off
    with pytest.raises(SessionExpiredError):
        holder.unlock()
    waiter.unlock()


def test_crashed_queued_waiter_is_dequeued():
    groups = _groups()
    s1 = groups.sessions.open_session()
    s2 = groups.sessions.open_session()
    s3 = groups.sessions.open_session()
    holder = DeviceLock(groups, 1, session=s1)
    dead_waiter = DeviceLock(groups, 1, session=s2)
    live_waiter = DeviceLock(groups, 1, session=s3)

    holder.lock()
    # queue s2 without blocking (raw acquire: 2 = queued on device)
    assert dead_waiter._call(OP_LOCK_ACQUIRE, s2.id, -1) == 2
    # s2 crashes while queued; s1 and s3 stay alive through their calls.
    for _ in range(30):
        holder._touch()
        groups.step_round()
        s3.keep_alive()
    assert s2.expired
    # release: the grant must skip the dead waiter and reach s3
    holder.unlock()
    live_waiter.lock()
    live_waiter.unlock()


def test_crashed_leader_promotes_next_listener():
    groups = _groups()
    s1 = groups.sessions.open_session()
    s2 = groups.sessions.open_session()
    e1 = DeviceElection(groups, 2, session=s1)
    e2 = DeviceElection(groups, 2, session=s2)

    epoch1 = e1.listen()
    assert epoch1 is not None and epoch1 > 0  # immediate leadership
    assert e2.listen() is None                # queued behind s1

    # s1 crashes; drive rounds keeping s2 alive until succession lands
    epoch2 = None
    for _ in range(120):
        groups.step_round()
        s2.keep_alive()
        epoch2 = e2.poll_elected()
        if epoch2:
            break
    assert s1.expired
    assert epoch2 and epoch2 != epoch1, "successor not promoted"
    assert e2.is_leader(epoch2)
    # the dead leader's epoch no longer fences
    assert not e2.is_leader(epoch1)


def test_graceful_close_releases_immediately():
    groups = _groups(timeout_rounds=10_000)  # expiry can't be the cause
    s1 = groups.sessions.open_session()
    s2 = groups.sessions.open_session()
    holder = DeviceLock(groups, 3, session=s1)
    waiter = DeviceLock(groups, 3, session=s2)

    holder.lock()
    assert not waiter.try_lock()
    s1.close()           # graceful: same fan-out, no timeout needed
    waiter.lock()
    waiter.unlock()
    with pytest.raises(SessionExpiredError):
        holder.lock()
