"""Replication differential suite: safety of the pipelined plane is
DEMONSTRATED, not asserted (ISSUE 5 acceptance).

- The same seeded workload runs through BOTH replication lanes
  (``COPYCAT_REPL_PIPELINE=1`` and ``=0``) and the committed logs are
  compared: bit-for-bit across the members of each cluster (replicated
  entries carry the leader's term/timestamp — any pipelining bug that
  reorders, drops or duplicates an entry breaks byte equality), and as
  the exact same committed command sequence + final state across lanes
  (timestamps/terms are leader-local wall clock, so cross-lane equality
  is over the replicated COMMAND content).
- Nemesis tests (delayed+reordered messages, partitioned peers, leader
  deposition mid-stream) run with ``COPYCAT_INVARIANTS=strict``: every
  commit advance re-verifies quorum support from first principles and
  raises on violation, so a pipelined ack stream that ever outran real
  replication would fail these loudly.

CI runs this module twice — pipeline on AND off (the strict re-check
guards both lanes).
"""

import asyncio
import random

import pytest

from helpers import async_test
from raft_fixtures import Get, Put, create_cluster

from copycat_tpu.io.serializer import Serializer
from copycat_tpu.server.log import CommandEntry
from copycat_tpu.server.raft import LEADER

SEED = 20260803
PHASES = 8
OPS_PER_PHASE = 40


async def _wait_converged(cluster, timeout=20.0):
    leader = cluster.leader
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        target = leader.commit_index
        if all(s.last_applied >= target for s in cluster.servers):
            return leader
        await asyncio.sleep(0.05)
    raise TimeoutError("cluster did not converge")


def _member_log_bytes(server, up_to):
    ser = Serializer()
    return {i: ser.write(e)
            for i in range(1, up_to + 1)
            if (e := server.log.get(i)) is not None}


def _command_stream(server, up_to):
    """The committed command content in log order — the cross-lane
    comparable view (indices/terms/timestamps are lane-local)."""
    out = []
    for i in range(1, up_to + 1):
        e = server.log.get(i)
        if isinstance(e, CommandEntry) and isinstance(e.operation, Put):
            out.append((e.seq, e.operation.key, e.operation.value))
    return out


async def _drive_workload():
    """One seeded workload: bursts of micro-batched writes through the
    public client API (the shape that exercises multi-window streams)."""
    cluster = await create_cluster(3, session_timeout=30.0)
    try:
        await cluster.await_leader()
        client = await cluster.client(session_timeout=30.0)
        rng = random.Random(SEED)
        for _ in range(PHASES):
            futs = [client.submit_command_nowait(
                Put(key=f"k{rng.randrange(8)}", value=rng.randrange(100)))
                for _ in range(OPS_PER_PHASE)]
            await asyncio.gather(*futs)
        leader = await _wait_converged(cluster)
        up_to = leader.commit_index
        member_logs = [_member_log_bytes(s, up_to) for s in cluster.servers]
        return {
            "commands": _command_stream(leader, up_to),
            "member_logs": member_logs,
            "state": dict(leader.state_machine.data),
            "states": [dict(s.state_machine.data) for s in cluster.servers],
        }
    finally:
        await cluster.close()


def _assert_no_invariant_violations(cluster):
    """The strict commit check raises inside an ack task (logged by the
    task reaper, not fatal), so the crisp test-visible signal is the
    counter it bumps before raising — it must never move."""
    for s in cluster.servers:
        assert s.metrics.counter("repl.invariant_violations").value == 0, \
            f"{s.address}: strict commit invariant violated"


def _assert_members_bit_identical(member_logs):
    base = member_logs[0]
    compared = 0
    for other in member_logs[1:]:
        for i, data in base.items():
            if i in other:
                assert data == other[i], f"member log divergence at {i}"
                compared += 1
    assert compared >= PHASES * OPS_PER_PHASE, compared


def test_lanes_commit_identical_logs(monkeypatch):
    results = {}
    for lane in ("1", "0"):
        monkeypatch.setenv("COPYCAT_REPL_PIPELINE", lane)

        @async_test(timeout=120)
        async def run(lane=lane):
            results[lane] = await _drive_workload()

        run()
    for lane, r in results.items():
        # within a lane: every member holds bit-identical committed bytes
        _assert_members_bit_identical(r["member_logs"])
        # and identical applied state
        for st in r["states"]:
            assert st == r["state"], f"lane {lane} member state diverged"
    # across lanes: the exact same command sequence committed, in the
    # same order, producing the same final state
    assert results["1"]["commands"] == results["0"]["commands"]
    assert len(results["1"]["commands"]) == PHASES * OPS_PER_PHASE
    assert results["1"]["state"] == results["0"]["state"]


# ---------------------------------------------------------------------------
# nemesis under COPYCAT_INVARIANTS=strict
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("lane", ("1", "0"))
def test_delayed_reordered_peers_strict(lane, monkeypatch):
    """Per-message random delays reorder in-flight append windows on the
    local transport (plus response loss for at-most-once ambiguity); the
    stream must stay exactly-once and commit must never outrun a real
    quorum (strict check raises inside _advance_commit if it does)."""
    monkeypatch.setenv("COPYCAT_REPL_PIPELINE", lane)
    monkeypatch.setenv("COPYCAT_INVARIANTS", "strict")

    @async_test(timeout=240)
    async def run():
        cluster = await create_cluster(3, session_timeout=60.0)
        try:
            leader = await cluster.await_leader()
            assert leader._strict_invariants
            client = await cluster.client(session_timeout=60.0)
            nem = cluster.registry.attach_nemesis()
            nem.set_delay(0.0, 0.004)
            nem.set_loss(response=0.05)
            for phase in range(4):
                futs = [client.submit_command_nowait(
                    Put(key="n", value=phase * 25 + i)) for i in range(25)]
                await asyncio.gather(*futs)
            nem.heal()
            await _wait_converged(cluster)
            for s in cluster.servers:
                assert s.state_machine.data.get("n") == 99
                assert s.state_machine.applied_ops == 100, \
                    (f"{s.address} applied {s.state_machine.applied_ops}: "
                     "double- or missed apply under reordering")
            _assert_no_invariant_violations(cluster)
        finally:
            await cluster.close()

    run()


def test_partitioned_peer_mid_stream_strict(monkeypatch):
    """A peer partitioned away mid-stream must not stall commit (quorum
    via the healthy follower), must not pin unbounded in-flight state,
    and must catch up on heal — all under the strict commit check."""
    monkeypatch.setenv("COPYCAT_REPL_PIPELINE", "1")
    monkeypatch.setenv("COPYCAT_INVARIANTS", "strict")

    @async_test(timeout=240)
    async def run():
        cluster = await create_cluster(3, session_timeout=60.0)
        try:
            leader = await cluster.await_leader()
            client = await cluster.client(session_timeout=60.0)
            victim = next(s for s in cluster.servers if s is not leader)
            rest = [s.address for s in cluster.servers if s is not victim]
            nem = cluster.registry.attach_nemesis()
            futs = [client.submit_command_nowait(Put(key="p", value=i))
                    for i in range(50)]
            nem.partition([victim.address], rest)  # cut mid-stream
            await asyncio.gather(*futs)            # commits via quorum
            futs = [client.submit_command_nowait(Put(key="p", value=50 + i))
                    for i in range(50)]
            await asyncio.gather(*futs)
            assert leader.role == LEADER
            nem.heal()
            deadline = asyncio.get_running_loop().time() + 30
            while asyncio.get_running_loop().time() < deadline:
                if victim.state_machine.data.get("p") == 99:
                    break
                await asyncio.sleep(0.05)
            assert victim.state_machine.data.get("p") == 99
            assert victim.state_machine.applied_ops == 100
            # drained: nothing in flight once the stream is caught up
            # (poll — an in-flight heartbeat window legitimately shows)
            deadline = asyncio.get_running_loop().time() + 5
            while asyncio.get_running_loop().time() < deadline:
                if leader.metrics.gauge("repl.windows_inflight").value == 0:
                    break
                await asyncio.sleep(0.02)
            assert leader.metrics.gauge("repl.windows_inflight").value == 0
            _assert_no_invariant_violations(cluster)
        finally:
            await cluster.close()

    run()


def test_leader_deposition_mid_stream_strict(monkeypatch):
    """Close the leader while a multi-window stream is in flight: the
    client re-routes, every ACKED write is applied exactly once on the
    survivors, and the survivors' logs are identical."""
    monkeypatch.setenv("COPYCAT_REPL_PIPELINE", "1")
    monkeypatch.setenv("COPYCAT_INVARIANTS", "strict")

    @async_test(timeout=240)
    async def run():
        cluster = await create_cluster(3, session_timeout=60.0)
        try:
            leader = await cluster.await_leader()
            client = await cluster.client(session_timeout=60.0)
            futs = [client.submit_command_nowait(Put(key=f"d{i}", value=i))
                    for i in range(120)]
            await asyncio.sleep(0)  # let the batch hit the wire
            await leader.close()    # deposition mid-stream
            results = await asyncio.gather(*futs, return_exceptions=True)
            survivors = [s for s in cluster.servers if s is not leader]
            deadline = asyncio.get_running_loop().time() + 30
            while asyncio.get_running_loop().time() < deadline:
                if any(s.role == LEADER for s in survivors):
                    target = max(s.commit_index for s in survivors)
                    if all(s.last_applied >= target for s in survivors):
                        break
                await asyncio.sleep(0.05)
            # every ACKED write is present on the survivors exactly once
            acked = [i for i, r in enumerate(results)
                     if not isinstance(r, BaseException)]
            for s in survivors:
                for i in acked:
                    assert s.state_machine.data.get(f"d{i}") == i, \
                        f"acked write d{i} missing on {s.address}"
            ser = Serializer()
            a, b = survivors
            up_to = min(a.commit_index, b.commit_index)
            for i in range(1, up_to + 1):
                ea, eb = a.log.get(i), b.log.get(i)
                if ea is not None and eb is not None:
                    assert ser.write(ea) == ser.write(eb), i
            # a fresh write through the new leader still works
            assert await asyncio.wait_for(
                client.submit(Put(key="after", value=1)), 30) is None
            for s in survivors:
                assert s.metrics.counter(
                    "repl.invariant_violations").value == 0
        finally:
            await cluster.close()

    run()
