"""Manager/facade tests (reference ``AtomixClientServerTest``/``AtomixReplicaTest``):
full stack — AtomixServers + AtomixClients, inline test resource, consistency
matrix, get-vs-create semantics, cross-node visibility, per-resource isolation.
"""

import asyncio

import pytest

from copycat_tpu.io.local import LocalServerRegistry, LocalTransport
from copycat_tpu.io.serializer import serialize_with
from copycat_tpu.io.buffer import BufferInput, BufferOutput
from copycat_tpu.manager.atomix import AtomixClient, AtomixReplica, AtomixServer
from copycat_tpu.protocol.operations import Command, Query
from copycat_tpu.resource.consistency import Consistency
from copycat_tpu.resource.resource import AbstractResource, resource_info
from copycat_tpu.resource.state_machine import ResourceStateMachine
from copycat_tpu.server.state_machine import Commit

from helpers import async_test
from raft_fixtures import next_ports


@serialize_with(920)
class EchoCommand(Command):
    def __init__(self, value=None):
        self.value = value

    def write_object(self, buf, s):
        s.write_object(self.value, buf)

    def read_object(self, buf, s):
        self.value = s.read_object(buf)


@serialize_with(921)
class EchoQuery(Query):
    def __init__(self, value=None):
        self.value = value

    def write_object(self, buf, s):
        s.write_object(self.value, buf)

    def read_object(self, buf, s):
        self.value = s.read_object(buf)


@serialize_with(922)
class SetValueCmd(Command):
    def __init__(self, value=None):
        self.value = value

    def write_object(self, buf, s):
        s.write_object(self.value, buf)

    def read_object(self, buf, s):
        self.value = s.read_object(buf)


@serialize_with(923)
class GetValueQry(Query):
    def write_object(self, buf, s):
        pass

    def read_object(self, buf, s):
        pass


@serialize_with(924)
class EchoStateMachine(ResourceStateMachine):
    """Echo machine (reference inline EchoStateMachine)."""

    def __init__(self):
        super().__init__()
        self.value = None

    def echo_command(self, commit: Commit[EchoCommand]):
        try:
            return commit.operation.value
        finally:
            commit.clean()

    def echo_query(self, commit: Commit[EchoQuery]):
        try:
            return commit.operation.value
        finally:
            commit.close()

    def set_value(self, commit: Commit[SetValueCmd]):
        self.value = commit.operation.value

    def get_value(self, commit: Commit[GetValueQry]):
        try:
            return self.value
        finally:
            commit.close()


@resource_info(state_machine=EchoStateMachine)
class EchoResource(AbstractResource):
    async def command(self, value):
        return await self.submit(EchoCommand(value))

    async def query(self, value):
        return await self.submit(EchoQuery(value))


@serialize_with(925)
class ValueStateMachine(EchoStateMachine):
    pass


@resource_info(state_machine=ValueStateMachine)
class ValueResource(AbstractResource):
    async def set(self, value):
        await self.submit(SetValueCmd(value))

    async def get(self):
        return await self.submit(GetValueQry())


async def _servers(n=3, registry=None, session_timeout=3.0):
    registry = registry or LocalServerRegistry()
    addrs = next_ports(n)
    servers = [
        AtomixServer(a, addrs, LocalTransport(registry),
                     election_timeout=0.2, heartbeat_interval=0.04,
                     session_timeout=session_timeout)
        for a in addrs
    ]
    await asyncio.gather(*(s.open() for s in servers))
    return servers, addrs, registry


async def _teardown(nodes):
    for node in nodes:
        try:
            await asyncio.wait_for(node.close(), 5)
        except (Exception, asyncio.TimeoutError):
            pass


@async_test(timeout=90)
async def test_client_server_all_consistency_levels():
    servers, addrs, registry = await _servers(3)
    client = AtomixClient(addrs, LocalTransport(registry), session_timeout=3.0)
    await client.open()
    try:
        resource = await client.get("test", EchoResource)
        for level in (Consistency.NONE, Consistency.PROCESS,
                      Consistency.SEQUENTIAL, Consistency.ATOMIC):
            resource.with_consistency(level)
            assert await resource.command(f"c-{level.value}") == f"c-{level.value}"
            assert await resource.query(f"q-{level.value}") == f"q-{level.value}"
    finally:
        await _teardown([client] + servers)


@async_test(timeout=90)
async def test_get_shares_state_create_is_distinct_session():
    servers, addrs, registry = await _servers(3)
    client = AtomixClient(addrs, LocalTransport(registry), session_timeout=3.0)
    await client.open()
    try:
        # Two gets of the same key share the node-local instance.
        r1 = await client.get("shared", ValueResource)
        r2 = await client.get("shared", ValueResource)
        assert r1 is r2
        # create() yields a distinct instance (unique virtual session) over the
        # same replicated state.
        r3 = await client.create("shared", ValueResource)
        assert r3 is not r1
        assert r3.client.instance_id != r1.client.instance_id
        await r1.set("from-get")
        assert await r3.get() == "from-get"
    finally:
        await _teardown([client] + servers)


@async_test(timeout=90)
async def test_cross_client_visibility():
    servers, addrs, registry = await _servers(3)
    c1 = AtomixClient(addrs, LocalTransport(registry), session_timeout=3.0)
    c2 = AtomixClient(addrs, LocalTransport(registry), session_timeout=3.0)
    await c1.open()
    await c2.open()
    try:
        r1 = await c1.get("xnode", ValueResource)
        r2 = await c2.get("xnode", ValueResource)
        await r1.set(42)
        assert await r2.get() == 42
    finally:
        await _teardown([c1, c2] + servers)


@async_test(timeout=90)
async def test_exists_and_delete():
    servers, addrs, registry = await _servers(3)
    client = AtomixClient(addrs, LocalTransport(registry), session_timeout=3.0)
    await client.open()
    try:
        assert not await client.exists("gone")
        resource = await client.get("gone", ValueResource)
        assert await client.exists("gone")
        await resource.delete()
        assert not await client.exists("gone")
    finally:
        await _teardown([client] + servers)


@async_test(timeout=90)
async def test_replicas_operate_many_isolated_resources():
    """Reference AtomixReplicaTest.testOperateMany: distinct keys on distinct
    replicas stay isolated over the shared log."""
    registry = LocalServerRegistry()
    addrs = next_ports(3)
    replicas = [
        AtomixReplica(a, addrs, LocalTransport(registry),
                      election_timeout=0.2, heartbeat_interval=0.04,
                      session_timeout=3.0)
        for a in addrs
    ]
    await asyncio.gather(*(r.open() for r in replicas))
    try:
        ra = await replicas[0].get("alpha", ValueResource)
        rb = await replicas[1].get("beta", ValueResource)
        await ra.set("A")
        await rb.set("B")
        ra2 = await replicas[2].get("alpha", ValueResource)
        rb2 = await replicas[2].get("beta", ValueResource)
        assert await ra2.get() == "A"
        assert await rb2.get() == "B"
    finally:
        await _teardown(replicas)


@async_test(timeout=90)
async def test_wrong_type_for_existing_key_fails():
    from copycat_tpu.client.client import ApplicationError

    servers, addrs, registry = await _servers(3)
    client = AtomixClient(addrs, LocalTransport(registry), session_timeout=3.0)
    client2 = AtomixClient(addrs, LocalTransport(registry), session_timeout=3.0)
    await client.open()
    await client2.open()
    try:
        await client.get("typed", ValueResource)
        # Same node: rejected by the local singleton cache.
        with pytest.raises(ValueError, match="already open"):
            await client.get("typed", EchoResource)
        # Different node: rejected by the replicated catalog.
        with pytest.raises(ApplicationError, match="exists with type"):
            await client2.get("typed", EchoResource)
    finally:
        await _teardown([client, client2] + servers)


@async_test(timeout=90)
async def test_factory_overloads_build_custom_facades():
    """Reference ``Atomix.get(key, type, factory)`` /
    ``create(key, type, factory)`` (``Atomix.java:205-208,303-306``): the
    factory builds the client-side facade from its InstanceClient; the
    replicated state machine still resolves from the resource type."""

    class TracingValue(ValueResource):
        def __init__(self, client):
            super().__init__(client)
            self.calls = 0

        async def set(self, value):
            self.calls += 1
            return await super().set(value)

    servers, addrs, registry = await _servers(3)
    client = AtomixClient(addrs, LocalTransport(registry), session_timeout=3.0)
    await client.open()
    try:
        r = await client.get("fac", ValueResource, factory=TracingValue)
        assert isinstance(r, TracingValue)
        await r.set("x")
        assert r.calls == 1
        # singleton cache returns the SAME factory-built facade
        assert await client.get("fac", ValueResource) is r
        # create(): fresh session, same replicated state, factory applies
        r2 = await client.create("fac", ValueResource, factory=TracingValue)
        assert isinstance(r2, TracingValue) and r2 is not r
        assert await r2.get() == "x"
        # a factory whose product is not a resource_type is rejected
        with pytest.raises(TypeError, match="factory built"):
            await client.create("fac2", ValueResource,
                                factory=lambda c: object())
    finally:
        await _teardown([client] + servers)
