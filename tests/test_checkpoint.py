"""Checkpoint/resume tests (models/checkpoint.py).

The reference recovers by log replay only (no snapshots, SURVEY.md §5.4);
here a full snapshot must resume bit-exactly: committed state, logs,
resource pools, event dedup cursors and the logical clock all survive.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.models import RaftGroups, checkpoint  # noqa: E402
from copycat_tpu.ops import apply as ap  # noqa: E402


def test_save_load_roundtrip(tmp_path):
    rg = RaftGroups(2, 3, log_slots=32)
    rg.wait_for_leaders()
    tags = [rg.submit(0, ap.OP_LONG_ADD, 2) for _ in range(5)]
    tags += [rg.submit(1, ap.OP_MAP_PUT, 7, 70)]
    tags += [rg.submit(1, ap.OP_LOCK_ACQUIRE, 4, -1)]
    rg.run_until(tags)
    rg.run(5)

    path = tmp_path / "snap.npz"
    checkpoint.save(rg, path)
    restored = checkpoint.load(path)

    assert restored.rounds == rg.rounds
    assert restored.clock == rg.clock
    for a, b in zip(jax.tree_util.tree_leaves(rg.state),
                    jax.tree_util.tree_leaves(restored.state)):
        assert (np.asarray(a) == np.asarray(b)).all()

    # the restored cluster continues committing from where it stopped
    t = restored.submit(0, ap.OP_LONG_ADD, 2)
    restored.run_until([t])
    assert restored.results[t] == 12  # 5 * 2 before + 2 after
    t2 = restored.submit(1, ap.OP_MAP_GET, 7)
    restored.run_until([t2])
    assert restored.results[t2] == 70
    # lock holder survived the snapshot
    t3 = restored.submit(1, ap.OP_LOCK_HOLDER)
    restored.run_until([t3])
    assert restored.results[t3] == 4


def test_restore_preserves_event_dedup(tmp_path):
    rg = RaftGroups(1, 3, log_slots=32)
    rg.wait_for_leaders()
    tags = [rg.submit(0, ap.OP_LOCK_ACQUIRE, 1, -1),
            rg.submit(0, ap.OP_LOCK_ACQUIRE, 2, -1),
            rg.submit(0, ap.OP_LOCK_RELEASE, 1)]
    rg.run_until(tags)
    rg.run(5)
    grants = [e for e in rg.events.get(0, []) if e[1] == ap.EV_LOCK_GRANT]
    assert len(grants) == 1  # grant to 2

    path = tmp_path / "snap.npz"
    checkpoint.save(rg, path)
    restored = checkpoint.load(path)
    restored.run(10)
    # the buffered grant survives the snapshot EXACTLY once: persisted in
    # rg.events and not re-harvested from the device ring (seq dedup)
    grants2 = [e for e in restored.events.get(0, [])
               if e[1] == ap.EV_LOCK_GRANT]
    assert grants2 == grants

    # a facade created AFTER restore must NOT consume the pre-snapshot
    # grant (session events die with the session); it recovers through the
    # authoritative holder register instead
    from copycat_tpu.models.device_resources import DeviceLock
    lock = DeviceLock(restored, 0, holder_id=2)
    assert not lock._next_grant()
    t = restored.submit(0, ap.OP_LOCK_HOLDER)
    restored.run_until([t])
    assert restored.results[t] == 2  # ground truth: 2 holds the lock


def test_load_snapshot_missing_newer_pool_leaves(tmp_path):
    """Snapshots saved before newer ResourceState pools/fields existed must
    restore with fresh template values — both the legacy positional
    format (trailing-leaf padding) and the path-keyed format (missing
    fields keep template values)."""
    import json

    import jax

    rg = RaftGroups(2, 3, log_slots=16)
    rg.wait_for_leaders()
    tag = rg.submit(0, ap.OP_LONG_ADD, 7)
    rg.run_until([tag])
    rg.run(5)  # let every lane (incl. peer 0) apply before snapshotting
    path = tmp_path / "now.npz"
    checkpoint.save(rg, path)

    with np.load(str(path), allow_pickle=False) as data:
        meta = json.loads(str(data["meta"]))
        arrays = {k: data[k] for k in data.files if k != "meta"}

    # (a) path-keyed format with newer fields missing entirely
    partial = {k: v for k, v in arrays.items()
               if not any(f in k for f in ("mm_", "tp_", "lease", "member"))}
    old_pk = tmp_path / "path-keyed-old.npz"
    np.savez_compressed(str(old_pk), meta=json.dumps(meta), **partial)
    restored = checkpoint.load(old_pk)
    assert restored.value(0) == 7
    t = restored.submit(0, ap.OP_MM_PUT, 1, 2)
    restored.run_until([t])
    assert restored.results[t] == 1

    # (b) legacy positional format (leaf_i), truncated before mm/tp/lease
    flat = jax.tree_util.tree_flatten_with_path(rg.state)[0]
    legacy = {k: v for k, v in arrays.items() if not k.startswith("state.")}
    n = 0
    for path_keys, leaf in flat:
        name = "state." + ".".join(
            getattr(pk, "name", str(pk)) for pk in path_keys)
        if any(f in name for f in ("mm_", "tp_", "lease", "member")):
            continue
        legacy[f"leaf_{n}"] = arrays[name]
        n += 1
    meta["num_leaves"] = n
    old_pos = tmp_path / "positional-old.npz"
    np.savez_compressed(str(old_pos), meta=json.dumps(meta), **legacy)
    restored2 = checkpoint.load(old_pos)
    assert restored2.value(0) == 7
    t2 = restored2.submit(0, ap.OP_MM_PUT, 3, 4)
    restored2.run_until([t2])
    assert restored2.results[t2] == 1


def test_restore_onto_different_device_layout(tmp_path):
    """Hardware elasticity: a snapshot from an UNSHARDED engine restores
    onto an 8-device mesh (and back), resumes identically, and the
    mesh restore really is distributed. The save format is placement-
    free (plain npz arrays), so layout is purely a load-time choice —
    the operational story for moving a cluster between hosts with
    different chip counts."""
    from copycat_tpu.parallel import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh (conftest)")

    rg = RaftGroups(16, 3, log_slots=32)
    rg.wait_for_leaders()
    tags = [rg.submit(g, ap.OP_LONG_ADD, g + 1) for g in range(16)]
    rg.run_until(tags)
    rg.run(3)
    path = tmp_path / "snap.npz"
    checkpoint.save(rg, path)

    def assert_states_equal(sa, sb):
        fa = jax.tree_util.tree_flatten_with_path(sa)[0]
        fb = jax.tree_util.tree_flatten_with_path(sb)[0]
        for (pa, a), (_, b) in zip(fa, fb, strict=True):
            assert np.array_equal(np.asarray(a), np.asarray(b)), pa

    mesh = make_mesh(groups=8)
    onto_mesh = checkpoint.load(path, mesh=mesh)
    assert len(onto_mesh.state.term.devices()) == 8  # really sharded
    assert_states_equal(rg.state, onto_mesh.state)

    # both resume and agree on new work
    for drv in (rg, onto_mesh):
        t2 = [drv.submit(g, ap.OP_LONG_ADD, 10) for g in range(16)]
        drv.run_until(t2)
    assert_states_equal(rg.state, onto_mesh.state)

    # and the mesh snapshot restores back onto a single device
    path2 = tmp_path / "snap2.npz"
    checkpoint.save(onto_mesh, path2)
    back = checkpoint.load(path2)
    assert len(back.state.term.devices()) == 1
    assert_states_equal(onto_mesh.state, back.state)
