"""The driver-graded entry points must be hermetic against accelerator state.

Round-3 post-mortem: ``dryrun_multichip`` called ``jax.devices("cpu")``
without pinning the platform; JAX backend discovery initializes *every*
registered plugin, and a dead TPU tunnel makes that enumeration hang
forever — three consecutive red MULTICHIP artifacts. These tests run the
real entry point in fresh subprocesses (backend init is process-global,
so in-process tests can't exercise the pin) and assert:

1. the cpu-platform pin is applied before the first backend init, so no
   non-cpu plugin is ever discovered, and
2. the full dryrun passes end-to-end from a cold process with NO
   environment hints (no JAX_PLATFORMS, no pre-set XLA_FLAGS).

Mirrors the obligation of the reference's 5-server cluster tests
(manager/src/test/java/io/atomix/AtomixClientServerTest.java) running
without real network hardware.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _clean_env():
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    return env


def test_dryrun_pins_cpu_platform_before_backend_init():
    # The subprocess would hang (not fail) if discovery touched a dead
    # tunneled plugin; the 300s timeout converts a regression to a hard
    # test failure well inside CI limits.
    code = (
        "import __graft_entry__ as g\n"
        "import jax\n"
        "g.dryrun_multichip(2)\n"
        "assert jax.config.jax_platforms == 'cpu', jax.config.jax_platforms\n"
        "plats = {d.platform for d in jax.devices()}\n"
        "assert plats == {'cpu'}, plats\n"
        "print('PINNED-OK')\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, env=_clean_env(),
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    assert "PINNED-OK" in out.stdout


def test_dryrun_full_eight_device_mesh_cold_process():
    code = "import __graft_entry__ as g; g.dryrun_multichip(8); print('DRYRUN-OK')"
    out = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, env=_clean_env(),
        capture_output=True, text=True, timeout=480)
    assert out.returncode == 0, out.stderr
    assert "DRYRUN-OK" in out.stdout
