"""Per-request tracing (utils/tracing.py): ring buffer semantics and
end-to-end trace-id propagation through the wire protocol."""

import asyncio

import pytest

from copycat_tpu.utils import tracing
from copycat_tpu.utils.tracing import Tracer

from helpers import async_test
from raft_fixtures import Put, create_cluster


@pytest.fixture(autouse=True)
def _clean_tracer():
    """Every test starts and ends with the global tracer disabled+empty."""
    tracing.disable()
    tracing.TRACER.clear()
    yield
    tracing.disable()
    tracing.TRACER.clear()


def test_tracer_ring_buffer_evicts_oldest_and_tombstones():
    t = Tracer(capacity=3)
    t.enabled = True
    ids = [t.new_trace() for _ in range(5)]
    for i, trace_id in enumerate(ids):
        t.span(trace_id, "work", 0.0, 0.001 * (i + 1))
    kept = t.traces()
    assert len(kept) == 3
    assert set(kept) == set(ids[-3:])
    # a late span for an EVICTED id is dropped (tombstoned), never
    # resurrected as a partial trace that would pollute dump_slowest
    # with a nonsense total
    t.span(ids[0], "late", 0.0, 0.5)
    assert ids[0] not in t.traces()
    assert t.spans_for(ids[0]) == []
    # a genuinely new id is still admitted
    fresh = t.new_trace()
    t.span(fresh, "work", 0.0, 0.001)
    assert fresh in t.traces()
    # clear() resets the tombstones too: the id becomes recordable
    # again (a fresh test/process epoch)
    t.clear()
    t.span(ids[0], "late", 0.0, 0.5)
    assert ids[0] in t.traces()


def test_slowest_orders_by_total_wall():
    t = Tracer()
    a, b = t.new_trace(), t.new_trace()
    t.span(a, "fast", 0.0, 0.001)
    t.span(b, "slow.1", 0.0, 0.002)
    t.span(b, "slow.2", 0.004, 0.010)  # total wall 10ms (first->last)
    slow = t.slowest(2)
    assert [s[0] for s in slow] == [b, a]
    assert slow[0][1] == pytest.approx(10.0)
    text = t.dump_slowest(2)
    assert "slow.1" in text and "fast" in text
    as_json = t.dump_slowest(2, as_json=True)
    assert '"total_ms"' in as_json


def test_dump_empty():
    assert "no traces" in Tracer().dump_slowest()


def test_span_cap_bounds_a_reused_trace_id():
    # a peer replaying one id forever must not grow server memory
    t = Tracer()
    for i in range(10 * t.MAX_SPANS_PER_TRACE):
        t.span(7, "replay", 0.0, 0.001)
    assert len(t.spans_for(7)) == t.MAX_SPANS_PER_TRACE


@async_test(timeout=60)
async def test_trace_ids_survive_the_wire_roundtrip():
    """A traced client submit yields server-side spans under the SAME
    trace id — the id crossed the wire in the frame (LocalTransport
    round-trips through the real serializer) and came back correlated."""
    cluster = await create_cluster(3)
    try:
        client = await cluster.client()
        tracing.enable()
        # single command -> CommandRequest.trace
        await client.submit(Put(key="a", value=1))
        # same-turn pair -> one CommandBatchRequest.trace
        await asyncio.gather(client.submit(Put(key="b", value=2)),
                             client.submit(Put(key="c", value=3)))
        tracing.disable()
        traces = tracing.TRACER.traces()
        assert traces, "no traces recorded"
        client_traces = {tid for tid, spans in traces.items()
                         if any(s.name == "client.submit" for s in spans)}
        assert client_traces
        for tid in client_traces:
            names = {s.name for s in traces[tid]}
            # server-side spans recorded under the CLIENT's id: the id
            # survived request serialization and handler dispatch
            # (vocabulary: docs/OBSERVABILITY.md — the single lane
            # records the coarse group.commit, the batch fast lane the
            # quorum.wait/apply split)
            assert "group.append" in names, names
            assert names & {"group.commit", "apply"}, names
            # every server-side span is member+group tagged for the
            # cross-member assembly
            for s in traces[tid]:
                if s.name.startswith(("group.", "quorum.", "apply",
                                      "respond", "follower.")):
                    assert (s.meta or {}).get("member"), s
                    assert "group" in (s.meta or {}), s
        # the batch trace carries the batch size through to its spans
        batch = [spans for spans in traces.values()
                 for s in spans
                 if s.name == "client.submit" and (s.meta or {}).get("n") == 2]
        assert batch, "batch submit span missing"
        # a 3-member cluster replicates the traced entry: the window
        # carried the id and the followers recorded their ingest
        followers = [s for spans in traces.values() for s in spans
                     if s.name == "follower.append"]
        assert followers, "no follower.append spans landed"
        # and the dump renders them
        assert "group.append" in tracing.TRACER.dump_slowest(5)
    finally:
        await cluster.close()


@async_test(timeout=60)
async def test_tracing_disabled_is_absent_from_the_wire():
    """With tracing off (the default), requests carry trace=None, no
    spans are recorded anywhere, and the hot path does no tracer work."""
    cluster = await create_cluster(1)
    try:
        client = await cluster.client()
        await client.submit(Put(key="x", value=1))
        await asyncio.gather(client.submit(Put(key="y", value=2)),
                             client.submit(Put(key="z", value=3)))
        assert tracing.TRACER.traces() == {}
        # a request built without a trace serializes/deserializes with
        # the field absent-as-None (the wire shape tracing rides on)
        from copycat_tpu.io.serializer import Serializer
        from copycat_tpu.protocol import messages as msg
        s = Serializer()
        req = s.read(s.write(msg.CommandRequest(
            session_id=1, seq=1, operation=None)))
        assert req.trace is None
        traced = s.read(s.write(msg.CommandBatchRequest(
            session_id=1, entries=[], trace=41)))
        assert traced.trace == 41
    finally:
        await cluster.close()


@async_test(timeout=60)
async def test_client_and_server_metrics_flow():
    """The observability counters move under real traffic: client
    submit latency histogram, server lane counters, transport frames."""
    cluster = await create_cluster(3)
    try:
        client = await cluster.client()
        for i in range(3):
            await client.submit(Put(key=f"k{i}", value=i))
        snap = client.metrics.snapshot()
        assert snap["commands_submitted"] == 3
        assert snap["submit_latency_ms"]["count"] == 3
        leader = cluster.leader
        stats = leader.stats_snapshot()
        assert stats["role"] == "leader"
        assert stats["raft"]["raft_is_leader"] == 1
        assert stats["raft"]["raft_term"] >= 1
        assert stats["raft"]["sessions_open"] >= 1
        assert stats["raft"]["commands_single_lane"] == 3
        assert stats["raft"]["applies_per_entry"] >= 3
        # per-message transport accounting on the leader's endpoints
        transport = stats.get("transport")
        assert transport is not None
        assert transport["frames_in"] > 0 and transport["bytes_in"] > 0
    finally:
        await cluster.close()
