"""Client command micro-batching (CommandBatchRequest/Response).

Same-turn submits from one session coalesce into ONE transport message;
per-entry results and application errors route back to the right
futures; a lone submit still rides the single-command path. Exactly-once
holds because the batch carries the same client seqs the single path
would (server-side dedup is seq-based either way).
"""

import asyncio

import pytest

from copycat_tpu.atomic import DistributedAtomicLong, DistributedAtomicValue
from copycat_tpu.collections import DistributedQueue
from copycat_tpu.io.local import LocalServerRegistry, LocalTransport
from copycat_tpu.manager.atomix import AtomixClient, AtomixServer
from copycat_tpu.protocol import messages as msg

from helpers import async_test
from raft_fixtures import next_ports


async def _cluster(n: int = 1):
    registry = LocalServerRegistry()
    addrs = next_ports(n)
    servers = [AtomixServer(a, addrs, LocalTransport(registry),
                            election_timeout=0.2, heartbeat_interval=0.04,
                            session_timeout=10.0) for a in addrs]
    await asyncio.gather(*(s.open() for s in servers))
    client = AtomixClient(addrs, LocalTransport(registry),
                          session_timeout=10.0)
    await client.open()
    return servers, client


async def _teardown(nodes):
    for node in nodes:
        try:
            await asyncio.wait_for(node.close(), 5)
        except (Exception, asyncio.TimeoutError):
            pass


def _spy_requests(client):
    """Count outgoing request types on the raft client under the facade."""
    raft_client = client.client  # AtomixClient -> RaftClient
    counts: dict[str, int] = {}
    original = raft_client._request

    async def spy(request, **kwargs):
        counts[type(request).__name__] = \
            counts.get(type(request).__name__, 0) + 1
        return await original(request, **kwargs)

    raft_client._request = spy
    return counts


@async_test(timeout=120)
async def test_concurrent_submits_coalesce_into_batches():
    servers, client = await _cluster()
    try:
        counters = await asyncio.gather(
            *(client.get(f"c{i}", DistributedAtomicLong) for i in range(16)))
        counts = _spy_requests(client)
        for rep in range(3):
            got = await asyncio.gather(
                *(c.increment_and_get() for c in counters))
        assert got == [3] * 16
        batched = counts.get("CommandBatchRequest", 0)
        singles = counts.get("CommandRequest", 0)
        # 48 commands; same-turn gathers must coalesce — far fewer
        # messages than commands, and batches actually used
        assert batched >= 1, counts
        assert batched + singles <= 24, counts
    finally:
        await _teardown([client] + servers)


@async_test(timeout=120)
async def test_batch_routes_application_errors_per_entry():
    servers, client = await _cluster()
    try:
        q = await client.get("q", DistributedQueue)
        await q.offer(1)

        # two removes race in one turn: exactly one pops the element, the
        # other must raise (remove on empty queue) — per-entry error routing
        async def safe_remove():
            try:
                return await q.remove()
            except Exception as e:
                return type(e).__name__

        a, b = await asyncio.gather(safe_remove(), safe_remove())
        assert sorted(str(x) for x in (a, b)) == ["1", "ApplicationError"], (a, b)
    finally:
        await _teardown([client] + servers)


@async_test(timeout=120)
async def test_single_submit_stays_on_single_command_path():
    servers, client = await _cluster()
    try:
        v = await client.get("v", DistributedAtomicValue)
        counts = _spy_requests(client)
        await v.set(5)
        assert await v.get() == 5
        assert counts.get("CommandBatchRequest", 0) == 0, counts
        assert counts.get("CommandRequest", 0) == 1, counts
    finally:
        await _teardown([client] + servers)


@async_test(timeout=120)
async def test_batching_across_three_replicas():
    servers, client = await _cluster(3)
    try:
        counters = await asyncio.gather(
            *(client.get(f"n{i}", DistributedAtomicLong) for i in range(12)))
        for _ in range(2):
            got = await asyncio.gather(
                *(c.add_and_get(2) for c in counters))
        assert got == [4] * 12
    finally:
        await _teardown([client] + servers)


@async_test(timeout=120)
async def test_concurrent_queries_coalesce_per_consistency():
    servers, client = await _cluster()
    try:
        values = await asyncio.gather(
            *(client.get(f"v{i}", DistributedAtomicValue) for i in range(10)))
        await asyncio.gather(*(v.set(i) for i, v in enumerate(values)))
        counts = _spy_requests(client)
        got = await asyncio.gather(*(v.get() for v in values))
        assert got == list(range(10))
        # one linearizable-read gate for the whole turn, not ten
        assert counts.get("QueryBatchRequest", 0) >= 1, counts
        assert counts.get("QueryRequest", 0) == 0, counts
    finally:
        await _teardown([client] + servers)


@async_test(timeout=180)
async def test_batched_submits_survive_leader_failover():
    """Concurrent (batched) submits during a leader loss must re-route
    transparently, exactly like the single-command path — routing errors
    are promoted to the batch response level where the client's retry
    loop handles them; seq dedup makes the resend exactly-once."""
    servers, client = await _cluster(3)
    try:
        counters = await asyncio.gather(
            *(client.get(f"f{i}", DistributedAtomicLong) for i in range(8)))
        got = await asyncio.gather(*(c.increment_and_get() for c in counters))
        assert got == [1] * 8

        leader = next(s for s in servers if s.server.role == "leader")
        await asyncio.wait_for(leader.close(), 10)

        got = await asyncio.wait_for(
            asyncio.gather(*(c.increment_and_get() for c in counters)), 60)
        assert got == [2] * 8
    finally:
        await _teardown([client] + servers)
