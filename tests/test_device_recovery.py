"""Restart-from-disk recovery with the device executor.

The two-plane design's recovery claim (manager/device_executor.py): the
engine's visible state is a pure function of the committed device-op
sequence, which is derived from the CPU log in apply order — so a server
restarted from its on-disk log rebuilds a FRESH device engine to exactly
the pre-crash resource state by replay. Reference obligation: recovery =
replay the un-compacted log (SURVEY.md §5.4).
"""

import asyncio

import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.atomic import DistributedAtomicLong, DistributedAtomicValue  # noqa: E402
from copycat_tpu.collections import DistributedMap  # noqa: E402
from copycat_tpu.io.local import LocalServerRegistry, LocalTransport  # noqa: E402
from copycat_tpu.manager.atomix import AtomixClient, AtomixServer  # noqa: E402
from copycat_tpu.manager.device_executor import DeviceEngineConfig  # noqa: E402
from copycat_tpu.server.log import Storage, StorageLevel  # noqa: E402

from helpers import async_test  # noqa: E402
from raft_fixtures import next_ports  # noqa: E402

ENGINE = DeviceEngineConfig(capacity=16, num_peers=3, log_slots=32)


@pytest.mark.parametrize("level", [StorageLevel.DISK, StorageLevel.MAPPED])
@async_test(timeout=300)
async def test_restart_replays_log_into_fresh_device_engine(tmp_path, level):
    registry = LocalServerRegistry()
    addrs = next_ports(1)
    storage = Storage(level, str(tmp_path), max_entries_per_segment=16)

    server = AtomixServer(addrs[0], addrs, LocalTransport(registry),
                          election_timeout=0.2, heartbeat_interval=0.04,
                          session_timeout=10.0, executor="tpu",
                          engine_config=ENGINE, storage=storage)
    await server.open()
    client = AtomixClient(addrs, LocalTransport(registry),
                          session_timeout=10.0)
    await client.open()

    ctr = await client.get("ctr", DistributedAtomicLong)
    for _ in range(5):
        await ctr.increment_and_get()
    m = await client.get("m", DistributedMap)
    await m.put(1, 11)
    await m.put(2, 22)
    await m.remove(1)
    v = await client.get("v", DistributedAtomicValue)
    await v.set(99)
    engine = server.server.state_machine.device_engine
    assert engine._next_group >= 3  # all three landed on-device

    await asyncio.wait_for(client.close(), 5)
    await asyncio.wait_for(server.close(), 5)

    # Fresh process-equivalent: new registry/server over the SAME log dir;
    # a brand-new device engine must be rebuilt purely by replay.
    registry2 = LocalServerRegistry()
    storage2 = Storage(level, str(tmp_path), max_entries_per_segment=16)
    server2 = AtomixServer(addrs[0], addrs, LocalTransport(registry2),
                           election_timeout=0.2, heartbeat_interval=0.04,
                           session_timeout=10.0, executor="tpu",
                           engine_config=ENGINE, storage=storage2)
    await server2.open()
    client2 = AtomixClient(addrs, LocalTransport(registry2),
                           session_timeout=10.0)
    await client2.open()
    try:
        ctr2 = await client2.get("ctr", DistributedAtomicLong)
        assert await ctr2.get() == 5
        assert await ctr2.increment_and_get() == 6  # still writable
        m2 = await client2.get("m", DistributedMap)
        assert await m2.get(2) == 22
        assert await m2.get(1) is None
        assert await m2.size() == 1
        v2 = await client2.get("v", DistributedAtomicValue)
        assert await v2.get() == 99
        engine2 = server2.server.state_machine.device_engine
        assert engine2 is not engine  # genuinely rebuilt
    finally:
        await asyncio.wait_for(client2.close(), 5)
        await asyncio.wait_for(server2.close(), 5)
