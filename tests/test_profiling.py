"""summarize_trace plumbing (utils/profiling.py): canned trace-JSON
aggregation, session-dir discovery fallback, and the missing-xprof
error — none of which need a TPU or the xprof package."""

import pytest

from copycat_tpu.utils.profiling import (
    aggregate_trace_events,
    find_xplane_files,
    summarize_trace,
)

#: a canned trace-viewer JSON event list: pid 1 is a device lane, pid 2
#: a host lane whose events must NOT be counted, pid 3 has no metadata.
CANNED_EVENTS = [
    {"ph": "M", "name": "process_name", "pid": 1,
     "args": {"name": "/device:TPU:0"}},
    {"ph": "M", "name": "process_name", "pid": 2,
     "args": {"name": "python host thread"}},
    {"ph": "X", "pid": 1, "name": "fusion.42", "dur": 3000},
    {"ph": "X", "pid": 1, "name": "fusion.42", "dur": 1000},
    {"ph": "X", "pid": 1, "name": "copy.7", "dur": 500},
    {"ph": "X", "pid": 2, "name": "host_overhead", "dur": 999999},
    {"ph": "X", "pid": 3, "name": "unknown_lane", "dur": 12345},
    {"ph": "B", "pid": 1, "name": "not_complete_event", "dur": 777},
]


def test_aggregate_counts_device_lanes_only():
    rows = aggregate_trace_events(CANNED_EVENTS)
    assert rows == [("fusion.42", 4.0, 2), ("copy.7", 0.5, 1)]


def test_aggregate_top_truncates():
    rows = aggregate_trace_events(CANNED_EVENTS, top=1)
    assert rows == [("fusion.42", 4.0, 2)]


def test_find_xplane_standard_layout_picks_newest_session(tmp_path):
    old = tmp_path / "plugins" / "profile" / "2026_01_01_00_00_00"
    new = tmp_path / "plugins" / "profile" / "2026_02_02_00_00_00"
    for d in (old, new):
        d.mkdir(parents=True)
        (d / "host.xplane.pb").write_bytes(b"x")
    files = find_xplane_files(str(tmp_path))
    assert files == [str(new / "host.xplane.pb")]


def test_find_xplane_falls_back_to_scanning(tmp_path):
    # a layout some jax versions produce: no plugins/profile nesting
    weird = tmp_path / "session_dir" / "nested"
    weird.mkdir(parents=True)
    (weird / "a.xplane.pb").write_bytes(b"x")
    (weird / "b.xplane.pb").write_bytes(b"x")
    files = find_xplane_files(str(tmp_path))
    assert sorted(files) == [str(weird / "a.xplane.pb"),
                             str(weird / "b.xplane.pb")]


def test_find_xplane_empty_dir_is_actionable(tmp_path):
    with pytest.raises(FileNotFoundError, match="xplane.pb"):
        find_xplane_files(str(tmp_path))


def test_summarize_trace_without_xprof_is_actionable(tmp_path, monkeypatch):
    # sys.modules[name] = None makes `from xprof.convert import ...`
    # raise ImportError — the no-xprof environment, simulated
    import sys
    monkeypatch.setitem(sys.modules, "xprof", None)
    monkeypatch.setitem(sys.modules, "xprof.convert", None)
    d = tmp_path / "plugins" / "profile" / "s1"
    d.mkdir(parents=True)
    (d / "host.xplane.pb").write_bytes(b"x")
    with pytest.raises(RuntimeError, match="xprof"):
        summarize_trace(str(tmp_path))

def test_cli_profile_device_routes_through_summarize_trace(
        tmp_path, monkeypatch, capsys):
    """``copycat-tpu profile --device <dir>`` is the device-side door:
    it routes through summarize_trace (monkeypatched here — no xprof
    needed), renders the op table, and keeps the actionable error
    when the trace dir is empty."""
    import json

    from copycat_tpu import cli

    def _ns(**kw):
        return type("A", (), kw)()

    calls = []

    def fake_summarize(trace_dir, top=15):
        calls.append((trace_dir, top))
        return [("fusion.42", 4.0, 2), ("copy.7", 0.5, 1)]

    # _profile_device imports lazily -> patch the source module
    monkeypatch.setattr("copycat_tpu.utils.profiling.summarize_trace",
                        fake_summarize)
    ns = _ns(addresses=[], last=None, top=5, json=True, diff=None,
             device=str(tmp_path))
    assert cli._profile(ns) == 0
    assert calls == [(str(tmp_path), 5)]
    rows = json.loads(capsys.readouterr().out)
    assert rows == [{"op": "fusion.42", "total_ms": 4.0, "count": 2},
                    {"op": "copy.7", "total_ms": 0.5, "count": 1}]
    # the real thing against an empty dir: one-line error, exit 1
    monkeypatch.undo()
    ns = _ns(addresses=[], last=None, top=5, json=False, diff=None,
             device=str(tmp_path))
    assert cli._profile(ns) == 1
    assert "xplane.pb" in capsys.readouterr().err
