"""The bench-baseline perf-regression gate (``testing/bench_gate.py``):
window math, unit/missing-baseline handling, the update path, and the
committed golden's shape."""

import json
import os

from copycat_tpu.testing import bench_gate


def _artifact(scenario="spi", value=10000.0, unit="ops/sec", **meta):
    return {"scenario": scenario, "value": value, "unit": unit,
            "meta": meta or {"git_sha": "abc", "host": {"cpus": 2}}}


def _golden(value=10000.0, tolerance=0.25, scenario="spi",
            unit="ops/sec"):
    return {"tolerance": tolerance,
            "scenarios": {scenario: {"value": value, "unit": unit,
                                     "recorded": {}}}}


def test_gate_passes_inside_the_window():
    ok, line = bench_gate.gate_artifact(_artifact(value=8000), _golden())
    assert ok and "ok 8,000.0" in line
    ok, _ = bench_gate.gate_artifact(_artifact(value=7500.0), _golden())
    assert ok  # exactly on the floor passes


def test_gate_fails_below_the_floor():
    ok, line = bench_gate.gate_artifact(_artifact(value=7000), _golden())
    assert not ok
    assert "REGRESSION" in line and "floor 7,500.0" in line


def test_gate_flags_stale_baseline_above_the_window():
    ok, line = bench_gate.gate_artifact(_artifact(value=20000), _golden())
    assert ok  # a win never fails the gate...
    assert "stale" in line  # ...but the window should be refreshed


def test_gate_missing_baseline_and_unit_change():
    ok, line = bench_gate.gate_artifact(
        _artifact(scenario="novel"), _golden())
    assert not ok and "--update-golden" in line
    ok, line = bench_gate.gate_artifact(
        _artifact(unit="reads/sec"), _golden())
    assert not ok and "unit changed" in line


def test_gate_degraded_mismatch_skips_the_floor():
    """A CPU-fallback ("degraded": true) artifact graded against a
    non-degraded window is a different experiment: the comparison is
    marked degraded_mismatch and the device-plane floor is SKIPPED —
    even a value far below the floor must not read as a regression."""
    art = _artifact(value=500.0)  # 20x below the 7,500 floor
    art["degraded"] = True
    ok, line = bench_gate.gate_artifact(art, _golden())
    assert ok, line
    assert "degraded_mismatch" in line and "skipped" in line
    assert "REGRESSION" not in line
    # ...and the mirror: a healthy run against a degraded window
    golden = _golden(value=500.0)
    golden["scenarios"]["spi"]["degraded"] = True
    ok, line = bench_gate.gate_artifact(_artifact(value=9000.0), golden)
    assert ok and "degraded_mismatch" in line
    assert "stale" not in line  # a lane change is not a perf win
    # matching degraded lanes still grade normally
    art2 = _artifact(value=300.0)  # below the 375 floor
    art2["degraded"] = True
    ok, line = bench_gate.gate_artifact(art2, golden)
    assert not ok and "REGRESSION" in line


def test_update_golden_records_the_degraded_lane(tmp_path):
    golden_path = str(tmp_path / "baseline.json")
    artifact_path = str(tmp_path / "a.json")
    art = _artifact(value=500.0)
    art["degraded"] = True
    with open(artifact_path, "w") as f:
        json.dump(art, f)
    assert bench_gate.main([artifact_path, "--golden", golden_path,
                            "--update-golden"]) == 0
    golden = json.load(open(golden_path))
    assert golden["scenarios"]["spi"]["degraded"] is True
    # the freshly recorded degraded window gates its own artifact green
    assert bench_gate.main([artifact_path, "--golden", golden_path]) == 0


def test_gate_rejects_empty_headline():
    ok, line = bench_gate.gate_artifact(
        {"scenario": "spi", "value": 0, "unit": "ops/sec"}, _golden())
    assert not ok and "no positive headline" in line


def test_update_golden_records_value_and_meta(tmp_path):
    golden_path = str(tmp_path / "baseline.json")
    artifact_path = str(tmp_path / "a.json")
    with open(artifact_path, "w") as f:
        json.dump(_artifact(value=12345.0), f)
    rc = bench_gate.main([artifact_path, "--golden", golden_path,
                          "--update-golden"])
    assert rc == 0
    golden = json.load(open(golden_path))
    assert golden["scenarios"]["spi"]["value"] == 12345.0
    assert golden["scenarios"]["spi"]["recorded"]["git_sha"] == "abc"
    # the freshly recorded window gates its own artifact green
    assert bench_gate.main([artifact_path, "--golden", golden_path]) == 0
    # and a regressed rerun red, printing the update command
    with open(artifact_path, "w") as f:
        json.dump(_artifact(value=3000.0), f)
    assert bench_gate.main([artifact_path, "--golden", golden_path]) == 1


def test_committed_golden_covers_the_ci_smokes():
    golden = bench_gate.load_golden(bench_gate.DEFAULT_GOLDEN)
    assert os.path.exists(bench_gate.DEFAULT_GOLDEN)
    for scenario in ("spi", "sharded", "apply"):
        entry = golden["scenarios"][scenario]
        assert entry["value"] > 0
        assert entry["unit"] == "ops/sec"
        # the recorded attribution explains a miss on a different host
        assert "host" in entry["recorded"]
        assert "knobs" in entry["recorded"]
    assert 0 < golden["tolerance"] < 1


def test_gate_tolerates_series_and_metrics_payloads(tmp_path):
    """Artifacts now carry the run's retained /series windows next to
    the metrics snapshots (bench.py SERIES_WINDOWS); the gate grades
    the headline value identically and never commits either bulky
    payload into the golden."""
    artifact = _artifact(value=8000)
    artifact["metrics"] = {"server": {"raft_term": 1}}
    artifact["series"] = {"server": {"node": "n", "role": "member",
                                     "samples": [{"t": 1.0,
                                                  "values": {"x": 1}}]}}
    ok, line = bench_gate.gate_artifact(artifact, _golden())
    assert ok and "ok 8,000.0" in line
    golden_path = tmp_path / "golden.json"
    golden = bench_gate.load_golden(str(golden_path))
    bench_gate.update_golden([artifact], golden)
    entry = golden["scenarios"]["spi"]
    assert "series" not in entry and "metrics" not in entry
    assert entry["value"] == 8000
