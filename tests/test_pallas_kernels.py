"""Pallas quorum-tally kernel tests (ops/pallas_kernels.py).

Differential against the jnp closed-form selection and against numpy
sort; plus a full consensus run with Config(use_pallas=True) — interpret
mode on CPU, Mosaic on TPU.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from copycat_tpu.ops.pallas_kernels import (  # noqa: E402
    kth_largest,
    kth_largest_pallas,
)


@pytest.mark.parametrize("P,k", [(3, 2), (5, 3), (7, 4), (4, 1), (3, 3)])
def test_kth_largest_matches_numpy(P, k):
    rng = np.random.default_rng(P * 10 + k)
    x = rng.integers(-100, 100, (257, P)).astype(np.int32)
    expect = np.sort(x, axis=1)[:, ::-1][:, k - 1]
    got = np.asarray(kth_largest(jnp.asarray(x), k))
    assert (got == expect).all()


@pytest.mark.parametrize("G", [64, 512, 1000])
def test_pallas_kernel_matches_reference(G):
    rng = np.random.default_rng(G)
    x = rng.integers(0, 1 << 20, (G, 3)).astype(np.int32)
    expect = np.asarray(kth_largest(jnp.asarray(x), 2))
    got = np.asarray(kth_largest_pallas(jnp.asarray(x), 2, block=256))
    assert (got == expect).all()


def test_pallas_with_duplicates():
    x = jnp.asarray([[5, 5, 5], [1, 1, 2], [0, 7, 7]], jnp.int32)
    got = np.asarray(kth_largest_pallas(x, 2, block=256))
    assert got.tolist() == [5, 1, 7]


def test_consensus_with_pallas_quorum():
    from copycat_tpu.models import RaftGroups
    from copycat_tpu.ops import apply as ap
    from copycat_tpu.ops.consensus import Config

    rg = RaftGroups(4, 3, log_slots=32, config=Config(use_pallas=True))
    rg.wait_for_leaders()
    tags = [rg.submit(g, ap.OP_LONG_ADD, g + 1) for g in range(4)
            for _ in range(3)]
    rg.run_until(tags)
    rg.run(5)
    val = np.asarray(rg.state.resources.value)
    for g in range(4):
        assert (val[g] == 3 * (g + 1)).all()
