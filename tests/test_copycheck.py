"""copycheck rule tests (copycat_tpu/analysis/ — docs/ANALYSIS.md).

Every rule gets a seeded-violation positive AND a clean negative, so a
rule that silently stops firing fails here before CI's `--strict` gate
goes blind. Engine behavior (suppressions, baseline, cache, exit codes)
is tested over a temp repo so the real tree's baseline never leaks in.
"""

import ast
import json
import os
import subprocess
import sys
import textwrap

from copycat_tpu.analysis import ALL_RULES
from copycat_tpu.analysis.engine import (
    LintContext,
    discover,
    lint_file,
    run_lint,
    update_wire_golden,
)
from copycat_tpu.analysis.findings import (
    Baseline,
    Finding,
    is_suppressed,
    scan_suppressions,
)
from copycat_tpu.analysis.rules_asyncio import (
    check_loop_blocking,
    check_orphan_task,
)
from copycat_tpu.analysis.rules_await_tear import check_await_tear
from copycat_tpu.analysis.rules_jit import check_jit_purity, collect_jit_roots
from copycat_tpu.analysis.rules_registries import (
    check_knob_registry,
    check_metric_registry,
    parse_knob_registry,
    parse_metric_catalog,
)
from copycat_tpu.analysis.rules_wire import check_wire_schema, render_golden

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tree(code: str) -> ast.Module:
    return ast.parse(textwrap.dedent(code))


# ---------------------------------------------------------------------------
# loop-blocking
# ---------------------------------------------------------------------------


def test_loop_blocking_flags_sleep_fsync_open_and_device_fetch():
    tree = _tree("""
        import time, os, jax

        async def bad(f):
            time.sleep(1)
            os.fsync(3)
            open("/tmp/x")
            jax.device_get(f)
            f.block_until_ready()
    """)
    rules = [f.message for f in check_loop_blocking(tree, "pkg/mod.py")]
    assert len(rules) == 5
    assert any("time.sleep" in m for m in rules)
    assert any("os.fsync" in m for m in rules)
    assert any("open" in m for m in rules)
    assert any("device_get" in m for m in rules)
    assert any("block_until_ready" in m for m in rules)


def test_loop_blocking_ignores_sync_defs_and_nested_sync_defs():
    tree = _tree("""
        import time

        def fine():
            time.sleep(1)

        async def outer():
            def helper():
                time.sleep(1)  # judged at helper's call site
            return helper
    """)
    assert check_loop_blocking(tree, "pkg/mod.py") == []


def test_loop_blocking_allows_asyncio_sleep():
    tree = _tree("""
        import asyncio

        async def fine():
            await asyncio.sleep(0.1)
    """)
    assert check_loop_blocking(tree, "pkg/mod.py") == []


# ---------------------------------------------------------------------------
# orphan-task
# ---------------------------------------------------------------------------


def test_orphan_task_flags_raw_spawns():
    tree = _tree("""
        import asyncio

        async def bad(loop, coro):
            loop.create_task(coro)
            asyncio.ensure_future(coro)
            asyncio.create_task(coro)
    """)
    found = check_orphan_task(tree, "pkg/mod.py")
    assert len(found) == 3
    assert all(f.rule == "orphan-task" for f in found)


def test_orphan_task_exempts_tasks_module_and_spawn_calls():
    tree = _tree("""
        from copycat_tpu.utils.tasks import spawn

        async def fine(coro):
            spawn(coro, name="x")
    """)
    assert check_orphan_task(tree, "pkg/mod.py") == []
    raw = _tree("async def f(loop, c):\n    loop.create_task(c)\n")
    assert check_orphan_task(raw, "copycat_tpu/utils/tasks.py") == []


def test_live_tree_has_no_raw_spawns():
    # the satellite fix: every create_task/ensure_future routed through
    # utils/tasks.spawn — keep it that way
    result = run_lint(root=REPO, use_cache=False)
    assert [f for f in result.findings if f.rule == "orphan-task"] == []


# ---------------------------------------------------------------------------
# await-tear
# ---------------------------------------------------------------------------

TEAR = """
    class RaftServer:
        async def transition(self, peer):
            term = self.term
            response = await self.send(peer, term)
            self.term = response.term
"""

GUARDED = """
    class RaftServer:
        async def transition(self, peer):
            term = self.term
            response = await self.send(peer, term)
            if self.term != term:
                return
            self.term = response.term
"""


def test_await_tear_flags_unguarded_write_after_await():
    found = check_await_tear(_tree(TEAR), "server/raft.py")
    assert len(found) == 1
    assert found[0].rule == "await-tear"
    assert "self.term" in found[0].message
    assert found[0].symbol == "RaftServer.transition"


def test_await_tear_accepts_epoch_guard():
    assert check_await_tear(_tree(GUARDED), "server/raft.py") == []


# The multi-raft refactor moved protected fields from ``self`` onto the
# group-state object (server/raft_group.py; server code reaches them
# through aliases like ``grp``): the rule keys events by (base, field),
# so a torn write through an alias still fires, a guard on the SAME base
# discharges it, and a guard on a DIFFERENT base does not.
GROUP_TEAR = """
    class RaftServer:
        async def transition(self, peer):
            grp = self.groups[0]
            term = grp.term
            response = await self.send(peer, term)
            grp.term = response.term
"""

GROUP_GUARDED = """
    class RaftServer:
        async def transition(self, peer):
            grp = self.groups[0]
            term = grp.term
            response = await self.send(peer, term)
            if grp.term != term:
                return
            grp.term = response.term
"""

GROUP_CROSS_BASE_GUARD = """
    class RaftServer:
        async def transition(self, peer, other):
            grp = self.groups[0]
            term = grp.term
            response = await self.send(peer, term)
            if other.term != term:
                return
            grp.term = response.term
"""


def test_await_tear_flags_group_state_write_after_await():
    found = check_await_tear(_tree(GROUP_TEAR), "server/raft_group.py")
    assert len(found) == 1
    assert "grp.term" in found[0].message


def test_await_tear_accepts_group_state_epoch_guard():
    assert check_await_tear(_tree(GROUP_GUARDED),
                            "server/raft_group.py") == []


def test_await_tear_guard_must_reread_the_same_base():
    found = check_await_tear(_tree(GROUP_CROSS_BASE_GUARD),
                             "server/raft_group.py")
    assert len(found) == 1
    assert "grp.term" in found[0].message


def test_await_tear_scope_covers_raft_group_file():
    # basename scope: the refactored per-group core is checked, other
    # modules are not
    assert check_await_tear(_tree(GROUP_TEAR), "server/raft_group.py")
    assert check_await_tear(_tree(GROUP_TEAR), "client/client.py") == []


def test_await_tear_accepts_role_guard_and_flags_log_tail():
    role_guard = _tree("""
        class RaftServer:
            async def ok(self):
                index = self.commit_index
                await self.quorum()
                if self.role != "leader":
                    return
                self.commit_index = index + 1
    """)
    assert check_await_tear(role_guard, "server/raft.py") == []
    log_tear = _tree("""
        class RaftServer:
            async def bad(self, entries):
                last = self.log.last_index
                await self.quorum()
                self.log.truncate(last)
    """)
    found = check_await_tear(log_tear, "server/raft.py")
    assert len(found) == 1 and "self.log" in found[0].message


def test_await_tear_ignores_pre_await_writes_and_other_files():
    pre = _tree("""
        class RaftServer:
            async def ok(self):
                self.term += 1
                await self.persist()
    """)
    assert check_await_tear(pre, "server/raft.py") == []
    # rule is scoped to raft modules
    assert check_await_tear(_tree(TEAR), "client/client.py") == []


def test_await_tear_live_tree_is_clean():
    result = run_lint(root=REPO, use_cache=False)
    assert [f for f in result.findings if f.rule == "await-tear"] == []


# ---------------------------------------------------------------------------
# knob-registry
# ---------------------------------------------------------------------------

KNOBS_SRC = '_knob("COPYCAT_GOOD", "int", 1, "doc", section="bench")\n'


def test_knob_registry_flags_direct_reads_and_unregistered_names():
    registered = parse_knob_registry(KNOBS_SRC)
    assert registered == {"COPYCAT_GOOD"}
    tree = _tree("""
        import os
        from copycat_tpu.utils import knobs

        a = os.environ.get("COPYCAT_GOOD", "1")
        b = os.getenv("COPYCAT_GOOD")
        c = os.environ["COPYCAT_GOOD"]
        d = knobs.get_int("COPYCAT_MISSING")
    """)
    found = check_knob_registry(tree, "copycat_tpu/mod.py", registered)
    assert len(found) == 4
    assert sum("direct env read" in f.message for f in found) == 3
    assert sum("not registered" in f.message for f in found) == 1


def test_knob_registry_allows_writes_typed_getters_and_knobs_module():
    registered = {"COPYCAT_GOOD"}
    tree = _tree("""
        import os
        from copycat_tpu.utils import knobs

        os.environ["COPYCAT_GOOD"] = "0"     # staging env for a child
        v = knobs.get_int("COPYCAT_GOOD")
        w = os.environ.get("OTHER_PREFIX")   # not a knob
    """)
    assert check_knob_registry(tree, "copycat_tpu/mod.py", registered) == []
    raw = _tree('x = os.environ.get("COPYCAT_GOOD")')
    assert check_knob_registry(raw, "copycat_tpu/utils/knobs.py",
                               registered) == []


def test_live_tree_knob_reads_all_routed():
    result = run_lint(root=REPO, use_cache=False)
    assert [f for f in result.findings if f.rule == "knob-registry"] == []


# ---------------------------------------------------------------------------
# metric-registry
# ---------------------------------------------------------------------------

CATALOG_MD = """
## Metric name catalog

| name | kind | meaning |
|---|---|---|
| `good_metric` | counter | fine |
| `labeled{lane}` | counter | fine |
"""


def test_metric_registry_flags_unknown_names_bad_labels_and_dynamic():
    catalog = parse_metric_catalog(CATALOG_MD)
    assert catalog == {"good_metric": set(), "labeled": {"lane"}}
    tree = _tree("""
        m.counter("good_metric")
        m.counter("labeled", lane="fast")
        m.counter("unknown_metric")
        m.counter("labeled", wrong="x")
        m.counter(dynamic_name)
    """)
    found = check_metric_registry(tree, "copycat_tpu/mod.py", catalog)
    msgs = [f.message for f in found]
    assert len(found) == 3
    assert any("unknown_metric" in m for m in msgs)
    assert any("labels {wrong}" in m for m in msgs)
    assert any("dynamic metric name" in m for m in msgs)


def test_metric_registry_checks_both_branches_of_a_ternary():
    catalog = {"a_metric": set(), "b_metric": set()}
    ok = _tree('m.counter("a_metric" if cond else "b_metric")')
    assert check_metric_registry(ok, "copycat_tpu/mod.py", catalog) == []
    bad = _tree('m.counter("a_metric" if cond else "nope")')
    found = check_metric_registry(bad, "copycat_tpu/mod.py", catalog)
    assert len(found) == 1 and "nope" in found[0].message


def test_live_tree_metric_names_all_cataloged():
    result = run_lint(root=REPO, use_cache=False)
    assert [f for f in result.findings if f.rule == "metric-registry"] == []


def test_catalog_has_no_orphan_entries():
    """Bidirectional sync: every catalog entry is recorded somewhere in
    the tree (a deleted metric must leave the catalog too)."""
    catalog = parse_metric_catalog(
        open(os.path.join(REPO, "docs", "OBSERVABILITY.md")).read())
    used: set[str] = set()
    for rel in discover(REPO):
        if not rel.startswith("copycat_tpu/"):
            continue
        tree = ast.parse(open(os.path.join(REPO, rel)).read())
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("counter", "gauge", "histogram",
                                           "timer")
                    and node.args):
                for arg in ([node.args[0].body, node.args[0].orelse]
                            if isinstance(node.args[0], ast.IfExp)
                            else [node.args[0]]):
                    if isinstance(arg, ast.Constant) and isinstance(
                            arg.value, str):
                        used.add(arg.value)
    # dynamic loops register the documented device.* families
    from copycat_tpu.models.telemetry import _COUNTERS, _GAUGES
    used |= set(_COUNTERS) | set(_GAUGES)
    orphans = set(catalog) - used
    assert not orphans, f"catalog entries no code records: {sorted(orphans)}"


# ---------------------------------------------------------------------------
# wire-schema
# ---------------------------------------------------------------------------

WIRE_OK = """
    @serialize_with(200)
    class Ping(Message):
        _fields = ("a", "b")
"""


def test_wire_schema_detects_drift_reorder_and_duplicate_ids():
    golden = {"200": ["Ping", ["a", "b"]]}
    assert check_wire_schema(_tree(WIRE_OK),
                             "copycat_tpu/protocol/messages.py",
                             golden) == []
    reordered = _tree("""
        @serialize_with(200)
        class Ping(Message):
            _fields = ("b", "a")
    """)
    found = check_wire_schema(reordered,
                              "copycat_tpu/protocol/messages.py", golden)
    assert len(found) == 1 and "drifted" in found[0].message
    assert "--update-golden" in found[0].message
    dup = _tree("""
        @serialize_with(200)
        class Ping(Message):
            _fields = ("a",)

        @serialize_with(200)
        class Pong(Message):
            _fields = ("b",)
    """)
    found = check_wire_schema(dup, "copycat_tpu/protocol/messages.py",
                              golden)
    assert any("reused" in f.message for f in found)


def test_wire_schema_flags_new_and_removed_ids():
    golden = {"200": ["Ping", ["a", "b"]], "201": ["Pong", ["c"]]}
    found = check_wire_schema(_tree(WIRE_OK),
                              "copycat_tpu/protocol/messages.py", golden)
    assert len(found) == 1 and "disappeared" in found[0].message
    added = _tree(WIRE_OK + """
    @serialize_with(202)
    class New(Message):
        _fields = ("x",)
    """)
    found = check_wire_schema(added, "copycat_tpu/protocol/messages.py",
                              {"200": ["Ping", ["a", "b"]]})
    assert len(found) == 1 and "new" in found[0].message


def test_wire_golden_matches_live_messages():
    src = open(os.path.join(REPO, "copycat_tpu", "protocol",
                            "messages.py")).read()
    rendered = render_golden(ast.parse(src))
    committed = open(os.path.join(REPO, "tests", "golden",
                                  "wire_schema.json")).read()
    assert rendered == committed, (
        "protocol/messages.py schema drifted from tests/golden/"
        "wire_schema.json — if intentional, regenerate with "
        "`copycat-tpu lint --update-golden` and commit the diff")


# ---------------------------------------------------------------------------
# jit-purity
# ---------------------------------------------------------------------------


def test_jit_purity_flags_impurity_reachable_from_jitted_root():
    jitter = _tree("step_fn = jax.jit(partial(step, config=c))")
    roots = collect_jit_roots({"models/raft_groups.py": jitter})
    assert "step" in roots
    opsmod = _tree("""
        import time

        def helper(x):
            return time.time() + x

        def step(state):
            return helper(state)

        def unrelated():
            return time.time()
    """)
    found = check_jit_purity(opsmod, "copycat_tpu/ops/consensus.py", roots)
    assert len(found) == 1
    assert found[0].symbol == "helper"
    assert "time.time" in found[0].message


def test_jit_purity_allows_jax_random_and_non_ops_files():
    roots = {"step"}
    opsmod = _tree("""
        def step(key):
            return jax.random.split(key)
    """)
    assert check_jit_purity(opsmod, "copycat_tpu/ops/consensus.py",
                            roots) == []
    impure = _tree("""
        import time

        def step(x):
            return time.time()
    """)
    assert check_jit_purity(impure, "copycat_tpu/models/bulk.py",
                            roots) == []


def test_jit_purity_decorated_roots_and_callbacks():
    tree = _tree("""
        import functools, jax

        @functools.partial(jax.jit, static_argnames=("k",))
        def topk(x, k):
            jax.debug.callback(print, x)
            return x
    """)
    roots = collect_jit_roots({"copycat_tpu/ops/pallas_kernels.py": tree})
    assert "topk" in roots
    found = check_jit_purity(tree, "copycat_tpu/ops/pallas_kernels.py",
                             roots)
    assert len(found) == 1 and "callback" in found[0].message


def test_live_ops_tree_is_pure():
    result = run_lint(root=REPO, use_cache=False)
    assert [f for f in result.findings if f.rule == "jit-purity"] == []


# ---------------------------------------------------------------------------
# engine: suppressions, baseline, cache, CLI
# ---------------------------------------------------------------------------


def test_suppression_scoping():
    src = ("import time\n"
           "async def f():\n"
           "    time.sleep(1)  # copycheck: ignore[loop-blocking] why\n"
           "    # copycheck: ignore[loop-blocking] next line\n"
           "    time.sleep(2)\n"
           "    time.sleep(3)\n")
    sups = scan_suppressions(src)
    tree = ast.parse(src)
    found = check_loop_blocking(tree, "m.py")
    assert len(found) == 3
    suppressed = [f for f in found if is_suppressed(f, sups)]
    assert {f.line for f in suppressed} == {3, 5}
    # a different rule on the same line is NOT suppressed
    other = Finding(rule="orphan-task", path="m.py", line=3, message="x")
    assert not is_suppressed(other, sups)
    # the documented wildcard covers every rule on its line
    wild = scan_suppressions("x()  # copycheck: ignore[*] escape hatch\n")
    assert is_suppressed(
        Finding(rule="orphan-task", path="m.py", line=1, message="x"), wild)


def _mini_repo(tmp_path, body):
    """A temp repo shaped like ours: package + a file with findings."""
    pkg = tmp_path / "copycat_tpu"
    (pkg / "utils").mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "utils" / "__init__.py").write_text("")
    (pkg / "utils" / "knobs.py").write_text(KNOBS_SRC)
    (pkg / "mod.py").write_text(body)
    return tmp_path


def test_engine_baseline_carries_findings_and_reports_stale(tmp_path):
    root = _mini_repo(
        tmp_path, "async def f(loop, c):\n    loop.create_task(c)\n")
    result = run_lint(root=str(root), use_cache=False)
    assert len(result.findings) == 1
    bl = Baseline()
    bl.entries[result.findings[0].identity()] = "kept: test"
    bl.entries[("orphan-task", "copycat_tpu/gone.py", "f", "old")] = "stale"
    bl_path = str(tmp_path / "bl.json")
    bl.save(bl_path)
    result = run_lint(root=str(root), baseline_path=bl_path,
                      use_cache=False)
    assert result.findings == []
    assert len(result.baselined) == 1
    assert len(result.stale_baseline) == 1


def test_strict_fails_and_reports_stale_baseline(tmp_path):
    from copycat_tpu.analysis.engine import render_text

    root = _mini_repo(tmp_path, "async def f():\n    pass\n")
    bl = Baseline()
    bl.entries[("orphan-task", "copycat_tpu/gone.py", "f", "old")] = "gone"
    bl_path = str(tmp_path / "bl.json")
    bl.save(bl_path)
    result = run_lint(root=str(root), baseline_path=bl_path,
                      use_cache=False)
    assert result.findings == [] and len(result.stale_baseline) == 1
    # strict: status line and exit path agree (a stale entry is a FAIL)
    assert "copycheck: FAIL" in render_text(result, strict=True)
    assert "copycheck: ok" in render_text(result, strict=False)


def test_engine_cache_hits_and_invalidates(tmp_path):
    root = _mini_repo(
        tmp_path, "async def f(loop, c):\n    loop.create_task(c)\n")
    r1 = run_lint(root=str(root), use_cache=True)
    assert len(r1.findings) == 1
    cache_path = root / ".copycheck-cache.json"
    assert cache_path.exists()
    cached = json.loads(cache_path.read_text())
    assert "copycat_tpu/mod.py" in cached["files"]
    # warm hit returns identical findings
    r2 = run_lint(root=str(root), use_cache=True)
    assert [f.to_json() for f in r2.findings] == \
        [f.to_json() for f in r1.findings]
    # editing the file invalidates just that entry
    (root / "copycat_tpu" / "mod.py").write_text("async def f():\n    pass\n")
    r3 = run_lint(root=str(root), use_cache=True)
    assert r3.findings == []


def test_cli_lint_exit_codes(tmp_path):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # the lint path never needs jax
    clean = subprocess.run(
        [sys.executable, "-m", "copycat_tpu.analysis", "--strict",
         "--no-cache"],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert "copycheck: ok" in clean.stdout
    # a seeded violation flips the exit code
    bad = tmp_path / "bad_raft.py"
    bad.write_text("async def f(loop, c):\n    loop.create_task(c)\n")
    dirty = subprocess.run(
        [sys.executable, "-m", "copycat_tpu.analysis", "--no-cache",
         str(bad)],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert dirty.returncode == 1
    assert "orphan-task" in dirty.stdout


def test_all_rules_have_coverage_here():
    """Every rule name is exercised by at least one seeded violation in
    this file — a new rule without a fixture test fails the suite."""
    src = open(__file__, encoding="utf-8").read()
    for rule in ALL_RULES:
        assert rule in src, f"rule {rule} has no fixture coverage"


def test_update_golden_roundtrip(tmp_path, monkeypatch):
    # regeneration produces exactly the committed artifact (idempotent)
    committed = open(os.path.join(REPO, "tests", "golden",
                                  "wire_schema.json")).read()
    import shutil

    root = tmp_path / "repo"
    (root / "copycat_tpu" / "protocol").mkdir(parents=True)
    shutil.copy(os.path.join(REPO, "copycat_tpu", "protocol",
                             "messages.py"),
                root / "copycat_tpu" / "protocol" / "messages.py")
    path = update_wire_golden(root=str(root))
    assert open(path).read() == committed
