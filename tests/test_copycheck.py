"""copycheck rule tests (copycat_tpu/analysis/ — docs/ANALYSIS.md).

Every rule gets a seeded-violation positive AND a clean negative, so a
rule that silently stops firing fails here before CI's `--strict` gate
goes blind. Engine behavior (suppressions, baseline, cache, exit codes)
is tested over a temp repo so the real tree's baseline never leaks in.
"""

import ast
import json
import os
import subprocess
import sys
import textwrap

from copycat_tpu.analysis import ALL_RULES
from copycat_tpu.analysis.engine import (
    LintContext,
    discover,
    lint_file,
    run_lint,
    update_wire_golden,
)
from copycat_tpu.analysis.findings import (
    Baseline,
    Finding,
    is_suppressed,
    scan_suppressions,
)
from copycat_tpu.analysis.rules_asyncio import (
    check_loop_blocking,
    check_orphan_task,
)
from copycat_tpu.analysis.callgraph import CallGraph
from copycat_tpu.analysis.rules_await_tear import check_await_tear
from copycat_tpu.analysis.rules_contracts import (
    check_durability_order,
    check_exit_contract,
    check_span_contract,
    parse_exit_codes,
    parse_span_catalog,
)
from copycat_tpu.analysis.rules_jit import check_jit_purity, collect_jit_roots
from copycat_tpu.analysis.rules_registries import (
    check_knob_registry,
    check_metric_registry,
    parse_knob_registry,
    parse_metric_catalog,
)
from copycat_tpu.analysis.rules_wire import check_wire_schema, render_golden

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tree(code: str) -> ast.Module:
    return ast.parse(textwrap.dedent(code))


# ---------------------------------------------------------------------------
# loop-blocking
# ---------------------------------------------------------------------------


def test_loop_blocking_flags_sleep_fsync_open_and_device_fetch():
    tree = _tree("""
        import time, os, jax

        async def bad(f):
            time.sleep(1)
            os.fsync(3)
            open("/tmp/x")
            jax.device_get(f)
            f.block_until_ready()
    """)
    rules = [f.message for f in check_loop_blocking(tree, "pkg/mod.py")]
    assert len(rules) == 5
    assert any("time.sleep" in m for m in rules)
    assert any("os.fsync" in m for m in rules)
    assert any("open" in m for m in rules)
    assert any("device_get" in m for m in rules)
    assert any("block_until_ready" in m for m in rules)


def test_loop_blocking_ignores_sync_defs_and_nested_sync_defs():
    tree = _tree("""
        import time

        def fine():
            time.sleep(1)

        async def outer():
            def helper():
                time.sleep(1)  # judged at helper's call site
            return helper
    """)
    assert check_loop_blocking(tree, "pkg/mod.py") == []


def test_loop_blocking_allows_asyncio_sleep():
    tree = _tree("""
        import asyncio

        async def fine():
            await asyncio.sleep(0.1)
    """)
    assert check_loop_blocking(tree, "pkg/mod.py") == []


def _graph(path: str, code: str) -> tuple[ast.Module, CallGraph]:
    tree = _tree(code)
    return tree, CallGraph.build({path: tree})


def test_loop_blocking_interprocedural_reaches_into_sync_helpers():
    # the v2 tentpole: the blocking call sits in a SYNC helper — lexically
    # invisible to the v1 rule — and is flagged because the call graph
    # proves the helper reachable from an async def
    tree, graph = _graph("pkg/mod.py", """
        import subprocess

        def run_tool(cmd):
            return subprocess.run(cmd)

        async def pump(cmd):
            return run_tool(cmd)
    """)
    assert check_loop_blocking(tree, "pkg/mod.py") == []  # lexical-only: blind
    found = check_loop_blocking(tree, "pkg/mod.py", graph)
    assert len(found) == 1
    assert found[0].symbol == "run_tool"
    assert "reachable from an async def" in found[0].message
    assert found[0].via == ["pkg/mod.py::pump", "pkg/mod.py::run_tool"]
    # ...and the chain closes transitively through sync middlemen
    tree2, graph2 = _graph("pkg/mod.py", """
        import subprocess

        def inner(cmd):
            return subprocess.run(cmd)

        def outer(cmd):
            return inner(cmd)

        async def pump(cmd):
            return outer(cmd)
    """)
    found = check_loop_blocking(tree2, "pkg/mod.py", graph2)
    assert len(found) == 1 and found[0].symbol == "inner"
    assert found[0].via[-1] == "pkg/mod.py::inner"


def test_loop_blocking_spares_helpers_no_async_def_reaches():
    tree, graph = _graph("pkg/mod.py", """
        import subprocess

        def run_tool(cmd):
            return subprocess.run(cmd)

        def sync_caller(cmd):
            return run_tool(cmd)
    """)
    assert check_loop_blocking(tree, "pkg/mod.py", graph) == []


def test_loop_blocking_deploy_plane_blocklist_entries():
    # the post-PR 7 hazards: child-process waits, blocking connects,
    # sync stream copies (the deploy plane's bread and butter)
    tree = _tree("""
        import os, socket, shutil, subprocess

        async def bad(a, b, proc):
            os.waitpid(1, 0)
            socket.create_connection(("host", 1))
            shutil.copyfileobj(a, b)
            subprocess.check_output(["x"])
            proc.wait()
    """)
    found = check_loop_blocking(tree, "pkg/mod.py")
    assert len(found) == 5


def test_loop_blocking_awaited_wait_is_the_asyncio_form():
    # `proc.wait()` blocks (Popen.wait); `await proc.wait()` is the
    # asyncio.subprocess coroutine — only the bare call is a finding
    tree = _tree("""
        import asyncio

        async def fine(proc, cond):
            await proc.wait()
            await asyncio.wait_for(cond.wait(), 1.0)

        async def bad(proc):
            proc.wait()
    """)
    found = check_loop_blocking(tree, "pkg/mod.py")
    assert len(found) == 1 and found[0].symbol == "bad"


def test_loop_blocking_live_tree_is_clean():
    result = run_lint(root=REPO, use_cache=False)
    assert [f for f in result.findings if f.rule == "loop-blocking"] == []


# ---------------------------------------------------------------------------
# orphan-task
# ---------------------------------------------------------------------------


def test_orphan_task_flags_raw_spawns():
    tree = _tree("""
        import asyncio

        async def bad(loop, coro):
            loop.create_task(coro)
            asyncio.ensure_future(coro)
            asyncio.create_task(coro)
    """)
    found = check_orphan_task(tree, "pkg/mod.py")
    assert len(found) == 3
    assert all(f.rule == "orphan-task" for f in found)


def test_orphan_task_exempts_tasks_module_and_spawn_calls():
    tree = _tree("""
        from copycat_tpu.utils.tasks import spawn

        async def fine(coro):
            spawn(coro, name="x")
    """)
    assert check_orphan_task(tree, "pkg/mod.py") == []
    raw = _tree("async def f(loop, c):\n    loop.create_task(c)\n")
    assert check_orphan_task(raw, "copycat_tpu/utils/tasks.py") == []


def test_live_tree_has_no_raw_spawns():
    # the satellite fix: every create_task/ensure_future routed through
    # utils/tasks.spawn — keep it that way
    result = run_lint(root=REPO, use_cache=False)
    assert [f for f in result.findings if f.rule == "orphan-task"] == []


# ---------------------------------------------------------------------------
# await-tear
# ---------------------------------------------------------------------------

TEAR = """
    class RaftServer:
        async def transition(self, peer):
            term = self.term
            response = await self.send(peer, term)
            self.term = response.term
"""

GUARDED = """
    class RaftServer:
        async def transition(self, peer):
            term = self.term
            response = await self.send(peer, term)
            if self.term != term:
                return
            self.term = response.term
"""


def test_await_tear_flags_unguarded_write_after_await():
    found = check_await_tear(_tree(TEAR), "server/raft.py")
    assert len(found) == 1
    assert found[0].rule == "await-tear"
    assert "self.term" in found[0].message
    assert found[0].symbol == "RaftServer.transition"


def test_await_tear_accepts_epoch_guard():
    assert check_await_tear(_tree(GUARDED), "server/raft.py") == []


# The multi-raft refactor moved protected fields from ``self`` onto the
# group-state object (server/raft_group.py; server code reaches them
# through aliases like ``grp``): the rule keys events by (base, field),
# so a torn write through an alias still fires, a guard on the SAME base
# discharges it, and a guard on a DIFFERENT base does not.
GROUP_TEAR = """
    class RaftServer:
        async def transition(self, peer):
            grp = self.groups[0]
            term = grp.term
            response = await self.send(peer, term)
            grp.term = response.term
"""

GROUP_GUARDED = """
    class RaftServer:
        async def transition(self, peer):
            grp = self.groups[0]
            term = grp.term
            response = await self.send(peer, term)
            if grp.term != term:
                return
            grp.term = response.term
"""

GROUP_CROSS_BASE_GUARD = """
    class RaftServer:
        async def transition(self, peer, other):
            grp = self.groups[0]
            term = grp.term
            response = await self.send(peer, term)
            if other.term != term:
                return
            grp.term = response.term
"""


def test_await_tear_flags_group_state_write_after_await():
    found = check_await_tear(_tree(GROUP_TEAR), "server/raft_group.py")
    assert len(found) == 1
    assert "grp.term" in found[0].message


def test_await_tear_accepts_group_state_epoch_guard():
    assert check_await_tear(_tree(GROUP_GUARDED),
                            "server/raft_group.py") == []


def test_await_tear_guard_must_reread_the_same_base():
    found = check_await_tear(_tree(GROUP_CROSS_BASE_GUARD),
                             "server/raft_group.py")
    assert len(found) == 1
    assert "grp.term" in found[0].message


def test_await_tear_scope_covers_raft_group_file():
    # basename scope: the refactored per-group core is checked, other
    # modules are not
    assert check_await_tear(_tree(GROUP_TEAR), "server/raft_group.py")
    assert check_await_tear(_tree(GROUP_TEAR), "client/client.py") == []


def test_await_tear_accepts_role_guard_and_flags_log_tail():
    role_guard = _tree("""
        class RaftServer:
            async def ok(self):
                index = self.commit_index
                await self.quorum()
                if self.role != "leader":
                    return
                self.commit_index = index + 1
    """)
    assert check_await_tear(role_guard, "server/raft.py") == []
    log_tear = _tree("""
        class RaftServer:
            async def bad(self, entries):
                last = self.log.last_index
                await self.quorum()
                self.log.truncate(last)
    """)
    found = check_await_tear(log_tear, "server/raft.py")
    assert len(found) == 1 and "self.log" in found[0].message


def test_await_tear_ignores_pre_await_writes_and_other_files():
    pre = _tree("""
        class RaftServer:
            async def ok(self):
                self.term += 1
                await self.persist()
    """)
    assert check_await_tear(pre, "server/raft.py") == []
    # rule is scoped to raft modules
    assert check_await_tear(_tree(TEAR), "client/client.py") == []


def test_await_tear_live_tree_is_clean():
    result = run_lint(root=REPO, use_cache=False)
    assert [f for f in result.findings if f.rule == "await-tear"] == []


# --- interprocedural (copycheck v2): the call graph closes the two
# lexical blind spots — writes hidden in called helpers, and suspension
# classification in both directions -----------------------------------------

HIDDEN_WRITE = """
    class RaftGroup:
        def _commit_term(self, t):
            self.term = t

        async def transition(self, peer):
            term = self.term
            response = await self.send(peer, term)
            self._commit_term(response.term)
"""


def test_await_tear_interprocedural_flags_write_hidden_in_helper():
    # the fixture the lexical rule PROVABLY missed: no attribute store
    # is lexically visible after the await — the torn write hides inside
    # the called helper, surfaced by the effect summary
    tree = _tree(HIDDEN_WRITE)
    assert check_await_tear(tree, "server/raft_group.py") == []  # v1 view
    graph = CallGraph.build({"server/raft_group.py": tree})
    found = check_await_tear(tree, "server/raft_group.py", graph)
    assert len(found) == 1
    assert "write hidden in" in found[0].message
    assert "self.term" in found[0].message
    assert found[0].via == ["server/raft_group.py::RaftGroup._commit_term"]


def test_await_tear_interprocedural_guard_still_discharges_hidden_write():
    tree = _tree("""
        class RaftGroup:
            def _commit_term(self, t):
                self.term = t

            async def transition(self, peer):
                term = self.term
                response = await self.send(peer, term)
                if self.term != term:
                    return
                self._commit_term(response.term)
    """)
    graph = CallGraph.build({"server/raft_group.py": tree})
    assert check_await_tear(tree, "server/raft_group.py", graph) == []


def test_await_tear_never_suspending_await_is_not_an_interleaving_point():
    # precision the lexical rule lacked the OTHER way: an await of a
    # local coroutine with no yield point of its own cannot interleave
    tree = _tree("""
        class RaftGroup:
            async def _bump(self, x):
                return x + 1

            async def transition(self):
                term = self.term
                term = await self._bump(term)
                self.term = term
    """)
    assert len(check_await_tear(tree, "server/raft.py")) == 1  # v1: flagged
    graph = CallGraph.build({"server/raft.py": tree})
    assert check_await_tear(tree, "server/raft.py", graph) == []


def test_await_tear_async_with_is_a_suspension_point():
    # `async with` acquires on entry — a yield point with no Await node,
    # invisible to the lexical rule
    tree = _tree("""
        class RaftGroup:
            async def transition(self):
                term = self.term
                async with self.gate:
                    self.term = term + 1
    """)
    graph = CallGraph.build({"server/raft.py": tree})
    found = check_await_tear(tree, "server/raft.py", graph)
    assert len(found) == 1 and "self.term" in found[0].message


def test_await_tear_summary_cache_never_keeps_truncated_entries():
    # regression: summarizing `_a` walks _b/_c/_d at depths 1-3 and the
    # depth cap truncates `_w`'s write out of `_d`'s summary — that
    # truncated view must NOT be cached, or the later direct
    # `self._d()` call site (a fresh depth-0 query) misses a real tear
    tree = _tree("""
        class RaftGroup:
            def _w(self):
                self.term = 0

            def _d(self):
                self._w()

            def _c(self):
                self._d()

            def _b(self):
                self._c()

            def _a(self):
                self._b()

            async def deep(self, peer):
                t = self.term
                await self.send(peer)
                self._a()

            async def shallow(self, peer):
                t = self.term
                await self.send(peer)
                self._d()
    """)
    graph = CallGraph.build({"server/raft_group.py": tree})
    found = check_await_tear(tree, "server/raft_group.py", graph)
    assert [f.symbol for f in found] == ["RaftGroup.shallow"]


def test_callgraph_ambiguous_module_basename_stays_conservative():
    # two homonymous modules both define `load`: resolution must refuse
    # to guess (a wrong never-suspending guess would un-flag a real
    # interleaving point) — the await stays a suspension and the tear
    # fires; with the ambiguity removed, the never-suspending resolution
    # discharges it
    raft = _tree("""
        from copycat_tpu.client import state

        class RaftGroup:
            async def t(self):
                term = self.term
                await state.load()
                self.term = term + 1
    """)
    pure_state = _tree("async def load():\n    return 1\n")
    trees = {"server/raft.py": raft,
             "client/state.py": pure_state,
             "server/state.py": _tree("async def load():\n    return 2\n")}
    graph = CallGraph.build(trees)
    assert len(check_await_tear(raft, "server/raft.py", graph)) == 1
    unique = CallGraph.build({"server/raft.py": raft,
                              "client/state.py": pure_state})
    assert check_await_tear(raft, "server/raft.py", unique) == []


def test_loop_blocking_skips_nested_defs_inside_reachable_sync_helpers():
    # a nested def inside a sync helper is a callback, not inline code:
    # reachability must not descend into it (same rule as nested defs
    # inside async defs — judged where something calls it)
    tree, graph = _graph("pkg/mod.py", """
        import shutil

        def helper(tmp, bus):
            def on_done():
                shutil.rmtree(tmp)
            bus.subscribe(on_done)

        async def pump(tmp, bus):
            helper(tmp, bus)
    """)
    assert check_loop_blocking(tree, "pkg/mod.py", graph) == []


def test_await_tear_scope_covers_the_deploy_plane():
    # the compartmentalized tiers run the same ordering contracts in
    # their own processes — in scope since v2
    assert check_await_tear(_tree(TEAR), "copycat_tpu/deploy/ingress.py")
    assert check_await_tear(_tree(TEAR), "copycat_tpu/deploy/supervisor.py")
    assert check_await_tear(_tree(TEAR), "copycat_tpu/deploy/topology.py") == []


# ---------------------------------------------------------------------------
# durability-order
# ---------------------------------------------------------------------------

RESOLVE_BEFORE_SYNC = """
    class RaftGroup:
        def on_quorum(self, index, result):
            fut = self._commit_futures.pop(index, None)
            if fut is not None and not fut.done():
                fut.set_result((index, result, None))
            self._sync_log()
"""

RESOLVE_AFTER_SYNC = """
    class RaftGroup:
        def on_quorum(self, index, result):
            self._sync_log()
            fut = self._commit_futures.pop(index, None)
            if fut is not None and not fut.done():
                fut.set_result((index, result, None))
"""


def test_durability_order_flags_resolve_before_sync():
    # the seeded fixture from the issue: the future resolves BEFORE the
    # commit-boundary fsync — an acknowledged write a power loss erases
    found = check_durability_order(_tree(RESOLVE_BEFORE_SYNC),
                                   "server/raft_group.py")
    assert len(found) == 1
    assert found[0].rule == "durability-order"
    assert "fut" in found[0].message
    assert found[0].symbol == "RaftGroup.on_quorum"


def test_durability_order_accepts_resolve_dominated_by_sync():
    assert check_durability_order(_tree(RESOLVE_AFTER_SYNC),
                                  "server/raft_group.py") == []


def test_durability_order_dominance_closes_through_class_callers():
    # the ack lives in a helper with no sync of its own: discharged
    # because every same-class caller reaches it past a commit-boundary
    # sync — and NOT discharged once the helper is also entered from
    # outside the class (the fused-dispatch seam)
    src = """
        class RaftGroup:
            def advance(self, index, result):
                self._sync_log()
                self._resolve(index, result)

            def _resolve(self, index, result):
                fut = self._commit_futures.pop(index, None)
                fut.set_result((index, result, None))
    """
    assert check_durability_order(_tree(src), "server/raft_group.py") == []
    found = check_durability_order(_tree(src), "server/raft_group.py",
                                   external_attr_calls={"_resolve"})
    assert len(found) == 1 and found[0].symbol == "RaftGroup._resolve"


def test_durability_order_exempts_error_resolves_and_other_classes():
    # a payload naming an msg.ERROR_CODE constant reports failure — it
    # acknowledges nothing; and the rule is scoped to RaftGroup
    err = _tree("""
        class RaftGroup:
            def reject(self, index):
                fut = self._commit_futures.pop(index, None)
                if fut is not None:
                    fut.set_result((index, None, msg.NO_LEADER))
    """)
    assert check_durability_order(err, "server/raft_group.py") == []
    other = _tree(RESOLVE_BEFORE_SYNC.replace("RaftGroup", "ReadIndexPlane"))
    assert check_durability_order(other, "server/raft_group.py") == []
    assert check_durability_order(_tree(RESOLVE_BEFORE_SYNC),
                                  "client/client.py") == []


def test_durability_order_flags_undominated_success_append_ack():
    tree = _tree("""
        class RaftGroup:
            def on_append(self, request):
                self.log.append_replicated_block(request.entries)
                return AppendResponse(term=self.term, success=True)
    """)
    found = check_durability_order(tree, "server/raft_group.py")
    assert len(found) == 1 and "success append ack" in found[0].message
    synced = _tree("""
        class RaftGroup:
            def on_append(self, request):
                self.log.append_replicated_block(request.entries)
                self._sync_log()
                return AppendResponse(term=self.term, success=True)
    """)
    assert check_durability_order(synced, "server/raft_group.py") == []


def test_durability_order_live_tree_carries_only_justified_baselines():
    result = run_lint(root=REPO, use_cache=False)
    assert [f for f in result.findings if f.rule == "durability-order"] == []
    # the fused-dispatch seam findings ride the baseline, each with a
    # written dominance argument (no TODO placeholders — CI's contract)
    carried = [f for f in result.baselined if f.rule == "durability-order"]
    assert carried, "the fused-seam findings should be baselined, not gone"
    baseline = json.load(open(os.path.join(REPO, ".copycheck-baseline.json")))
    for entry in baseline["findings"]:
        assert entry["justification"].strip(), entry
        assert "TODO" not in entry["justification"], entry


# ---------------------------------------------------------------------------
# span-pairing
# ---------------------------------------------------------------------------

SPAN_VOCAB_MD = """
### Span-name vocabulary

| name | phase |
|---|---|
| `quorum.wait` | commit |
| `group.fsync` | commit |
"""


def test_span_pairing_validates_names_against_the_vocabulary():
    catalog = parse_span_catalog(SPAN_VOCAB_MD)
    assert catalog == {"quorum.wait", "group.fsync"}
    tree = _tree("""
        class G:
            def ok(self, trace, t0, t1):
                self._trace_span(trace, "quorum.wait", t0, t1)

            def bad(self, trace, t0, t1):
                self._trace_span(trace, "quorum.wiat", t0, t1)
    """)
    found = check_span_contract(tree, "copycat_tpu/server/raft_group.py",
                                catalog)
    assert len(found) == 1
    assert "quorum.wiat" in found[0].message
    assert found[0].symbol == "G.bad"


def test_span_pairing_forwarding_wrappers_and_dynamic_names():
    catalog = {"quorum.wait"}
    # the name is a parameter of the enclosing function: a forwarding
    # wrapper — its CALLERS are checked instead
    wrapper = _tree("""
        class G:
            def _trace_span(self, trace, name, start, end):
                self.tracer.span(trace, name, start, end)
    """)
    assert check_span_contract(wrapper, "copycat_tpu/server/raft.py",
                               catalog) == []
    # any other dynamic name is a finding (it dodges the vocabulary)
    dynamic = _tree("""
        class G:
            def record(self, trace, t0, t1):
                self.tracer.span(trace, self.pick_name(), t0, t1)
    """)
    found = check_span_contract(dynamic, "copycat_tpu/server/raft.py",
                                catalog)
    assert len(found) == 1 and "dynamic span name" in found[0].message


def test_span_pairing_flags_with_over_span_and_bare_timer():
    tree = _tree("""
        class G:
            def timed(self, trace, metrics, t0, t1):
                with self.tracer.span(trace, "quorum.wait", t0, t1):
                    pass
                metrics.timer("commit_ms")
                with metrics.timer("commit_ms"):
                    pass
    """)
    found = check_span_contract(tree, "copycat_tpu/server/raft.py",
                                {"quorum.wait"})
    msgs = [f.message for f in found]
    assert len(found) == 2
    assert any("`with` over a span-record call" in m for m in msgs)
    assert any("opened and discarded" in m for m in msgs)


def test_span_pairing_flags_call_missing_timestamps():
    # the record family's signature is (trace, name, start, end, ...):
    # a 3-arg call has no end timestamp — nothing pairable is recorded
    tree = _tree("""
        class G:
            def bad(self, trace, t0):
                self._trace_span(trace, "quorum.wait", t0)
    """)
    found = check_span_contract(tree, "copycat_tpu/server/raft.py",
                                {"quorum.wait"})
    assert len(found) == 1 and "fewer than 4" in found[0].message


def test_durability_order_error_exemption_is_msg_scoped():
    # only msg.X constants mark an error resolve; an unrelated all-caps
    # constant in a SUCCESS payload must not dodge the dominance check
    tree = _tree("""
        class RaftGroup:
            def resolve(self, index):
                fut = self._commit_futures.pop(index, None)
                fut.set_result((index, cfg.MAX_INFLIGHT, None))
    """)
    found = check_durability_order(tree, "server/raft_group.py")
    assert len(found) == 1


def test_span_pairing_live_tree_names_all_in_vocabulary():
    catalog = parse_span_catalog(
        open(os.path.join(REPO, "docs", "OBSERVABILITY.md")).read())
    assert catalog and "quorum.wait" in catalog
    result = run_lint(root=REPO, use_cache=False)
    assert [f for f in result.findings if f.rule == "span-pairing"] == []


def test_span_pairing_admits_edge_spans_and_still_fires_uncataloged():
    """The edge read tier's span names (docs/EDGE_READS.md) are in the
    REAL vocabulary table, and the rule still fires on an uncataloged
    edge-adjacent name — the seeded-violation proof that adding rows
    did not blunt the gate."""
    catalog = parse_span_catalog(
        open(os.path.join(REPO, "docs", "OBSERVABILITY.md")).read())
    assert {"client.edge_serve", "client.delta"} <= catalog
    tree = _tree("""
        class EdgeReadTier:
            def ok(self, tracer, trace, t0, t1):
                tracer.span(trace, "client.edge_serve", t0, t1)
                tracer.span(trace, "client.delta", t0, t1)

            def bad(self, tracer, trace, t0, t1):
                tracer.span(trace, "client.edge_servee", t0, t1)
    """)
    found = check_span_contract(tree, "copycat_tpu/client/edge.py",
                                catalog)
    assert len(found) == 1
    assert "client.edge_servee" in found[0].message


# ---------------------------------------------------------------------------
# exit-code
# ---------------------------------------------------------------------------


def test_exit_code_contract_flags_undocumented_codes():
    codes = parse_exit_codes(
        open(os.path.join(REPO, "docs", "DEPLOYMENT.md")).read())
    assert codes == {0, 1, 2}
    tree = _tree("""
        import sys

        def main():
            if bad_config():
                sys.exit(2)
            if crashed():
                sys.exit(1)
            sys.exit(3)
    """)
    found = check_exit_contract(tree, "copycat_tpu/deploy/child.py", codes)
    assert len(found) == 1
    assert "exit code 3" in found[0].message
    # scope: only the deploy-plane mains are under the contract
    assert check_exit_contract(tree, "copycat_tpu/bench.py", codes) == []


def test_exit_code_contract_sees_negative_literals():
    # sys.exit(-1) is a UnaryOp, not a Constant — and 255 at the
    # process boundary, squarely in the crash-restart lane
    tree = _tree("""
        import sys

        def main():
            sys.exit(-1)
    """)
    found = check_exit_contract(tree, "copycat_tpu/deploy/child.py",
                                {0, 1, 2})
    assert len(found) == 1 and "exit code -1" in found[0].message
    # strings exit with code 1 (the documented crash code) — not flagged
    s = _tree("import sys\nsys.exit('bad config')\n")
    assert check_exit_contract(s, "copycat_tpu/deploy/child.py",
                               {0, 1, 2}) == []


def test_exit_code_contract_live_tree_is_clean():
    result = run_lint(root=REPO, use_cache=False)
    assert [f for f in result.findings if f.rule == "exit-code"] == []


# ---------------------------------------------------------------------------
# knob-registry
# ---------------------------------------------------------------------------

KNOBS_SRC = '_knob("COPYCAT_GOOD", "int", 1, "doc", section="bench")\n'


def test_knob_registry_flags_direct_reads_and_unregistered_names():
    registered = parse_knob_registry(KNOBS_SRC)
    assert registered == {"COPYCAT_GOOD"}
    tree = _tree("""
        import os
        from copycat_tpu.utils import knobs

        a = os.environ.get("COPYCAT_GOOD", "1")
        b = os.getenv("COPYCAT_GOOD")
        c = os.environ["COPYCAT_GOOD"]
        d = knobs.get_int("COPYCAT_MISSING")
    """)
    found = check_knob_registry(tree, "copycat_tpu/mod.py", registered)
    assert len(found) == 4
    assert sum("direct env read" in f.message for f in found) == 3
    assert sum("not registered" in f.message for f in found) == 1


def test_knob_registry_allows_writes_typed_getters_and_knobs_module():
    registered = {"COPYCAT_GOOD"}
    tree = _tree("""
        import os
        from copycat_tpu.utils import knobs

        os.environ["COPYCAT_GOOD"] = "0"     # staging env for a child
        v = knobs.get_int("COPYCAT_GOOD")
        w = os.environ.get("OTHER_PREFIX")   # not a knob
    """)
    assert check_knob_registry(tree, "copycat_tpu/mod.py", registered) == []
    raw = _tree('x = os.environ.get("COPYCAT_GOOD")')
    assert check_knob_registry(raw, "copycat_tpu/utils/knobs.py",
                               registered) == []


def test_live_tree_knob_reads_all_routed():
    result = run_lint(root=REPO, use_cache=False)
    assert [f for f in result.findings if f.rule == "knob-registry"] == []


# ---------------------------------------------------------------------------
# metric-registry
# ---------------------------------------------------------------------------

CATALOG_MD = """
## Metric name catalog

| name | kind | meaning |
|---|---|---|
| `good_metric` | counter | fine |
| `labeled{lane}` | counter | fine |
"""


def test_metric_registry_flags_unknown_names_bad_labels_and_dynamic():
    catalog = parse_metric_catalog(CATALOG_MD)
    assert catalog == {"good_metric": set(), "labeled": {"lane"}}
    tree = _tree("""
        m.counter("good_metric")
        m.counter("labeled", lane="fast")
        m.counter("unknown_metric")
        m.counter("labeled", wrong="x")
        m.counter(dynamic_name)
    """)
    found = check_metric_registry(tree, "copycat_tpu/mod.py", catalog)
    msgs = [f.message for f in found]
    assert len(found) == 3
    assert any("unknown_metric" in m for m in msgs)
    assert any("labels {wrong}" in m for m in msgs)
    assert any("dynamic metric name" in m for m in msgs)


def test_metric_registry_checks_both_branches_of_a_ternary():
    catalog = {"a_metric": set(), "b_metric": set()}
    ok = _tree('m.counter("a_metric" if cond else "b_metric")')
    assert check_metric_registry(ok, "copycat_tpu/mod.py", catalog) == []
    bad = _tree('m.counter("a_metric" if cond else "nope")')
    found = check_metric_registry(bad, "copycat_tpu/mod.py", catalog)
    assert len(found) == 1 and "nope" in found[0].message


def test_live_tree_metric_names_all_cataloged():
    result = run_lint(root=REPO, use_cache=False)
    assert [f for f in result.findings if f.rule == "metric-registry"] == []


def test_catalog_has_no_orphan_entries():
    """Bidirectional sync: every catalog entry is recorded somewhere in
    the tree (a deleted metric must leave the catalog too)."""
    catalog = parse_metric_catalog(
        open(os.path.join(REPO, "docs", "OBSERVABILITY.md")).read())
    used: set[str] = set()
    for rel in discover(REPO):
        if not rel.startswith("copycat_tpu/"):
            continue
        tree = ast.parse(open(os.path.join(REPO, rel)).read())
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("counter", "gauge", "histogram",
                                           "timer")
                    and node.args):
                for arg in ([node.args[0].body, node.args[0].orelse]
                            if isinstance(node.args[0], ast.IfExp)
                            else [node.args[0]]):
                    if isinstance(arg, ast.Constant) and isinstance(
                            arg.value, str):
                        used.add(arg.value)
    # dynamic loops register the documented device.* families
    from copycat_tpu.models.telemetry import _COUNTERS, _GAUGES
    used |= set(_COUNTERS) | set(_GAUGES)
    orphans = set(catalog) - used
    assert not orphans, f"catalog entries no code records: {sorted(orphans)}"


# ---------------------------------------------------------------------------
# wire-schema
# ---------------------------------------------------------------------------

WIRE_OK = """
    @serialize_with(200)
    class Ping(Message):
        _fields = ("a", "b")
"""


def test_wire_schema_detects_drift_reorder_and_duplicate_ids():
    golden = {"200": ["Ping", ["a", "b"]]}
    assert check_wire_schema(_tree(WIRE_OK),
                             "copycat_tpu/protocol/messages.py",
                             golden) == []
    reordered = _tree("""
        @serialize_with(200)
        class Ping(Message):
            _fields = ("b", "a")
    """)
    found = check_wire_schema(reordered,
                              "copycat_tpu/protocol/messages.py", golden)
    assert len(found) == 1 and "drifted" in found[0].message
    assert "--update-golden" in found[0].message
    dup = _tree("""
        @serialize_with(200)
        class Ping(Message):
            _fields = ("a",)

        @serialize_with(200)
        class Pong(Message):
            _fields = ("b",)
    """)
    found = check_wire_schema(dup, "copycat_tpu/protocol/messages.py",
                              golden)
    assert any("reused" in f.message for f in found)


def test_wire_schema_flags_new_and_removed_ids():
    golden = {"200": ["Ping", ["a", "b"]], "201": ["Pong", ["c"]]}
    found = check_wire_schema(_tree(WIRE_OK),
                              "copycat_tpu/protocol/messages.py", golden)
    assert len(found) == 1 and "disappeared" in found[0].message
    added = _tree(WIRE_OK + """
    @serialize_with(202)
    class New(Message):
        _fields = ("x",)
    """)
    found = check_wire_schema(added, "copycat_tpu/protocol/messages.py",
                              {"200": ["Ping", ["a", "b"]]})
    assert len(found) == 1 and "new" in found[0].message


def test_wire_golden_matches_live_messages():
    src = open(os.path.join(REPO, "copycat_tpu", "protocol",
                            "messages.py")).read()
    rendered = render_golden(ast.parse(src))
    committed = open(os.path.join(REPO, "tests", "golden",
                                  "wire_schema.json")).read()
    assert rendered == committed, (
        "protocol/messages.py schema drifted from tests/golden/"
        "wire_schema.json — if intentional, regenerate with "
        "`copycat-tpu lint --update-golden` and commit the diff")


# ---------------------------------------------------------------------------
# jit-purity
# ---------------------------------------------------------------------------


def test_jit_purity_flags_impurity_reachable_from_jitted_root():
    jitter = _tree("step_fn = jax.jit(partial(step, config=c))")
    roots = collect_jit_roots({"models/raft_groups.py": jitter})
    assert "step" in roots
    opsmod = _tree("""
        import time

        def helper(x):
            return time.time() + x

        def step(state):
            return helper(state)

        def unrelated():
            return time.time()
    """)
    found = check_jit_purity(opsmod, "copycat_tpu/ops/consensus.py", roots)
    assert len(found) == 1
    assert found[0].symbol == "helper"
    assert "time.time" in found[0].message


def test_jit_purity_allows_jax_random_and_non_ops_files():
    roots = {"step"}
    opsmod = _tree("""
        def step(key):
            return jax.random.split(key)
    """)
    assert check_jit_purity(opsmod, "copycat_tpu/ops/consensus.py",
                            roots) == []
    impure = _tree("""
        import time

        def step(x):
            return time.time()
    """)
    assert check_jit_purity(impure, "copycat_tpu/models/bulk.py",
                            roots) == []


def test_jit_purity_decorated_roots_and_callbacks():
    tree = _tree("""
        import functools, jax

        @functools.partial(jax.jit, static_argnames=("k",))
        def topk(x, k):
            jax.debug.callback(print, x)
            return x
    """)
    roots = collect_jit_roots({"copycat_tpu/ops/pallas_kernels.py": tree})
    assert "topk" in roots
    found = check_jit_purity(tree, "copycat_tpu/ops/pallas_kernels.py",
                             roots)
    assert len(found) == 1 and "callback" in found[0].message


def test_live_ops_tree_is_pure():
    result = run_lint(root=REPO, use_cache=False)
    assert [f for f in result.findings if f.rule == "jit-purity"] == []


# ---------------------------------------------------------------------------
# engine: suppressions, baseline, cache, CLI
# ---------------------------------------------------------------------------


def test_suppression_scoping():
    src = ("import time\n"
           "async def f():\n"
           "    time.sleep(1)  # copycheck: ignore[loop-blocking] why\n"
           "    # copycheck: ignore[loop-blocking] next line\n"
           "    time.sleep(2)\n"
           "    time.sleep(3)\n")
    sups = scan_suppressions(src)
    tree = ast.parse(src)
    found = check_loop_blocking(tree, "m.py")
    assert len(found) == 3
    suppressed = [f for f in found if is_suppressed(f, sups)]
    assert {f.line for f in suppressed} == {3, 5}
    # a different rule on the same line is NOT suppressed
    other = Finding(rule="orphan-task", path="m.py", line=3, message="x")
    assert not is_suppressed(other, sups)
    # the documented wildcard covers every rule on its line
    wild = scan_suppressions("x()  # copycheck: ignore[*] escape hatch\n")
    assert is_suppressed(
        Finding(rule="orphan-task", path="m.py", line=1, message="x"), wild)


def _mini_repo(tmp_path, body):
    """A temp repo shaped like ours: package + a file with findings."""
    pkg = tmp_path / "copycat_tpu"
    (pkg / "utils").mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "utils" / "__init__.py").write_text("")
    (pkg / "utils" / "knobs.py").write_text(KNOBS_SRC)
    (pkg / "mod.py").write_text(body)
    return tmp_path


def test_engine_baseline_carries_findings_and_reports_stale(tmp_path):
    root = _mini_repo(
        tmp_path, "async def f(loop, c):\n    loop.create_task(c)\n")
    result = run_lint(root=str(root), use_cache=False)
    assert len(result.findings) == 1
    bl = Baseline()
    bl.entries[result.findings[0].identity()] = "kept: test"
    bl.entries[("orphan-task", "copycat_tpu/gone.py", "f", "old")] = "stale"
    bl_path = str(tmp_path / "bl.json")
    bl.save(bl_path)
    result = run_lint(root=str(root), baseline_path=bl_path,
                      use_cache=False)
    assert result.findings == []
    assert len(result.baselined) == 1
    assert len(result.stale_baseline) == 1


def test_strict_fails_and_reports_stale_baseline(tmp_path):
    from copycat_tpu.analysis.engine import render_text

    root = _mini_repo(tmp_path, "async def f():\n    pass\n")
    bl = Baseline()
    bl.entries[("orphan-task", "copycat_tpu/gone.py", "f", "old")] = "gone"
    bl_path = str(tmp_path / "bl.json")
    bl.save(bl_path)
    result = run_lint(root=str(root), baseline_path=bl_path,
                      use_cache=False)
    assert result.findings == [] and len(result.stale_baseline) == 1
    # strict: status line and exit path agree (a stale entry is a FAIL)
    assert "copycheck: FAIL" in render_text(result, strict=True)
    assert "copycheck: ok" in render_text(result, strict=False)


def test_engine_cache_hits_and_invalidates(tmp_path):
    root = _mini_repo(
        tmp_path, "async def f(loop, c):\n    loop.create_task(c)\n")
    r1 = run_lint(root=str(root), use_cache=True)
    assert len(r1.findings) == 1
    cache_path = root / ".copycheck-cache.json"
    assert cache_path.exists()
    cached = json.loads(cache_path.read_text())
    assert "copycat_tpu/mod.py" in cached["files"]
    # warm hit returns identical findings
    r2 = run_lint(root=str(root), use_cache=True)
    assert [f.to_json() for f in r2.findings] == \
        [f.to_json() for f in r1.findings]
    # editing the file invalidates just that entry
    (root / "copycat_tpu" / "mod.py").write_text("async def f():\n    pass\n")
    r3 = run_lint(root=str(root), use_cache=True)
    assert r3.findings == []


def test_engine_cache_invalidates_per_rule_group(tmp_path, monkeypatch):
    """The v2 cache satellite: editing ONE rule module re-lints only its
    group — every other group's cached results survive."""
    from copycat_tpu.analysis import engine

    root = _mini_repo(
        tmp_path, "async def f(loop, c):\n    loop.create_task(c)\n")
    r1 = run_lint(root=str(root), use_cache=True)
    assert len(r1.findings) == 1

    import collections
    counts: collections.Counter = collections.Counter()
    for spec in engine.RULE_GROUPS:
        def counted(path, src, tree, ctx, _key=spec.key, _orig=spec.run):
            counts[_key] += 1
            return _orig(path, src, tree, ctx)

        monkeypatch.setattr(spec, "run", counted)

    # warm run: every group is a cache hit, nothing recomputes
    r2 = run_lint(root=str(root), use_cache=True)
    assert not counts
    assert [f.to_json() for f in r2.findings] == \
        [f.to_json() for f in r1.findings]

    # "edit" one rule module: exactly that group recomputes
    real = engine._analysis_source
    monkeypatch.setattr(
        engine, "_analysis_source",
        lambda mod: real(mod) + ("\n# edited" if mod == "rules_wire.py"
                                 else ""))
    r3 = run_lint(root=str(root), use_cache=True)
    assert set(counts) == {"wire"}
    assert [f.to_json() for f in r3.findings] == \
        [f.to_json() for f in r1.findings]


def test_sarif_emitter_levels_and_suppressions(tmp_path):
    from copycat_tpu.analysis.engine import render_sarif

    root = _mini_repo(tmp_path, (
        "async def f(loop, c):\n"
        "    loop.create_task(c)\n"
        "    loop.create_task(c)  # copycheck: ignore[orphan-task] test\n"))
    result = run_lint(root=str(root), use_cache=False)
    assert len(result.findings) == 1 and len(result.suppressed) == 1
    doc = json.loads(render_sarif(result))
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "copycheck"
    assert {"id": "orphan-task"} in run["tool"]["driver"]["rules"]
    live = [r for r in run["results"] if "suppressions" not in r]
    sup = [r for r in run["results"] if "suppressions" in r]
    assert len(live) == 1 and live[0]["level"] == "error"
    assert live[0]["ruleId"] == "orphan-task"
    loc = live[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "copycat_tpu/mod.py"
    assert loc["region"]["startLine"] == 2
    assert live[0]["partialFingerprints"]["copycheckIdentity/v1"]
    assert len(sup) == 1
    assert sup[0]["suppressions"] == [{"kind": "inSource"}]
    assert sup[0]["level"] == "note"


def _git(root, *argv):
    subprocess.run(["git", "-c", "user.email=t@t", "-c", "user.name=t",
                    *argv], cwd=root, check=True, capture_output=True)


def test_changed_mode_filters_findings_to_the_diff(tmp_path):
    root = _mini_repo(
        tmp_path, "async def f(loop, c):\n    loop.create_task(c)\n")
    _git(root, "init", "-q")
    _git(root, "add", "-A")
    _git(root, "commit", "-qm", "seed")
    # an UNTRACKED module with a violation: the diff gate must see it
    (root / "copycat_tpu" / "fresh.py").write_text(
        "async def g(loop, c):\n    loop.create_task(c)\n")
    full = run_lint(root=str(root), use_cache=False)
    assert sorted(f.path for f in full.findings) == [
        "copycat_tpu/fresh.py", "copycat_tpu/mod.py"]
    diff = run_lint(root=str(root), use_cache=False, changed_base="HEAD")
    assert diff.changed_files == ["copycat_tpu/fresh.py"]
    # the committed file's finding is out of scope; analysis still ran
    # package-wide (files count is the whole tree)
    assert [f.path for f in diff.findings] == ["copycat_tpu/fresh.py"]
    assert diff.files == full.files


def test_changed_mode_uses_merge_base_not_two_dot(tmp_path):
    # a branch BEHIND the base rev must not inherit files only the
    # base's own history changed (two-dot `git diff BASE` would)
    root = _mini_repo(
        tmp_path, "async def f(loop, c):\n    loop.create_task(c)\n")
    _git(root, "init", "-q", "-b", "main")
    _git(root, "add", "-A")
    _git(root, "commit", "-qm", "seed")
    _git(root, "branch", "feature")
    # main moves ahead with its own violating module...
    (root / "copycat_tpu" / "mainonly.py").write_text(
        "async def m(loop, c):\n    loop.create_task(c)\n")
    _git(root, "add", "-A")
    _git(root, "commit", "-qm", "main moves on")
    # ...while the PR branch (behind main) adds just its own file
    _git(root, "checkout", "-q", "feature")
    (root / "copycat_tpu" / "fresh.py").write_text(
        "async def g(loop, c):\n    loop.create_task(c)\n")
    diff = run_lint(root=str(root), use_cache=False,
                    changed_base="main")
    assert diff.changed_files == ["copycat_tpu/fresh.py"]
    assert [f.path for f in diff.findings] == ["copycat_tpu/fresh.py"]


def test_write_baseline_refuses_changed_scope(tmp_path, capsys):
    from copycat_tpu.analysis.engine import main as lint_main

    import pytest
    with pytest.raises(SystemExit) as exc:
        lint_main(["--write-baseline", "--changed", "HEAD"])
    assert exc.value.code == 2
    assert "--write-baseline needs the full-tree view" in \
        capsys.readouterr().err


def test_cli_lint_exit_codes(tmp_path):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # the lint path never needs jax
    clean = subprocess.run(
        [sys.executable, "-m", "copycat_tpu.analysis", "--strict",
         "--no-cache"],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert "copycheck: ok" in clean.stdout
    # a seeded violation flips the exit code
    bad = tmp_path / "bad_raft.py"
    bad.write_text("async def f(loop, c):\n    loop.create_task(c)\n")
    dirty = subprocess.run(
        [sys.executable, "-m", "copycat_tpu.analysis", "--no-cache",
         str(bad)],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert dirty.returncode == 1
    assert "orphan-task" in dirty.stdout


def test_all_rules_have_coverage_here():
    """Every rule name is exercised by at least one seeded violation in
    this file — a new rule without a fixture test fails the suite."""
    src = open(__file__, encoding="utf-8").read()
    for rule in ALL_RULES:
        assert rule in src, f"rule {rule} has no fixture coverage"


def test_update_golden_roundtrip(tmp_path, monkeypatch):
    # regeneration produces exactly the committed artifact (idempotent)
    committed = open(os.path.join(REPO, "tests", "golden",
                                  "wire_schema.json")).read()
    import shutil

    root = tmp_path / "repo"
    (root / "copycat_tpu" / "protocol").mkdir(parents=True)
    shutil.copy(os.path.join(REPO, "copycat_tpu", "protocol",
                             "messages.py"),
                root / "copycat_tpu" / "protocol" / "messages.py")
    path = update_wire_golden(root=str(root))
    assert open(path).read() == committed
