"""SPMD worker for the multihost DEEP-drive test (VERDICT r4 #2): both
processes run THIS program over a 2-process × 4-virtual-CPU-device mesh
with a monotone-tag engine, and drive it through the SESSIONED bulk
client — the unified plane (sessions + deep pipeline + multihost) in
one program. Asymmetric per-process loads exercise the agreed
accumulator sizing and the empty-window padding (process 1 submits a
quarter of process 0's ops, and one wave is entirely empty on
process 1). Launched by tests/test_multihost.py."""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from copycat_tpu.models import BulkSessionClient  # noqa: E402
from copycat_tpu.ops import apply as ap  # noqa: E402
from copycat_tpu.ops.consensus import Config  # noqa: E402
from copycat_tpu.parallel import multihost  # noqa: E402


def main() -> None:
    coord, nproc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    multihost.initialize(coord, num_processes=nproc, process_id=pid)
    rg = multihost.MultiHostRaftGroups(
        groups_per_process=4, num_peers=3, log_slots=32,
        config=Config(monotone_tag_accept=True))
    rg.wait_for_leaders()

    client = BulkSessionClient(rg)
    s = client.open_session()

    # wave 1: asymmetric — process 0 submits 64 ops, process 1 only 16,
    # so the agreed accumulator width comes from process 0 and process 1
    # pads with empty dispatch windows.
    n_ops = 64 if pid == 0 else 16
    seqs = s.submit_batch(np.arange(n_ops) % 4, ap.OP_LONG_ADD, 1)
    client.flush()
    vals = s.results_window(int(seqs[0]), n_ops)
    # per-group FIFO: results of group g's ops are its running count
    per_group = n_ops // 4
    fifo_ok = all(
        list(vals[np.arange(n_ops) % 4 == g]) == list(
            range(1, per_group + 1))
        for g in range(4))

    # wave 2: ENTIRELY empty on process 1 (local n=0 through a
    # collective drive)
    if pid == 0:
        s.submit_batch([0] * 8, ap.OP_LONG_ADD, 1)
    client.flush()

    # wave 3: deep drive under a PARTIAL delivery mask — peer lane 2 cut
    # everywhere (quorum {0,1} keeps committing; phase-2 suffix retries
    # absorb any leader shuffle). Both processes install the same local
    # mask — the staged global deliver stays lockstep-consistent.
    cut = np.ones((4, 3, 3), bool)
    cut[:, 2, :] = False
    cut[:, :, 2] = False
    healthy = rg.deliver
    rg.deliver = rg._stage_deliver(cut)
    s.submit_batch(np.arange(4), ap.OP_LONG_ADD, 100)
    client.flush()
    rg.deliver = healthy

    # read back through the lockstep query lane: local group 0 sums to
    # per_group (+8 for process 0's second wave) + 100 from the fault wave
    v0 = rg.serve_query(0, ap.OP_VALUE_GET)
    expect0 = per_group + (8 if pid == 0 else 0) + 100

    print("RESULT " + json.dumps(
        {"pid": pid, "fifo_ok": bool(fifo_ok), "v0": v0,
         "expect0": expect0, "committed": int(n_ops)}), flush=True)


if __name__ == "__main__":
    main()
