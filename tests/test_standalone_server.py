"""Cross-process server: the packaged `copycat-server` driven by a real
remote client over TCP.

Single-process tests import the whole package, so they can never catch a
server that fails to REGISTER the resource catalog with the serializer —
which is exactly what happened through round 4: a standalone server
could not decode ``GetResource("x", DistributedAtomicValue)`` from a
client ("unknown class id 56") because class references travel by
registry id (the documented Class.forName deviation) and the server
process had never imported ``atomic/``. This test runs the server in a
REAL subprocess (fresh interpreter, fresh registry) like a user would.
"""

import asyncio
import os
import subprocess
import sys

import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.atomic import DistributedAtomicLong, DistributedAtomicValue  # noqa: E402
from copycat_tpu.io.tcp import TcpTransport  # noqa: E402
from copycat_tpu.io.transport import Address  # noqa: E402
from copycat_tpu.manager.atomix import AtomixClient  # noqa: E402

from helpers import async_test  # noqa: E402

PORT = 19341  # fixed high port; TIME_WAIT is fine (fresh listen each run)


@async_test(timeout=240)
async def test_packaged_server_serves_remote_client():
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(__file__)))
    # log to a FILE, not a pipe: an undrained pipe fills at ~64KB and
    # blocks the server mid-run (review finding)
    import tempfile
    logf = tempfile.NamedTemporaryFile("w+b", suffix=".log", delete=False)
    proc = subprocess.Popen(
        [sys.executable, "-c",
         f"from copycat_tpu.cli import server; server(['127.0.0.1:{PORT}'])"],
        env=env, stdout=logf, stderr=subprocess.STDOUT)
    try:
        client = (AtomixClient.builder([Address("127.0.0.1", PORT)])
                  .with_transport(TcpTransport()).build())
        # server boot = jax import + election; retry until reachable
        for attempt in range(40):
            try:
                await asyncio.wait_for(client.open(), 15)
                break
            except Exception:
                if proc.poll() is not None:
                    logf.seek(0)
                    out = logf.read().decode(errors="replace")
                    pytest.fail(f"server died rc={proc.returncode}: "
                                f"{out[-800:]}")
                await asyncio.sleep(2)
        else:
            pytest.fail("client never connected")

        value = await client.get("value", DistributedAtomicValue)
        await value.set("hello")
        assert await value.get() == "hello"

        counter = await client.get("hits", DistributedAtomicLong)
        assert await counter.increment_and_get() == 1
        assert await counter.increment_and_get() == 2

        await client.close()
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
