"""utils/knobs.py registry tests (satellite of the copycheck PR).

Three sync properties, asserted — not hand-maintained:

1. README's *Knob reference* section is byte-identical to the
   registry's renderer (regenerate: ``python -m copycat_tpu.utils.knobs``);
2. every ``COPYCAT_*`` name the tree passes to ``knobs.get_*`` is
   registered, and every registered knob is actually read somewhere
   (no zombie registry rows);
3. the typed getters honor env overrides, call-site defaults for
   computed knobs, and the documented bool normalization.
"""

import ast
import os

import pytest

from copycat_tpu.analysis.engine import discover
from copycat_tpu.utils import knobs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _knob_literals_in_tree() -> set[str]:
    """Every COPYCAT_* name passed to a knobs getter anywhere."""
    used: set[str] = set()
    getters = set(knobs.__dict__) & {
        "get_raw", "get_str", "get_int", "get_float", "get_bool"}
    for rel in discover(REPO):
        tree = ast.parse(open(os.path.join(REPO, rel),
                              encoding="utf-8").read())
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in getters and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                used.add(node.args[0].value)
    return used


def test_readme_knob_table_in_sync():
    readme = open(os.path.join(REPO, "README.md"), encoding="utf-8").read()
    section = knobs.readme_section(readme)
    assert section is not None, "README lost the knobs:begin/end markers"
    assert section == knobs.render_markdown(), (
        "README Knob reference drifted from utils/knobs.py — regenerate "
        "with `python -m copycat_tpu.utils.knobs` and paste between the "
        "markers (or fix the registry)")


def test_every_used_knob_is_registered_and_vice_versa():
    used = _knob_literals_in_tree()
    registered = set(knobs.REGISTRY)
    assert used - registered == set(), (
        f"unregistered knobs in code: {sorted(used - registered)}")
    # knobs passed by parameter (require_devices(env=...)) reach the
    # getters as variables, so they can't be collected statically —
    # they're exactly the platform probe family
    indirect = {"COPYCAT_DEVICE_TIMEOUT", "COPYCAT_DEVICE_PROBES",
                "COPYCAT_ENTRY_DEVICE_TIMEOUT",
                "COPYCAT_BENCH_DEVICE_TIMEOUT",
                "COPYCAT_VERDICT_DEVICE_TIMEOUT"}
    zombies = registered - used - indirect
    assert zombies == set(), (
        f"registered knobs no code reads: {sorted(zombies)}")


def test_registry_docs_complete():
    for knob in knobs.REGISTRY.values():
        assert knob.doc.strip(), f"{knob.name} has no doc"
        assert knob.kind in ("int", "float", "str", "bool", "raw"), knob
        assert knob.default_text(), knob.name
        if knob.default is None and knob.kind != "raw":
            # computed default: the call site must pass default=, and
            # the README needs a human-readable rule
            assert knob.default_doc, (
                f"{knob.name}: computed default needs default_doc")


def test_typed_getters(monkeypatch):
    monkeypatch.delenv("COPYCAT_BENCH_ROUNDS", raising=False)
    assert knobs.get_int("COPYCAT_BENCH_ROUNDS") == 200
    monkeypatch.setenv("COPYCAT_BENCH_ROUNDS", "7")
    assert knobs.get_int("COPYCAT_BENCH_ROUNDS") == 7

    monkeypatch.delenv("COPYCAT_REPL_MAX_INFLIGHT", raising=False)
    # computed default: registry has none, the call site provides it
    assert knobs.get_int("COPYCAT_REPL_MAX_INFLIGHT", default=512) == 512
    with pytest.raises(ValueError):
        knobs.get_int("COPYCAT_REPL_MAX_INFLIGHT")

    monkeypatch.setenv("COPYCAT_CLUSTER_NOPE", "1")
    with pytest.raises(KeyError):
        knobs.get_int("COPYCAT_CLUSTER_NOPE")


def test_bool_normalization(monkeypatch):
    monkeypatch.delenv("COPYCAT_SNAPSHOTS", raising=False)
    assert knobs.get_bool("COPYCAT_SNAPSHOTS") is True  # registered default
    for off in ("0", "false", "OFF", "no", ""):
        monkeypatch.setenv("COPYCAT_SNAPSHOTS", off)
        assert knobs.get_bool("COPYCAT_SNAPSHOTS") is False, off
    for on in ("1", "true", "yes", "on"):
        monkeypatch.setenv("COPYCAT_SNAPSHOTS", on)
        assert knobs.get_bool("COPYCAT_SNAPSHOTS") is True, on


def test_raw_tristate(monkeypatch):
    monkeypatch.delenv("COPYCAT_INVARIANTS", raising=False)
    assert knobs.get_raw("COPYCAT_INVARIANTS") is None
    monkeypatch.setenv("COPYCAT_INVARIANTS", "strict")
    assert knobs.get_raw("COPYCAT_INVARIANTS") == "strict"


def test_cli_renders_the_readme_body(capsys):
    knobs.main()
    out = capsys.readouterr().out
    assert out == knobs.render_markdown()
    assert "| `COPYCAT_SNAPSHOTS` | `1` |" in out
