"""Multi-raft keyspace sharding (docs/SHARDING.md): hash-routing
stability, leadership spread, the proxy ingress, per-group event
channels, and the single-group differential.

The load-bearing contracts:

- resource→group assignment is a pure function of (key, group count) —
  deterministic across restarts and IDENTICAL on every member (a member
  disagreeing about ownership would apply a command to the wrong shard);
- session events route back from the OWNING group's replicated apply on
  the ingress member, each group numbering its own event channel;
- ``--groups 1`` / ``COPYCAT_MULTI_GROUP=0`` IS the pre-refactor
  single-group plane: same logs, same command stream, same responses.
"""

from __future__ import annotations

import asyncio
import zlib

import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.client.client import PinnedConnectionStrategy, RaftClient  # noqa: E402
from copycat_tpu.io.local import LocalServerRegistry, LocalTransport  # noqa: E402
from copycat_tpu.io.serializer import serialize_with  # noqa: E402
from copycat_tpu.io.transport import Address  # noqa: E402
from copycat_tpu.manager.operations import (  # noqa: E402
    GetResource,
    InstanceCommand,
)
from copycat_tpu.manager.state import ResourceManager  # noqa: E402
from copycat_tpu.protocol.messages import Message  # noqa: E402
from copycat_tpu.protocol.operations import Command  # noqa: E402
from copycat_tpu.server.raft import LEADER, RaftServer  # noqa: E402
from copycat_tpu.server.state_machine import Commit  # noqa: E402

from helpers import async_test  # noqa: E402
from raft_fixtures import Get, KVStateMachine, Put, SeqGet, next_ports  # noqa: E402


@serialize_with(930)
class NotifyKey(Message, Command):
    """Publishes an event from the group OWNING ``key``."""

    _fields = ("key", "payload")


class ShardedKV(KVStateMachine):
    """KV fixture with stable hash routing (the bench/test shard shape)."""

    def configure(self, executor) -> None:
        super().configure(executor)
        executor.register(NotifyKey, self.notify_key)

    def notify_key(self, commit: "Commit") -> str:
        commit.session.publish(
            "poked", (commit.operation.key, commit.operation.payload))
        return commit.operation.key

    @classmethod
    def route_group(cls, operation, groups: int) -> int:
        key = getattr(operation, "key", None)
        if isinstance(key, str):
            return zlib.crc32(key.encode()) % groups
        return 0


async def sharded_cluster(n: int = 3, groups: int = 4,
                          machine_cls=ShardedKV,
                          session_timeout: float = 30.0):
    registry = LocalServerRegistry()
    addresses = next_ports(n)
    servers = [
        RaftServer(addr, addresses,
                   LocalTransport(registry, local_address=addr),
                   (lambda g: machine_cls()), groups=groups,
                   election_timeout=0.2, heartbeat_interval=0.04,
                   session_timeout=session_timeout)
        for addr in addresses]
    await asyncio.gather(*(s.open() for s in servers))
    deadline = asyncio.get_running_loop().time() + 15
    while asyncio.get_running_loop().time() < deadline:
        led = {g.group_id for s in servers for g in s.groups
               if g.role == LEADER}
        if len(led) == groups:
            return registry, servers
        await asyncio.sleep(0.02)
    raise TimeoutError("not every group elected a leader")


async def close_all(servers, *clients) -> None:
    for c in clients:
        try:
            await asyncio.wait_for(c.close(), 5)
        except (Exception, asyncio.TimeoutError):
            pass
    for s in servers:
        try:
            await asyncio.wait_for(s.close(), 5)
        except (Exception, asyncio.TimeoutError):
            pass


# ---------------------------------------------------------------------------
# hash-routing stability
# ---------------------------------------------------------------------------


def test_route_group_is_deterministic_and_restart_stable():
    """The routing function is pure: same (operation, groups) -> same
    group on every call, every instance, every 'process' — it must never
    depend on object identity, dict order, or PYTHONHASHSEED (which is
    why it is crc32, not hash())."""
    keys = [f"resource-{i}" for i in range(100)]
    for groups in (1, 2, 4, 7):
        first = [ResourceManager.route_group(GetResource(k, None), groups)
                 for k in keys]
        again = [ResourceManager.route_group(GetResource(k, None), groups)
                 for k in keys]
        assert first == again
        expected = [zlib.crc32(k.encode()) % groups for k in keys]
        assert first == expected
        assert all(0 <= g < groups for g in first)
    # instance ops are self-routing: ids carry their group residue
    for groups in (2, 4):
        for raw_index in (3, 10, 57):
            for g in range(groups):
                iid = raw_index * groups + g
                assert ResourceManager.route_group(
                    InstanceCommand(resource=iid, operation=None),
                    groups) == g


def test_manager_ids_are_group_stamped_and_unsharded_identity():
    mgr = ResourceManager(group_id=3, num_groups=4)
    assert mgr.num_groups == 4 and mgr.group_id == 3
    # the id a commit at index 7 would mint: 7*4+3 — residue = group
    assert (7 * 4 + 3) % 4 == 3
    # single-group managers mint raw indices (the pre-sharding ids)
    plain = ResourceManager()
    assert plain.num_groups == 1 and plain.group_id == 0


@async_test(timeout=120)
async def test_resource_placement_identical_on_every_member():
    """Create resources across the keyspace through the public API, then
    assert every member placed every key in the SAME group — the group
    the routing function names — including followers (placement is
    replicated state, not an ingress-local choice)."""
    from copycat_tpu.atomic import DistributedAtomicLong

    registry = LocalServerRegistry()
    addresses = next_ports(3)
    groups = 4
    servers = [
        RaftServer(addr, addresses,
                   LocalTransport(registry, local_address=addr),
                   (lambda g: ResourceManager(group_id=g,
                                              num_groups=groups)),
                   groups=groups,
                   election_timeout=0.2, heartbeat_interval=0.04,
                   session_timeout=30.0)
        for addr in addresses]
    await asyncio.gather(*(s.open() for s in servers))
    client = RaftClient(addresses, LocalTransport(registry),
                        session_timeout=30.0)
    keys = [f"counter-{i}" for i in range(12)]
    try:
        await client.open()
        from copycat_tpu.resource.resource import resource_state_machine_of
        machine = resource_state_machine_of(DistributedAtomicLong)
        for k in keys:
            iid = await client.submit(GetResource(k, machine))
            # id residue IS the owning group, and it matches the hash
            assert iid % groups == zlib.crc32(k.encode()) % groups
        # wait until every member applied every group's catalog writes
        deadline = asyncio.get_running_loop().time() + 20
        while asyncio.get_running_loop().time() < deadline:
            placements = [
                {k: g.group_id
                 for s in [srv] for g in s.groups
                 for k in g.state_machine.keys}
                for srv in servers]
            if all(len(p) == len(keys) for p in placements):
                break
            await asyncio.sleep(0.05)
        assert all(len(p) == len(keys) for p in placements), \
            [len(p) for p in placements]
        # identical on every member, and equal to the routing function
        assert placements[0] == placements[1] == placements[2]
        for k, g in placements[0].items():
            assert g == zlib.crc32(k.encode()) % groups, (k, g)
    finally:
        await close_all(servers, client)


# ---------------------------------------------------------------------------
# leadership spread
# ---------------------------------------------------------------------------


@async_test(timeout=120)
async def test_leadership_spreads_across_members_at_boot():
    registry, servers = await sharded_cluster(n=3, groups=6)
    try:
        led = {str(s.address): sum(1 for g in s.groups
                                   if g.role == LEADER)
               for s in servers}
        assert sum(led.values()) == 6
        # seed-spread: every member leads exactly G/N groups at boot
        assert sorted(led.values()) == [2, 2, 2], led
        # and the preference is the deterministic one: group g's leader
        # is members[g % N] over the sorted member list
        ranked = sorted((s.address for s in servers),
                        key=lambda a: (a.host, a.port))
        for s in servers:
            for g in s.groups:
                if g.role == LEADER:
                    assert ranked[g.group_id % 3] == s.address
    finally:
        await close_all(servers)


# ---------------------------------------------------------------------------
# the proxy ingress + per-group event channels
# ---------------------------------------------------------------------------


@async_test(timeout=120)
async def test_commands_route_and_apply_exactly_once_via_any_ingress():
    """Pin a client to each member in turn: every member is a full
    ingress (local staging for groups it leads, proxy for the rest), and
    a key's increments land exactly once wherever they entered."""
    registry, servers = await sharded_cluster(n=3, groups=4)
    clients = []
    try:
        keys = [f"k{i}" for i in range(24)]
        for i, s in enumerate(servers):
            client = RaftClient(
                [x.address for x in servers], LocalTransport(registry),
                session_timeout=30.0,
                connection_strategy=PinnedConnectionStrategy(s.address))
            await client.open()
            clients.append(client)
            await asyncio.gather(*(
                client.submit_command_nowait(Put(key=k, value=(i, k)))
                for k in keys))
        # last writer wins per key: client 2's values
        got = await asyncio.gather(*(clients[0].submit(Get(key=k))
                                     for k in keys))
        assert [tuple(v) for v in got] == [(2, k) for k in keys], got
        # sequential reads agree (per-group client indices)
        seq = await asyncio.gather(*(clients[1].submit(SeqGet(key=k))
                                     for k in keys))
        assert [tuple(v) for v in seq] == [(2, k) for k in keys], seq
        # the proxy lane actually ran: with 4 groups over 3 members at
        # least one pinned ingress forwarded sub-blocks
        proxied = sum(s._metrics.counter("shard.commands_proxied").value
                      for s in servers)
        local = sum(s._metrics.counter("shard.commands_local").value
                    for s in servers)
        assert proxied > 0 and local > 0, (proxied, local)
    finally:
        await close_all(servers, *clients)


@async_test(timeout=120)
async def test_session_events_route_back_from_the_owning_group():
    """Events published by a group's apply reach the client through the
    ingress member's replica of THAT group — one independently numbered
    channel per group (the PublishRequest ``group`` field)."""
    registry, servers = await sharded_cluster(n=3, groups=4)
    client = RaftClient([s.address for s in servers],
                        LocalTransport(registry), session_timeout=30.0)
    try:
        await client.open()
        got: list = []
        client.session().on_event("poked", got.append)
        # pick keys covering EVERY group
        cover: dict[int, str] = {}
        i = 0
        while len(cover) < 4:
            k = f"evt{i}"
            cover.setdefault(zlib.crc32(k.encode()) % 4, k)
            i += 1
        for g, k in sorted(cover.items()):
            await client.submit(NotifyKey(key=k, payload=f"p{g}"))
        deadline = asyncio.get_running_loop().time() + 10
        while asyncio.get_running_loop().time() < deadline \
                and len(got) < 4:
            await asyncio.sleep(0.02)
        assert sorted(tuple(e) for e in got) == sorted(
            (k, f"p{g}") for g, k in cover.items()), got
        # each owning group advanced ITS channel exactly once
        idx = client.session()._event_indices
        assert {g: idx.get(g) for g in cover} == {g: 1 for g in cover}, idx
    finally:
        await close_all(servers, client)


# ---------------------------------------------------------------------------
# the single-group differential (the sharding A/B): COPYCAT_MULTI_GROUP=0
# / --groups 1 IS the pre-refactor plane
# ---------------------------------------------------------------------------


def _command_stream(server) -> list:
    """The applied command stream: (session_id, seq, op identity) in log
    order — the deterministic core the A/B compares (terms/timestamps
    are election-timing artifacts, deliberately excluded)."""
    from copycat_tpu.server.log import CommandEntry

    out = []
    log = server.log
    for index in range(max(1, log.first_index), log.last_index + 1):
        entry = log.get(index)
        if type(entry) is CommandEntry:
            op = entry.operation
            out.append((entry.session_id, entry.seq, type(op).__name__,
                        getattr(op, "key", None),
                        getattr(op, "value", None)))
    return out


def _entry_stream(server) -> list:
    """Full log identity including layout (entry types in order)."""
    log = server.log
    return [(type(log.get(i)).__name__ if log.get(i) is not None else None)
            for i in range(max(1, log.first_index), log.last_index + 1)]


async def _drive_single_plane(n_keys: int = 20):
    """One seeded sequential workload against a fresh 3-member cluster
    built from the CURRENT env (the caller pins the knobs); returns the
    (logs, state, stream) triple for comparison."""
    registry = LocalServerRegistry()
    addresses = next_ports(3)
    servers = [
        RaftServer(addr, addresses,
                   LocalTransport(registry, local_address=addr),
                   ShardedKV(),
                   election_timeout=0.2, heartbeat_interval=0.04,
                   session_timeout=60.0)
        for addr in addresses]
    await asyncio.gather(*(s.open() for s in servers))
    client = RaftClient(addresses, LocalTransport(registry),
                        session_timeout=60.0)
    try:
        await client.open()
        for i in range(n_keys):
            await client.submit(Put(key=f"d{i}", value=i))
        # convergence: every member applied everything
        leader = next(s for s in servers if s.role == LEADER)
        deadline = asyncio.get_running_loop().time() + 15
        while asyncio.get_running_loop().time() < deadline:
            if all(s.last_applied >= leader.commit_index
                   and s.log.last_index == leader.log.last_index
                   for s in servers):
                break
            await asyncio.sleep(0.02)
        return ([_command_stream(s) for s in servers],
                [dict(s.state_machine.data) for s in servers],
                [s.num_groups for s in servers],
                [s.log.name if hasattr(s.log, "name") else "" for s in servers])
    finally:
        await close_all(servers, client)


def test_multi_group_knob_off_is_the_single_group_plane(monkeypatch):
    """COPYCAT_GROUPS=4 + COPYCAT_MULTI_GROUP=0 builds EXACTLY the
    single-group plane: one group, unsuffixed log names, and the same
    command stream + applied state as an explicit groups=1 server for
    the same seeded workload."""

    @async_test(timeout=120)
    async def run_baseline():
        global _BASE
        _BASE = await _drive_single_plane()

    @async_test(timeout=120)
    async def run_knob_off():
        global _OFF
        _OFF = await _drive_single_plane()

    monkeypatch.delenv("COPYCAT_GROUPS", raising=False)
    monkeypatch.delenv("COPYCAT_MULTI_GROUP", raising=False)
    run_baseline()
    monkeypatch.setenv("COPYCAT_GROUPS", "4")
    monkeypatch.setenv("COPYCAT_MULTI_GROUP", "0")
    run_knob_off()
    base_streams, base_states, base_groups, _ = _BASE
    off_streams, off_states, off_groups, _ = _OFF
    assert off_groups == [1, 1, 1]  # the knob FORCED the single plane
    assert base_groups == [1, 1, 1]
    # cross-member identity within each run, and identity ACROSS runs
    assert base_streams[0] == base_streams[1] == base_streams[2]
    assert off_streams[0] == off_streams[1] == off_streams[2]
    assert base_streams[0] == off_streams[0]
    assert base_states == off_states


def test_single_plane_differential_under_nemesis_strict(monkeypatch):
    """The acceptance differential: the knob-forced single-group plane
    under nemesis (partition + leader deposition) with
    COPYCAT_INVARIANTS=strict — all members' logs converge
    bit-identically (serialized bytes), the applied command stream is
    exactly-once, and the strict commit-quorum tripwire never fired."""
    monkeypatch.setenv("COPYCAT_GROUPS", "4")
    monkeypatch.setenv("COPYCAT_MULTI_GROUP", "0")
    monkeypatch.setenv("COPYCAT_INVARIANTS", "strict")

    @async_test(timeout=240)
    async def run():
        from copycat_tpu.io.serializer import Serializer

        registry = LocalServerRegistry()
        addresses = next_ports(3)
        servers = [
            RaftServer(addr, addresses,
                       LocalTransport(registry, local_address=addr),
                       ShardedKV(),
                       election_timeout=0.2, heartbeat_interval=0.04,
                       session_timeout=60.0)
            for addr in addresses]
        await asyncio.gather(*(s.open() for s in servers))
        client = RaftClient(addresses, LocalTransport(registry),
                            session_timeout=60.0)
        try:
            await client.open()
            assert all(s.single and s.num_groups == 1 for s in servers)
            submitted = []
            for i in range(15):
                await client.submit(Put(key=f"n{i}", value=i))
                submitted.append((f"n{i}", i))
            # clean unregister, then depose the leader: partition it from
            # the other two; the majority elects and keeps committing
            # through a majority-scoped client (the nemesis idiom —
            # tests/test_nemesis_raft.py)
            await client.close()
            nem = registry.attach_nemesis()
            old_leader = next(s for s in servers if s.role == LEADER)
            majority = [s.address for s in servers if s is not old_leader]
            nem.partition([old_leader.address], majority)
            # wait for the majority to elect before registering: a
            # follower still hinting the OLD leader would route the
            # register to an uncommittable append (clients bypass the
            # partition by design), burning a whole per-try timeout
            deadline = asyncio.get_running_loop().time() + 15
            while asyncio.get_running_loop().time() < deadline:
                if any(s.role == LEADER and s is not old_leader
                       for s in servers):
                    break
                await asyncio.sleep(0.05)
            assert any(s.role == LEADER and s is not old_leader
                       for s in servers), "majority never elected"
            maj_client = RaftClient(majority, LocalTransport(registry),
                                    session_timeout=60.0)
            await maj_client.open()
            try:
                for i in range(15, 30):
                    await asyncio.wait_for(
                        maj_client.submit(Put(key=f"n{i}", value=i)), 30)
                    submitted.append((f"n{i}", i))
            finally:
                nem.heal()
                await asyncio.wait_for(maj_client.close(), 10)
            # the deposed leader rejoins and truncates/reconverges
            deadline = asyncio.get_running_loop().time() + 20
            while asyncio.get_running_loop().time() < deadline:
                leader = next((s for s in servers if s.role == LEADER),
                              None)
                if leader is not None and all(
                        s.log.last_index == leader.log.last_index
                        and s.last_applied == leader.last_applied
                        for s in servers):
                    break
                await asyncio.sleep(0.05)
            # 1) bit-identical logs: serialized entry bytes per slot.
            # Compaction is member-LOCAL GC (cleaned noop/keepalive
            # slots release at each member's own pace), so a slot may
            # read None on one member and bytes on another — every
            # SURVIVING copy of a slot must be byte-identical, and the
            # tails must agree.
            ser = Serializer()
            last = servers[0].log.last_index
            assert all(s.log.last_index == last for s in servers)
            for i in range(1, last + 1):
                copies = {ser.write(e) for e in
                          (s.log.get(i) for s in servers)
                          if e is not None}
                assert len(copies) <= 1, f"slot {i} diverged"
            # 2) exactly-once command stream covering every submit
            streams = [_command_stream(s) for s in servers]
            assert streams[0] == streams[1] == streams[2]
            applied = [(k, v) for _sid, _seq, name, k, v in streams[0]
                       if name == "Put"]
            assert applied == submitted
            # 3) the strict tripwire stayed silent on every member
            for s in servers:
                assert s.metrics.counter(
                    "repl.invariant_violations").value == 0
            # 4) final state agrees everywhere
            states = [dict(s.state_machine.data) for s in servers]
            assert states[0] == states[1] == states[2]
            assert states[0] == {k: v for k, v in submitted}
        finally:
            await close_all(servers, client)

    run()
