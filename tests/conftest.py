"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh (multi-chip sharding without
hardware) — flags must be set before the first ``import jax`` anywhere.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the shell may pre-set a TPU platform
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

try:
    # Importing the package re-asserts JAX_PLATFORMS (set above) against
    # plugin site config before any backend initializes — the same pin
    # every entry point gets (copycat_tpu/__init__.py); tests run on the
    # virtual 8-device CPU mesh.
    import copycat_tpu  # noqa: F401

    # Persist XLA executables across suite runs (engine steps take seconds
    # to compile each; the cache is keyed by HLO+backend+flags so it can
    # never serve a stale program). COPYCAT_COMPILE_CACHE=0 disables.
    from copycat_tpu.utils.platform import enable_compilation_cache

    enable_compilation_cache()
except ImportError:  # pragma: no cover - jax is part of the baked image
    pass
