"""Conflict-partitioned apply (ops/apply.py apply_window) equivalence.

The partitioned path (Config.pool_budgets set) must be observably
identical to the sequential apply_entry scan: same per-tag results, same
final resource state, same event streams — budgets only defer entries
across rounds, never reorder them within a pool.
"""

import numpy as np
import pytest

from copycat_tpu.models.raft_groups import RaftGroups
from copycat_tpu.ops import apply as ap
from copycat_tpu.ops.consensus import Config


def _drive(config: Config, seed: int) -> RaftGroups:
    """FIXED step schedule (not run_until): both executions see identical
    round counts, hence identical logical clocks — so even TTL deadlines
    (now + c) must come out bit-equal between the two paths."""
    rg = RaftGroups(8, 3, log_slots=32, submit_slots=8, config=config,
                    seed=3)
    rg.wait_for_leaders(max_rounds=60)
    extra = 60 - rg.rounds
    for _ in range(extra):  # normalize the election warm-up length
        rg.step_round()
    rng = np.random.default_rng(seed)
    ops_pool = [
        (ap.OP_LONG_ADD, lambda r: (int(r.integers(1, 5)), 0, 0)),
        (ap.OP_VALUE_SET, lambda r: (int(r.integers(1, 9)), 0,
                                     int(r.integers(0, 6)))),  # TTL'd
        (ap.OP_VALUE_CAS, lambda r: (int(r.integers(0, 3)),
                                     int(r.integers(0, 9)), 0)),
        (ap.OP_MAP_PUT, lambda r: (int(r.integers(0, 6)),
                                   int(r.integers(1, 9)),
                                   int(r.integers(0, 8)))),    # TTL'd
        (ap.OP_MAP_GET, lambda r: (int(r.integers(0, 6)), 0, 0)),
        (ap.OP_MAP_REMOVE, lambda r: (int(r.integers(0, 6)), 0, 0)),
        (ap.OP_SET_ADD, lambda r: (int(r.integers(0, 6)), 0,
                                   int(r.integers(0, 8)))),    # TTL'd
        (ap.OP_SET_REMOVE, lambda r: (int(r.integers(0, 6)), 0, 0)),
        (ap.OP_Q_OFFER, lambda r: (int(r.integers(1, 9)), 0, 0)),
        (ap.OP_Q_POLL, lambda r: (0, 0, 0)),
        (ap.OP_LOCK_ACQUIRE, lambda r: (int(r.integers(1, 4)), -1, 0)),
        (ap.OP_LOCK_RELEASE, lambda r: (int(r.integers(1, 4)), 0, 0)),
        (ap.OP_ELECT_LISTEN, lambda r: (int(r.integers(10, 14)), 0, 0)),
        (ap.OP_ELECT_RESIGN, lambda r: (int(r.integers(10, 14)), 0, 0)),
    ]
    tags = []
    for _ in range(25):  # 25 batches of one op per group, 4 rounds each
        for g in range(8):
            opcode, gen = ops_pool[rng.integers(0, len(ops_pool))]
            a, b, c = gen(rng)
            tags.append(rg.submit(g, opcode, a, b, c))
        for _ in range(4):
            rg.step_round()
    for _ in range(60):  # settle tail: tight budgets drain their backlog
        rg.step_round()
    missing = [t for t in tags if t not in rg.results]
    assert not missing, f"unresolved tags: {missing[:5]}"
    return rg


def test_partitioned_apply_matches_sequential():
    sequential = Config(applies_per_round=8)                # legacy scan
    partitioned = sequential._replace(
        pool_budgets=(2,) * 8)                    # tight budgets
    rg_seq = _drive(sequential, seed=99)
    rg_par = _drive(partitioned, seed=99)

    # identical per-tag results for the identical op stream
    assert rg_seq.results == rg_par.results

    # identical final resource state — EVERY field, TTL deadlines and
    # wait/listener rings included (clocks are aligned by construction)
    seq_res = rg_seq.state.resources
    par_res = rg_par.state.resources
    for name in seq_res._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(seq_res, name)),
            np.asarray(getattr(par_res, name)), err_msg=name)

    # identical event streams (order included)
    assert rg_seq.events == rg_par.events


def test_tight_budgets_still_apply_everything():
    """Budgets of 1 defer heavily but must never drop or reorder."""
    config = Config(applies_per_round=8,
                    pool_budgets=(1,) * 8)
    rg = RaftGroups(4, 3, log_slots=32, submit_slots=8, config=config)
    rg.wait_for_leaders()
    tags = [rg.submit(0, ap.OP_LONG_ADD, 1) for _ in range(24)]
    tags += [rg.submit(0, ap.OP_MAP_PUT, k, k * 2) for k in range(6)]
    rg.run_until(tags, max_rounds=400)
    assert rg.results[tags[23]] == 24          # all increments, in order
    get = rg.submit(0, ap.OP_MAP_GET, 3)
    rg.run_until([get])
    assert rg.results[get] == 6
