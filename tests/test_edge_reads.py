"""Edge read tier tests (docs/EDGE_READS.md).

Covers the tentpole contracts:

- local serving + delta convergence on the live stack (reads stop
  touching the cluster once seeded; writes propagate via deltas);
- the knob-off differential: ``COPYCAT_EDGE_READS=0`` produces the
  same observable results with ZERO edge machinery (no subscriptions,
  no deltas, no extra wire fields — byte-identity of the unsubscribed
  frames is locked by the PR 9 goldens in test_trace_plane.py);
- merge safety: duplicated / reordered / re-delivered deltas converge
  (join-semilattice, max-version-wins);
- session guarantees under the delta-plane nemesis (partition,
  reconnect, leader failover) under ``COPYCAT_INVARIANTS=strict``:
  no cache-served read ever violates monotone-reads or
  read-your-writes against a linearizable witness read;
- the staleness gate, the LRU bound + keep-alive unsubscribe, and
  retirement on resource delete.
"""

import asyncio
import os

import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.atomic import DistributedAtomicLong  # noqa: E402
from copycat_tpu.collections import DistributedMap  # noqa: E402
from copycat_tpu.io.local import (  # noqa: E402
    LocalServerRegistry, LocalTransport, NetworkNemesis)
from copycat_tpu.manager.atomix import AtomixClient, AtomixServer  # noqa: E402
from copycat_tpu.resource.consistency import Consistency  # noqa: E402
from copycat_tpu.server.raft import LEADER  # noqa: E402

from helpers import async_test  # noqa: E402
from raft_fixtures import next_ports  # noqa: E402


async def _stack(registry, members: int = 1, session_timeout: float = 20.0):
    addrs = next_ports(members)
    servers = [AtomixServer(a, addrs,
                            LocalTransport(registry, local_address=a),
                            election_timeout=0.3, heartbeat_interval=0.05,
                            session_timeout=session_timeout)
               for a in addrs]
    await asyncio.gather(*(s.open() for s in servers))
    return servers


async def _close_all(clients, servers):
    for c in clients:
        try:
            await asyncio.wait_for(c.close(), 5)
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass
    for s in servers:
        await asyncio.wait_for(s.close(), 10)


def _edge_snap(client) -> dict:
    return {k: v for k, v in client.client.metrics.snapshot().items()
            if str(k).startswith("edge.")}


# ---------------------------------------------------------------------------
# local serving + delta propagation
# ---------------------------------------------------------------------------


@async_test(timeout=120)
async def test_warm_reads_never_touch_the_server():
    """After the subscribing first read, SEQUENTIAL reads serve from
    the client replica: the server's read counters stop moving while
    local serves accumulate, and a write propagates via the delta."""
    registry = LocalServerRegistry()
    (server,) = await _stack(registry)
    writer = AtomixClient([server.server.address],
                          LocalTransport(registry), session_timeout=20.0)
    reader = AtomixClient([server.server.address],
                          LocalTransport(registry), session_timeout=20.0)
    await writer.open()
    await reader.open()
    try:
        w = await writer.get("ctr", DistributedAtomicLong)
        r = await reader.get("ctr", DistributedAtomicLong)
        r.with_consistency(Consistency.SEQUENTIAL)
        await w.add_and_get(3)
        assert await r.get() == 3  # subscribing read (server, seeds)

        def server_reads() -> int:
            snap = server.server.metrics.snapshot()
            return sum(v for k, v in snap.items()
                       if str(k).startswith("query_reads"))

        before = server_reads()
        for _ in range(20):
            assert await r.get() == 3
        assert server_reads() == before, "warm reads must stay local"
        snap = _edge_snap(reader)
        assert snap["edge.local_serves"] >= 20, snap

        await w.add_and_get(4)
        # the delta flush rides the apply turn; give the push a beat
        for _ in range(50):
            if await r.get() == 7:
                break
            await asyncio.sleep(0.01)
        assert await r.get() == 7
        assert _edge_snap(reader)["edge.deltas_in"] >= 1
        ssnap = server.server.metrics.snapshot()
        assert ssnap["edge.subscribes"] >= 1
        assert ssnap["edge.deltas_sent"] >= 1
        assert ssnap["edge.subscriptions"] >= 1
    finally:
        await _close_all([writer, reader], [server])


@async_test(timeout=120)
async def test_map_reads_serve_locally():
    """Map gets/sizes/membership evaluate client-side from the tagged
    full-state replica with the CPU machine's exact semantics."""
    registry = LocalServerRegistry()
    (server,) = await _stack(registry)
    client = AtomixClient([server.server.address],
                          LocalTransport(registry), session_timeout=20.0)
    await client.open()
    try:
        m = await client.get("m", DistributedMap)
        m.with_consistency(Consistency.SEQUENTIAL)
        await m.put("a", 1)
        await m.put("b", None)
        assert await m.get("a") == 1  # seeds
        serves0 = _edge_snap(client)["edge.local_serves"]
        assert await m.get("a") == 1
        assert await m.get("b") is None
        assert await m.get("missing") is None
        assert await m.get_or_default("b", 9) is None  # present-but-None
        assert await m.get_or_default("missing", 9) == 9
        assert await m.contains_key("a") is True
        assert await m.size() == 2
        assert await m.is_empty() is False
        assert _edge_snap(client)["edge.local_serves"] > serves0
    finally:
        await _close_all([client], [server])


# ---------------------------------------------------------------------------
# the knob-off differential
# ---------------------------------------------------------------------------


@async_test(timeout=240)
async def test_knob_off_differential(monkeypatch):
    """A SAME-session write/read script — the strongest sequence the
    CAUSAL/SEQUENTIAL contract promises determinism for (every read
    must reflect the session's own completed writes) — produces
    identical results on both planes, and a cross-client phase
    converges to the same final value. With the knob off there is NO
    edge machinery — the client has no tier, requests carry no
    subscribe field, the server registers nothing and pushes nothing
    (the unsubscribed wire frames are byte-identical to the PR 9
    goldens — locked on both codecs by tests/test_trace_plane.py)."""
    outcomes = []
    for edge_on in (True, False):
        monkeypatch.setenv("COPYCAT_EDGE_READS", "1" if edge_on else "0")
        registry = LocalServerRegistry()
        (server,) = await _stack(registry)
        writer = AtomixClient([server.server.address],
                              LocalTransport(registry),
                              session_timeout=20.0)
        reader = AtomixClient([server.server.address],
                              LocalTransport(registry),
                              session_timeout=20.0)
        await writer.open()
        await reader.open()
        try:
            c = await reader.get("own", DistributedAtomicLong)
            c.with_consistency(Consistency.SEQUENTIAL)
            seen = []
            for i in range(6):  # same-session: deterministic via RYW
                await c.add_and_get(i + 1)
                seen.append(await c.get())
                seen.append(await c.get())
            # cross-client phase: eventual convergence (per-read
            # freshness against ANOTHER session's writes is exactly
            # what CAUSAL/SEQUENTIAL do not promise)
            w = await writer.get("shared", DistributedAtomicLong)
            r = await reader.get("shared", DistributedAtomicLong)
            r.with_consistency(Consistency.SEQUENTIAL)
            for _ in range(5):
                await w.add_and_get(2)
            final = None
            for _ in range(200):
                final = await r.get()
                if final == 10:
                    break
                await asyncio.sleep(0.01)
            seen.append(final)
            outcomes.append(seen)
            if edge_on:
                assert reader.client._edge is not None
            else:
                assert reader.client._edge is None
                assert _edge_snap(reader) == {}
                ssnap = server.server.metrics.snapshot()
                assert ssnap["edge.subscribes"] == 0
                assert ssnap["edge.deltas_sent"] == 0
        finally:
            await _close_all([writer, reader], [server])
    assert outcomes[0] == outcomes[1]


# ---------------------------------------------------------------------------
# merge safety: duplicated / reordered / re-delivered deltas
# ---------------------------------------------------------------------------


def test_merge_is_idempotent_commutative_associative():
    """max-version-wins over log-ordered versions is a join-semilattice:
    any delivery order, duplication, or re-delivery of the same delta
    set converges to the same replica state."""
    import itertools
    import random

    from copycat_tpu.client.edge import EdgeReadTier

    class _FakeClient:
        _num_groups = 1
        _indices: dict = {}

        def _note_index(self, value):
            pass

    from copycat_tpu.utils.metrics import MetricsRegistry

    deltas = [(7, 3, ("val", 30)), (7, 5, ("val", 50)),
              (7, 4, ("val", 40)), (7, 5, ("val", 50)),
              (7, 6, ("r", None))]

    states = set()
    orders = list(itertools.permutations(deltas))
    random.Random(5).shuffle(orders)
    for order in orders[:40]:
        fake = _FakeClient()
        fake.metrics = MetricsRegistry()
        tier = EdgeReadTier(fake)
        tier.seed([(7, 1, ("val", 10))])
        for d in order:
            tier.ingest([d])
            tier.ingest([d])  # duplicated delivery
        entry = tier._replica[7]
        states.add((entry.version, entry.state))
    assert states == {(6, 50)}


def test_retire_delta_drops_the_entry():
    from copycat_tpu.client.edge import EdgeReadTier
    from copycat_tpu.utils.metrics import MetricsRegistry

    class _FakeClient:
        _num_groups = 1
        _indices: dict = {}
        metrics = MetricsRegistry()

        def _note_index(self, value):
            pass

    tier = EdgeReadTier(_FakeClient())
    tier.seed([(7, 1, ("val", 10))])
    assert 7 in tier._replica
    tier.ingest([(7, 9, None)])
    assert 7 not in tier._replica
    # unknown-instance deltas are never adopted
    tier.ingest([(8, 1, ("val", 5))])
    assert 8 not in tier._replica


# ---------------------------------------------------------------------------
# staleness gate, LRU bound, unsubscribe, delete retirement
# ---------------------------------------------------------------------------


@async_test(timeout=120)
async def test_staleness_gate_re_seeds(monkeypatch):
    monkeypatch.setenv("COPYCAT_EDGE_TTL_S", "0.05")
    registry = LocalServerRegistry()
    (server,) = await _stack(registry)
    client = AtomixClient([server.server.address],
                          LocalTransport(registry), session_timeout=20.0)
    await client.open()
    try:
        c = await client.get("ctr", DistributedAtomicLong)
        c.with_consistency(Consistency.SEQUENTIAL)
        await c.add_and_get(1)
        assert await c.get() == 1  # seeds
        assert await c.get() == 1  # local
        await asyncio.sleep(0.1)   # TTL expires with no delta traffic
        assert await c.get() == 1  # falls back + re-seeds
        snap = _edge_snap(client)
        assert snap["edge.stale_rejections"] >= 1, snap
        assert snap["edge.seeds"] >= 2, snap
    finally:
        await _close_all([client], [server])


@async_test(timeout=120)
async def test_lru_bound_and_keepalive_unsubscribe(monkeypatch):
    monkeypatch.setenv("COPYCAT_EDGE_MAX_RESOURCES", "2")
    registry = LocalServerRegistry()
    (server,) = await _stack(registry, session_timeout=1.2)
    client = AtomixClient([server.server.address],
                          LocalTransport(registry), session_timeout=1.2)
    await client.open()
    try:
        ctrs = []
        for i in range(4):
            c = await client.get(f"c{i}", DistributedAtomicLong)
            c.with_consistency(Consistency.SEQUENTIAL)
            await c.add_and_get(1)
            assert await c.get() == 1
            ctrs.append(c)
        snap = _edge_snap(client)
        assert snap["edge.replica_entries"] <= 2, snap
        assert snap["edge.evictions"] >= 2, snap
        # the keep-alive carries the staged unsubscribes (interval =
        # session_timeout / 4 = 0.3 s)
        for _ in range(40):
            if server.server.metrics.snapshot()["edge.unsubscribes"] >= 2:
                break
            await asyncio.sleep(0.05)
        ssnap = server.server.metrics.snapshot()
        assert ssnap["edge.unsubscribes"] >= 2, ssnap
        assert ssnap["edge.subscriptions"] <= 2, ssnap
    finally:
        await _close_all([client], [server])


@async_test(timeout=120)
async def test_ttl_state_never_seeds_and_declines_negative_cache():
    """A value with an armed TTL is not edge-servable (the expiry fires
    outside the apply path, invisible to the delta plane): subscribing
    reads come back seedless, the instance negative-caches so later
    reads stop asking, and every read keeps hitting the server — which
    serves the post-expiry truth."""
    registry = LocalServerRegistry()
    (server,) = await _stack(registry)
    client = AtomixClient([server.server.address],
                          LocalTransport(registry), session_timeout=20.0)
    await client.open()
    try:
        c = await client.get("ttl", DistributedAtomicLong)
        c.with_consistency(Consistency.SEQUENTIAL)
        await c.set(5, ttl=0.2)
        assert await c.get() == 5          # server read, no seed
        assert await c.get() == 5          # still server (negative-cached)
        snap = _edge_snap(client)
        assert snap["edge.seeds"] == 0, snap
        assert snap["edge.replica_entries"] == 0, snap
        assert server.server.metrics.snapshot()["edge.subscribes"] == 0
        assert client.client._edge._no_seed, "seedless decline not cached"
        await asyncio.sleep(0.4)           # device/host TTL fires
        assert await c.get() == 0          # post-expiry truth, via server
    finally:
        await _close_all([client], [server])


def test_seed_response_negative_cache_unit():
    """Declined seeds stop subscribe attempts for one TTL interval and
    clear the moment a seed arrives."""
    from copycat_tpu.client.edge import EdgeReadTier
    from copycat_tpu.manager.operations import InstanceQuery
    from copycat_tpu.resource.operations import ResourceQuery
    from copycat_tpu.atomic import commands as vc
    from copycat_tpu.utils.metrics import MetricsRegistry

    class _FakeClient:
        _num_groups = 1
        _indices: dict = {}
        metrics = MetricsRegistry()

        def _note_index(self, value):
            pass

    tier = EdgeReadTier(_FakeClient())
    op = InstanceQuery(7, ResourceQuery(vc.Get(), "sequential"))
    items = [(op, None)]
    assert tier.wants_subscribe(items) is True
    tier.seed_response(items, None)        # server declined
    assert tier.wants_subscribe(items) is False
    tier.seed_response(items, [(7, 3, ("val", 9))])  # later seed clears
    assert 7 not in tier._no_seed
    assert 7 in tier._replica


@async_test(timeout=120)
async def test_delete_retires_the_replica():
    """Deleting a subscribed resource pushes retire deltas: the replica
    entry drops and the next read surfaces the server's error instead
    of a cached ghost value."""
    registry = LocalServerRegistry()
    (server,) = await _stack(registry)
    client = AtomixClient([server.server.address],
                          LocalTransport(registry), session_timeout=20.0)
    await client.open()
    try:
        c = await client.get("doomed", DistributedAtomicLong)
        c.with_consistency(Consistency.SEQUENTIAL)
        await c.set(5)
        assert await c.get() == 5
        assert await c.get() == 5  # local
        assert _edge_snap(client)["edge.replica_entries"] >= 1
        await c.delete()
        for _ in range(50):
            if _edge_snap(client)["edge.replica_entries"] == 0:
                break
            await asyncio.sleep(0.01)
        assert _edge_snap(client)["edge.replica_entries"] == 0
        assert server.server.metrics.snapshot()["edge.entries_retired"] >= 1
    finally:
        await _close_all([client], [server])


# ---------------------------------------------------------------------------
# delta-plane nemesis: partition, reconnect, failover — session
# guarantees against a linearizable witness, strict invariants
# ---------------------------------------------------------------------------


@async_test(timeout=420)
async def test_nemesis_monotone_and_ryw_against_linearizable_witness(
        monkeypatch):
    """A reader serving from its edge replica through a leader
    partition + failover + heal never observes the counter going
    BACKWARDS (monotone reads) and never observes a value the
    linearizable witness hasn't admitted yet (the counter only grows,
    so any served v must satisfy last_seen <= v <= witness-now).
    Per-read freshness against the WRITER's session is deliberately
    not asserted — CAUSAL/SEQUENTIAL permit bounded staleness — but
    the run must converge to the full total."""
    monkeypatch.setenv("COPYCAT_INVARIANTS", "strict")
    registry = LocalServerRegistry()
    nem = NetworkNemesis(seed=3)
    registry.attach_nemesis(nem)
    # session_timeout is a harness parameter, not what's under test: it
    # only needs to outlive any slow moment (cold jit compiles, a
    # saturated CI host) so keep-alives never starve mid-nemesis —
    # 8 s flaked as SessionExpiredError deep in the full suite
    servers = await _stack(registry, members=3, session_timeout=20.0)
    addrs = [s.server.address for s in servers]
    writer = AtomixClient(addrs, LocalTransport(registry),
                          session_timeout=20.0)
    reader = AtomixClient(addrs, LocalTransport(registry),
                          session_timeout=20.0)
    await writer.open()
    await reader.open()
    try:
        w = await writer.get("ctr", DistributedAtomicLong)
        r = await reader.get("ctr", DistributedAtomicLong)
        r.with_consistency(Consistency.SEQUENTIAL)
        # the witness reads linearizably through its own client
        witness = await writer.get("ctr", DistributedAtomicLong)

        total = 0
        last_seen = 0

        async def check_read() -> None:
            nonlocal last_seen
            v = await asyncio.wait_for(r.get(), 10.0)
            assert v >= last_seen, (v, last_seen, "monotone violation")
            wit = await asyncio.wait_for(witness.get(), 10.0)
            assert v <= wit, (v, wit, "read ahead of linearizable state")
            last_seen = v

        for i in range(4):
            total += 1
            await asyncio.wait_for(w.add_and_get(1), 10.0)
            await check_read()
        # partition the current leader away; the majority elects
        leader = next(s.server for s in servers
                      if s.server.role == LEADER)
        minority = [leader.address]
        majority = [a for a in addrs if a != leader.address]
        nem.partition(minority, majority)
        # reads during the partition keep serving (stale-but-monotone
        # from the replica, or via a reachable member once re-routed)
        for _ in range(3):
            await check_read()
        # writes re-route to the new leader; reads must catch up
        for _ in range(4):
            total += 1
            await asyncio.wait_for(w.add_and_get(1), 30.0)
            await check_read()
        nem.heal()
        for _ in range(3):
            total += 1
            await asyncio.wait_for(w.add_and_get(1), 30.0)
            await check_read()
        # convergence: the reader eventually serves the full total
        for _ in range(200):
            if await asyncio.wait_for(r.get(), 10.0) == total:
                break
            await asyncio.sleep(0.05)
        assert await r.get() == total
    finally:
        nem.heal()
        await _close_all([writer, reader], servers)


@async_test(timeout=300)
async def test_ryw_through_own_writes(monkeypatch):
    """Read-your-writes via the client seq space: a client that writes
    then reads through the edge tier sees its own write — the write's
    response index raises the read floor past any stale replica entry
    (stale-reject + re-seed, never a stale serve)."""
    monkeypatch.setenv("COPYCAT_INVARIANTS", "strict")
    registry = LocalServerRegistry()
    (server,) = await _stack(registry)
    client = AtomixClient([server.server.address],
                          LocalTransport(registry), session_timeout=20.0)
    await client.open()
    try:
        c = await client.get("ctr", DistributedAtomicLong)
        c.with_consistency(Consistency.SEQUENTIAL)
        v = 0
        for i in range(12):
            v = await c.add_and_get(1)
            got = await c.get()
            assert got == v, (got, v, "read-your-writes violation")
    finally:
        await _close_all([client], [server])


@async_test(timeout=300)
async def test_reconnect_re_seeds_instead_of_serving_blind():
    """When the session connection moves (server restart of the event
    channel's holder is approximated by bouncing the connection), the
    server retires the undeliverable subscriptions; the client's TTL +
    re-seed path takes over — reads still return correct values."""
    registry = LocalServerRegistry()
    (server,) = await _stack(registry)
    client = AtomixClient([server.server.address],
                          LocalTransport(registry), session_timeout=20.0)
    await client.open()
    try:
        c = await client.get("ctr", DistributedAtomicLong)
        c.with_consistency(Consistency.SEQUENTIAL)
        await c.add_and_get(1)
        assert await c.get() == 1
        # bounce the session connection: deltas in the gap are lost and
        # the flush-side dead-connection rule drops the subscriptions
        client.client._drop_connection()
        await c.add_and_get(1)  # reconnects, commits
        for _ in range(100):
            if await c.get() == 2:
                break
            await asyncio.sleep(0.02)
        assert await c.get() == 2
    finally:
        await _close_all([client], [server])
