"""Collection tests (reference ``DistributedMapTest`` incl. TTL expiry,
``DistributedMultiMapTest``, ``DistributedSetTest``, ``DistributedQueueTest``)."""

import asyncio

import pytest

from copycat_tpu.client.client import ApplicationError
from copycat_tpu.collections import (
    DistributedMap,
    DistributedMultiMap,
    DistributedQueue,
    DistributedSet,
)

from atomix_fixtures import Stack
from helpers import async_test


@async_test(timeout=120)
async def test_map_basic_ops():
    stack = await Stack().start(3)
    try:
        client = await stack.client()
        m = await client.get("map", DistributedMap)
        assert await m.is_empty()
        assert await m.put("a", 1) is None
        assert await m.put("a", 2) == 1
        assert await m.get("a") == 2
        assert await m.get_or_default("zz", 9) == 9
        assert await m.contains_key("a")
        assert not await m.contains_key("b")
        assert await m.contains_value(2)
        assert await m.put_if_absent("a", 99) == 2
        assert await m.put_if_absent("b", 3) is None
        assert await m.size() == 2
        assert await m.replace("a", 5) == 2
        assert await m.replace("zz", 5) is None
        assert await m.replace_if_present("a", 5, 6) is True
        assert await m.replace_if_present("a", 5, 7) is False
        assert await m.remove_if_present("b", 999) is False
        assert await m.remove_if_present("b", 3) is True
        assert await m.remove("a") == 6
        assert await m.remove("a") is None
        assert await m.is_empty()
    finally:
        await stack.close()


@async_test(timeout=120)
async def test_map_ttl_expiry():
    """Reference testMapPutTtl: value gone after expiry through the log clock."""
    stack = await Stack().start(3)
    try:
        client = await stack.client()
        m = await client.get("ttlmap", DistributedMap)
        await m.put("k", "v", ttl=0.3)
        assert await m.get("k") == "v"
        await asyncio.sleep(0.9)
        assert await m.get("k") is None
        await m.put_if_absent("k2", "v2", ttl=0.3)
        await asyncio.sleep(0.9)
        assert await m.get("k2") is None
    finally:
        await stack.close()


@async_test(timeout=120)
async def test_map_clear():
    stack = await Stack().start(3)
    try:
        client = await stack.client()
        m = await client.get("clearmap", DistributedMap)
        await m.put("x", 1)
        await m.put("y", 2)
        await m.clear()
        assert await m.is_empty()
    finally:
        await stack.close()


@async_test(timeout=120)
async def test_multimap_ops():
    stack = await Stack().start(3)
    try:
        client = await stack.client()
        mm = await client.get("mmap", DistributedMultiMap)
        assert await mm.put("k", 1)
        assert await mm.put("k", 2)
        assert not await mm.put("k", 1)  # duplicate entry
        assert sorted(await mm.get("k")) == [1, 2]
        assert await mm.size("k") == 2
        assert await mm.size() == 2
        assert await mm.contains_key("k")
        assert await mm.contains_entry("k", 2)
        assert await mm.contains_value(1)
        assert await mm.remove("k", 1) is True
        assert await mm.remove("k", 1) is False
        assert await mm.get("k") == [2]
        removed = await mm.remove("k")
        assert removed == [2]
        assert await mm.is_empty()
    finally:
        await stack.close()


@async_test(timeout=120)
async def test_set_ops():
    stack = await Stack().start(3)
    try:
        client = await stack.client()
        s = await client.get("set", DistributedSet)
        assert await s.add("x")
        assert not await s.add("x")
        assert await s.contains("x")
        assert await s.size() == 1
        assert await s.remove("x")
        assert not await s.remove("x")
        assert await s.is_empty()
        # TTL member
        await s.add("temp", ttl=0.3)
        assert await s.contains("temp")
        await asyncio.sleep(0.9)
        assert not await s.contains("temp")
    finally:
        await stack.close()


@async_test(timeout=120)
async def test_queue_fifo_and_errors():
    stack = await Stack().start(3)
    try:
        client = await stack.client()
        q = await client.get("queue", DistributedQueue)
        assert await q.is_empty()
        await q.add("first")
        await q.offer("second")
        assert await q.peek() == "first"
        assert await q.element() == "first"
        assert await q.size() == 2
        assert await q.contains("second")
        assert await q.poll() == "first"
        assert await q.remove() == "second"  # head removal
        assert await q.poll() is None  # poll on empty -> None
        with pytest.raises(ApplicationError):  # element on empty -> raises
            await q.element()
        await q.add("a")
        await q.add("b")
        assert await q.remove("a") is True  # remove by value
        assert await q.remove("zz") is False
        await q.clear()
        assert await q.is_empty()
    finally:
        await stack.close()
