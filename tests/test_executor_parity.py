"""Executor parity: the SAME resource API against the CPU state machines
and the TPU device engine (``AtomixServer(..., executor="tpu")``).

This is the SPI obligation of SURVEY.md §7.1 — the device engine selectable
at replica build time, mirroring ``withStateMachine(new ResourceManager())``
(``AtomixReplica.java:374``) — and it subsumes the differential harness:
every test runs once per executor with identical assertions, and
``test_differential_map_sequences`` drives one randomized op stream through
both executors and compares every result.

Engine pools are deliberately tiny (map_slots=16 etc., DeviceEngineConfig
defaults) so the overflow tests genuinely spill device pools into the host
shadow (SURVEY.md §7.3 #1 "eviction-to-host for overflow").
"""

import asyncio
import random

import pytest

from copycat_tpu.atomic import DistributedAtomicLong, DistributedAtomicValue
from copycat_tpu.collections import (
    DistributedMap,
    DistributedQueue,
    DistributedSet,
)
from copycat_tpu.coordination import DistributedLeaderElection, DistributedLock
from copycat_tpu.io.local import LocalServerRegistry, LocalTransport
from copycat_tpu.manager.atomix import AtomixClient, AtomixServer
from copycat_tpu.manager.device_executor import DeviceEngineConfig

from helpers import async_test
from raft_fixtures import next_ports

EXECUTORS = ("cpu", "tpu")

# one small engine shape for every parity test → one jit compile per process
ENGINE = DeviceEngineConfig(capacity=8, num_peers=3, log_slots=32)


async def _cluster(executor: str, n: int = 3, n_clients: int = 1):
    registry = LocalServerRegistry()
    addrs = next_ports(n)
    servers = [
        AtomixServer(a, addrs, LocalTransport(registry),
                     election_timeout=0.2, heartbeat_interval=0.04,
                     session_timeout=10.0, executor=executor,
                     engine_config=ENGINE)
        for a in addrs
    ]
    await asyncio.gather(*(s.open() for s in servers))
    clients = []
    for _ in range(n_clients):
        client = AtomixClient(addrs, LocalTransport(registry),
                              session_timeout=10.0)
        await client.open()
        clients.append(client)
    return servers, clients


async def _teardown(nodes):
    for node in nodes:
        try:
            await asyncio.wait_for(node.close(), 5)
        except (Exception, asyncio.TimeoutError):
            pass


@pytest.mark.parametrize("executor", EXECUTORS)
@async_test(timeout=180)
async def test_value_and_long(executor):
    servers, (client,) = await _cluster(executor)
    try:
        value = await client.get("val", DistributedAtomicValue)
        assert await value.get() is None
        await value.set(41)
        assert await value.get() == 41
        assert await value.compare_and_set(41, 42)
        assert not await value.compare_and_set(41, 43)
        assert await value.get_and_set(7) == 42
        # non-int32 payloads transparently take the host shadow
        await value.set("a string")
        assert await value.get() == "a string"
        assert await value.compare_and_set("a string", 99)
        assert await value.get() == 99
        await value.set(None)
        assert await value.get() is None

        counter = await client.get("ctr", DistributedAtomicLong)
        assert await counter.increment_and_get() == 1
        assert await counter.add_and_get(9) == 10
        assert await counter.get_and_add(5) == 10
        assert await counter.get() == 15
        assert await counter.decrement_and_get() == 14
    finally:
        await _teardown([client] + servers)


@pytest.mark.parametrize("executor", EXECUTORS)
@async_test(timeout=180)
async def test_map_overflow_and_mixed_payloads(executor):
    """Puts far past the device pool capacity (map_slots=16) and with
    non-int32 keys/values must succeed transparently — the overflow story
    (reference ``MapState.java:32`` has no capacity bound)."""
    servers, (client,) = await _cluster(executor)
    try:
        m = await client.get("m", DistributedMap)
        n = 40  # device pool holds 16: >half the entries spill to host
        for k in range(n):
            assert await m.put(k, k * 10) is None
        assert await m.size() == n
        for k in range(n):
            assert await m.get(k) == k * 10
        # mixed payload types
        await m.put("skey", [1, 2, 3])
        assert await m.get("skey") == [1, 2, 3]
        assert await m.put(5, "now a string") == 50
        assert await m.get(5) == "now a string"
        assert await m.contains_value("now a string")
        assert await m.contains_value(70)
        assert not await m.contains_value(50)
        # conditional ops across the device/shadow boundary
        assert await m.put_if_absent(5, 1) == "now a string"
        assert await m.replace_if_present(5, "now a string", 500)
        assert await m.get(5) == 500
        assert await m.remove(5) == 500
        assert await m.get(5) is None
        assert await m.remove_if_present(7, 70)
        assert await m.size() == n - 1  # removed 5, removed 7, added skey
        await m.clear()
        assert await m.is_empty()
    finally:
        await _teardown([client] + servers)


@pytest.mark.parametrize("executor", EXECUTORS)
@async_test(timeout=180)
async def test_set_and_queue_overflow(executor):
    servers, (client,) = await _cluster(executor)
    try:
        s = await client.get("s", DistributedSet)
        for v in range(30):  # past set_slots=16
            assert await s.add(v)
        assert not await s.add(3)
        assert await s.size() == 30
        assert await s.contains(29)
        assert await s.remove(29)
        assert not await s.contains(29)
        assert await s.add("str-member")
        assert await s.contains("str-member")
        assert await s.size() == 30

        q = await client.get("q", DistributedQueue)
        for v in range(25):  # past queue_slots=16
            assert await q.offer(v)
        await q.offer("tail-str")
        assert await q.size() == 26
        assert await q.peek() == 0
        for v in range(25):
            assert await q.poll() == v
        assert await q.poll() == "tail-str"
        assert await q.poll() is None
        # remove-by-value from the middle
        for v in (1, 2, 3, 4):
            await q.offer(v)
        assert await q.remove(3) is True
        assert await q.contains(2)
        assert not await q.contains(3)
        assert [await q.poll() for _ in range(3)] == [1, 2, 4]
    finally:
        await _teardown([client] + servers)


@pytest.mark.parametrize("executor", EXECUTORS)
@async_test(timeout=180)
async def test_lock_contention_and_session_release(executor):
    servers, (c1, c2) = await _cluster(executor, n_clients=2)
    try:
        l1 = await c1.get("lk", DistributedLock)
        l2 = await c2.get("lk", DistributedLock)
        await l1.lock()
        assert not await l2.try_lock()          # immediate attempt fails
        waiter = asyncio.ensure_future(l2.lock())  # queue behind holder
        await asyncio.sleep(0.3)
        assert not waiter.done()
        await l1.unlock()
        await asyncio.wait_for(waiter, 15)       # grant via session event
        await l2.unlock()

        # session death releases the lock (the capability fix over the
        # reference, preserved on the device path)
        await l1.lock()
        waiter2 = asyncio.ensure_future(l2.lock())
        await asyncio.sleep(0.3)
        await c1.close()                          # holder's client dies
        await asyncio.wait_for(waiter2, 15)
        await l2.unlock()
    finally:
        await _teardown([c1, c2] + servers)


@pytest.mark.parametrize("executor", EXECUTORS)
@async_test(timeout=180)
async def test_election_succession_and_fencing(executor):
    servers, (c1, c2) = await _cluster(executor, n_clients=2)
    try:
        e1 = await c1.get("el", DistributedLeaderElection)
        e2 = await c2.get("el", DistributedLeaderElection)
        epochs1: list[int] = []
        epochs2: list[int] = []
        await e1.on_election(epochs1.append)
        await e2.on_election(epochs2.append)
        for _ in range(100):
            if epochs1:
                break
            await asyncio.sleep(0.05)
        assert epochs1, "first listener was not elected"
        # is_leader(epoch) is a pure fencing-token check: it validates the
        # epoch against the CURRENT leadership (reference
        # LeaderElectionState.isLeader:96), regardless of who asks.
        assert await e1.is_leader(epochs1[0])
        assert not await e1.is_leader(epochs1[0] + 999)
        # leader's client dies -> succession to the second listener
        await c1.close()
        for _ in range(200):
            if epochs2:
                break
            await asyncio.sleep(0.05)
        assert epochs2, "successor was not promoted"
        assert await e2.is_leader(epochs2[0])
        # the old epoch no longer fences
        assert not await e2.is_leader(epochs1[0])
    finally:
        await _teardown([c1, c2] + servers)


@async_test(timeout=300)
async def test_differential_map_sequences():
    """One randomized op stream through BOTH executors; every result must
    match — the differential harness collapsed into the SPI
    parametrization (round-2 VERDICT directive #2)."""
    rng = random.Random(1234)
    script = []
    for _ in range(60):
        op = rng.choice(["put", "get", "remove", "pia", "rip", "size"])
        k = rng.randrange(24)            # > map_slots → guaranteed overflow
        v = rng.randrange(100)
        script.append((op, k, v))

    async def run(executor):
        servers, (client,) = await _cluster(executor)
        try:
            m = await client.get("diff", DistributedMap)
            out = []
            for op, k, v in script:
                if op == "put":
                    out.append(await m.put(k, v))
                elif op == "get":
                    out.append(await m.get(k))
                elif op == "remove":
                    out.append(await m.remove(k))
                elif op == "pia":
                    out.append(await m.put_if_absent(k, v))
                elif op == "rip":
                    out.append(await m.remove_if_present(k, v))
                elif op == "size":
                    out.append(await m.size())
            return out
        finally:
            await _teardown([client] + servers)

    cpu = await run("cpu")
    tpu = await run("tpu")
    assert cpu == tpu


@async_test(timeout=180)
async def test_device_group_reuse_after_delete():
    """Deleting a device-backed resource resets and frees its group, so the
    engine can host capacity-many LIVE resources regardless of history —
    and a recycled group must not leak its predecessor's state."""
    servers, (client,) = await _cluster("tpu")
    try:
        first = await client.get("reuse-seed", DistributedMap)
        await first.put(1, 111)
        await first.delete()
        # capacity is 8: with the freed group back in the pool, all 8 new
        # resources get device placement (no CPU fallback anywhere)
        maps = []
        for i in range(8):
            m = await client.get(f"reuse-{i}", DistributedMap)
            await m.put(i + 100, i)
            maps.append(m)
        sm = servers[0].server.state_machine
        kinds = sorted(type(h.state_machine).__name__
                       for h in sm.resources.values())
        assert kinds == ["DeviceMapState"] * 8, kinds
        # the recycled group starts clean: the predecessor's key is gone
        for m in maps:
            assert await m.get(1) is None
        for i, m in enumerate(maps):
            assert await m.get(i + 100) == i
    finally:
        await _teardown([client] + servers)


@pytest.mark.parametrize("executor", EXECUTORS)
@async_test(timeout=180)
async def test_multimap_overflow_and_mixed_payloads(executor):
    from copycat_tpu.collections import DistributedMultiMap

    servers, (client,) = await _cluster(executor)
    try:
        mm = await client.get("mm", DistributedMultiMap)
        # past the device pair-table capacity (multimap_slots=16)
        for k in range(5):
            for v in range(5):
                assert await mm.put(k, v * 10)
        assert not await mm.put(0, 0)            # duplicate pair
        assert await mm.size() == 25
        assert await mm.size(2) == 5
        assert sorted(await mm.get(3)) == [0, 10, 20, 30, 40]
        # non-int32 payloads (hashable, as the reference requires)
        assert await mm.put("sk", "sv")
        assert await mm.contains_entry("sk", "sv")
        assert await mm.contains_value("sv")
        # remove-entry and remove-key across the device/shadow boundary
        assert await mm.remove(1, 10)            # remove one entry
        assert not await mm.contains_entry(1, 10)
        removed = await mm.remove(4)             # remove whole key
        assert sorted(removed) == [0, 10, 20, 30, 40]
        assert not await mm.contains_key(4)
        assert await mm.size() == 20             # 25 - 1 - 5 + 1(sk)
        await mm.clear()
        assert await mm.is_empty()
    finally:
        await _teardown([client] + servers)
