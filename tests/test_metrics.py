"""Metrics subsystem tests (utils/metrics.py + driver wiring)."""

import json

import pytest

from copycat_tpu.utils.metrics import (
    Histogram,
    MetricsRegistry,
    merge_snapshots,
)


def test_histogram_percentiles_interpolate():
    h = Histogram()
    for v in range(1, 101):
        h.record(float(v))
    assert h.count == 100 and h.mean == pytest.approx(50.5)
    # linear interpolation at rank p/100*(n-1) — numpy's default method
    assert h.percentile(50) == pytest.approx(50.5)
    assert h.percentile(99) == pytest.approx(99.01)
    assert h.percentile(0) == pytest.approx(1.0)
    assert h.percentile(100) == pytest.approx(100.0)
    assert Histogram().percentile(99) == 0.0


def test_histogram_small_sample_not_biased():
    # two samples: any mid percentile interpolates between them instead
    # of snapping to an endpoint
    h = Histogram()
    h.record(10.0)
    h.record(20.0)
    assert h.percentile(50) == pytest.approx(15.0)
    assert 10.0 < h.percentile(99) < 20.0
    one = Histogram()
    one.record(7.0)
    assert one.percentile(99) == 7.0


def test_histogram_reservoir_bounded():
    h = Histogram(reservoir=100)
    for v in range(10_000):
        h.record(float(v))
    assert h.count == 10_000
    assert len(h._values) == 100
    assert 0 < h.percentile(50) < 10_000


def test_histogram_merge():
    a = Histogram()
    b = Histogram()
    for v in range(100):
        a.record(float(v))
        b.record(float(v + 1000))
    a.merge_from(b)
    assert a.count == 200
    assert a.sum == pytest.approx(sum(range(100)) + sum(range(1000, 1100)))
    assert a.percentile(99) > 1000


def test_registry_snapshot():
    reg = MetricsRegistry()
    reg.counter("ops").inc(5)
    reg.histogram("lat").record(2.0)
    with reg.timer("step"):
        pass
    snap = reg.snapshot()
    assert snap["ops"] == 5
    assert snap["lat"]["count"] == 1 and snap["lat"]["p99"] == 2.0
    assert snap["step"]["count"] == 1
    assert reg.rate("ops") > 0


def test_rate_of_missing_counter_is_zero():
    reg = MetricsRegistry()
    assert reg.rate("never_incremented") == 0.0
    assert reg.rate("never", node="5001") == 0.0


def test_gauge():
    reg = MetricsRegistry()
    g = reg.gauge("depth")
    g.set(10)
    g.inc(5)
    g.dec(2)
    assert g.value == 13
    assert reg.snapshot()["depth"] == 13
    # same name+labels -> same gauge object
    assert reg.gauge("depth") is g


def test_labels_key_metrics_independently():
    reg = MetricsRegistry()
    reg.counter("frames", direction="in").inc(3)
    reg.counter("frames", direction="out").inc(7)
    reg.counter("frames").inc(1)
    snap = reg.snapshot()
    assert snap["frames{direction=in}"] == 3
    assert snap["frames{direction=out}"] == 7
    assert snap["frames"] == 1
    # label order does not matter
    assert reg.counter("x", a=1, b=2) is reg.counter("x", b=2, a=1)


def test_registry_merge_with_labels():
    total = MetricsRegistry()
    for port in (5001, 5002):
        node = MetricsRegistry()
        node.counter("ops").inc(10)
        node.gauge("term").set(port)
        node.histogram("lat").record(float(port))
        total.merge(node, node=str(port))
    snap = total.snapshot()
    assert snap["ops{node=5001}"] == 10
    assert snap["ops{node=5002}"] == 10
    assert snap["term{node=5002}"] == 5002
    assert snap["lat{node=5001}"]["count"] == 1
    # merging the same node again accumulates counters
    again = MetricsRegistry()
    again.counter("ops").inc(1)
    total.merge(again, node="5001")
    assert total.snapshot()["ops{node=5001}"] == 11


def test_render_prometheus():
    reg = MetricsRegistry()
    reg.counter("ops_total").inc(5)
    reg.gauge("commit_lag", node="5001").set(2)
    reg.histogram("latency_ms").record(1.5)
    text = reg.render_prometheus()
    assert "# TYPE copycat_ops_total counter" in text
    assert "copycat_ops_total 5" in text
    assert 'copycat_commit_lag{node="5001"} 2' in text
    assert "# TYPE copycat_latency_ms summary" in text
    assert 'copycat_latency_ms{quantile="0.99"} 1.5' in text
    assert "copycat_latency_ms_count 1" in text
    # namespace override (the stats listener uses per-layer namespaces)
    assert "custom_ops_total 5" in reg.render_prometheus(namespace="custom")


def test_render_json_roundtrips():
    reg = MetricsRegistry()
    reg.counter("ops").inc(2)
    parsed = json.loads(reg.render_json())
    assert parsed["ops"] == 2


def test_merge_snapshots():
    a = MetricsRegistry()
    a.counter("ops").inc(5)
    a.histogram("lat").record(1.0)
    b = MetricsRegistry()
    b.counter("ops").inc(7)
    b.histogram("lat").record(3.0)
    merged = merge_snapshots([a.snapshot(), b.snapshot()])
    assert merged["ops"] == 12
    assert merged["lat"]["count"] == 2
    assert merged["lat"]["mean"] == pytest.approx(2.0)
    assert merged["lat"]["p99"] == 3.0


def test_merge_snapshots_keeps_gauges_point_in_time():
    # summing per-node gauges would fabricate values (term 5+5=10); the
    # _gauge_keys hint keeps them max'd instead
    a = MetricsRegistry()
    a.gauge("raft_term").set(5)
    a.gauge("raft_is_leader").set(1)
    a.counter("ops").inc(2)
    b = MetricsRegistry()
    b.gauge("raft_term").set(5)
    b.gauge("raft_is_leader").set(0)
    b.counter("ops").inc(3)
    merged = merge_snapshots([a.snapshot(), b.snapshot()])
    assert merged["raft_term"] == 5
    assert merged["raft_is_leader"] == 1
    assert merged["ops"] == 5
    assert "raft_term" in merged["_gauge_keys"]


def test_driver_records_commit_latency():
    jax = pytest.importorskip("jax")  # noqa: F841
    from copycat_tpu.models import RaftGroups
    from copycat_tpu.ops import apply as ap

    rg = RaftGroups(2, 3, log_slots=32)
    rg.wait_for_leaders()
    tags = [rg.submit(0, ap.OP_LONG_ADD, 1) for _ in range(8)]
    rg.run_until(tags)
    snap = rg.metrics.snapshot()
    assert snap["ops_submitted"] == 8
    assert snap["ops_committed"] == 8
    lat = snap["commit_latency_rounds"]
    assert lat["count"] == 8 and lat["p50"] >= 1
    assert snap["step_wall_ms"]["count"] == rg.rounds
