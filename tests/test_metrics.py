"""Metrics subsystem tests (utils/metrics.py + driver wiring)."""

import pytest

from copycat_tpu.utils.metrics import Histogram, MetricsRegistry


def test_histogram_percentiles():
    h = Histogram()
    for v in range(1, 101):
        h.record(float(v))
    assert h.count == 100 and h.mean == pytest.approx(50.5)
    assert h.percentile(50) == pytest.approx(51.0)
    assert h.percentile(99) == pytest.approx(100.0)
    assert Histogram().percentile(99) == 0.0


def test_histogram_reservoir_bounded():
    h = Histogram(reservoir=100)
    for v in range(10_000):
        h.record(float(v))
    assert h.count == 10_000
    assert len(h._values) == 100
    assert 0 < h.percentile(50) < 10_000


def test_registry_snapshot():
    reg = MetricsRegistry()
    reg.counter("ops").inc(5)
    reg.histogram("lat").record(2.0)
    with reg.timer("step"):
        pass
    snap = reg.snapshot()
    assert snap["ops"] == 5
    assert snap["lat"]["count"] == 1 and snap["lat"]["p99"] == 2.0
    assert snap["step"]["count"] == 1
    assert reg.rate("ops") > 0


def test_driver_records_commit_latency():
    jax = pytest.importorskip("jax")  # noqa: F841
    from copycat_tpu.models import RaftGroups
    from copycat_tpu.ops import apply as ap

    rg = RaftGroups(2, 3, log_slots=32)
    rg.wait_for_leaders()
    tags = [rg.submit(0, ap.OP_LONG_ADD, 1) for _ in range(8)]
    rg.run_until(tags)
    snap = rg.metrics.snapshot()
    assert snap["ops_submitted"] == 8
    assert snap["ops_committed"] == 8
    lat = snap["commit_latency_rounds"]
    assert lat["count"] == 8 and lat["p50"] >= 1
    assert snap["step_wall_ms"]["count"] == rg.rounds
