"""Tests for the batched TPU consensus engine (ops/ models/ parallel/).

Mirrors the reference's "real consensus, fake network" strategy
(SURVEY.md §4): full elections, replication, commitment and apply run for
every group, with message delivery masked for partitions — all inside the
compiled step.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from copycat_tpu.models import RaftGroups  # noqa: E402
from copycat_tpu.ops import apply as ap  # noqa: E402
from copycat_tpu.ops.consensus import LEADER, Config  # noqa: E402


def make(groups=4, peers=3, **kw):
    kw.setdefault("log_slots", 32)
    return RaftGroups(groups, peers, **kw)


class LeaderLedger:
    """Tracks (group, term) -> leader across rounds; asserts election safety."""

    def __init__(self):
        self.seen = {}

    def observe(self, rg: RaftGroups):
        role = np.asarray(rg.state.role)
        term = np.asarray(rg.state.term)
        for g, p in zip(*np.nonzero(role == LEADER)):
            key = (int(g), int(term[g, p]))
            prev = self.seen.setdefault(key, int(p))
            assert prev == int(p), f"two leaders for group {g} term {term[g, p]}"


def test_every_group_elects_one_leader():
    rg = make(groups=8, peers=3)
    ledger = LeaderLedger()
    leaders = None
    for _ in range(100):
        out = rg.step_round()
        ledger.observe(rg)
        leaders = np.asarray(out.leader)
        if (leaders >= 0).all():
            break
    assert (leaders >= 0).all()
    # exactly one leader lane per group at max term
    role = np.asarray(rg.state.role)
    assert (np.sum(role == LEADER, axis=1) >= 1).all()


def test_counter_ops_commit_and_replicate():
    rg = make(groups=2, peers=3)
    rg.wait_for_leaders()
    tags = [rg.submit(0, ap.OP_LONG_ADD, 1) for _ in range(10)]
    tags += [rg.submit(1, ap.OP_LONG_ADD, 5) for _ in range(4)]
    rg.run_until(tags)
    # addAndGet semantics: strictly increasing prefix sums per group
    g0 = [rg.results[t] for t in tags[:10]]
    g1 = [rg.results[t] for t in tags[10:]]
    assert g0 == list(range(1, 11))
    assert g1 == [5, 10, 15, 20]
    # replicas converge once followers learn the commit index
    rg.run(5)
    val = np.asarray(rg.state.resources.value)
    assert (val[0] == 10).all()
    assert (val[1] == 20).all()


def test_value_set_cas_get_semantics():
    rg = make(groups=1, peers=3)
    rg.wait_for_leaders()
    t_set = rg.submit(0, ap.OP_VALUE_SET, 5)
    t_cas_hit = rg.submit(0, ap.OP_VALUE_CAS, 5, 7)
    t_cas_miss = rg.submit(0, ap.OP_VALUE_CAS, 5, 9)
    t_gas = rg.submit(0, ap.OP_VALUE_GET_AND_SET, 42)
    t_get = rg.submit(0, ap.OP_VALUE_GET)
    rg.run_until([t_set, t_cas_hit, t_cas_miss, t_gas, t_get])
    assert rg.results[t_cas_hit] == 1
    assert rg.results[t_cas_miss] == 0
    assert rg.results[t_gas] == 7
    assert rg.results[t_get] == 42


def test_leader_partition_failover_preserves_committed_writes():
    rg = make(groups=1, peers=3, log_slots=32)
    ledger = LeaderLedger()
    rg.wait_for_leaders()
    t1 = rg.submit(0, ap.OP_LONG_ADD, 7)
    rg.run_until([t1])
    old_leader = rg.leader(0)
    assert old_leader >= 0

    # Partition the leader from both followers.
    deliver = np.ones((1, 3, 3), bool)
    deliver[0, old_leader, :] = False
    deliver[0, :, old_leader] = False
    rg.deliver = jnp.asarray(deliver)
    for _ in range(60):
        rg.step_round()
        ledger.observe(rg)
        new_leader = rg.leader(0)
        if new_leader >= 0 and new_leader != old_leader:
            break
    assert rg.leader(0) != old_leader

    # The new leader must still have the committed write (leader completeness).
    t2 = rg.submit(0, ap.OP_LONG_ADD, 3)
    rg.run_until([t2], max_rounds=100)
    assert rg.results[t2] == 10

    # Heal; the deposed leader catches up and converges.
    rg.deliver = jnp.ones((1, 3, 3), bool)
    rg.run(20)
    ledger.observe(rg)
    val = np.asarray(rg.state.resources.value)
    assert (val[0] == 10).all()


def test_exactly_once_under_partitions():
    """The provable-loss retry protocol end to end: every queue-managed
    op submitted across random partitions eventually resolves, and the
    final counter equals the number of increments — nothing lost
    (entries overwritten by new leaders get re-submitted) and nothing
    double-applied (re-submission only on proof of loss)."""
    rng = np.random.default_rng(11)
    rg = make(groups=3, peers=3, log_slots=32)
    rg.wait_for_leaders()
    tags = {g: [] for g in range(3)}
    for r in range(240):
        if r % 2 == 0:
            g = int(rng.integers(3))
            tags[g].append(rg.submit(g, ap.OP_LONG_ADD, 1))
        deliver = None
        if 0 < (r % 24) < 10:  # partition window
            deliver = jnp.asarray(rng.random((3, 3, 3)) > 0.3)
        rg.step_round(deliver=deliver)
    all_tags = [t for ts in tags.values() for t in ts]
    rg.run_until(all_tags, max_rounds=300)
    for g, ts in tags.items():
        t = rg.submit(g, ap.OP_LONG_ADD, 0)
        rg.run_until([t])
        assert rg.results[t] == len(ts), \
            f"group {g}: {rg.results[t]} applied vs {len(ts)} submitted"


def test_submit_batch_matches_scalar_submits():
    """The vectorized bulk-submit path must be behaviorally identical to
    per-op submits: same per-group FIFO order, same results, tags
    aligned with the input."""
    rg = make(groups=4, peers=3)
    rg.wait_for_leaders()
    groups = np.array([0, 0, 1, 2, 3, 3, 3])
    deltas = np.array([1, 2, 10, 5, 7, 1, 2])
    tags = rg.submit_batch(groups, ap.OP_LONG_ADD, deltas)
    assert tags.shape == (7,)
    rg.run_until(tags.tolist())
    # prefix sums per group prove FIFO within each group
    assert [rg.results[t] for t in tags.tolist()] == [1, 3, 10, 5, 7, 8, 10]
    # interleaves with scalar submits
    t = rg.submit(0, ap.OP_LONG_ADD, 4)
    more = rg.submit_batch([0], ap.OP_LONG_ADD, [5])
    rg.run_until([t, int(more[0])])
    assert rg.results[t] == 7 and rg.results[int(more[0])] == 12
    with pytest.raises(ValueError):
        rg.submit_batch([0], ap.OP_CFG_ADD, [1])


def test_checkquorum_releases_asymmetric_partition():
    """Stable ASYMMETRIC partition: the leader's outbound links to two of
    its three followers are cut, everything else stays up. The reachable
    follower is kept sticky by heartbeats (it refuses RequestVote —
    leader stickiness), so without CheckQuorum the group would wedge
    forever at 2 < 3 acks. CheckQuorum steps the quorumless leader down
    after an election timeout, heartbeats stop, and the fully-connected
    majority elects a working leader."""
    rg = make(groups=1, peers=4, log_slots=32)
    rg.wait_for_leaders()
    lead = rg.leader(0)
    others = [p for p in range(4) if p != lead]
    dl = np.ones((1, 4, 4), bool)
    dl[0, lead, others[1]] = False
    dl[0, lead, others[2]] = False
    tag = rg.submit(0, ap.OP_LONG_ADD, 5)
    for _ in range(80):
        rg.step_round(deliver=jnp.asarray(dl))
        if tag in rg.results:
            break
    assert rg.results.get(tag) == 5, \
        "group wedged under asymmetric partition (CheckQuorum inactive?)"


def test_safety_under_random_partitions():
    G, P = 4, 3
    rg = make(groups=G, peers=P, log_slots=64,
              config=Config(append_window=4, applies_per_round=4,
                            timer_min=4, timer_max=9))
    ledger = LeaderLedger()
    rng = np.random.default_rng(7)
    submitted = {g: [] for g in range(G)}
    for round_no in range(250):
        if round_no % 10 == 0:  # reshuffle partitions
            deliver = rng.random((G, P, P)) > 0.25
            rg.deliver = jnp.asarray(deliver)
        if round_no == 180:  # heal for convergence
            rg.deliver = jnp.ones((G, P, P), bool)
        if round_no < 150 and round_no % 3 == 0:
            g = int(rng.integers(G))
            submitted[g].append(rg.submit(g, ap.OP_LONG_ADD, 1))
        rg.step_round()
        ledger.observe(rg)

    # Completed results per group are strictly increasing prefix sums.
    for g in range(G):
        res = [rg.results[t] for t in submitted[g] if t in rg.results]
        assert res == sorted(res)
        assert len(res) == len(set(res))
    # After healing, replicas of each group converge on a single value.
    rg.run(30)
    val = np.asarray(rg.state.resources.value)
    applied = np.asarray(rg.state.applied_index)
    for g in range(G):
        assert len(set(val[g].tolist())) == 1, (g, val[g], applied[g])

    # Committed-prefix log matching across replicas (within ring window).
    log_term = np.asarray(rg.state.log_term)
    log_tag = np.asarray(rg.state.log_tag)
    last = np.asarray(rg.state.last_index)
    commit = np.asarray(rg.state.commit_index)
    L = rg.log_slots
    for g in range(G):
        lo = max(1, int(last[g].max()) - L + 1)
        hi = int(commit[g].min())
        for idx in range(lo, hi + 1):
            slot = (idx - 1) % L
            terms = {int(log_term[g, p, slot]) for p in range(P)
                     if idx > last[g, p] - L and idx <= last[g, p]}
            tags = {int(log_tag[g, p, slot]) for p in range(P)
                    if idx > last[g, p] - L and idx <= last[g, p]}
            assert len(terms) <= 1, (g, idx, terms)
            assert len(tags) <= 1, (g, idx, tags)


def test_stale_follower_caught_up_by_snapshot_install():
    """A follower partitioned past the ring window reconverges via
    host-side snapshot install (``install_snapshots``)."""
    L = 8
    rg = make(groups=1, peers=3, log_slots=L)
    rg.wait_for_leaders()
    leader = rg.leader(0)
    follower = next(p for p in range(3) if p != leader)

    # Fully isolate one follower; quorum of 2 keeps committing far past L.
    deliver = np.ones((1, 3, 3), bool)
    deliver[0, :, follower] = False
    deliver[0, follower, :] = False
    rg.deliver = jnp.asarray(deliver)
    tags = []
    for i in range(3 * L):
        tags.append(rg.submit(0, ap.OP_LONG_ADD, 1))
        rg.step_round()
    rg.run_until(tags, max_rounds=200)
    assert int(np.asarray(rg.state.commit_index)[0, leader]) > L

    # Heal: AppendEntries can no longer serve the follower (beyond the ring);
    # the stale flag must trigger snapshot install and full reconvergence.
    rg.deliver = jnp.ones((1, 3, 3), bool)
    rg.run(30)
    val = np.asarray(rg.state.resources.value)
    applied = np.asarray(rg.state.applied_index)
    assert (val[0] == 3 * L).all(), (val[0], applied[0])
    assert len(set(applied[0].tolist())) == 1


def test_single_peer_group_commits_immediately():
    rg = make(groups=1, peers=1)
    rg.wait_for_leaders()
    t = rg.submit(0, ap.OP_LONG_ADD, 9)
    rg.run_until([t], max_rounds=20)
    assert rg.results[t] == 9


@pytest.mark.parametrize("mesh_kind", ["groups", "groups_peers"])
def test_sharded_over_mesh(mesh_kind):
    from copycat_tpu.parallel import make_mesh

    if mesh_kind == "groups":
        mesh = make_mesh(groups=8)
        rg = RaftGroups(16, 3, log_slots=16, mesh=mesh)
    else:
        mesh = make_mesh(groups=2, peers=4)
        rg = RaftGroups(8, 4, log_slots=16, mesh=mesh)
    rg.wait_for_leaders()
    tags = [rg.submit(g, ap.OP_LONG_ADD, g + 1) for g in range(4)]
    rg.run_until(tags)
    for g in range(4):
        assert rg.results[tags[g]] == g + 1


def test_out_latency_tracks_append_to_apply_lag():
    """out_latency = rounds an entry waited in the log before apply (0 when
    the synchronous round replicates+commits+applies it immediately)."""
    rg = make(groups=2, peers=3)
    rg.wait_for_leaders()
    tags = [rg.submit(0, ap.OP_LONG_ADD, 1) for _ in range(3)]
    lats = []
    for _ in range(30):
        out = rg.step_round()
        v = np.asarray(out.out_valid)
        lats += list(np.asarray(out.out_latency)[v])
        if all(t in rg.results for t in tags):
            break
    assert all(t in rg.results for t in tags)
    assert lats, "no applied entries observed"
    L = rg.log_slots
    assert all(0 <= x <= L for x in lats), lats


def test_leader_lease_tracks_quorum_contact():
    """The lease bit must be HELD under full delivery and CLEARED within
    one round of the leader losing contact with a quorum — the
    falsifiable core of the BOUNDED_LINEARIZABLE read gate (a served
    atomic read relies on exactly this bit)."""
    import numpy as np

    from copycat_tpu.models.raft_groups import RaftGroups

    rg = RaftGroups(4, 3, log_slots=32, seed=2)
    leaders = rg.wait_for_leaders()
    rg.run(2)
    assert bool(np.asarray(rg.state.lease).any(axis=1).all()), \
        "full delivery must hold every group's lease"

    # isolate group 0's leader from BOTH followers: next round it cannot
    # assemble a quorum of acks, so its lease must drop (groups 1..3 keep
    # theirs)
    deliver = np.ones((4, 3, 3), bool)
    lead0 = int(leaders[0])
    deliver[0, lead0, :] = False
    deliver[0, :, lead0] = False
    deliver[0, lead0, lead0] = True
    rg.deliver = __import__("jax").numpy.asarray(deliver)
    rg.run(1)
    lease = np.asarray(rg.state.lease).any(axis=1)
    assert not lease[0], "isolated leader must lose the lease immediately"
    assert lease[1:].all(), "connected groups keep their leases"

    # heal: the lease returns once a quorum acks again
    rg.deliver = __import__("jax").numpy.asarray(np.ones((4, 3, 3), bool))
    rg.run(3)
    assert np.asarray(rg.state.lease).any(axis=1).all()


def test_step_rounds_fused_matches_single_steps_and_installs_stale():
    """``step_rounds(n)`` is semantically n ``step_round()`` calls with
    empty later rounds — including the deferred snapshot-install branch:
    a follower isolated past the ring window during a FUSED block must
    reconverge the same way it does under single-round stepping
    (round-5 review finding: the stale slice-and-install path had no
    coverage)."""
    L = 8
    rg = make(groups=2, peers=3, log_slots=L)
    rg.wait_for_leaders()
    leader = rg.leader(0)
    follower = next(p for p in range(3) if p != leader)

    deliver = np.ones((2, 3, 3), bool)
    deliver[0, :, follower] = False
    deliver[0, follower, :] = False
    rg.deliver = jnp.asarray(deliver)
    # drive the quorum side far past the ring with FUSED blocks only
    tags = []
    for _ in range(3 * L):
        tags.append(rg.submit(0, ap.OP_LONG_ADD, 1))
        rg.step_rounds(2)
    assert all(t in rg.results for t in tags)
    assert int(np.asarray(rg.state.commit_index)[0, leader]) > L

    # heal; the isolated follower is beyond AppendEntries range, so the
    # fused path's stale branch must snapshot-install it
    rg.deliver = jnp.ones((2, 3, 3), bool)
    for _ in range(8):
        rg.step_rounds(4)
    val = np.asarray(rg.state.resources.value)
    applied = np.asarray(rg.state.applied_index)
    assert (val[0] == 3 * L).all(), (val[0], applied[0])
    assert len(set(applied[0].tolist())) == 1

    # fused and single-round stepping agree on a fresh workload
    t2 = rg.submit_batch(np.arange(2), ap.OP_LONG_ADD, 5)
    rg.step_rounds(3)
    assert all(t in rg.results for t in t2.tolist())
