"""Typed device facade tests (models/device_resources.py).

Facades mirror the reference's client resource classes; each test drives
real quorum commitment through the batched step.
"""

import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.ops import apply as ap  # noqa: E402
from copycat_tpu.models import (  # noqa: E402
    DeviceElection,
    DeviceLock,
    DeviceLong,
    DeviceMap,
    DeviceQueue,
    DeviceSet,
    DeviceValue,
    RaftGroups,
)


@pytest.fixture(scope="module")
def rg():
    groups = RaftGroups(4, 3, log_slots=64)
    groups.wait_for_leaders()
    return groups


def test_value_and_long(rg):
    v = DeviceValue(rg, 0)
    v.set(10)
    assert v.get() == 10
    assert v.compare_and_set(10, 20)
    assert not v.compare_and_set(10, 30)
    assert v.get_and_set(5) == 20

    n = DeviceLong(rg, 1)
    assert n.increment_and_get() == 1
    assert n.add_and_get(9) == 10
    assert n.get_and_add(5) == 10
    assert n.decrement_and_get() == 14
    assert n.get() == 14


def test_map_facade(rg):
    m = DeviceMap(rg, 2)
    assert m.put(1, 100) == 0
    assert m.get(1) == 100
    assert m.put_if_absent(1, 999) is False
    assert m.put_if_absent(2, 200) is True
    assert m.contains_key(2) and not m.contains_key(3)
    assert m.contains_value(200)
    assert m.size() == 2
    assert m.replace(1, 111) == 100
    assert m.replace(42, 1) is None
    assert m.replace_if(1, 111, 112)
    assert m.remove(1) == 112
    assert m.get_or_default(1, 7) == 7
    m.clear()
    assert m.is_empty()


def test_set_queue_facades(rg):
    s = DeviceSet(rg, 3)
    assert s.add(5) and not s.add(5)
    assert s.contains(5) and s.size() == 1
    assert s.remove(5) and s.is_empty()

    q = DeviceQueue(rg, 3)
    assert q.poll() is None
    q.add(1)
    assert q.offer(2)
    assert q.peek() == 1 and q.size() == 2
    assert q.poll() == 1 and q.poll() == 2 and q.poll() is None


def test_lock_facade_two_clients():
    rg = RaftGroups(1, 3, log_slots=64)
    rg.wait_for_leaders()
    a = DeviceLock(rg, 0, holder_id=101)
    b = DeviceLock(rg, 0, holder_id=102)
    a.lock()
    assert not b.try_lock()          # immediate try fails while held
    assert not b.try_lock(timeout=3)  # expires in log time, race-free cancel
    a.unlock()
    assert b.try_lock()
    b.unlock()


def test_lock_blocking_handoff():
    rg = RaftGroups(1, 3, log_slots=64)
    rg.wait_for_leaders()
    a = DeviceLock(rg, 0, holder_id=1)
    b = DeviceLock(rg, 0, holder_id=2)
    a.lock()
    # queue b, then release a: the grant event must complete b's lock()
    tag = rg.submit(0, __import__("copycat_tpu.ops.apply", fromlist=["x"])
                    .OP_LOCK_ACQUIRE, 2, -1)
    rg.run_until([tag])
    a.unlock()
    assert b._await_grant(None)
    b.unlock()


def test_no_stale_grant_after_immediate_grant():
    """An immediate grant is synchronous-only; a later queued try_lock must
    not be satisfied by any stale event (mutual exclusion regression)."""
    rg = RaftGroups(1, 3, log_slots=64)
    rg.wait_for_leaders()
    a = DeviceLock(rg, 0, holder_id=1)
    b = DeviceLock(rg, 0, holder_id=2)
    assert a.try_lock()
    a.unlock()
    b.lock()
    assert not a.try_lock(timeout=5)
    b.unlock()


def test_election_facade():
    rg = RaftGroups(1, 3, log_slots=64)
    rg.wait_for_leaders()
    e1 = DeviceElection(rg, 0, candidate_id=11)
    e2 = DeviceElection(rg, 0, candidate_id=22)
    epoch1 = e1.listen()
    assert epoch1 and e1.is_leader()
    assert e2.listen() is None
    assert not e2.is_leader()
    e1.resign()
    rg.run(10)
    assert e2.poll_elected() is not None
    assert e2.is_leader()
    assert not e1.is_leader(epoch1)  # stale fencing token rejected


def test_sequential_reads_via_query_lane():
    """SEQUENTIAL reads are served from the leader's applied state (no log
    append): committed writes are visible and the log does not grow."""
    import numpy as np
    groups = RaftGroups(2, 3, log_slots=64)
    groups.wait_for_leaders()
    m = DeviceMap(groups, 0).with_consistency("sequential")
    v = DeviceValue(groups, 1).with_consistency("sequential")
    m.put(3, 33)
    v.set(77)
    last_before = int(np.asarray(groups.state.last_index[0]).max())
    assert m.get(3) == 33
    assert m.get_or_default(9, 42) == 42
    assert m.contains_key(3) and not m.contains_key(9)
    assert m.size() == 1
    assert v.get() == 77
    last_after = int(np.asarray(groups.state.last_index[0]).max())
    assert last_after == last_before  # reads appended nothing
    assert groups.metrics.counter("queries_served").value >= 5


def test_query_lane_escalates_without_leader():
    """A query submitted before any leader exists cannot be served from
    applied state; it falls back to the command path and resolves through
    the log once a leader is elected (queries are never silently
    dropped — reference routes every query to a leader)."""
    groups = RaftGroups(1, 3, log_slots=64)
    assert groups.leader(0) == -1  # pre-election: genuinely leaderless
    tag = groups.submit_query(0, ap.OP_VALUE_GET)
    groups.step_round()  # query lane attempts + escalates
    assert groups.metrics.counter("queries_escalated").value >= 1
    groups.run_until([tag])  # election happens, command path serves it
    assert groups.results[tag] == 0


def test_sequential_reads_are_monotone():
    """Mixed read/write history: query-lane reads of a counter never go
    backwards (sequential consistency on one session)."""
    groups = RaftGroups(1, 3, log_slots=64)
    groups.wait_for_leaders()
    counter = DeviceLong(groups, 0)
    reader = DeviceLong(groups, 0).with_consistency("sequential")
    seen = 0
    for _ in range(10):
        counter.add_and_get(1)
        got = reader.get()
        assert got >= seen, f"read went backwards: {got} < {seen}"
        seen = got
    assert seen == 10  # quiesced: all committed increments visible


def test_query_lane_rejects_write_opcodes():
    """The query lane discards state, so writes must be rejected up front
    (a put 'served' there would be silently dropped with a success ack)."""
    groups = RaftGroups(1, 3, log_slots=64)
    with pytest.raises(ValueError, match="not read-only"):
        groups.submit_query(0, ap.OP_MAP_PUT, 1, 2)
