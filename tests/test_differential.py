"""Differential oracle harness (SURVEY §7.3 #6).

One seeded, randomized, multi-resource-type op sequence is driven through
BOTH execution paths and every single result is diffed:

- the CPU oracle: a real 3-server Raft cluster (AtomixServers over
  LocalTransport) with the resource library on top — the reference test
  topology ("real consensus, fake network"), and
- the device engine: ``RaftGroups`` stepping the batched ``[G,P]``
  consensus + apply kernels, driven through the typed facades.

Results are normalized to a canonical form (the CPU path's ``None`` absent
sentinel ↔ the device path's 0/FAIL encodings) by per-op adapters; any
divergence fails with the op index and full history prefix for replay.
"""

import asyncio
import random

import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.atomic import DistributedAtomicLong, DistributedAtomicValue
from copycat_tpu.collections import (
    DistributedMap,
    DistributedMultiMap,
    DistributedQueue,
    DistributedSet,
)
from copycat_tpu.coordination import DistributedLock
from copycat_tpu.models import (
    DeviceLock,
    DeviceLong,
    DeviceMap,
    DeviceMultiMap,
    DeviceQueue,
    DeviceSet,
    DeviceValue,
    RaftGroups,
)

from atomix_fixtures import Stack
from helpers import async_test

SEED = 20260729
NUM_OPS = 1000
KEYS = list(range(1, 11))       # map keyspace well under map_slots=16
VALUES = list(range(1, 51))     # nonzero: 0 is the canonical absent value
QUEUE_CAP = 12                  # stay under queue_slots=16 on both paths


def _gen_ops(rng: random.Random, n: int) -> list[tuple]:
    """Generate (resource, op, args) tuples; stateful guards keep the
    sequence within the device pools' fixed capacities and the lock
    protocol (only the tracked holder unlocks)."""
    ops = []
    queue_size = 0
    lock_holder = None  # None | "a" | "b"
    mm_pairs: set = set()      # live (key, value) pairs; device pool is 16
    for _ in range(n):
        kind = rng.choice(("value", "long", "map", "set", "queue", "lock",
                           "mmap"))
        if kind == "value":
            op = rng.choice(("get", "set", "cas", "get_and_set"))
            if op == "get":
                ops.append(("value", "get", ()))
            elif op == "set":
                ops.append(("value", "set", (rng.choice(VALUES),)))
            elif op == "cas":
                ops.append(("value", "cas",
                            (rng.choice(VALUES), rng.choice(VALUES))))
            else:
                ops.append(("value", "get_and_set", (rng.choice(VALUES),)))
        elif kind == "long":
            op = rng.choice(("get", "add", "inc", "dec"))
            if op == "get":
                ops.append(("long", "get", ()))
            elif op == "add":
                ops.append(("long", "add", (rng.randint(-7, 7),)))
            else:
                ops.append(("long", op, ()))
        elif kind == "map":
            k = rng.choice(KEYS)
            v = rng.choice(VALUES)
            op = rng.choice(("put", "get", "get_or_default", "put_if_absent",
                             "remove", "remove_if", "replace", "replace_if",
                             "contains_key", "contains_value", "size",
                             "is_empty"))
            args = {"put": (k, v), "get": (k,), "get_or_default": (k, v),
                    "put_if_absent": (k, v), "remove": (k,),
                    "remove_if": (k, v), "replace": (k, v),
                    "replace_if": (k, rng.choice(VALUES), v),
                    "contains_key": (k,), "contains_value": (v,),
                    "size": (), "is_empty": ()}[op]
            ops.append(("map", op, args))
        elif kind == "set":
            v = rng.choice(KEYS)
            op = rng.choice(("add", "remove", "contains", "size"))
            ops.append(("set", op, (v,) if op != "size" else ()))
        elif kind == "queue":
            op = rng.choice(("offer", "poll", "peek", "size"))
            if op == "offer":
                if queue_size >= QUEUE_CAP:
                    op = "poll"
                else:
                    queue_size += 1
            if op == "poll" and queue_size > 0:
                queue_size -= 1
            ops.append(("queue", op,
                        (rng.choice(VALUES),) if op == "offer" else ()))
        elif kind == "mmap":
            k = rng.choice(KEYS[:5])
            v = rng.choice(VALUES[:6])
            op = rng.choice(("put", "remove_all", "remove_entry",
                             "contains_key", "contains_entry",
                             "contains_value", "count", "size", "is_empty"))
            if op == "put" and len(mm_pairs | {(k, v)}) > 14:
                op = "remove_all"  # stay under the device pair pool
            if op == "put":
                mm_pairs.add((k, v))
            elif op == "remove_all":
                mm_pairs = {p for p in mm_pairs if p[0] != k}
            elif op == "remove_entry":
                mm_pairs.discard((k, v))
            args = {"put": (k, v), "remove_all": (k,),
                    "remove_entry": (k, v), "contains_key": (k,),
                    "contains_entry": (k, v), "contains_value": (v,),
                    "count": (k,), "size": (), "is_empty": ()}[op]
            ops.append(("mmap", op, args))
        else:  # lock
            if lock_holder is None:
                who = rng.choice(("a", "b"))
                lock_holder = who
                ops.append(("lock", "try_lock", (who,)))
            elif rng.random() < 0.6:
                ops.append(("lock", "unlock", (lock_holder,)))
                lock_holder = None
            else:
                # contended try_lock by the other client: must fail on both
                other = "b" if lock_holder == "a" else "a"
                ops.append(("lock", "try_lock_contended", (other,)))
    return ops


class CpuPath:
    """The oracle: resource library over a real 3-server CPU cluster."""

    def __init__(self, stack, client_a, client_b):
        self.stack = stack
        self.client_a = client_a
        self.client_b = client_b

    async def open(self):
        self.value = await self.client_a.get("value", DistributedAtomicValue)
        self.long = await self.client_a.get("long", DistributedAtomicLong)
        self.map = await self.client_a.get("map", DistributedMap)
        self.set = await self.client_a.get("set", DistributedSet)
        self.queue = await self.client_a.get("queue", DistributedQueue)
        self.mmap = await self.client_a.get("mmap", DistributedMultiMap)
        self.lock = {"a": await self.client_a.get("lock", DistributedLock),
                     "b": await self.client_b.get("lock", DistributedLock)}

    async def run(self, kind, op, args):
        if kind == "value":
            if op == "get":
                return (await self.value.get()) or 0
            if op == "set":
                return await self.value.set(*args)
            if op == "cas":
                return bool(await self.value.compare_and_set(*args))
            if op == "get_and_set":
                return (await self.value.get_and_set(*args)) or 0
        if kind == "long":
            if op == "get":
                return await self.long.get()
            if op == "add":
                return await self.long.add_and_get(*args)
            if op == "inc":
                return await self.long.increment_and_get()
            if op == "dec":
                return await self.long.decrement_and_get()
        if kind == "map":
            m = self.map
            if op == "put":
                return (await m.put(*args)) or 0
            if op == "get":
                return (await m.get(*args)) or 0
            if op == "get_or_default":
                return await m.get_or_default(*args)
            if op == "put_if_absent":
                return (await m.put_if_absent(*args)) is None
            if op == "remove":
                return (await m.remove(*args)) or 0
            if op == "remove_if":
                return bool(await m.remove_if_present(*args))
            if op == "replace":
                return await m.replace(*args)          # old value | None
            if op == "replace_if":
                return bool(await m.replace_if_present(*args))
            if op == "contains_key":
                return bool(await m.contains_key(*args))
            if op == "contains_value":
                return bool(await m.contains_value(*args))
            if op == "size":
                return await m.size()
            if op == "is_empty":
                return bool(await m.is_empty())
        if kind == "set":
            s = self.set
            if op == "add":
                return bool(await s.add(*args))
            if op == "remove":
                return bool(await s.remove(*args))
            if op == "contains":
                return bool(await s.contains(*args))
            if op == "size":
                return await s.size()
        if kind == "queue":
            q = self.queue
            if op == "offer":
                return bool(await q.offer(*args))
            if op == "poll":
                return await q.poll()                  # value | None
            if op == "peek":
                return await q.peek()
            if op == "size":
                return await q.size()
        if kind == "mmap":
            mm = self.mmap
            if op == "put":
                return bool(await mm.put(*args))
            if op == "remove_all":
                return len(await mm.remove(*args))   # removed-values list
            if op == "remove_entry":
                return bool(await mm.remove(*args))
            if op == "contains_key":
                return bool(await mm.contains_key(*args))
            if op == "contains_entry":
                return bool(await mm.contains_entry(*args))
            if op == "contains_value":
                return bool(await mm.contains_value(*args))
            if op == "count":
                return await mm.size(*args)          # per-key size
            if op == "size":
                return await mm.size()
            if op == "is_empty":
                return bool(await mm.is_empty())
        if kind == "lock":
            (who,) = args
            if op in ("try_lock", "try_lock_contended"):
                return bool(await self.lock[who].try_lock())
            if op == "unlock":
                return await self.lock[who].unlock()
        raise AssertionError(f"unhandled {kind}.{op}")


class DevicePath:
    """The engine under test: typed facades over the batched device step."""

    def __init__(self):
        # one group per resource type: value/long share an opcode register,
        # so they must live in separate groups
        self.rg = RaftGroups(7, 3, log_slots=64)
        self.rg.wait_for_leaders()
        self.value = DeviceValue(self.rg, 0)
        self.long = DeviceLong(self.rg, 1)
        self.map = DeviceMap(self.rg, 2)
        self.set = DeviceSet(self.rg, 3)
        self.queue = DeviceQueue(self.rg, 4)
        self.lock = {"a": DeviceLock(self.rg, 5, 1),
                     "b": DeviceLock(self.rg, 5, 2)}
        self.mmap = DeviceMultiMap(self.rg, 6)

    def run(self, kind, op, args):
        if kind == "value":
            v = self.value
            return {"get": v.get, "set": v.set, "cas": v.compare_and_set,
                    "get_and_set": v.get_and_set}[op](*args)
        if kind == "long":
            n = self.long
            return {"get": n.get, "add": n.add_and_get,
                    "inc": n.increment_and_get,
                    "dec": n.decrement_and_get}[op](*args)
        if kind == "map":
            m = self.map
            if op == "put_if_absent":
                return m.put_if_absent(*args)
            return {"put": m.put, "get": m.get,
                    "get_or_default": m.get_or_default, "remove": m.remove,
                    "remove_if": m.remove_if, "replace": m.replace,
                    "replace_if": m.replace_if,
                    "contains_key": m.contains_key,
                    "contains_value": m.contains_value, "size": m.size,
                    "is_empty": m.is_empty}[op](*args)
        if kind == "set":
            s = self.set
            return {"add": s.add, "remove": s.remove, "contains": s.contains,
                    "size": s.size}[op](*args)
        if kind == "queue":
            q = self.queue
            return {"offer": q.offer, "poll": q.poll, "peek": q.peek,
                    "size": q.size}[op](*args)
        if kind == "mmap":
            mm = self.mmap
            return {"put": mm.put, "remove_all": mm.remove,
                    "remove_entry": mm.remove_entry,
                    "contains_key": mm.contains_key,
                    "contains_entry": mm.contains_entry,
                    "contains_value": mm.contains_value,
                    "count": mm.count, "size": mm.size,
                    "is_empty": mm.is_empty}[op](*args)
        if kind == "lock":
            (who,) = args
            if op in ("try_lock", "try_lock_contended"):
                return self.lock[who].try_lock(0)
            if op == "unlock":
                return self.lock[who].unlock()
        raise AssertionError(f"unhandled {kind}.{op}")


@pytest.mark.parametrize("seed", [SEED, SEED + 1, SEED + 2])
@async_test(timeout=900)
async def test_differential_cpu_oracle_vs_device_engine(seed):
    rng = random.Random(seed)
    ops = _gen_ops(rng, NUM_OPS)

    # Build the device path FIRST: its jit compile blocks the event loop,
    # and the CPU cluster's session keep-alives must not miss their window
    # while XLA compiles (a long block expires sessions, whose fan-out
    # detaches resource instances — correct behavior, wrong test).
    dev = DevicePath()

    stack = await Stack().start(3, session_timeout=30.0)
    try:
        client_a = await stack.client(session_timeout=30.0)
        client_b = await stack.client(session_timeout=30.0)
        cpu = CpuPath(stack, client_a, client_b)
        await cpu.open()

        mismatches = []
        for i, (kind, op, args) in enumerate(ops):
            got_cpu = await asyncio.wait_for(cpu.run(kind, op, args), 30)
            got_dev = dev.run(kind, op, args)
            if got_cpu != got_dev:
                mismatches.append((i, kind, op, args, got_cpu, got_dev))
                if len(mismatches) >= 5:
                    break
        assert not mismatches, (
            "CPU oracle and device engine diverged "
            f"(seed={seed}):\n" + "\n".join(
                f"  op[{i}] {k}.{o}{a}: cpu={c!r} device={d!r}"
                for i, k, o, a, c, d in mismatches))
    finally:
        await stack.close()
