"""Replication-plane tests: the pipelined leader->follower stream
(server/raft.py `_replicate_pipelined`), the stop-and-wait lane it
A/Bs against (COPYCAT_REPL_PIPELINE=0), the log-rewind path (conflicting
suffix -> truncate -> last_index hint rewind -> reconverge), the
no-progress backoff branch, backpressure caps, the COPYCAT_REPL_WINDOW
knob, and the transport-level pending-correlation leak fix.
"""

import asyncio

import pytest

from helpers import async_test
from raft_fixtures import Get, Put, create_cluster

from copycat_tpu.client.client import RaftClient
from copycat_tpu.io.local import LocalTransport
from copycat_tpu.io.serializer import Serializer
from copycat_tpu.io.transport import Address
from copycat_tpu.protocol import messages as msg
from copycat_tpu.server.log import NoOpEntry
from copycat_tpu.server.raft import FOLLOWER, LEADER, _PeerStream

LANES = ("1", "0")  # pipelined, stop-and-wait


async def _await_leader_among(servers, timeout=15.0):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        for s in servers:
            if s.is_open and s.role == LEADER:
                return s
        await asyncio.sleep(0.02)
    raise TimeoutError("no leader among the given servers")


def _assert_logs_converged(servers, up_to=None):
    """Committed logs are bit-identical across members: every index both
    members still hold (compaction timing may differ) serializes to the
    same bytes — replicated entries carry the leader's term/timestamp."""
    ser = Serializer()
    base = servers[0]
    limit = up_to or min(s.commit_index for s in servers)
    compared = 0
    for other in servers[1:]:
        for i in range(1, limit + 1):
            a, b = base.log.get(i), other.log.get(i)
            if a is None or b is None:
                continue
            assert ser.write(a) == ser.write(b), \
                f"log divergence at {i}: {a!r} != {b!r}"
            compared += 1
    assert compared > 0, "nothing compared: logs fully compacted?"


# ---------------------------------------------------------------------------
# divergence -> truncate -> hint rewind -> reconverge (both lanes)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("lane", LANES)
def test_follower_divergence_truncates_and_reconverges(lane, monkeypatch):
    monkeypatch.setenv("COPYCAT_REPL_PIPELINE", lane)

    @async_test(timeout=120)
    async def run():
        cluster = await create_cluster(3, session_timeout=30.0)
        try:
            old = await cluster.await_leader()
            client = await cluster.client(session_timeout=30.0)
            await client.submit(Put(key="base", value=1))

            # isolate the leader and grow an uncommitted CONFLICTING
            # suffix on it (its own term; a quorum never sees it)
            nem = cluster.registry.attach_nemesis()
            others = [s for s in cluster.servers if s is not old]
            nem.partition([old.address], [s.address for s in others])
            for _ in range(5):
                old._append(NoOpEntry())
            diverged_at = old.log.last_index

            # the majority elects and commits PAST the divergence point
            new = await _await_leader_among(others, timeout=20)
            assert new.term > old.term
            maj = RaftClient([s.address for s in others],
                             LocalTransport(cluster.registry),
                             session_timeout=30.0)
            await maj.open()
            cluster.clients.append(maj)
            for i in range(10):
                await asyncio.wait_for(
                    maj.submit(Put(key="post", value=i)), 30)

            # heal: the old leader's suffix must truncate (conflict scan)
            # and the stream rewind via the last_index hint, then converge
            nem.heal()
            deadline = asyncio.get_running_loop().time() + 20
            while asyncio.get_running_loop().time() < deadline:
                if (old.role == FOLLOWER
                        and old.state_machine.data.get("post") == 9
                        and old.commit_index >= new.commit_index):
                    break
                await asyncio.sleep(0.05)
            assert old.role == FOLLOWER
            assert old.state_machine.data.get("post") == 9
            # the conflicting suffix is gone: whatever occupies those
            # indices now carries the NEW leader's term
            for i in range(diverged_at - 4, diverged_at + 1):
                e = old.log.get(i)
                if e is not None:
                    assert e.term >= new.term or e.term < old.term, (i, e)
            _assert_logs_converged(cluster.servers)
        finally:
            await cluster.close()

    run()


@pytest.mark.parametrize("lane", LANES)
def test_lagging_follower_last_index_hint_rewind(lane, monkeypatch):
    """A fresh leader starts every peer at next_index = last+1; a
    follower that missed a burst refuses the first append (prev past its
    tail) with its last_index as the hint, and the stream must rewind to
    it in ONE step and re-stream the gap (repl.rewinds counts it)."""
    monkeypatch.setenv("COPYCAT_REPL_PIPELINE", lane)

    @async_test(timeout=120)
    async def run():
        cluster = await create_cluster(3, session_timeout=30.0)
        try:
            old = await cluster.await_leader()
            client = await cluster.client(session_timeout=30.0)
            # isolate one FOLLOWER, commit a burst past it
            lagging = next(s for s in cluster.servers if s is not old)
            rest = [s for s in cluster.servers if s is not lagging]
            nem = cluster.registry.attach_nemesis()
            nem.partition([lagging.address], [s.address for s in rest])
            futs = [client.submit_command_nowait(Put(key="k", value=i))
                    for i in range(80)]
            await asyncio.gather(*futs)
            behind_by = old.log.last_index - lagging.log.last_index
            assert behind_by > 0

            # depose the old leader and heal: the surviving up-to-date
            # member elects, starts the lagging peer at ITS last+1, and
            # must hint-rewind to the peer's tail
            await old.close()
            nem.heal()
            survivor = next(s for s in rest if s is not old)
            new = await _await_leader_among([survivor, lagging], timeout=30)
            deadline = asyncio.get_running_loop().time() + 20
            while asyncio.get_running_loop().time() < deadline:
                if lagging.state_machine.data.get("k") == 79:
                    break
                await asyncio.sleep(0.05)
            assert lagging.state_machine.data.get("k") == 79
            assert new.metrics.counter("repl.rewinds").value >= 1
            _assert_logs_converged([new, lagging])
        finally:
            await cluster.close()

    run()


@pytest.mark.parametrize("lane", LANES)
def test_no_progress_backoff_branch(lane, monkeypatch):
    """A follower that refuses every append without a usable hint drives
    the leader's rewind to the log base; the leader must back off (stall
    counter) instead of hot-spinning, stay leader via the healthy
    follower, and reconverge once the refusal clears."""
    monkeypatch.setenv("COPYCAT_REPL_PIPELINE", lane)

    @async_test(timeout=120)
    async def run():
        cluster = await create_cluster(3, session_timeout=30.0)
        try:
            leader = await cluster.await_leader()
            client = await cluster.client(session_timeout=30.0)
            await client.submit(Put(key="a", value=1))
            victim = next(s for s in cluster.servers if s is not leader)

            async def reject(request):
                return msg.AppendResponse(term=victim.term, success=False,
                                          last_index=0)

            victim._on_append = reject  # new connections pick this up
            conn = leader._peer_connections.get(victim.address)
            if conn is not None:
                await conn.close()  # force a re-dial onto the patched handler

            stalls0 = leader.metrics.counter("repl.stalls").value
            for i in range(5):
                await asyncio.wait_for(
                    client.submit(Put(key="b", value=i)), 30)
            deadline = asyncio.get_running_loop().time() + 10
            while asyncio.get_running_loop().time() < deadline:
                if leader.metrics.counter("repl.stalls").value > stalls0:
                    break
                await asyncio.sleep(0.05)
            assert leader.role == LEADER
            assert leader.metrics.counter("repl.stalls").value > stalls0

            # clear the fault: the class handler serves again
            del victim.__dict__["_on_append"]
            conn = leader._peer_connections.get(victim.address)
            if conn is not None:
                await conn.close()
            deadline = asyncio.get_running_loop().time() + 20
            while asyncio.get_running_loop().time() < deadline:
                if victim.state_machine.data.get("b") == 4:
                    break
                await asyncio.sleep(0.05)
            assert victim.state_machine.data.get("b") == 4
        finally:
            await cluster.close()

    run()


# ---------------------------------------------------------------------------
# knobs, backpressure, adaptive window
# ---------------------------------------------------------------------------


def test_repl_window_knob_reaches_both_lanes(monkeypatch):
    monkeypatch.setenv("COPYCAT_REPL_WINDOW", "16")

    @async_test(timeout=60)
    async def run():
        cluster = await create_cluster(3, session_timeout=30.0)
        try:
            leader = await cluster.await_leader()
            assert leader._repl_window == 16
            client = await cluster.client(session_timeout=30.0)
            futs = [client.submit_command_nowait(Put(key="k", value=i))
                    for i in range(100)]
            await asyncio.gather(*futs)
            hist = leader.metrics.histogram("repl.window_entries")
            assert hist.count > 0
            assert hist.max_value <= 16, hist.max_value
        finally:
            await cluster.close()

    run()


def test_backpressure_caps_inflight_entries(monkeypatch):
    """A tiny in-flight budget + wire latency: the pump must hold the
    stream at the cap (backpressure counter moves) and still commit
    everything; the gauges return to zero once the stream drains."""
    monkeypatch.setenv("COPYCAT_REPL_PIPELINE", "1")
    monkeypatch.setenv("COPYCAT_REPL_WINDOW", "8")
    monkeypatch.setenv("COPYCAT_REPL_DEPTH", "1")
    monkeypatch.setenv("COPYCAT_REPL_MAX_INFLIGHT", "8")

    @async_test(timeout=120)
    async def run():
        cluster = await create_cluster(3, session_timeout=30.0)
        try:
            leader = await cluster.await_leader()
            client = await cluster.client(session_timeout=30.0)
            nem = cluster.registry.attach_nemesis()
            nem.set_delay(0.002)
            futs = [client.submit_command_nowait(Put(key="k", value=i))
                    for i in range(150)]
            await asyncio.gather(*futs)
            assert leader.metrics.counter(
                "repl.backpressure_waits").value > 0
            nem.heal()
            # poll for the drain — an in-flight heartbeat window may
            # legitimately show at any instant
            deadline = asyncio.get_running_loop().time() + 5
            while asyncio.get_running_loop().time() < deadline:
                if (leader.metrics.gauge("repl.windows_inflight").value == 0
                        and leader.metrics.gauge(
                            "repl.entries_inflight").value == 0):
                    break
                await asyncio.sleep(0.02)
            assert leader.metrics.gauge("repl.windows_inflight").value == 0
            assert leader.metrics.gauge("repl.entries_inflight").value == 0
            assert await client.submit(Get(key="k")) == 149
        finally:
            await cluster.close()

    run()


def test_peer_stream_adaptive_window():
    ps = _PeerStream(64)
    assert ps.window == 64 and ps.floor == 8
    ps.observe_ack(1.0)          # baseline
    ps.observe_ack(50.0)         # spike vs baseline: shrink
    assert ps.window < ps.ceiling
    # escalating congestion outruns the EWMA every ack: collapse to floor
    for lat in (100.0, 1000.0, 10000.0):
        ps.observe_ack(lat)
    assert ps.window == ps.floor
    # a PERSISTENT latency shift re-baselines (EWMA, not all-time best)
    # and the window regrows to the ceiling instead of reading the new
    # RTT as congestion forever
    for _ in range(60):
        ps.observe_ack(10000.0)
    assert ps.window == ps.ceiling
    for _ in range(200):         # never leaves [floor, ceiling]
        ps.observe_ack(0.1)
        assert ps.floor <= ps.window <= ps.ceiling


# ---------------------------------------------------------------------------
# satellite regressions: pending-correlation leak, stale-term metrics
# ---------------------------------------------------------------------------


@async_test
async def test_tcp_send_timeout_pops_pending_correlation():
    """A timed-out correlated send (the replication/ping pattern:
    asyncio.wait_for around conn.send) must not strand its future in the
    connection's _pending map until the connection closes."""
    from copycat_tpu.io.tcp import TcpTransport

    transport = TcpTransport()
    server = transport.server()
    release = asyncio.Event()

    def on_connect(conn):
        async def slow(m):
            await release.wait()
            return m.value

        conn.handler(Put, slow)

    await server.listen(Address("127.0.0.1", 0), on_connect)
    port = server._server.sockets[0].getsockname()[1]
    client = transport.client()
    conn = await client.connect(Address("127.0.0.1", port))
    try:
        with pytest.raises(asyncio.TimeoutError):
            await asyncio.wait_for(conn.send(Put(key="k", value=1)), 0.05)
        assert conn._pending == {}, "timed-out correlation leaked"
        # the connection is still usable after the leak-free timeout
        release.set()
        assert await asyncio.wait_for(
            conn.send(Put(key="k", value=2)), 5) == 2
        assert conn._pending == {}
    finally:
        await client.close()
        await server.close()


@async_test
async def test_stale_term_append_not_recorded(monkeypatch):
    """Appends from deposed leaders are rejected BEFORE touching the
    append-size histogram / heartbeat counter."""
    from copycat_tpu.io.local import LocalServerRegistry
    from copycat_tpu.server.raft import RaftServer
    from raft_fixtures import KVStateMachine, next_ports

    registry = LocalServerRegistry()
    addr, peer = next_ports(2)
    server = RaftServer(addr, [addr, peer], LocalTransport(registry),
                        KVStateMachine())
    server.term = 5
    entry = NoOpEntry(term=3, timestamp=0.0)
    entry.index = 1
    stale = msg.AppendRequest(term=3, leader=peer, prev_index=0,
                              prev_term=0, entries=[entry], commit_index=0)
    response = await server._on_append(stale)
    assert response.success is False and response.term == 5
    assert server.metrics.histogram("append_batch_entries").count == 0
    response = await server._on_append(msg.AppendRequest(
        term=3, leader=peer, prev_index=0, prev_term=0, entries=[],
        commit_index=0))
    assert response.success is False
    assert server.metrics.counter("append_heartbeats").value == 0

    # a CURRENT-term append still records (and a heartbeat still counts)
    fresh_entry = NoOpEntry(term=5, timestamp=0.0)
    fresh_entry.index = 1
    await server._on_append(msg.AppendRequest(
        term=5, leader=peer, prev_index=0, prev_term=0,
        entries=[fresh_entry], commit_index=0))
    assert server.metrics.histogram("append_batch_entries").count == 1
    await server._on_append(msg.AppendRequest(
        term=5, leader=peer, prev_index=1, prev_term=5, entries=[],
        commit_index=0))
    assert server.metrics.counter("append_heartbeats").value == 1
    if server._election_timer is not None:
        server._election_timer.cancel()
