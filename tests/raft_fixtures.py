"""Shared Raft test fixtures: a tiny KV state machine + cluster builders.

Mirrors the reference's test strategy (SURVEY.md §4): real N-server consensus
over the in-memory transport, tiny inline state machines, no mocks.
"""

from __future__ import annotations

import asyncio
from typing import Any

from copycat_tpu.io.local import LocalServerRegistry, LocalTransport
from copycat_tpu.io.transport import Address
from copycat_tpu.io.serializer import serialize_with
from copycat_tpu.protocol.messages import Message
from copycat_tpu.protocol.operations import Command, Query
from copycat_tpu.server.log import Storage, StorageLevel
from copycat_tpu.server.raft import LEADER, RaftServer
from copycat_tpu.server.state_machine import Commit, StateMachine
from copycat_tpu.client.client import RaftClient


@serialize_with(910)
class Put(Message, Command):
    _fields = ("key", "value")


@serialize_with(911)
class Get(Message, Query):
    _fields = ("key",)


@serialize_with(916)
class SeqGet(Get):
    def consistency(self):
        from copycat_tpu.protocol.operations import QueryConsistency

        return QueryConsistency.SEQUENTIAL


@serialize_with(917)
class BoundedGet(Get):
    def consistency(self):
        from copycat_tpu.protocol.operations import QueryConsistency

        return QueryConsistency.BOUNDED_LINEARIZABLE


@serialize_with(912)
class Notify(Message, Command):
    """Publishes an event back to the submitting session."""

    _fields = ("payload",)


@serialize_with(913)
class Fail(Message, Command):
    """Always raises inside the state machine."""

    _fields = ()


@serialize_with(914)
class PutTtl(Message, Command):
    _fields = ("key", "value", "ttl")


@serialize_with(915)
class Count(Message, Query):
    _fields = ()


class KVStateMachine(StateMachine):
    """Inline test machine exercising auto-registration, events, timers,
    and the crash-recovery plane's snapshot hooks (docs/DURABILITY.md):
    pending TTL deadlines are part of the snapshot image and re-scheduled
    on restore, so a recovered member expires keys at the same log time a
    never-crashed member does."""

    def __init__(self) -> None:
        super().__init__()
        self.data: dict[Any, Any] = {}
        self.applied_ops = 0
        self.expired_sessions: list[int] = []
        self.closed_sessions: list[int] = []
        self.ttl_deadlines: dict[Any, float] = {}  # key -> log-clock deadline

    def put(self, commit: Commit[Put]) -> Any:
        self.applied_ops += 1
        old = self.data.get(commit.operation.key)
        self.data[commit.operation.key] = commit.operation.value
        return old

    def put_ttl(self, commit: Commit[PutTtl]) -> Any:
        self.applied_ops += 1
        op = commit.operation
        old = self.data.get(op.key)
        self.data[op.key] = op.value
        key = op.key
        self.ttl_deadlines[key] = commit.time + op.ttl

        def expire() -> None:
            self.data.pop(key, None)
            self.ttl_deadlines.pop(key, None)
            commit.clean()

        self.executor.schedule(op.ttl, expire)
        return old

    # -- snapshot hooks ----------------------------------------------------

    def snapshot_state(self) -> Any:
        return {"data": dict(self.data),
                "applied_ops": self.applied_ops,
                "expired": list(self.expired_sessions),
                "closed": list(self.closed_sessions),
                "ttl": dict(self.ttl_deadlines)}

    def restore_state(self, data: Any, sessions: dict) -> None:
        self.data = dict(data["data"])
        self.applied_ops = data["applied_ops"]
        self.expired_sessions = list(data["expired"])
        self.closed_sessions = list(data["closed"])
        self.ttl_deadlines = dict(data["ttl"])
        clock = self.executor.context.clock
        for key, deadline in list(self.ttl_deadlines.items()):
            def expire(_key=key) -> None:
                # the creating commit is behind the snapshot boundary —
                # its log entry is already released, nothing to clean()
                self.data.pop(_key, None)
                self.ttl_deadlines.pop(_key, None)

            self.executor.schedule(max(0.0, deadline - clock), expire)

    def get(self, commit: Commit[Get]) -> Any:
        return self.data.get(commit.operation.key)

    def count(self, commit: Commit[Count]) -> int:
        return len(self.data)

    def notify(self, commit: Commit[Notify]) -> str:
        commit.session.publish("poked", commit.operation.payload)
        commit.clean()
        return "notified"

    def fail(self, commit: Commit[Fail]) -> None:
        commit.clean()
        raise ValueError("deliberate failure")

    def expire(self, session: Any) -> None:
        self.expired_sessions.append(session.id)

    def close(self, session: Any) -> None:
        self.closed_sessions.append(session.id)


def _norm(obj: Any) -> Any:
    """Order-insensitive canonical form for dict-shaped state (dict
    insertion order is an implementation detail, not replicated state)."""
    if isinstance(obj, dict):
        return tuple(sorted((repr(k), _norm(v)) for k, v in obj.items()))
    if isinstance(obj, (list, tuple)):
        return tuple(_norm(x) for x in obj)
    if isinstance(obj, set):
        return tuple(sorted(repr(x) for x in obj))
    return repr(obj)


def server_fingerprint(server: RaftServer, from_index: int | None = None):
    """Bit-comparable image of a server's replicated state — the
    recovery differential's equality subject: serialized log entries
    (from ``from_index``, so a prefix-truncated recovered member compares
    over the shared range), the state machine's snapshot image, and the
    session table's replicated halves."""
    from copycat_tpu.io.serializer import Serializer

    ser = Serializer()
    log = server.log
    start = log.first_index if from_index is None else max(
        log.first_index, from_index)
    entries = []
    for i in range(start, log.last_index + 1):
        e = log.get(i)
        entries.append(None if e is None else ser.write(e))
    machine = server.state_machine.snapshot_state()
    sessions = sorted(
        (sid, _norm(s.snapshot_dict())) for sid, s in server.sessions.items())
    return {
        "log_start": start,
        "log_last": log.last_index,
        "log": entries,
        "machine": None if machine is NotImplemented else _norm(machine),
        "sessions": sessions,
        "last_applied": server.last_applied,
        "clock": server.context.clock,
    }


_port_counter = [6000]


def next_ports(n: int) -> list[Address]:
    base = _port_counter[0]
    _port_counter[0] += n
    return [Address("local", base + i) for i in range(n)]


class Cluster:
    def __init__(self, servers: list[RaftServer], registry: LocalServerRegistry):
        self.servers = servers
        self.registry = registry
        self.clients: list[RaftClient] = []

    @property
    def leader(self) -> RaftServer | None:
        for server in self.servers:
            if server.is_open and server.role == LEADER:
                return server
        return None

    async def await_leader(self, timeout: float = 10.0) -> RaftServer:
        deadline = asyncio.get_running_loop().time() + timeout
        while asyncio.get_running_loop().time() < deadline:
            leader = self.leader
            # Require a stable leader whose term is seen by a quorum
            if leader is not None:
                return leader
            await asyncio.sleep(0.02)
        raise TimeoutError("no leader elected")

    async def client(self, session_timeout: float = 2.0) -> RaftClient:
        client = RaftClient(
            [s.address for s in self.servers],
            LocalTransport(self.registry),
            session_timeout=session_timeout,
        )
        await client.open()
        self.clients.append(client)
        return client

    async def close(self) -> None:
        for client in self.clients:
            try:
                await asyncio.wait_for(client.close(), 5)
            except (Exception, asyncio.TimeoutError):
                pass
        for server in self.servers:
            try:
                await asyncio.wait_for(server.close(), 5)
            except (Exception, asyncio.TimeoutError):
                pass


async def create_cluster(
    n: int = 3,
    machine_factory=KVStateMachine,
    election_timeout: float = 0.2,
    heartbeat_interval: float = 0.04,
    session_timeout: float = 2.0,
    storage: Storage | None = None,
    storage_factory=None,
) -> Cluster:
    registry = LocalServerRegistry()
    addresses = next_ports(n)
    servers = []
    for i, addr in enumerate(addresses):
        store = storage_factory(i) if storage_factory else (storage or Storage(StorageLevel.MEMORY))
        servers.append(
            RaftServer(
                addr,
                addresses,
                # local_address identifies this server's DIALS to the
                # nemesis (partition membership for peer connections)
                LocalTransport(registry, local_address=addr),
                machine_factory(),
                storage=store,
                election_timeout=election_timeout,
                heartbeat_interval=heartbeat_interval,
                session_timeout=session_timeout,
            )
        )
    await asyncio.gather(*(s.open() for s in servers))
    cluster = Cluster(servers, registry)
    await cluster.await_leader()
    return cluster
