"""Shared Raft test fixtures: a tiny KV state machine + cluster builders.

Mirrors the reference's test strategy (SURVEY.md §4): real N-server consensus
over the in-memory transport, tiny inline state machines, no mocks.
"""

from __future__ import annotations

import asyncio
from typing import Any

from copycat_tpu.io.local import LocalServerRegistry, LocalTransport
from copycat_tpu.io.transport import Address
from copycat_tpu.io.serializer import serialize_with
from copycat_tpu.protocol.messages import Message
from copycat_tpu.protocol.operations import Command, Query
from copycat_tpu.server.log import Storage, StorageLevel
from copycat_tpu.server.raft import LEADER, RaftServer
from copycat_tpu.server.state_machine import Commit, StateMachine
from copycat_tpu.client.client import RaftClient


@serialize_with(910)
class Put(Message, Command):
    _fields = ("key", "value")


@serialize_with(911)
class Get(Message, Query):
    _fields = ("key",)


@serialize_with(916)
class SeqGet(Get):
    def consistency(self):
        from copycat_tpu.protocol.operations import QueryConsistency

        return QueryConsistency.SEQUENTIAL


@serialize_with(917)
class BoundedGet(Get):
    def consistency(self):
        from copycat_tpu.protocol.operations import QueryConsistency

        return QueryConsistency.BOUNDED_LINEARIZABLE


@serialize_with(912)
class Notify(Message, Command):
    """Publishes an event back to the submitting session."""

    _fields = ("payload",)


@serialize_with(913)
class Fail(Message, Command):
    """Always raises inside the state machine."""

    _fields = ()


@serialize_with(914)
class PutTtl(Message, Command):
    _fields = ("key", "value", "ttl")


@serialize_with(915)
class Count(Message, Query):
    _fields = ()


class KVStateMachine(StateMachine):
    """Inline test machine exercising auto-registration, events, timers."""

    def __init__(self) -> None:
        super().__init__()
        self.data: dict[Any, Any] = {}
        self.applied_ops = 0
        self.expired_sessions: list[int] = []
        self.closed_sessions: list[int] = []

    def put(self, commit: Commit[Put]) -> Any:
        self.applied_ops += 1
        old = self.data.get(commit.operation.key)
        self.data[commit.operation.key] = commit.operation.value
        return old

    def put_ttl(self, commit: Commit[PutTtl]) -> Any:
        self.applied_ops += 1
        op = commit.operation
        old = self.data.get(op.key)
        self.data[op.key] = op.value
        key = op.key

        def expire() -> None:
            self.data.pop(key, None)
            commit.clean()

        self.executor.schedule(op.ttl, expire)
        return old

    def get(self, commit: Commit[Get]) -> Any:
        return self.data.get(commit.operation.key)

    def count(self, commit: Commit[Count]) -> int:
        return len(self.data)

    def notify(self, commit: Commit[Notify]) -> str:
        commit.session.publish("poked", commit.operation.payload)
        commit.clean()
        return "notified"

    def fail(self, commit: Commit[Fail]) -> None:
        commit.clean()
        raise ValueError("deliberate failure")

    def expire(self, session: Any) -> None:
        self.expired_sessions.append(session.id)

    def close(self, session: Any) -> None:
        self.closed_sessions.append(session.id)


_port_counter = [6000]


def next_ports(n: int) -> list[Address]:
    base = _port_counter[0]
    _port_counter[0] += n
    return [Address("local", base + i) for i in range(n)]


class Cluster:
    def __init__(self, servers: list[RaftServer], registry: LocalServerRegistry):
        self.servers = servers
        self.registry = registry
        self.clients: list[RaftClient] = []

    @property
    def leader(self) -> RaftServer | None:
        for server in self.servers:
            if server.is_open and server.role == LEADER:
                return server
        return None

    async def await_leader(self, timeout: float = 10.0) -> RaftServer:
        deadline = asyncio.get_running_loop().time() + timeout
        while asyncio.get_running_loop().time() < deadline:
            leader = self.leader
            # Require a stable leader whose term is seen by a quorum
            if leader is not None:
                return leader
            await asyncio.sleep(0.02)
        raise TimeoutError("no leader elected")

    async def client(self, session_timeout: float = 2.0) -> RaftClient:
        client = RaftClient(
            [s.address for s in self.servers],
            LocalTransport(self.registry),
            session_timeout=session_timeout,
        )
        await client.open()
        self.clients.append(client)
        return client

    async def close(self) -> None:
        for client in self.clients:
            try:
                await asyncio.wait_for(client.close(), 5)
            except (Exception, asyncio.TimeoutError):
                pass
        for server in self.servers:
            try:
                await asyncio.wait_for(server.close(), 5)
            except (Exception, asyncio.TimeoutError):
                pass


async def create_cluster(
    n: int = 3,
    machine_factory=KVStateMachine,
    election_timeout: float = 0.2,
    heartbeat_interval: float = 0.04,
    session_timeout: float = 2.0,
    storage: Storage | None = None,
    storage_factory=None,
) -> Cluster:
    registry = LocalServerRegistry()
    addresses = next_ports(n)
    servers = []
    for i, addr in enumerate(addresses):
        store = storage_factory(i) if storage_factory else (storage or Storage(StorageLevel.MEMORY))
        servers.append(
            RaftServer(
                addr,
                addresses,
                # local_address identifies this server's DIALS to the
                # nemesis (partition membership for peer connections)
                LocalTransport(registry, local_address=addr),
                machine_factory(),
                storage=store,
                election_timeout=election_timeout,
                heartbeat_interval=heartbeat_interval,
                session_timeout=session_timeout,
            )
        )
    await asyncio.gather(*(s.open() for s in servers))
    cluster = Cluster(servers, registry)
    await cluster.await_leader()
    return cluster
