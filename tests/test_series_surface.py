"""The retrospective-telemetry plane against live servers
(docs/OBSERVABILITY.md "Retrospective telemetry"): the ``/series``
routes, the ``COPYCAT_SERIES=0`` off-plane differential, the
nemesis-driven timeline (fault mark before election spike), and the
``doctor --last N`` retrospective."""

import asyncio
import json

import pytest

jax = pytest.importorskip("jax")

from copycat_tpu import cli  # noqa: E402
from copycat_tpu.io.local import NetworkNemesis  # noqa: E402
from copycat_tpu.server.log import Storage, StorageLevel  # noqa: E402
from copycat_tpu.server.stats import StatsListener, fetch_stats  # noqa: E402
from copycat_tpu.utils.health import assemble_doctor_report  # noqa: E402
from copycat_tpu.utils.timeseries import (  # noqa: E402
    assemble_timeline,
    render_timeline,
)

from helpers import arun  # noqa: E402
from raft_fixtures import Put, create_cluster  # noqa: E402


def test_series_route_serves_windowed_samples(monkeypatch):
    monkeypatch.setenv("COPYCAT_SERIES_INTERVAL_S", "0.05")

    async def run():
        cluster = await create_cluster(1)
        try:
            server = cluster.servers[0]
            assert server.series is not None
            client = await cluster.client()
            for i in range(4):
                await client.submit(Put(key=f"k{i}", value=i))
                server.series_tick()
                await asyncio.sleep(0.06)
            listener = await StatsListener(server, port=0).open()
            try:
                addr = f"127.0.0.1:{listener.port}"
                p = json.loads(await fetch_stats(addr, "/series"))
                assert p["node"] == str(server.address)
                assert p["role"] == "member"
                assert len(p["samples"]) >= 2
                sample = p["samples"][-1]["values"]
                # gauges sampled as-is, counters as per-interval deltas
                assert sample["raft_commit_index"] >= 1
                assert "commands_single_lane" in sample
                # the series.* self-family rides the ring too
                assert "series.samples" in sample
                # ?since windows, ?names prefix-filters
                mid = p["samples"][1]["t"]
                since = json.loads(await fetch_stats(
                    addr, f"/series?since={mid}"))
                assert all(r["t"] > mid for r in since["samples"])
                assert len(since["samples"]) < len(p["samples"])
                named = json.loads(await fetch_stats(
                    addr, "/series?names=raft_commit"))
                assert named["samples"]
                assert all(k.startswith("raft_commit")
                           for r in named["samples"] for k in r["values"])
                text = (await fetch_stats(addr, "/series.txt")).decode()
                assert "raft_commit_index" in text
                unknown = json.loads(await fetch_stats(addr, "/nope"))
                assert "/series" in unknown["routes"]
                assert "/series.txt" in unknown["routes"]
            finally:
                await listener.close()
        finally:
            await cluster.close()

    arun(run(), timeout=120)


def test_series_off_knob_removes_the_plane(monkeypatch):
    """COPYCAT_SERIES=0 differential: no store, no /series route, no
    series.*/slo.* registry keys, no slo_burn detector gauge — the
    registry key set and detector set match the pre-series plane
    exactly (the bit-identity A/B the plane is gated on)."""

    async def snapshot_keys():
        cluster = await create_cluster(1)
        try:
            server = cluster.servers[0]
            client = await cluster.client()
            await client.submit(Put(key="k", value=1))
            server.health.tick()
            listener = await StatsListener(server, port=0).open()
            try:
                addr = f"127.0.0.1:{listener.port}"
                series_body = json.loads(await fetch_stats(addr, "/series"))
                unknown = json.loads(await fetch_stats(addr, "/nope"))
                snap = server.stats_snapshot()["raft"]
                detectors = set(server.health.tick()["detectors"])
                return (server.series, series_body, unknown["routes"],
                        set(snap), detectors)
            finally:
                await listener.close()
        finally:
            await cluster.close()

    monkeypatch.setenv("COPYCAT_SERIES", "0")
    store_off, series_off, routes_off, keys_off, det_off = arun(
        snapshot_keys(), timeout=120)
    assert store_off is None
    # /series is ABSENT, not empty: the unknown-route error, unlisted
    assert "error" in series_off and "/series" not in routes_off
    assert not any(k.startswith(("series.", "slo.")) for k in keys_off)

    monkeypatch.setenv("COPYCAT_SERIES", "1")
    store_on, series_on, routes_on, keys_on, det_on = arun(
        snapshot_keys(), timeout=120)
    assert store_on is not None
    assert "samples" in series_on and "/series" in routes_on
    # the on-plane adds EXACTLY the series.* self-family, the slo_burn
    # detector and its status gauge (slo.* data gauges need objectives
    # set); everything else is bit-identical
    assert keys_on - keys_off == {
        "series.samples", "series.evictions", "series.names",
        "health.detector_status{detector=slo_burn}"}
    assert det_on - det_off == {"slo_burn"}


def test_nemesis_timeline_fault_before_election(monkeypatch, tmp_path):
    """The acceptance differential: a 3-member cluster with a fault
    mark recorded at injection time, then a full partition forcing
    elections — the merged timeline renders the fault mark BEFORE the
    election spike, member-attributed, on every member that spiked."""
    monkeypatch.setenv("COPYCAT_SERIES_INTERVAL_S", "0.05")

    async def run():
        cluster = await create_cluster(
            3, election_timeout=0.15, heartbeat_interval=0.03,
            storage_factory=lambda i: Storage(
                StorageLevel.DISK, str(tmp_path / str(i)),
                max_entries_per_segment=64))
        listeners = []
        try:
            client = await cluster.client()
            for i in range(5):
                await client.submit(Put(key=f"k{i}", value=i))
            for s in cluster.servers:
                s.series_tick()
            await asyncio.sleep(0.06)
            # the fault mark: recorded durably on every member at
            # injection time (what the device-plane nemesis does via
            # the flight ring; the host black-box is the CPU-plane home)
            for s in cluster.servers:
                s.health_note("fault", fault="partition")
            nemesis = cluster.registry.attach_nemesis(NetworkNemesis())
            nemesis.partition(*[[s.address] for s in cluster.servers])
            # isolated followers time out and start elections; keep
            # sampling until >= 2 members retained an election spike
            deadline = asyncio.get_running_loop().time() + 5.0
            while asyncio.get_running_loop().time() < deadline:
                await asyncio.sleep(0.06)
                spiked = 0
                for s in cluster.servers:
                    s.series_tick()
                    if any(r["values"].get("raft_elections_started")
                           for r in s.series.payload()["samples"]):
                        spiked += 1
                if spiked >= 2:
                    break
            assert spiked >= 2, "partition forced no election spikes"
            nemesis.heal()
            # assemble over the REAL wire: one listener per member, the
            # CLI's fan-out, the shipped assembler
            for s in cluster.servers:
                listeners.append(await StatsListener(s, port=0).open())
            addrs = [f"127.0.0.1:{ln.port}" for ln in listeners]
            members, failed = await cli.collect_timeline(addrs)
            assert not failed and len(members) == 3
            timeline = assemble_timeline(members, failed_members=failed,
                                         last_s=60)
            assert timeline["incomplete"] is False
            assert len(timeline["members"]) == 3
            ts = [e["t"] for e in timeline["events"]]
            assert ts == sorted(ts)  # merged stream is time-ordered
            election_members = set()
            for node in timeline["members"]:
                mine = [e for e in timeline["events"]
                        if e["member"] == node]
                faults = [e for e in mine if e["kind"] == "fault"]
                elections = [e for e in mine if e["kind"] == "election"]
                assert faults, f"{node}: fault mark missing"
                if elections:
                    election_members.add(node)
                    # the differential: cause strictly before symptom
                    assert min(f["t"] for f in faults) \
                        <= min(e["t"] for e in elections), node
            assert len(election_members) >= 2
            text = render_timeline(timeline)
            assert "fault" in text and "election" in text
        finally:
            for ln in listeners:
                await ln.close()
            await cluster.close()

    arun(run(), timeout=180)


def test_doctor_last_pulls_series_and_reports_onsets(monkeypatch):
    monkeypatch.setenv("COPYCAT_SERIES_INTERVAL_S", "0.05")

    async def run():
        cluster = await create_cluster(1)
        try:
            server = cluster.servers[0]
            client = await cluster.client()
            await client.submit(Put(key="k", value=1))
            # a quiet baseline, then a lag breach — the onset shape
            # (real wall timestamps: /series?since= windows on them)
            import time
            t0 = time.time() - 7.0
            base = server._series_snapshot()
            for i in range(6):
                server.series.ingest(dict(base), t=t0 + i)
            spike = dict(base)
            spike["raft_commit_lag"] = 40
            server.series.ingest(spike, t=t0 + 6)
            listener = await StatsListener(server, port=0).open()
            try:
                addr = f"127.0.0.1:{listener.port}"
                members, failed, traces = await cli.collect_doctor(
                    [addr], last_s=3600.0)
                payload = members[addr]
                assert payload["series"] is not None
                assert payload["series"]["samples"]
                report = assemble_doctor_report(members,
                                                failed_members=failed)
                node = str(server.address)
                assert node in report["retrospect"]
                onset = report["retrospect"][node][0]
                assert onset["key"] == "raft_commit_lag"
                assert onset["value"] == 40
                # without --last no series is fetched and no
                # retrospect section appears
                members2, _, _ = await cli.collect_doctor([addr])
                assert "series" not in members2[addr]
                report2 = assemble_doctor_report(members2)
                assert "retrospect" not in report2
            finally:
                await listener.close()
        finally:
            await cluster.close()

    arun(run(), timeout=120)


def _ns(**kw):
    return type("A", (), kw)()


def test_cli_timeline_verb_json_and_text(capsys, monkeypatch):
    monkeypatch.setenv("COPYCAT_SERIES_INTERVAL_S", "0.05")

    async def run():
        cluster = await create_cluster(1)
        try:
            client = await cluster.client()
            await client.submit(Put(key="k", value=1))
            server = cluster.servers[0]
            server.series_tick()
            await asyncio.sleep(0.06)
            server.series_tick()
            listener = await StatsListener(server, port=0).open()
            try:
                addr = f"127.0.0.1:{listener.port}"
                # to_thread: the verb owns its own asyncio.run, like
                # the real process would
                rc = await asyncio.to_thread(
                    cli._timeline, _ns(addresses=[addr], last=60.0,
                                       names=None, json=True))
                assert rc == 0
                timeline = json.loads(capsys.readouterr().out)
                assert timeline["members"] == [str(server.address)]
                assert timeline["incomplete"] is False
                assert timeline["series"][timeline["members"][0]]
                rc = await asyncio.to_thread(
                    cli._timeline, _ns(addresses=[addr], last=60.0,
                                       names="raft_commit_index",
                                       json=False))
                assert rc == 0
                out = capsys.readouterr().out
                assert "cluster timeline" in out
                assert "raft_commit_index" in out
            finally:
                await listener.close()
        finally:
            await cluster.close()

    arun(run(), timeout=120)
    # a fully unreachable cluster is a one-line error + exit 1
    rc = cli._timeline(_ns(addresses=["127.0.0.1:1"], last=60.0,
                           names=None, json=True))
    assert rc == 1
    assert "--stats-port" in capsys.readouterr().err


def test_cli_top_once(capsys):
    async def run():
        cluster = await create_cluster(1)
        try:
            client = await cluster.client()
            await client.submit(Put(key="k", value=1))
            listener = await StatsListener(cluster.servers[0],
                                           port=0).open()
            try:
                addr = f"127.0.0.1:{listener.port}"
                rc = await asyncio.to_thread(
                    cli._top, _ns(addresses=[addr, "127.0.0.1:1"],
                                  watch=0.1, once=True))
                assert rc == 0
                out = capsys.readouterr().out
                assert "cluster top" in out
                assert str(cluster.servers[0].address) in out
                # the dead addr renders as a row, never drops
                assert "UNREACHABLE" in out
            finally:
                await listener.close()
        finally:
            await cluster.close()

    arun(run(), timeout=120)
    rc = cli._top(_ns(addresses=["127.0.0.1:1"], watch=0.1, once=True))
    assert rc == 1


def test_cli_top_json_one_shot(capsys):
    """``top --json`` is the machine-readable one-shot the CI smoke
    reads: one frame as JSON, ``commit_rate`` honestly ``null`` (a
    single poll has no delta), unreachable members as rows."""
    async def run():
        cluster = await create_cluster(1)
        try:
            client = await cluster.client()
            await client.submit(Put(key="k", value=1))
            listener = await StatsListener(cluster.servers[0],
                                           port=0).open()
            try:
                addr = f"127.0.0.1:{listener.port}"
                rc = await asyncio.to_thread(
                    cli._top, _ns(addresses=[addr, "127.0.0.1:1"],
                                  watch=0.1, once=False, json=True))
                assert rc == 0
                frame = json.loads(capsys.readouterr().out)
                assert frame["failed"] == ["127.0.0.1:1"]
                member = str(cluster.servers[0].address)
                row = frame["members"][member]
                assert row["role"] in ("leader", "follower", "candidate")
                assert row["commit_rate"] is None  # one poll, no delta
                assert frame["worst_health"] in ("ok", "warn",
                                                 "critical",
                                                 "unreachable")
            finally:
                await listener.close()
        finally:
            await cluster.close()

    arun(run(), timeout=120)
    # every member down: --json exits 1 like --once
    rc = cli._top(_ns(addresses=["127.0.0.1:1"], watch=0.1, once=False,
                      json=True))
    assert rc == 1


def test_cli_parser_registers_new_verbs_and_doctor_last(capsys):
    import pytest as _pytest

    for argv in (["timeline"], ["top"]):
        with _pytest.raises(SystemExit):
            cli.main(argv)  # addresses are required
        capsys.readouterr()
    with _pytest.raises(SystemExit) as e:
        cli.main(["doctor", "--last", "nope", "127.0.0.1:1"])
    assert e.value.code == 2  # --last takes a float
    capsys.readouterr()
