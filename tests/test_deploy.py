"""Deployment-plane tests (docs/DEPLOYMENT.md): topology specs, the
supervisor's exit-code contract against real OS processes, and the
standalone ingress/proxy tier — in-process over the local transport for
the routing/event/exactly-once seams, and as genuinely killed-and-
restarted processes for the failover story.

The failover contract under test is PR 1's: a command whose outcome the
client cannot know (the proxy died holding it) surfaces as INDETERMINATE
(routing-exhaustion ``NO_LEADER`` / ``TimeoutError``) — never as a
definite failure, and never applied twice once the client re-routes
within the ingress tier.
"""

import asyncio
import socket

import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.client.client import (  # noqa: E402
    PinnedConnectionStrategy,
    RaftClient,
)
from copycat_tpu.deploy.ingress import IngressServer  # noqa: E402
from copycat_tpu.deploy.supervisor import (  # noqa: E402
    CONFIG_ERROR,
    RUNNING,
    Supervisor,
)
from copycat_tpu.deploy.topology import (  # noqa: E402
    TopologySpec,
    allocate_ports,
    load_machine,
)
from copycat_tpu.io.local import (  # noqa: E402
    LocalServerRegistry,
    LocalTransport,
)
from copycat_tpu.io.serializer import serialize_with  # noqa: E402
from copycat_tpu.io.transport import Address, TransportError  # noqa: E402
from copycat_tpu.protocol import messages as msg  # noqa: E402
from copycat_tpu.protocol.messages import Message  # noqa: E402
from copycat_tpu.protocol.operations import Command  # noqa: E402
from copycat_tpu.server.raft import LEADER, RaftServer  # noqa: E402
from copycat_tpu.testing.counter_machine import (  # noqa: E402
    ClusterAdd,
    ClusterGet,
    CounterMachine,
)

from helpers import async_test  # noqa: E402

MACHINE_SPEC = "copycat_tpu.testing.counter_machine:counter_machine"


@serialize_with(951)
class Poke(Message, Command):
    """Publishes a session event from the owning group's apply."""

    _fields = ("key", "payload")


class PokeCounterMachine(CounterMachine):
    def configure(self, executor) -> None:
        super().configure(executor)
        executor.register(Poke, self.poke)

    def poke(self, commit) -> str:
        commit.session.publish("poked", commit.operation.payload)
        commit.clean()
        return "poked"


# ---------------------------------------------------------------------------
# topology specs (pure units)
# ---------------------------------------------------------------------------


def test_allocate_ports_unique_and_bindable():
    ports = allocate_ports(20)
    assert len(set(ports)) == 20
    # each released port is actually bindable right after the probe
    s = socket.socket()
    try:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", ports[0]))
    finally:
        s.close()


def test_topology_spec_local_shape():
    spec = TopologySpec.local(members=3, ingresses=2, groups=4,
                              storage="mapped", machine=MACHINE_SPEC)
    assert [m.name for m in spec.members] == \
        ["member-0", "member-1", "member-2"]
    assert [i.name for i in spec.ingresses] == ["ingress-0", "ingress-1"]
    # every port in the topology is distinct: raft + stats x every role
    ports = [m.address.rsplit(":", 1)[1] for m in spec.members]
    ports += [str(m.stats_port) for m in spec.members]
    ports += [i.address.rsplit(":", 1)[1] for i in spec.ingresses]
    ports += [str(i.stats_port) for i in spec.ingresses]
    assert len(set(ports)) == len(ports)
    # clients dial the ingress tier when deployed, members otherwise
    assert spec.client_addrs() == spec.ingress_addrs()
    bare = TopologySpec.local(members=3, ingresses=0)
    assert bare.client_addrs() == bare.member_addrs()
    # each member gets its own log dir under the base
    dirs = {m.log_dir for m in spec.members}
    assert len(dirs) == 3
    assert all(d.startswith(spec.base_dir) for d in dirs)
    # stats_addrs covers every child by name
    assert set(spec.stats_addrs()) == {
        "member-0", "member-1", "member-2", "ingress-0", "ingress-1"}
    # the /topology control payload round-trips exactly
    again = TopologySpec.from_json(spec.to_json())
    assert again.to_json() == spec.to_json()


def test_member_and_ingress_argv_shape():
    spec = TopologySpec.local(members=2, ingresses=1, groups=2,
                              machine=MACHINE_SPEC)
    argv = spec.members[0].argv()
    assert argv[2:4] == ["copycat_tpu.deploy.child", "member"]
    assert spec.members[0].address in argv
    # peers exclude self (copycat-server's positional contract)
    assert argv.count(spec.members[0].address) == 1
    assert "--machine" in argv
    iargv = spec.ingresses[0].argv()
    assert iargv[2:4] == ["copycat_tpu.deploy.child", "ingress"]
    assert ",".join(spec.member_addrs()) in iargv


def test_load_machine_contract():
    assert load_machine(None) is None
    assert load_machine("") is None
    fn = load_machine(MACHINE_SPEC)
    assert isinstance(fn(0), CounterMachine)
    with pytest.raises(ValueError, match="expected module.path:factory"):
        load_machine("no-colon")
    with pytest.raises(ValueError, match="no attribute"):
        load_machine("copycat_tpu.testing.counter_machine:missing")
    with pytest.raises(ImportError):
        load_machine("copycat_tpu.not_a_module:thing")


# ---------------------------------------------------------------------------
# the standalone ingress tier, in-process (local transport)
# ---------------------------------------------------------------------------


async def _local_cluster(groups: int, machine_cls=CounterMachine,
                         n: int = 3):
    registry = LocalServerRegistry()
    addrs = [Address("local", p) for p in
             range(18500 + groups * 10, 18500 + groups * 10 + n)]
    servers = [
        RaftServer(addr, addrs,
                   LocalTransport(registry, local_address=addr),
                   (lambda g: machine_cls()), groups=groups,
                   election_timeout=0.2, heartbeat_interval=0.04,
                   session_timeout=30.0)
        for addr in addrs]
    await asyncio.gather(*(s.open() for s in servers))
    deadline = asyncio.get_running_loop().time() + 15
    while asyncio.get_running_loop().time() < deadline:
        led = {g.group_id for s in servers for g in s.groups
               if g.role == LEADER}
        if len(led) == groups:
            return registry, servers
        await asyncio.sleep(0.02)
    raise TimeoutError("not every group elected a leader")


async def _ingress_tier(registry, servers, groups: int, width: int = 1,
                        machine_cls=CounterMachine, base_port: int = 18900):
    tier_addrs = [Address("local", base_port + i) for i in range(width)]
    ingresses = [
        IngressServer(addr, [s.address for s in servers],
                      LocalTransport(registry, local_address=addr),
                      groups=groups, tier=tier_addrs,
                      route_machine=machine_cls,
                      session_timeout=30.0, election_timeout=0.2,
                      name=f"ingress-{i}")
        for i, addr in enumerate(tier_addrs)]
    await asyncio.gather(*(i.open() for i in ingresses))
    return ingresses


async def _close_all(*nodes) -> None:
    for node in nodes:
        try:
            await asyncio.wait_for(node.close(), 10)
        except (Exception, asyncio.TimeoutError):
            pass


@async_test(timeout=120)
async def test_ingress_routes_commands_and_reads_exactly_once():
    """Writes and linearizable reads through a standalone ingress land
    exactly once across 4 groups, and the client is told the INGRESS
    tier is the cluster (it never learns the members)."""
    registry, servers = await _local_cluster(groups=4)
    ingresses = await _ingress_tier(registry, servers, groups=4, width=1)
    client = RaftClient([ingresses[0].address], LocalTransport(registry),
                        session_timeout=30.0)
    try:
        await client.open()
        keys = [f"key-{i}" for i in range(24)]
        for rep in range(2):
            out = await asyncio.gather(*(
                client.submit(ClusterAdd(key=k, delta=1)) for k in keys))
            assert out == [rep + 1] * len(keys), out
        got = await asyncio.gather(*(client.submit(ClusterGet(key=k))
                                     for k in keys))
        assert got == [2] * len(keys), got
        # the members the client knows are the ingress tier, not the
        # Raft members behind it
        assert set(client.members) == {ingresses[0].address}
        # routing spread across groups actually happened
        forwarded = ingresses[0].metrics.counter(
            "ingress.commands_forwarded").value
        assert forwarded == 2 * len(keys)
        # every member applied each increment exactly once
        for s in servers:
            merged: dict = {}
            for g in s.groups:
                merged.update(g.state_machine.data)
            for k in keys:
                assert merged.get(k) == 2, (str(s.address), k)
    finally:
        await _close_all(client, *ingresses, *servers)


@async_test(timeout=120)
async def test_ingress_relays_session_events():
    """Events published by the owning group's apply travel member ->
    ingress (the proxied session binds to the ingress's peer connection)
    -> the client connection the ingress holds."""
    registry, servers = await _local_cluster(
        groups=2, machine_cls=PokeCounterMachine)
    ingresses = await _ingress_tier(registry, servers, groups=2, width=1,
                                    machine_cls=PokeCounterMachine)
    client = RaftClient([ingresses[0].address], LocalTransport(registry),
                        session_timeout=30.0)
    try:
        await client.open()
        got: list = []
        client.session().on_event("poked", got.append)
        # keys owned by BOTH groups: each owning group publishes on its
        # own channel, both relayed through the one ingress
        keys = []
        g_seen = set()
        i = 0
        while len(g_seen) < 2:
            k = f"evt{i}"
            g = CounterMachine.route_group(ClusterAdd(key=k, delta=0), 2)
            if g not in g_seen:
                g_seen.add(g)
                keys.append(k)
            i += 1
        for k in keys:
            assert await client.submit(Poke(key=k, payload=k)) == "poked"
        deadline = asyncio.get_running_loop().time() + 10
        while asyncio.get_running_loop().time() < deadline \
                and len(got) < 2:
            await asyncio.sleep(0.02)
        assert sorted(got) == sorted(keys), got
        assert ingresses[0].metrics.counter(
            "ingress.events_relayed").value >= 2
    finally:
        await _close_all(client, *ingresses, *servers)


@async_test(timeout=180)
async def test_ingress_failover_midbatch_exactly_once():
    """Kill the ingress a client is pinned to MID-BATCH: the client
    re-routes within the tier (it only ever knew the tier) and every
    submitted command lands at most once — acknowledged ones exactly
    once, failed ones only as INDETERMINATE (routing exhaustion /
    timeout), never a definite error, never a double apply."""
    registry, servers = await _local_cluster(groups=2)
    ingresses = await _ingress_tier(registry, servers, groups=2, width=2)
    client = RaftClient([i.address for i in ingresses],
                        LocalTransport(registry), session_timeout=30.0,
                        connection_strategy=PinnedConnectionStrategy(
                            ingresses[0].address))
    try:
        await client.open()
        assert client._connected_to == ingresses[0].address
        keys = [f"fk{i}" for i in range(120)]
        futs = {k: client.submit_command_nowait(ClusterAdd(key=k, delta=1))
                for k in keys}
        # half the batch is staged/in flight: hard-kill ingress-0 (the
        # in-process stand-in for the SIGKILL the supervisor test does
        # with real processes)
        await asyncio.sleep(0)
        await ingresses[0].close()
        acked: dict[str, int] = {}
        indet: dict[str, int] = {}
        for k, fut in futs.items():
            try:
                await asyncio.wait_for(fut, 30)
                acked[k] = 1
            except asyncio.TimeoutError:
                indet[k] = 1
            except msg.ProtocolError as e:
                # the PR 1 contract: only the in-doubt codes may surface
                assert e.code in (msg.NO_LEADER, msg.NOT_LEADER), e.code
                indet[k] = 1
        # the client re-routed WITHIN the tier
        follow_up = await client.submit(ClusterAdd(key="after", delta=1))
        assert follow_up == 1
        assert client._connected_to == ingresses[1].address
        # exactly-once: every acked write present, in-doubt ones at most
        # once — read through the surviving ingress
        for k in keys:
            v = await client.submit(ClusterGet(key=k))
            lo = acked.get(k, 0)
            hi = lo + indet.get(k, 0)
            assert lo <= v <= hi, (k, v, lo, hi)
        assert acked, "kill window swallowed the whole batch"
    finally:
        await _close_all(client, *ingresses, *servers)


# ---------------------------------------------------------------------------
# COPYCAT_INGRESS_TIER=0: the in-server ingress plane, pinned
# ---------------------------------------------------------------------------


@async_test(timeout=60)
async def test_ingress_tier_knob_off_single_group_has_no_proxy_handler(
        monkeypatch):
    """With the knob off, a single-group server registers NO
    ProxyRequest handler at all — the wire surface is the pre-deployment
    plane bit-identically, not a live-but-refusing route."""
    monkeypatch.setenv("COPYCAT_INGRESS_TIER", "0")
    registry, servers = await _local_cluster(groups=1)
    transport = LocalTransport(registry)
    try:
        conn = await transport.client().connect(servers[0].address)
        with pytest.raises(TransportError, match="no handler"):
            await conn.send(msg.ProxyRequest(
                group=None, kind="ingress:register",
                payload=("cid", 5.0, None)))
    finally:
        await _close_all(*servers)


@async_test(timeout=60)
async def test_ingress_tier_knob_off_multi_group_refuses(monkeypatch):
    """Multi-group servers keep their member->member proxy plane with
    the knob off, but refuse INGRESS-kind traffic explicitly."""
    monkeypatch.setenv("COPYCAT_INGRESS_TIER", "0")
    registry, servers = await _local_cluster(groups=2)
    transport = LocalTransport(registry)
    try:
        conn = await transport.client().connect(servers[0].address)
        response = await conn.send(msg.ProxyRequest(
            group=0, kind="ingress:register", payload=("cid", 5.0, None)))
        assert response.error == msg.INTERNAL
        assert "ingress tier disabled" in response.error_detail
    finally:
        await _close_all(*servers)


@async_test(timeout=60)
async def test_ingress_tier_knob_off_in_server_path_unchanged(monkeypatch):
    """The A/B differential: with the knob off, the classic client ->
    member ingress works exactly as before (same results, same
    exactly-once), because the knob only gates the NEW acceptance."""
    monkeypatch.setenv("COPYCAT_INGRESS_TIER", "0")
    registry, servers = await _local_cluster(groups=2)
    client = RaftClient([s.address for s in servers],
                        LocalTransport(registry), session_timeout=30.0)
    try:
        await client.open()
        for rep in range(2):
            out = await asyncio.gather(*(
                client.submit(ClusterAdd(key=f"d{i}", delta=1))
                for i in range(8)))
            assert out == [rep + 1] * 8, out
    finally:
        await _close_all(client, *servers)


# ---------------------------------------------------------------------------
# the supervisor against real OS processes
# ---------------------------------------------------------------------------


@async_test(timeout=600)
async def test_supervisor_restarts_sigkilled_children_and_clients_survive():
    """The process-level nemesis, test edition: SIGKILL the ingress
    proxy a client is pinned to AND a Raft member mid-run; the client
    re-routes within the tier with zero lost acknowledged writes and
    the supervisor restarts both corpses with backoff."""
    from copycat_tpu.io.tcp import TcpTransport

    # disk storage is load-bearing: this test SIGKILLs member-1, and a
    # MEMORY member restarts blank (log + voted_for gone) — it could
    # then grant a vote electing a leader missing an acked entry, a
    # true lost acknowledged write the zero-lost assertion would catch
    spec = TopologySpec.local(members=3, ingresses=2, groups=1,
                              storage="disk", machine=MACHINE_SPEC)
    sup = Supervisor(spec)
    await sup.open()
    client = None
    try:
        await sup.wait_healthy(timeout=240)
        addrs = [Address.parse(a) for a in spec.client_addrs()]
        client = RaftClient(addrs, TcpTransport(), session_timeout=60.0,
                            connection_strategy=PinnedConnectionStrategy(
                                addrs[0]))
        await client.open()
        acked = 0
        for _ in range(5):
            await client.submit(ClusterAdd(key="n", delta=1))
            acked += 1

        # SIGKILL the proxy holding this client mid-batch
        futs = [client.submit_command_nowait(ClusterAdd(key="n", delta=1))
                for _ in range(40)]
        await asyncio.sleep(0)
        ok, detail = sup.kill("ingress-0")
        assert ok, detail
        indet = 0
        for fut in futs:
            try:
                await asyncio.wait_for(fut, 60)
                acked += 1
            except asyncio.TimeoutError:
                indet += 1
            except msg.ProtocolError as e:
                assert e.code in (msg.NO_LEADER, msg.NOT_LEADER), e.code
                indet += 1

        # and a member too (quorum survives)
        ok, detail = sup.kill("member-1")
        assert ok, detail
        await client.submit(ClusterAdd(key="n", delta=1))
        acked += 1

        # zero lost acknowledged writes, at-most-once for in-doubt ones
        v = await client.submit(ClusterGet(key="n"))
        assert acked <= v <= acked + indet, (v, acked, indet)

        # both corpses come back under supervision
        deadline = asyncio.get_running_loop().time() + 90
        while asyncio.get_running_loop().time() < deadline:
            children = sup.status()["children"]
            if all(children[n]["state"] == RUNNING and children[n]["pid"]
                   and children[n]["restarts"] >= 1
                   for n in ("ingress-0", "member-1")):
                break
            await asyncio.sleep(0.25)
        children = sup.status()["children"]
        for name in ("ingress-0", "member-1"):
            assert children[name]["state"] == RUNNING, children[name]
            assert children[name]["restarts"] >= 1, children[name]
    finally:
        if client is not None:
            await _close_all(client)
        await sup.close()


@async_test(timeout=300)
async def test_supervisor_config_error_is_terminal():
    """Exit code 2 (a port that can never bind) is a CONFIG error: the
    supervisor surfaces it and never crash-loops the child."""
    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    port = blocker.getsockname()[1]
    spec = TopologySpec.local(members=1, ingresses=0, storage="memory",
                              machine=MACHINE_SPEC)
    spec.members[0].address = f"127.0.0.1:{port}"
    spec.members[0].peers = [f"127.0.0.1:{port}"]
    sup = Supervisor(spec)
    await sup.open()
    try:
        child = sup._children["member-0"]
        deadline = asyncio.get_running_loop().time() + 240
        while child.state != CONFIG_ERROR:
            assert asyncio.get_running_loop().time() < deadline, child.state
            await asyncio.sleep(0.2)
        assert child.last_exit == 2
        assert child.restarts == 0
        assert sup.healthz_info()["ok"] is False
    finally:
        await sup.close()
        blocker.close()


@async_test(timeout=60)
async def test_deploy_tier_healthz_identity_and_series_route():
    """Every deployed role's `/healthz` carries the process identity
    (`uptime_s` + `git_sha`; members are covered in test_health), and
    the deploy tiers serve their own `/series` ring
    (docs/OBSERVABILITY.md § Retrospective telemetry)."""
    from copycat_tpu.deploy.supervisor import ControlListener
    from copycat_tpu.server.stats import StatsListener, fetch_stats

    registry, servers = await _local_cluster(groups=1)
    ingresses = await _ingress_tier(registry, servers, groups=1)
    spec = TopologySpec.local(members=1, ingresses=0, storage="memory",
                              machine=MACHINE_SPEC)
    sup = Supervisor(spec)  # never opened: no children, just the surface
    listeners = [await StatsListener(ingresses[0], port=0).open(),
                 await ControlListener(sup, port=0).open()]
    try:
        import json as _json
        roles = set()
        for ln in listeners:
            hz = _json.loads(await fetch_stats(
                f"127.0.0.1:{ln.port}", "/healthz"))
            assert hz["uptime_s"] >= 0.0
            assert "git_sha" in hz
            series = _json.loads(await fetch_stats(
                f"127.0.0.1:{ln.port}", "/series"))
            assert series["window"] >= 2
            roles.add(series["role"])
        assert roles == {"ingress", "supervisor"}
    finally:
        for ln in listeners:
            await ln.close()
        await _close_all(*ingresses, *servers)
