"""Host-stack fault injection: partitions, asymmetric blocks, message
loss over the asyncio Raft (VERDICT r4 #3).

The device plane has first-class ``deliver`` masks; until round 5 the
HOST stack (``server/raft.py`` + SPI) was only ever killed cleanly. The
reference's pyramid runs real consensus over a controllable fake network
(``AbstractServerTest.java:53-57``) and claims Jepsen testing
(``README.md:8``) — these tests drive the same envelope through
``io/local.NetworkNemesis``: the stale-leader lease-read hunt the round-4
verdict called the weakest correctness evidence in the tree, plus a
partition/loss soak asserting convergence and exactly-once apply.
"""

import asyncio

import pytest

from helpers import async_test
from raft_fixtures import (
    BoundedGet,
    Cluster,
    Get,
    KVStateMachine,
    Put,
    create_cluster,
)

from copycat_tpu.client.client import RaftClient
from copycat_tpu.protocol.operations import QueryConsistency
from copycat_tpu.io.local import (
    LocalServerRegistry,
    LocalTransport,
    NetworkNemesis,
)
from copycat_tpu.io.transport import Address, TransportError
from copycat_tpu.server.raft import FOLLOWER, LEADER


# ---------------------------------------------------------------------------
# transport-level semantics
# ---------------------------------------------------------------------------


@async_test
async def test_transport_fault_semantics():
    """Partition blocks both ways; block() is one-directional; response
    loss runs the handler; heal restores everything."""
    registry = LocalServerRegistry()
    nem = registry.attach_nemesis()
    a, b = Address("local", 1), Address("local", 2)
    handled = []

    async def serve(addr):
        server = LocalTransport(registry).server()

        def on_connect(conn):
            async def handle(m):
                handled.append((addr.port, m.key))
                return m.value

            conn.handler(Put, handle)

        await server.listen(addr, on_connect)
        return server

    sa, sb = await serve(a), await serve(b)
    ca = await LocalTransport(registry, local_address=a).client().connect(b)
    cb = await LocalTransport(registry, local_address=b).client().connect(a)
    assert await ca.send(Put(key="x", value=1)) == 1

    nem.partition([a], [b])
    with pytest.raises(TransportError):
        await ca.send(Put(key="y", value=2))
    with pytest.raises(TransportError):
        await cb.send(Put(key="z", value=3))
    # a partitioned dial is refused too
    with pytest.raises(TransportError):
        await LocalTransport(registry, local_address=a).client().connect(b)
    # anonymous clients reach every side (Jepsen client model)
    anon = await LocalTransport(registry).client().connect(b)
    assert await anon.send(Put(key="w", value=4)) == 4
    nem.heal()
    assert await ca.send(Put(key="y", value=2)) == 2

    # asymmetric: cut only the b -> a response direction; a's REQUESTS
    # still run b's handler but a never learns the outcome
    n_handled = len(handled)
    nem.block(b, a)
    with pytest.raises(TransportError, match="response"):
        await ca.send(Put(key="q", value=5))
    assert len(handled) == n_handled + 1  # handler ran; reply was lost
    with pytest.raises(TransportError, match="request"):
        await cb.send(Put(key="r", value=6))  # b -> a request leg is cut
    nem.heal()

    # probabilistic loss: with request loss 1.0 nothing gets through
    nem.set_loss(request=1.0)
    with pytest.raises(TransportError):
        await ca.send(Put(key="s", value=7))
    nem.heal()
    assert await ca.send(Put(key="s", value=7)) == 7

    # delay: a fixed floor is actually paid per message, and
    # set_delay(x) means "exactly x" (the round-5 review fixed the
    # min-without-max silent-zero footgun)
    nem.set_delay(0.02)
    t0 = asyncio.get_running_loop().time()
    await ca.send(Put(key="t", value=8))
    assert asyncio.get_running_loop().time() - t0 >= 0.02
    with pytest.raises(ValueError):
        nem.set_delay(0.01, 0.005)   # reversed bounds refuse loudly
    nem.heal()
    assert nem.delivered > 0
    await sa.close()
    await sb.close()


# ---------------------------------------------------------------------------
# stale-leader lease reads (the round-4 hunt target)
# ---------------------------------------------------------------------------


#: generous sessions throughout this module: partitions deliberately
#: starve keep-alives, and a session expiring mid-choreography turns a
#: lease/soak check into a SessionExpiredError timing flake
SESSION_T = 30.0


async def _nemesis_cluster(n=3, **kwargs) -> tuple[Cluster, NetworkNemesis]:
    kwargs.setdefault("session_timeout", SESSION_T)
    cluster = await create_cluster(n, **kwargs)
    nem = cluster.registry.attach_nemesis()
    return cluster, nem


@async_test(timeout=120)
async def test_stale_leader_refuses_lease_read_under_asymmetric_partition():
    """The nastiest lease trap: the leader can still SEND heartbeats
    (followers stay followers — no new election) but the ack direction
    is cut, so its lease silently expires. A BOUNDED_LINEARIZABLE read
    at that leader MUST be refused, not served from stale lease state
    (``server/raft.py`` ``_lease_valid``/``_gate_query``)."""
    cluster, nem = await _nemesis_cluster()
    try:
        leader = await cluster.await_leader()
        client = await cluster.client(session_timeout=SESSION_T)
        assert await client.submit(Put(key="k", value=1)) is None
        # lease-read sanity while healthy
        assert await client.submit(BoundedGet(key="k")) == 1

        # cut every ack path TO the leader (peer->leader direction only)
        for s in cluster.servers:
            if s is not leader:
                nem.block(s.address, leader.address)
        # wait out the lease window: no successful quorum round-trips
        await asyncio.sleep(leader.election_timeout * 2.5)
        assert leader.role == LEADER, "one-way heartbeats should keep peers"
        assert not leader._lease_valid(), "lease must expire without acks"
        # a lease read at the stale leader must REFUSE (NOT_LEADER path
        # after the failed leadership confirmation), never serve stale
        refused = await leader._gate_query(
            QueryConsistency.BOUNDED_LINEARIZABLE, 0)
        assert refused is not None, \
            "stale leader served a lease read with an expired lease"
        nem.heal()
        # after heal the lease re-arms and lease reads serve again
        deadline = asyncio.get_running_loop().time() + 10
        while asyncio.get_running_loop().time() < deadline:
            if (await leader._gate_query(
                    QueryConsistency.BOUNDED_LINEARIZABLE, 0)) is None:
                break
            await asyncio.sleep(0.05)
        assert await client.submit(BoundedGet(key="k")) == 1
    finally:
        await cluster.close()


@async_test(timeout=120)
async def test_majority_progress_and_stale_leader_refusal_symmetric():
    """Symmetric partition: {leader} | {majority}. The majority elects,
    commits NEW writes; the old leader still in its lease window must
    not serve a lease read with the OLD value once its lease lapses."""
    cluster, nem = await _nemesis_cluster()
    try:
        old = await cluster.await_leader()
        client = await cluster.client(session_timeout=SESSION_T)
        assert await client.submit(Put(key="k", value=1)) is None

        minority = [old.address]
        majority = [s.address for s in cluster.servers if s is not old]
        nem.partition(minority, majority)

        # majority side elects and commits a NEWER value
        maj_client = RaftClient(majority, LocalTransport(cluster.registry),
                                session_timeout=SESSION_T)
        await maj_client.open()
        cluster.clients.append(maj_client)
        assert await asyncio.wait_for(
            maj_client.submit(Put(key="k", value=2)), 30) == 1
        new_leader = next(s for s in cluster.servers
                          if s is not old and s.role == LEADER)
        assert new_leader.term > old.term

        # the deposed leader's lease is stale; once it lapses a lease
        # read must refuse rather than return k=1
        await asyncio.sleep(old.election_timeout * 2.5)
        if old.role == LEADER:  # it can't learn of the new term yet
            refused = await old._gate_query(
                QueryConsistency.BOUNDED_LINEARIZABLE, 0)
            assert refused is not None, \
                "deposed leader served a stale lease read"

        nem.heal()
        # healed: old leader steps down and converges to k=2
        deadline = asyncio.get_running_loop().time() + 15
        while asyncio.get_running_loop().time() < deadline:
            if old.role == FOLLOWER and \
                    old.state_machine.data.get("k") == 2:
                break
            await asyncio.sleep(0.05)
        assert old.role == FOLLOWER
        assert old.state_machine.data.get("k") == 2
        assert await client.submit(BoundedGet(key="k")) == 2
    finally:
        await cluster.close()


# ---------------------------------------------------------------------------
# partition + loss soak: convergence and exactly-once apply
# ---------------------------------------------------------------------------


@async_test(timeout=480)
async def test_soak_partitions_and_loss_exactly_once():
    """30 acked writes through rolling partitions + 15%/10% message loss
    + 0-3ms delays. After heal: every server applied each committed
    command EXACTLY once (the session dedup surviving lost responses)
    and all logs converge to the same final state."""
    # generous session timeout: under full-suite load the event loop can
    # starve keep-alives for seconds, and an expiry mid-soak fails the
    # run with SessionExpiredError — a timing artifact, not a finding
    cluster, nem = await _nemesis_cluster(
        session_timeout=SESSION_T)
    try:
        await cluster.await_leader()
        client = await cluster.client(session_timeout=SESSION_T)
        nem.set_loss(request=0.15, response=0.10)
        nem.set_delay(0.0, 0.003)

        addrs = [s.address for s in cluster.servers]
        n_puts = 30
        for i in range(n_puts):
            if i % 10 == 3:
                # rotate a symmetric minority partition mid-stream
                loner = addrs[(i // 10) % len(addrs)]
                nem.partition([loner], [a for a in addrs if a != loner])
            elif i % 10 == 8:
                nem.partition()  # heal partition, keep loss+delay
            # generous per-op cap: under rotating partitions + 15% loss,
            # elections can thrash for tens of seconds (split votes with
            # lost RequestVotes) before a commit lands — slowness here is
            # the nemesis working, not a failure
            await asyncio.wait_for(
                client.submit(Put(key="n", value=i)), 150)

        nem.heal()
        # convergence: all servers apply all n_puts puts exactly once
        deadline = asyncio.get_running_loop().time() + 30
        while asyncio.get_running_loop().time() < deadline:
            if all(s.state_machine.applied_ops >= n_puts
                   and s.state_machine.data.get("n") == n_puts - 1
                   for s in cluster.servers):
                break
            await asyncio.sleep(0.1)
        for s in cluster.servers:
            assert s.state_machine.data.get("n") == n_puts - 1, \
                f"{s.address} did not converge"
            assert s.state_machine.applied_ops == n_puts, \
                (f"{s.address} applied {s.state_machine.applied_ops} != "
                 f"{n_puts}: double- or missed apply under loss")
        # the nemesis actually did something
        assert nem.dropped_requests + nem.dropped_responses > 0
        assert await client.submit(Get(key="n")) == n_puts - 1
    finally:
        await cluster.close()
