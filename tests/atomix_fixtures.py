"""Full-stack fixture: N AtomixServers + AtomixClients over LocalTransport
(the reference's AbstractAtomicTest/AbstractCollectionsTest/
AbstractCoordinationTest pattern — real consensus, fake network)."""

from __future__ import annotations

import asyncio

from copycat_tpu.io.local import LocalServerRegistry, LocalTransport
from copycat_tpu.manager.atomix import AtomixClient, AtomixServer

from raft_fixtures import next_ports


class Stack:
    def __init__(self) -> None:
        self.registry = LocalServerRegistry()
        self.servers: list[AtomixServer] = []
        self.clients: list[AtomixClient] = []
        self.addrs = []

    async def start(self, n: int = 3, session_timeout: float = 3.0) -> "Stack":
        self.addrs = next_ports(n)
        self.servers = [
            AtomixServer(a, self.addrs, LocalTransport(self.registry),
                         election_timeout=0.2, heartbeat_interval=0.04,
                         session_timeout=session_timeout)
            for a in self.addrs
        ]
        await asyncio.gather(*(s.open() for s in self.servers))
        return self

    async def client(self, session_timeout: float = 3.0) -> AtomixClient:
        client = AtomixClient(self.addrs, LocalTransport(self.registry),
                              session_timeout=session_timeout)
        await client.open()
        self.clients.append(client)
        return client

    async def close(self) -> None:
        for node in self.clients + self.servers:
            try:
                await asyncio.wait_for(node.close(), 5)
            except (Exception, asyncio.TimeoutError):
                pass
