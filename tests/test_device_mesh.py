"""The SPI device plane over a sharded mesh.

``DeviceEngineConfig.mesh`` shards each server's engine group axis
across its local devices (`parallel/mesh.py` placement specs). This
drives the FULL public stack — AtomixServers with ``executor="tpu"``,
real client sessions — on an engine sharded over the suite's 8 virtual
CPU devices, and asserts both the results and the placement (the state
really is distributed). Sharding is a local placement choice: a sharded
and an unsharded engine replicate identically (same shapes, same seed),
which the mixed-mesh cluster test exercises directly.

Reference obligation: the public API is the data path
(``Atomix.java:205``); scale axes ride the mesh (SURVEY §2.2).
"""

import asyncio

import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.atomic import DistributedAtomicLong  # noqa: E402
from copycat_tpu.io.local import LocalServerRegistry, LocalTransport  # noqa: E402
from copycat_tpu.manager.atomix import AtomixClient, AtomixServer  # noqa: E402
from copycat_tpu.manager.device_executor import (  # noqa: E402
    DeviceEngine,
    DeviceEngineConfig,
)
from copycat_tpu.parallel import make_mesh  # noqa: E402

from helpers import async_test  # noqa: E402
from raft_fixtures import next_ports  # noqa: E402


def _mesh_or_skip():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh (conftest)")
    return make_mesh(groups=8)


def test_capacity_must_divide_mesh():
    mesh = _mesh_or_skip()
    engine = DeviceEngine(DeviceEngineConfig(capacity=12, mesh=mesh))
    with pytest.raises(ValueError, match="not divisible"):
        engine._ensure()


def test_engine_state_sharded_over_mesh():
    mesh = _mesh_or_skip()
    engine = DeviceEngine(DeviceEngineConfig(
        capacity=16, num_peers=3, log_slots=32, mesh=mesh))
    rg = engine._ensure()
    shardings = {str(rg.state.term.sharding.spec),
                 str(rg.state.log_term.sharding.spec)}
    assert all("groups" in s for s in shardings), shardings
    # 16 groups over 8 devices: each device holds a [2, ...] slice
    assert len(rg.state.term.devices()) == 8


@async_test
async def test_public_api_through_sharded_engine():
    mesh = _mesh_or_skip()
    registry = LocalServerRegistry()
    addrs = next_ports(3)
    cfg = DeviceEngineConfig(capacity=16, num_peers=3, log_slots=32,
                             mesh=mesh)
    servers = [
        AtomixServer(a, addrs, LocalTransport(registry),
                     election_timeout=0.2, heartbeat_interval=0.04,
                     executor="tpu", engine_config=cfg)
        for a in addrs
    ]
    await asyncio.gather(*(s.open() for s in servers))
    client = AtomixClient(addrs, LocalTransport(registry))
    await client.open()
    try:
        counters = [
            await client.get(f"c{i}", DistributedAtomicLong)
            for i in range(4)
        ]
        for rep in range(3):
            for i, c in enumerate(counters):
                got = await asyncio.wait_for(c.add_and_get(i + 1), 30)
                assert got == (i + 1) * (rep + 1)
    finally:
        await client.close()
        for s in servers:
            await s.close()


@async_test
async def test_mixed_mesh_cluster_replicates_identically():
    """A sharded server and unsharded servers form one cluster: the mesh
    is placement-only, so their replicated engine histories agree."""
    mesh = _mesh_or_skip()
    registry = LocalServerRegistry()
    addrs = next_ports(3)
    base = dict(capacity=16, num_peers=3, log_slots=32)
    configs = [DeviceEngineConfig(mesh=mesh, **base),
               DeviceEngineConfig(**base),
               DeviceEngineConfig(**base)]
    servers = [
        AtomixServer(a, addrs, LocalTransport(registry),
                     election_timeout=0.2, heartbeat_interval=0.04,
                     executor="tpu", engine_config=c)
        for a, c in zip(addrs, configs)
    ]
    await asyncio.gather(*(s.open() for s in servers))
    client = AtomixClient(addrs, LocalTransport(registry))
    await client.open()
    try:
        c = await client.get("n", DistributedAtomicLong)
        for i in range(1, 6):
            assert await asyncio.wait_for(c.increment_and_get(), 30) == i
    finally:
        await client.close()
        for s in servers:
            await s.close()
