"""Atomic resource tests (reference ``DistributedAtomicValueTest``/
``DistributedAtomicLongTest``)."""

import asyncio

from copycat_tpu.atomic import DistributedAtomicLong, DistributedAtomicValue

from atomix_fixtures import Stack
from helpers import async_test


@async_test(timeout=90)
async def test_atomic_value_set_get_cas():
    stack = await Stack().start(3)
    try:
        client = await stack.client()
        value = await client.get("value", DistributedAtomicValue)
        assert await value.get() is None
        await value.set("a")
        assert await value.get() == "a"
        assert await value.get_and_set("b") == "a"
        assert await value.compare_and_set("b", "c") is True
        assert await value.compare_and_set("b", "d") is False
        assert await value.get() == "c"
    finally:
        await stack.close()


@async_test(timeout=90)
async def test_atomic_value_ttl():
    stack = await Stack().start(3)
    try:
        client = await stack.client()
        value = await client.get("ttl-value", DistributedAtomicValue)
        await value.set("temp", ttl=0.3)
        assert await value.get() == "temp"
        await asyncio.sleep(0.9)
        assert await value.get() is None
    finally:
        await stack.close()


@async_test(timeout=90)
async def test_atomic_value_change_events():
    stack = await Stack().start(3)
    try:
        c1 = await stack.client()
        c2 = await stack.client()
        v1 = await c1.get("watched", DistributedAtomicValue)
        v2 = await c2.get("watched", DistributedAtomicValue)
        changes: list = []
        got = asyncio.Event()

        async def setup():
            await v2.on_change(lambda v: (changes.append(v), got.set()))

        await setup()
        await v1.set("ping")
        await asyncio.wait_for(got.wait(), 5)
        assert changes == ["ping"]
    finally:
        await stack.close()


@async_test(timeout=90)
async def test_atomic_long_counter_ops():
    """Reference DistributedAtomicLongTest: the 6 counter ops."""
    stack = await Stack().start(3)
    try:
        client = await stack.client()
        counter = await client.get("counter", DistributedAtomicLong)
        assert await counter.increment_and_get() == 1
        assert await counter.increment_and_get() == 2
        assert await counter.decrement_and_get() == 1
        assert await counter.get_and_increment() == 1
        assert await counter.get_and_decrement() == 2
        assert await counter.add_and_get(10) == 11
        assert await counter.get_and_add(-1) == 11
        assert await counter.get() == 10
    finally:
        await stack.close()


@async_test(timeout=120)
async def test_atomic_long_contended_cas():
    """Two clients racing increments: CAS-retry must not lose updates."""
    stack = await Stack().start(3)
    try:
        c1 = await stack.client()
        c2 = await stack.client()
        l1 = await c1.get("contended", DistributedAtomicLong)
        l2 = await c2.get("contended", DistributedAtomicLong)

        async def bump(counter, n):
            for _ in range(n):
                await counter.increment_and_get()

        await asyncio.gather(bump(l1, 10), bump(l2, 10))
        assert await l1.get() == 20
    finally:
        await stack.close()
