"""Differential proof for the batched server-side session pump.

The vector lane (``RaftServer._apply_vector_run`` + ``DeviceEngine.
run_vector``) commits whole runs of device-eligible commands as tensors
through one shared engine round instead of per-op generator chains. Its
contract is BIT-IDENTICAL observable behavior to the per-op windowed
apply: same results, same per-session event order, same exactly-once
dedup under duplicate delivery and faults. These tests prove it by
running the same seeded op script through both engines and comparing
everything the client can see, then racing the batched path against a
response-dropping / lossy-partition nemesis.

The flush-error split (ADVICE r5 #1: pre-dispatch failures restore
``_pending`` and re-raise, only abandoned drives mark INDETERMINATE)
and the deliver-until-close event contract (ADVICE r5 #2) are covered
at the BulkSessionClient layer below.
"""

import asyncio
import random

import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.atomic import DistributedAtomicLong, DistributedAtomicValue  # noqa: E402
from copycat_tpu.io.local import (  # noqa: E402
    LocalServerRegistry, LocalTransport, NetworkNemesis)
from copycat_tpu.manager.atomix import AtomixClient, AtomixServer  # noqa: E402
from copycat_tpu.manager.device_executor import DeviceEngineConfig  # noqa: E402
from copycat_tpu.models import BulkSessionClient, RaftGroups  # noqa: E402
from copycat_tpu.models.session_client import (  # noqa: E402
    CommandIndeterminateError)
from copycat_tpu.ops import apply as ap  # noqa: E402
from copycat_tpu.ops.consensus import Config  # noqa: E402

from helpers import async_test  # noqa: E402
from raft_fixtures import next_ports  # noqa: E402

ENGINE = DeviceEngineConfig(capacity=16, num_peers=3, log_slots=32)


async def _spi_cluster(registry, vector_pump: bool):
    """One standalone server + client; the pump lane forced on or off."""
    (addr,) = next_ports(1)
    server = AtomixServer(addr, [addr], LocalTransport(registry),
                          election_timeout=0.5, heartbeat_interval=0.1,
                          session_timeout=20.0, executor="tpu",
                          engine_config=ENGINE)
    server.server._vector_pump = vector_pump
    await server.open()
    client = AtomixClient([addr], LocalTransport(registry),
                          session_timeout=20.0)
    await client.open()
    return server, client


def _script(seed: int, n_waves: int, wave: int):
    """Seeded op script over 3 plain values (vector-eligible steady
    state) + 1 listened value (listener forces the generator path, so
    every wave mixes eligible and ineligible entries and the pump's
    run-bounding is exercised)."""
    rng = random.Random(seed)
    waves = []
    for _ in range(n_waves):
        ops = []
        for _ in range(wave):
            target = rng.randrange(4)
            kind = rng.randrange(4)
            ops.append((target, kind, rng.randrange(5), rng.randrange(5)))
        waves.append(ops)
    return waves


async def _run_script(client, waves):
    """Execute the script; returns (results, events, finals) — the full
    client-observable history."""
    values = [await client.get(f"v{i}", DistributedAtomicValue)
              for i in range(4)]
    events: list[tuple[int, int]] = []
    listener = await values[3].on_change(
        lambda v: events.append((3, v)))
    for i, v in enumerate(values):
        await v.set(i)  # deterministic non-None base; lands on device
    results = []
    for ops in waves:
        async def one(target, kind, a, b):
            v = values[target]
            if kind == 0:
                await v.set(a)
                return ("set", None)
            if kind == 1:
                return ("cas", await v.compare_and_set(a, b))
            if kind == 2:
                return ("gas", await v.get_and_set(a))
            return ("get", await v.get())
        results.append(await asyncio.gather(
            *(one(*op) for op in ops)))
    finals = [await v.get() for v in values]
    listener.close()
    await asyncio.sleep(0.05)  # drain in-flight publishes
    return results, events, finals


@async_test(timeout=300)
async def test_vector_pump_bit_identical_to_per_op_path():
    """Same seeded script, two engines (pump on / pump off): results,
    per-session event order, and final state must be identical."""
    waves = _script(seed=42, n_waves=6, wave=32)
    histories = []
    for pump in (True, False):
        registry = LocalServerRegistry()
        server, client = await _spi_cluster(registry, vector_pump=pump)
        try:
            histories.append(await _run_script(client, waves))
        finally:
            await asyncio.wait_for(client.close(), 5)
            await asyncio.wait_for(server.close(), 5)
    (res_on, ev_on, fin_on), (res_off, ev_off, fin_off) = histories
    assert res_on == res_off, "vector pump diverged from per-op results"
    assert ev_on == ev_off, "vector pump diverged in event order"
    assert fin_on == fin_off, "vector pump diverged in final state"
    # the script genuinely exercised both lanes: CAS outcomes of both
    # kinds appeared (device CAS success + failure finalize arms)
    cas = [r[1] for wave in res_on for r in wave if r[0] == "cas"]
    assert True in cas and False in cas


@async_test(timeout=300)
async def test_vector_pump_exactly_once_under_duplicate_delivery():
    """Response-leg loss makes the client resend whole committed batches
    (duplicate delivery of every entry): the server's session-seq dedup
    must serve cached responses, never re-apply. The final counter
    equals the exact number of acked increments."""
    registry = LocalServerRegistry()
    nemesis = registry.attach_nemesis(NetworkNemesis(seed=7))
    server, client = await _spi_cluster(registry, vector_pump=True)
    try:
        counter = await client.get("c", DistributedAtomicLong)
        await counter.increment_and_get()  # settle to steady state
        nemesis.set_loss(response=0.3)
        acked = 0
        for _ in range(40):
            await counter.increment_and_get()
            acked += 1
        nemesis.heal()
        value = await counter.get()
        assert value == acked + 1, (
            f"duplicate delivery broke exactly-once: {value} != {acked + 1}")
        assert nemesis.dropped_responses > 0, "nemesis never fired"
    finally:
        nemesis.heal()
        await asyncio.wait_for(client.close(), 5)
        await asyncio.wait_for(server.close(), 5)


@async_test(timeout=300)
async def test_vector_pump_partition_mid_batch_no_duplicate_applies():
    """A lossy partition (both legs) opens mid-storm and heals: every
    increment is eventually acked exactly once — a dropped request never
    applied, a dropped response applied once and deduped on resend."""
    registry = LocalServerRegistry()
    nemesis = registry.attach_nemesis(NetworkNemesis(seed=11))
    server, client = await _spi_cluster(registry, vector_pump=True)
    try:
        counter = await client.get("c", DistributedAtomicLong)
        await counter.increment_and_get()
        acked = 0

        async def storm(n):
            nonlocal acked
            for _ in range(n):
                await asyncio.wait_for(counter.increment_and_get(), 60)
                acked += 1

        task = asyncio.ensure_future(storm(30))
        await asyncio.sleep(0.02)
        nemesis.set_loss(request=0.4, response=0.4)  # partition opens
        await asyncio.sleep(0.3)
        nemesis.heal()
        await asyncio.wait_for(task, 120)
        value = await counter.get()
        assert value == acked + 1, (
            f"partition mid-batch broke exactly-once: {value} != "
            f"{acked + 1}")
    finally:
        nemesis.heal()
        await asyncio.wait_for(client.close(), 5)
        await asyncio.wait_for(server.close(), 5)


# ---------------------------------------------------------------------------
# BulkSessionClient flush-error split + deliver-until-close (ADVICE r5)


@pytest.fixture()
def deep_rg():
    rg = RaftGroups(8, 3, log_slots=32, submit_slots=4, seed=13,
                    config=Config(monotone_tag_accept=True))
    rg.wait_for_leaders()
    return rg


def test_flush_pre_dispatch_error_restores_pending(deep_rg):
    """A failure raised BEFORE any device dispatch (no tags consumed)
    must restore the chunks to the sessions' _pending and re-raise —
    the commands definitely did not apply, so INDETERMINATE (which
    forces the correlate-a-read recovery path) would discard that."""
    client = BulkSessionClient(deep_rg)
    s = client.open_session()
    seqs = s.submit_batch([0] * 4, ap.OP_LONG_ADD, 1)
    real_drive = client._driver.drive
    client._driver.drive = lambda *a, **k: (_ for _ in ()).throw(
        ValueError("accumulators too skewed"))
    with pytest.raises(ValueError):
        client.flush()
    assert len(s._pending) == 1, "pre-dispatch failure must restore chunks"
    for q in seqs:
        assert int(q) not in s._results, "no result may be recorded"
    # the restored chunk commits exactly once on the next (healthy) flush
    client._driver.drive = real_drive
    assert client.flush() == 4
    assert list(s.results_window(int(seqs[0]), 4)) == [1, 2, 3, 4]


def test_flush_timeout_marks_indeterminate(deep_rg):
    """An abandoned drive (TimeoutError: the command MAY have applied)
    keeps the indeterminate marking."""
    client = BulkSessionClient(deep_rg)
    s = client.open_session()
    seqs = s.submit_batch([1] * 3, ap.OP_LONG_ADD, 1)
    client._driver.drive = lambda *a, **k: (_ for _ in ()).throw(
        TimeoutError("drive abandoned"))
    with pytest.raises(TimeoutError):
        client.flush()
    assert not s._pending, "abandoned commands must not be re-staged"
    with pytest.raises(CommandIndeterminateError):
        s.result(int(seqs[0]))


def test_events_delivered_until_close(deep_rg):
    """A gracefully closed session's listeners still receive the events
    committed by the flush that commits its close (the reference's
    deliver-until-close session event contract)."""
    client = BulkSessionClient(deep_rg)
    watcher = client.open_session()
    worker = client.open_session()
    group = 2
    got: list = []
    watcher.on_event(group, got.append)
    # the worker's topic publish emits a broadcast event on the group;
    # the watcher closes in the SAME flush that commits the event
    worker.submit(group, ap.OP_TOPIC_LISTEN, worker.id)
    worker.submit(group, ap.OP_TOPIC_PUB, 41)
    watcher.close()
    client.flush()
    assert [e.arg for e in got] == [41], (
        "closing session missed events committed by its own flush")
    assert watcher.id not in client._sessions, "closed session must leave"
