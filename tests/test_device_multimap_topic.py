"""Device multimap + topic kernels (round-2 VERDICT directive #7).

Raw-path coverage for the two kernels added in round 3: the (key,value)
pair-probe multimap (reference ``MultiMapState.java:30``) and the topic
subscriber table with broadcast-event publish (``TopicState.java:31``),
plus replica-convergence and facade behavior.
"""

import numpy as np

from copycat_tpu.models.device_resources import DeviceMultiMap, DeviceTopic
from copycat_tpu.models.raft_groups import RaftGroups
from copycat_tpu.ops import apply as ap


def _groups(G: int = 2) -> RaftGroups:
    rg = RaftGroups(G, 3, log_slots=32, submit_slots=4, seed=5)
    rg.wait_for_leaders()
    return rg


def test_multimap_kernel_semantics():
    rg = _groups()
    mm = DeviceMultiMap(rg, 0)
    assert mm.is_empty()
    assert mm.put(1, 10)
    assert mm.put(1, 11)
    assert not mm.put(1, 10)        # duplicate (key, value) pair
    assert mm.put(2, 10)
    assert mm.size() == 3
    assert mm.count(1) == 2
    assert mm.contains_key(1)
    assert mm.contains_entry(1, 11)
    assert not mm.contains_entry(2, 11)
    assert mm.contains_value(10)
    assert mm.remove_entry(1, 11)
    assert not mm.remove_entry(1, 11)
    assert mm.count(1) == 1
    assert mm.remove(1) == 1        # removes every pair under the key
    assert not mm.contains_key(1)
    assert mm.size() == 1
    mm.clear()
    assert mm.is_empty()


def test_multimap_ttl_expiry_is_lazy_and_deterministic():
    rg = _groups()
    mm = DeviceMultiMap(rg, 0)
    assert mm.put(7, 70, ttl=3)     # expires at clock+3
    assert mm.contains_entry(7, 70)
    rg.run(6)                       # advance the replicated clock past ttl
    assert not mm.contains_entry(7, 70)
    assert mm.size() == 0
    # replicas converge bit-exactly (same applied prefix)
    for field in ("mm_key", "mm_val", "mm_live", "mm_dl"):
        arr = np.asarray(getattr(rg.state.resources, field))
        for p in range(1, arr.shape[1]):
            np.testing.assert_array_equal(arr[:, 0], arr[:, p], err_msg=field)


def test_topic_publish_fans_out_to_subscribers():
    rg = _groups()
    alice = DeviceTopic(rg, 0, subscriber_id=1)
    bob = DeviceTopic(rg, 0, subscriber_id=2)
    alice.subscribe()
    assert alice.subscriber_count() == 1
    assert rg.events is not None

    # published before bob subscribes: only alice sees it
    assert DeviceTopic(rg, 0, subscriber_id=9).publish(41) == 1
    rg.run(4)
    assert alice.poll_messages() == [41]
    assert bob.poll_messages() == []  # not subscribed

    bob.subscribe()
    assert bob.subscriber_count() == 2
    pub = DeviceTopic(rg, 0, subscriber_id=9)
    assert pub.publish(42) == 2
    assert pub.publish(43) == 2
    rg.run(4)
    assert alice.poll_messages() == [42, 43]
    assert bob.poll_messages() == [42, 43]

    alice.unsubscribe()
    assert pub.publish(44) == 1
    rg.run(4)
    assert alice.poll_messages() == []
    assert bob.poll_messages() == [44]


def test_topic_subscribe_is_idempotent_and_bounded():
    rg = _groups()
    t = DeviceTopic(rg, 1, subscriber_id=5)
    t.subscribe()
    t.subscribe()                    # idempotent: no duplicate entry
    assert t.subscriber_count() == 1
    # fill the table (topic_slots=8)
    for i in range(7):
        DeviceTopic(rg, 1, subscriber_id=10 + i).subscribe()
    full = DeviceTopic(rg, 1, subscriber_id=99)
    result = full._call(ap.OP_TOPIC_LISTEN, 99)
    assert result == ap.FAIL         # table full -> explicit overflow


def test_multimap_topic_independent_of_other_pools():
    """Multimap/topic ops interleaved with every other pool in one batch
    stream — the conflict-partitioned window must keep them all straight."""
    from copycat_tpu.ops.consensus import Config
    config = Config(applies_per_round=8,
                    pool_budgets=(2, 2, 2, 2, 2, 2, 2, 2))
    rg = RaftGroups(2, 3, log_slots=32, submit_slots=8, config=config)
    rg.wait_for_leaders()
    tags = {}
    tags["add"] = rg.submit(0, ap.OP_LONG_ADD, 5)
    tags["mapput"] = rg.submit(0, ap.OP_MAP_PUT, 1, 100)
    tags["mmput"] = rg.submit(0, ap.OP_MM_PUT, 1, 200)
    tags["sub"] = rg.submit(0, ap.OP_TOPIC_LISTEN, 3)
    tags["pub"] = rg.submit(0, ap.OP_TOPIC_PUB, 77)
    tags["mmcount"] = rg.submit(0, ap.OP_MM_COUNT, 1)
    rg.run_until(list(tags.values()))
    assert rg.results[tags["add"]] == 5
    assert rg.results[tags["mapput"]] == 0
    assert rg.results[tags["mmput"]] == 1
    assert rg.results[tags["sub"]] == 1
    assert rg.results[tags["pub"]] == 1      # one subscriber at publish
    assert rg.results[tags["mmcount"]] == 1
    evs = rg.events.get(0, [])
    assert any(c == ap.EV_TOPIC_MSG and a == 77 for _, c, _t, a in evs)
