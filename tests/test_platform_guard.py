"""Bounded probe-retry behavior of the shared device guard.

Round-3 post-mortem: a single transient dead-tunnel window at snapshot
time zeroed out the round's benchmark evidence because ``require_devices``
probed exactly once. The guard now probes in subprocesses (a hung child is
killed without poisoning the parent's backend lock) with bounded retries.
These tests drive both outcomes with real subprocess probes.
"""

import pytest

from copycat_tpu.utils.platform import require_devices


def test_require_devices_exhausts_probes_then_exit2(monkeypatch):
    # An unknown platform makes every probe fail deterministically and
    # quickly — standing in for a dead tunnel without needing one.
    monkeypatch.setenv("JAX_PLATFORMS", "no_such_platform")
    monkeypatch.setenv("COPYCAT_DEVICE_PROBES", "2")
    with pytest.raises(SystemExit) as exc:
        require_devices(retry_wait_s=0.0)
    assert exc.value.code == 2


def test_require_devices_passes_on_healthy_backend(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("COPYCAT_DEVICE_PROBES", "1")
    require_devices()  # returns (no SystemExit) when enumeration works
