"""Bounded probe-retry behavior of the shared device guard.

Round-3 post-mortem: a single transient dead-tunnel window at snapshot
time zeroed out the round's benchmark evidence because ``require_devices``
probed exactly once. The guard now probes in subprocesses (a hung child is
killed without poisoning the parent's backend lock) with bounded retries.
These tests drive both outcomes with real subprocess probes.
"""

import pytest

from copycat_tpu.utils.platform import require_devices


def test_require_devices_exhausts_probes_then_exit2(monkeypatch):
    # An unknown platform makes every probe fail deterministically and
    # quickly — standing in for a dead tunnel without needing one.
    monkeypatch.setenv("JAX_PLATFORMS", "no_such_platform")
    monkeypatch.setenv("COPYCAT_DEVICE_PROBES", "2")
    with pytest.raises(SystemExit) as exc:
        require_devices(retry_wait_s=0.0)
    assert exc.value.code == 2


def test_require_devices_passes_on_healthy_backend(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("COPYCAT_DEVICE_PROBES", "1")
    require_devices()  # returns (no SystemExit) when enumeration works


class TestCompilationCache:
    """Precedence rules of ``enable_compilation_cache``.

    The helper must (a) honor an explicit disable, (b) never shadow a
    cache the operator configured through JAX's own surface (env var or
    jax.config), and (c) otherwise point jax at the copycat default.
    Config state is saved/restored because the suite's conftest already
    enabled the default cache for this process.
    """

    @pytest.fixture(autouse=True)
    def _hermetic_env(self, monkeypatch):
        # precedence logic under test, not the ambient environment: a
        # developer's COPYCAT_COMPILE_CACHE / JAX_COMPILATION_CACHE_DIR
        # must not leak in (the cache-disabled CI run sets the former)
        monkeypatch.delenv("COPYCAT_COMPILE_CACHE", raising=False)
        monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)

    def _saved(self):
        import jax

        return getattr(jax.config, "jax_compilation_cache_dir", None)

    def test_disable_env(self, monkeypatch):
        from copycat_tpu.utils.platform import enable_compilation_cache

        monkeypatch.setenv("COPYCAT_COMPILE_CACHE", "0")
        assert enable_compilation_cache() is None

    def test_user_jax_env_wins(self, monkeypatch):
        from copycat_tpu.utils.platform import enable_compilation_cache

        monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", "/tmp/fleet-cache")
        assert enable_compilation_cache() == "/tmp/fleet-cache"

    def test_user_jax_config_wins(self, monkeypatch, tmp_path):
        import jax

        from copycat_tpu.utils.platform import enable_compilation_cache

        saved = self._saved()
        try:
            jax.config.update("jax_compilation_cache_dir", str(tmp_path))
            assert enable_compilation_cache() == str(tmp_path)
        finally:
            jax.config.update("jax_compilation_cache_dir", saved)

    def test_default_path_set_and_returned(self, monkeypatch, tmp_path):
        import jax

        from copycat_tpu.utils.platform import enable_compilation_cache

        from copycat_tpu.utils import platform

        saved = self._saved()
        saved_applied = platform._cache_dir_applied
        try:
            jax.config.update("jax_compilation_cache_dir", None)
            monkeypatch.setenv("COPYCAT_COMPILE_CACHE", str(tmp_path / "c"))
            got = enable_compilation_cache()
            assert got == str(tmp_path / "c")
            assert jax.config.jax_compilation_cache_dir == got
        finally:
            platform._cache_dir_applied = saved_applied
            jax.config.update("jax_compilation_cache_dir", saved)

    def test_explicit_path_beats_own_earlier_default(self, monkeypatch,
                                                     tmp_path):
        import jax

        from copycat_tpu.utils import platform

        saved = self._saved()
        saved_applied = platform._cache_dir_applied
        try:
            first = str(tmp_path / "a")
            second = str(tmp_path / "b")
            assert platform.enable_compilation_cache(first) == first
            # a later NO-ARG call (entry points) never downgrades an
            # earlier explicit choice to the default
            assert platform.enable_compilation_cache() == first
            # our own earlier dir is not "theirs" — explicit path wins
            assert platform.enable_compilation_cache(second) == second
            assert jax.config.jax_compilation_cache_dir == second
            # but an operator-set dir (different from what we applied) is
            jax.config.update("jax_compilation_cache_dir", str(tmp_path))
            assert platform.enable_compilation_cache(first) == str(tmp_path)
        finally:
            platform._cache_dir_applied = saved_applied
            jax.config.update("jax_compilation_cache_dir", saved)

    def test_trim_only_touches_cache_entries(self, tmp_path):
        import os

        from copycat_tpu.utils import platform

        h = "ab" * 32
        for i in range(6):
            p = tmp_path / f"jit_f{i}-{h}-cache"
            p.write_bytes(b"x" * 100)
            os.utime(p, (i, i))
        precious = tmp_path / "precious.txt"
        precious.write_bytes(b"y" * 1000)   # over budget, but NOT ours
        platform._trim_cache_dir(str(tmp_path), max_bytes=350)
        left = sorted(q.name for q in tmp_path.iterdir())
        # least-recently-used cache entries dropped; user file untouched
        assert left == [f"jit_f3-{h}-cache", f"jit_f4-{h}-cache",
                        f"jit_f5-{h}-cache", "precious.txt"], left
