"""Lease soundness across membership change (VERDICT r3 #6).

The leader lease certifies BOUNDED_LINEARIZABLE reads without a log
append (``ops/consensus.py`` ``RaftState.lease``). Its soundness hinge
under dynamic membership: the lease quorum must be evaluated against the
leader's ACTIVE (latest-in-log) config — an implementation that kept
counting acks against the config the lease was first acquired under
would let a partitioned ex-leader serve stale atomic reads after config
changes replaced its ack voters (old-config quorums need not intersect
late-config quorums; only ADJACENT single-server configs must).

Scenario driven here: voters grow {0,1,2} → {0,1,2,3,4}, then the leader
is partitioned WITH one companion — a 2-node island that IS a quorum of
the original 3-voter config but is NOT a quorum of the active 5-voter
config. The unsound lease holds; the sound one drops. Meanwhile the
majority side elects, removes both islanders from the config
(single-server steps), commits new writes, and serves atomic reads of
the new value.

Reference obligation: ``Consistency.java:157-176`` BOUNDED_LINEARIZABLE;
membership change per ``AtomixServerTest.testServerJoin/Leave``.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from copycat_tpu.models.raft_groups import RaftGroups  # noqa: E402
from copycat_tpu.ops import apply as ap  # noqa: E402
from copycat_tpu.ops.consensus import Config  # noqa: E402


def _island_deliver(G: int, P: int, island: set[int]) -> jnp.ndarray:
    """Full connectivity within ``island`` and within its complement;
    nothing across."""
    deliver = np.zeros((G, P, P), bool)
    for a in range(P):
        for b in range(P):
            deliver[:, a, b] = (a in island) == (b in island)
    return jnp.asarray(deliver)


def test_partitioned_ex_leader_lease_drops_under_grown_config():
    rg = RaftGroups(2, 5, log_slots=32, submit_slots=4, seed=3,
                    config=Config(dynamic_membership=True), voters=3)
    rg.wait_for_leaders()

    # grow the voter set to all 5 lanes (single-server steps)
    for lane in (3, 4):
        tags = [rg.add_peer(g, lane) for g in range(2)]
        rg.run_until(tags)
    assert rg.voting_members(0) == [0, 1, 2, 3, 4]

    # baseline write + lease held under full delivery
    t = rg.submit(0, ap.OP_VALUE_SET, a=111)
    rg.run_until([t])
    rg.run(2)
    leader = rg.leader(0)
    assert leader >= 0
    assert bool(np.asarray(rg.state.lease)[0].any())

    # island = old leader + one companion: a quorum of the ORIGINAL
    # 3-voter config (2 of {0,1,2}) but not of the active 5-voter one
    companion = next(p for p in (0, 1, 2) if p != leader)
    island = {leader, companion}
    rg.deliver = _island_deliver(2, 5, island)

    for _ in range(3):
        rg.step_round()
        lease = np.asarray(rg.state.lease)[0]
        # the sound lease (quorum vs ACTIVE config = 3 of 5) is gone on
        # the island even though the island still acks the ex-leader —
        # an old-config lease (2 of {0,1,2}) would survive here
        assert not lease[leader], \
            "partitioned ex-leader holds a lease its active config denies"
        assert not lease[companion]

    # majority side: elect, then single-server-remove both islanders
    for _ in range(60):
        rg.step_round()
        lead2 = rg.leader(0)
        if lead2 >= 0 and lead2 not in island:
            break
    else:
        raise AssertionError("majority never elected a new leader")

    for lane in sorted(island):
        t = rg.remove_peer(0, lane)
        rg.run_until([t], max_rounds=120)
    members = rg.voting_members(0)
    assert set(members) == {0, 1, 2, 3, 4} - island, members

    # new writes commit on the majority; atomic lease reads see them
    t = rg.submit(0, ap.OP_VALUE_SET, a=222)
    rg.run_until([t], max_rounds=120)
    q = rg.submit_query(0, ap.OP_VALUE_GET, consistency="atomic")
    rg.run_until([q], max_rounds=120)
    assert rg.results[q] == 222

    # the ex-leader cannot be serving anything: CheckQuorum stepped it
    # down (no quorum contact under its 5-voter active config) and its
    # term is stale relative to the majority line. (state.lease is a
    # group-level bit replicated across lanes — it now reports the NEW
    # leader's held lease, which is the sound outcome.)
    roles = np.asarray(rg.state.role)[0]
    terms = np.asarray(rg.state.term)[0]
    assert roles[leader] != 2, "partitioned ex-leader still claims leadership"
    assert terms[leader] < terms.max()

    # heal: the ex-leader steps down; no stale value resurfaces
    from copycat_tpu.ops.consensus import full_delivery
    rg.deliver = full_delivery(2, 5)
    rg.run(10)
    q = rg.submit_query(0, ap.OP_VALUE_GET, consistency="atomic")
    rg.run_until([q], max_rounds=120)
    assert rg.results[q] == 222


def test_lease_read_never_serves_during_config_island():
    """While the ex-leader's island holds an old-config quorum, an atomic
    query routed at it must escalate to the command path (and therefore
    only complete on the true leader's line) — never serve locally from
    the stale lane."""
    rg = RaftGroups(1, 5, log_slots=32, submit_slots=4, seed=5,
                    config=Config(dynamic_membership=True), voters=3)
    rg.wait_for_leaders()
    for lane in (3, 4):
        rg.run_until([rg.add_peer(0, lane)])
    t = rg.submit(0, ap.OP_VALUE_SET, a=7)
    rg.run_until([t])

    leader = rg.leader(0)
    companion = next(p for p in (0, 1, 2) if p != leader)
    rg.deliver = _island_deliver(1, 5, {leader, companion})

    # atomic read during the partition: it must reflect the majority
    # line's state (the islanded lanes cannot serve it via lease)
    q = rg.submit_query(0, ap.OP_VALUE_GET, consistency="atomic")
    rg.run_until([q], max_rounds=200)
    assert rg.results[q] == 7
