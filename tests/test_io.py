"""Substrate tests: buffers, serializer registry, local + TCP transports."""

import pytest

from copycat_tpu.io.buffer import BufferInput, BufferOutput
from copycat_tpu.io.serializer import SerializationError, Serializer, serialize_with
from copycat_tpu.io.local import LocalServerRegistry, LocalTransport
from copycat_tpu.io.tcp import TcpTransport
from copycat_tpu.io.transport import Address, TransportError

from helpers import async_test


def test_buffer_primitives_roundtrip():
    out = BufferOutput()
    out.write_u8(200).write_bool(True).write_i16(-5).write_i32(1 << 20)
    out.write_i64(-(1 << 40)).write_f64(3.5).write_varint(-123456789)
    out.write_bytes(b"\x00\xff").write_utf8("héllo")
    buf = BufferInput(out.to_bytes())
    assert buf.read_u8() == 200
    assert buf.read_bool() is True
    assert buf.read_i16() == -5
    assert buf.read_i32() == 1 << 20
    assert buf.read_i64() == -(1 << 40)
    assert buf.read_f64() == 3.5
    assert buf.read_varint() == -123456789
    assert buf.read_bytes() == b"\x00\xff"
    assert buf.read_utf8() == "héllo"
    assert buf.remaining == 0


def test_varint_edge_cases():
    for value in (0, 1, -1, 127, 128, -128, 2**31, -(2**31), 2**62):
        out = BufferOutput()
        out.write_varint(value)
        assert BufferInput(out.to_bytes()).read_varint() == value


@serialize_with(900)
class _Point:
    def __init__(self, x=0, y=0, tags=None):
        self.x, self.y, self.tags = x, y, tags or []

    def write_object(self, buf, serializer):
        buf.write_i64(self.x)
        buf.write_i64(self.y)
        serializer.write_object(self.tags, buf)

    def read_object(self, buf, serializer):
        self.x = buf.read_i64()
        self.y = buf.read_i64()
        self.tags = serializer.read_object(buf)


def test_serializer_graph_roundtrip():
    s = Serializer()
    graph = {
        "a": [1, 2.5, None, True, False, "x", b"bytes"],
        "nested": {"p": _Point(3, 4, ["t1"]), "tuple": (1, 2), "set": {1, 2}},
        "addr": Address("localhost", 5000),
    }
    back = s.read(s.write(graph))
    assert back["a"] == graph["a"]
    assert back["nested"]["tuple"] == (1, 2)
    assert back["nested"]["set"] == {1, 2}
    p = back["nested"]["p"]
    assert (p.x, p.y, p.tags) == (3, 4, ["t1"])
    assert back["addr"] == Address("localhost", 5000)


def test_serializer_fuzz_roundtrip():
    """Randomized deep-structure roundtrips: every generated value must
    survive write->read bit-exactly (the wire format is the contract
    every log entry and RPC rides on). 200 structures x depth<=4 across
    all primitive tags, containers, unicode edge cases and int widths."""
    import random
    s = Serializer()
    rng = random.Random(1234)
    strings = ["", "ascii", "unié中\U0001f600", "\x00nul", "x" * 300]

    def gen(depth: int):
        kinds = ["int", "float", "str", "bytes", "bool", "none"]
        if depth > 0:
            kinds += ["list", "dict", "tuple", "set"] * 2
        k = rng.choice(kinds)
        if k == "int":
            # varint edges: signs, byte-width boundaries, 64-bit extremes
            return rng.choice([
                0, 1, -1, 127, 128, -128, 2**31 - 1, -2**31, 2**63 - 1,
                -2**63, rng.randint(-2**62, 2**62)])
        if k == "float":
            return rng.choice([0.0, -1.5, 3.141592653589793, 1e308, -1e-308])
        if k == "str":
            return rng.choice(strings)
        if k == "bytes":
            return bytes(rng.randrange(256)
                         for _ in range(rng.randrange(0, 40)))
        if k == "bool":
            return rng.random() < 0.5
        if k == "none":
            return None
        n = rng.randrange(0, 5)
        if k == "list":
            return [gen(depth - 1) for _ in range(n)]
        if k == "tuple":
            return tuple(gen(depth - 1) for _ in range(n))
        if k == "set":
            return {rng.randint(-1000, 1000) for _ in range(n)}
        return {rng.choice(strings): gen(depth - 1) for _ in range(n)}

    for _ in range(200):
        value = gen(4)
        assert s.read(s.write(value)) == value


def test_serializer_class_reference():
    s = Serializer()
    assert s.read(s.write(_Point)) is _Point


def test_serializer_rejects_unregistered():
    class Unregistered:
        pass

    with pytest.raises(SerializationError):
        Serializer().write(Unregistered())


@async_test
async def test_local_transport_request_response():
    registry = LocalServerRegistry()
    transport = LocalTransport(registry)
    server = transport.server()
    address = Address("local", 1)

    def on_connect(conn):
        async def echo(msg):
            return {"echo": msg}

        conn.handler(str, echo)

    await server.listen(address, on_connect)
    client = transport.client()
    conn = await client.connect(address)
    assert await conn.send("hi") == {"echo": "hi"}
    await client.close()
    await server.close()


@async_test
async def test_local_transport_connect_failure():
    transport = LocalTransport(LocalServerRegistry())
    with pytest.raises(TransportError):
        await transport.client().connect(Address("local", 99))


@async_test
async def test_local_transport_handler_exception_propagates():
    registry = LocalServerRegistry()
    transport = LocalTransport(registry)
    server = transport.server()
    address = Address("local", 2)

    def on_connect(conn):
        async def boom(msg):
            raise RuntimeError("kaboom")

        conn.handler(str, boom)

    await server.listen(address, on_connect)
    conn = await transport.client().connect(address)
    # Same marshalling contract as TCP: handler errors cross as TransportError.
    with pytest.raises(TransportError, match="kaboom"):
        await conn.send("hi")
    await server.close()


@async_test
async def test_tcp_transport_roundtrip():
    transport = TcpTransport()
    server = transport.server()
    address = Address("127.0.0.1", 18765)

    def on_connect(conn):
        async def double(msg):
            return [msg, msg]

        conn.handler(int, double)

    await server.listen(address, on_connect)
    client = transport.client()
    conn = await client.connect(address)
    assert await conn.send(21) == [21, 21]
    await client.close()
    await server.close()


@async_test
async def test_tcp_transport_roundtrip_pure_python_walk(monkeypatch):
    """The TCP burst walk must work identically WITHOUT the native
    codec (toolchain-less deployments): force codec() to None so both
    the frame walk and the write path take the Python struct lane."""
    from copycat_tpu.io import tcp as tcp_mod
    monkeypatch.setattr(tcp_mod, "codec", lambda: None)
    transport = TcpTransport()
    server = transport.server()
    address = Address("127.0.0.1", 18767)

    def on_connect(conn):
        async def double(msg):
            return [msg, msg]

        conn.handler(int, double)
        conn.handler(str, double)

    await server.listen(address, on_connect)
    conn = await transport.client().connect(address)
    # a burst of concurrent requests lands as one multi-frame read
    import asyncio
    results = await asyncio.gather(*(conn.send(i) for i in range(16)),
                                   conn.send("s"))
    assert results == [[i, i] for i in range(16)] + [["s", "s"]]
    await conn.close()
    await server.close()


@async_test
async def test_tcp_transport_error_marshalling():
    transport = TcpTransport()
    server = transport.server()
    address = Address("127.0.0.1", 18766)

    def on_connect(conn):
        async def fail(msg):
            raise ValueError("bad input")

        conn.handler(int, fail)

    await server.listen(address, on_connect)
    conn = await transport.client().connect(address)
    with pytest.raises(TransportError, match="bad input"):
        await conn.send(1)
    await conn.close()
    await server.close()


@async_test
async def test_listeners_schedule_async_callbacks():
    """Async callbacks registered on Listener/Listeners run to completion.

    An asyncio-first API must not drop a coroutine callback on the floor
    (the sync-only dispatch used to leave it "never awaited" — e.g. an
    ``async def`` handed to ``on_election`` simply never fired)."""
    import asyncio

    from copycat_tpu.utils.listeners import Listeners

    listeners: Listeners = Listeners()
    got: list = []
    done = asyncio.Event()

    async def async_cb(event):
        await asyncio.sleep(0)
        got.append(("async", event))
        done.set()

    def sync_cb(event):
        got.append(("sync", event))

    listeners.add(sync_cb)
    listeners.add(async_cb)
    listeners.accept(41)
    assert ("sync", 41) in got          # sync path unchanged, immediate
    await asyncio.wait_for(done.wait(), 5)
    assert ("async", 41) in got

    # a closed listener's coroutine is never created
    lst = listeners.add(async_cb)
    lst.close()
    done.clear()
    listeners.accept(42)
    await asyncio.wait_for(done.wait(), 5)  # the still-open async_cb fires
    assert got.count(("async", 42)) == 1
