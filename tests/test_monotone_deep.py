"""Monotone-tag accept gate + deep pipelined bulk drive (round 4).

The gate (``Config.monotone_tag_accept``, ops/consensus.py) is the
device-side analogue of the reference client's session command
sequencing (Copycat client runtime — SURVEY §2.3): a submit is accepted
only when its tag is exactly (max live-ring stream tag) + 1 + its rank
among the window's valid slots. That makes per-group FIFO
device-enforced and duplicate re-sends idempotent, which is what lets
``models/bulk.py``'s deep drive dispatch blindly with ZERO blocking
fetches per round (the tunnel-latency killer in the round-4 TPU
profile).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from copycat_tpu.models import BulkDriver, RaftGroups  # noqa: E402
from copycat_tpu.ops import apply as ap  # noqa: E402
from copycat_tpu.ops.consensus import (  # noqa: E402
    Config,
    Submits,
    full_delivery,
)


@pytest.fixture(scope="module")
def rg():
    groups = RaftGroups(8, 3, log_slots=32, submit_slots=4, seed=7,
                        config=Config(monotone_tag_accept=True))
    groups.wait_for_leaders()
    return groups


def _submit_window(rg, group, tags, opcode=ap.OP_LONG_ADD, a=1):
    """Hand-build one submit window for ``group`` carrying ``tags``."""
    G, S = rg.num_groups, rg.submit_slots
    sub = rg._empty_submits()
    for s, t in enumerate(tags):
        sub.opcode[group, s] = opcode
        sub.a[group, s] = a
        sub.tag[group, s] = t
        sub.valid[group, s] = True
    return sub


def _step_raw(rg, sub):
    rg._key, key = jax.random.split(rg._key)
    rg.state, out = rg._step(rg.state, sub, rg.deliver, key)
    return out


def test_gate_accepts_dense_stream_rejects_duplicates_and_gaps(rg):
    # fresh group 0: stream starts at tag 1
    out = _step_raw(rg, _submit_window(rg, 0, [1, 2]))
    acc = np.asarray(out.accepted)[0]
    assert acc[0] and acc[1]
    # duplicate (1,2) again: both rejected — idempotent re-send
    out = _step_raw(rg, _submit_window(rg, 0, [1, 2]))
    acc = np.asarray(out.accepted)[0]
    assert not acc.any()
    # gap (skip 3, send 4): rejected — FIFO enforced on device
    out = _step_raw(rg, _submit_window(rg, 0, [4]))
    assert not np.asarray(out.accepted)[0].any()
    # the successor (3) is accepted, and a same-window gap suffix-rejects
    out = _step_raw(rg, _submit_window(rg, 0, [3, 5]))
    acc = np.asarray(out.accepted)[0]
    assert acc[0] and not acc[1]


def test_gate_election_noop_does_not_break_the_chain():
    groups = RaftGroups(4, 3, log_slots=16, submit_slots=2, seed=3,
                        config=Config(monotone_tag_accept=True,
                                      timer_min=2, timer_max=4))
    groups.wait_for_leaders()
    for _ in range(10):  # lease-gated accept needs a warm leader: retry
        out = _step_raw(groups, _submit_window(groups, 1, [1, 2]))
        if np.asarray(out.accepted)[1].all():
            break
    else:
        pytest.fail("initial window never accepted")
    for _ in range(4):  # commit + apply everywhere (leader completeness
        _step_raw(groups, groups._empty_submits())  # preserves them)
    # force a re-election in group 1: isolate the leader for a while
    lead = int(np.asarray(jax.device_get(
        groups.state.leader_hint)).max(axis=1)[1])
    deliver = np.ones((4, 3, 3), bool)
    deliver[1, lead, :] = False
    deliver[1, :, lead] = False
    saved = groups.deliver
    groups.deliver = jnp.asarray(deliver)
    for _ in range(12):
        _step_raw(groups, groups._empty_submits())
    groups.deliver = saved
    groups.wait_for_leaders()
    # the new leader's log has an election no-op (tag 0) on top of the
    # stream; tag 3 must still be the next accepted
    for _ in range(20):
        out = _step_raw(groups, _submit_window(groups, 1, [3]))
        if np.asarray(out.accepted)[1].any():
            break
    else:
        pytest.fail("successor tag never accepted after re-election")
    # and the duplicate of 3 is still rejected afterwards
    out = _step_raw(groups, _submit_window(groups, 1, [3]))
    assert not np.asarray(out.accepted)[1].any()


def test_compact_leaves_match_full_arrays():
    """Scalar opcode/payload leaves and the [G,1] consecutive-tag leaf
    must behave exactly like full [G,S] arrays."""
    groups = RaftGroups(4, 3, log_slots=16, submit_slots=4, seed=5,
                        config=Config(monotone_tag_accept=True))
    groups.wait_for_leaders()
    G, S = 4, 4
    # compact: every group submits tags 1..4, op/a scalar
    sub = Submits(opcode=np.int32(ap.OP_LONG_ADD), a=np.int32(1),
                  b=np.int32(0), c=np.int32(0),
                  tag=np.ones((G, 1), np.int32),
                  valid=np.ones((G, S), bool))
    got = np.zeros((G, S), bool)
    for _ in range(10):  # retry: leaders elected late lack the lease;
        out = _step_raw(groups, sub)  # duplicate re-sends are rejected,
        got |= np.asarray(out.accepted)  # so acceptance is once per op
        if got.all():
            break
    else:
        pytest.fail(f"compact window never fully accepted: {got}")
    for _ in range(4):
        out = _step_raw(groups, groups._empty_submits())
    applied = np.asarray(jax.device_get(
        groups.state.applied_index)).max(axis=1)
    assert (applied >= 4).all()


def test_deep_drive_fifo_across_drives(rg_deep=None):
    groups = RaftGroups(8, 3, log_slots=32, submit_slots=4, seed=11,
                        config=Config(monotone_tag_accept=True))
    groups.wait_for_leaders()
    driver = BulkDriver(groups)
    g = np.repeat(np.arange(8), 10)
    amounts = np.tile(np.arange(1, 11), 8)
    res = driver.drive(g, ap.OP_LONG_ADD, amounts)
    want = np.tile(np.cumsum(np.arange(1, 11)), 8)
    assert (res.results == want).all()
    # second drive continues each group's stream (tags persist via
    # rg._stream_count) and stays FIFO
    res2 = driver.drive(g, ap.OP_LONG_ADD, 1)
    assert (res2.results.reshape(8, 10)
            == want[-1] + np.arange(1, 11)).all()
    assert (res2.latency_rounds() >= 1).all()


def test_deep_drive_mixed_payloads_map_roundtrip():
    groups = RaftGroups(8, 3, log_slots=32, submit_slots=4, seed=13,
                        config=Config(monotone_tag_accept=True))
    groups.wait_for_leaders()
    driver = BulkDriver(groups)
    n = 8 * 10
    g = np.repeat(np.arange(8), 10)
    ops = np.where(np.arange(n) % 2 == 0, ap.OP_MAP_PUT, ap.OP_MAP_GET)
    keys = np.repeat(np.arange(n // 2), 2) % 5
    vals = np.where(np.arange(n) % 2 == 0, 100 + np.arange(n), 0)
    res = driver.drive(g, ops, keys, vals)
    # each GET immediately follows its PUT in group FIFO order
    assert (res.results[1::2] == 100 + np.arange(0, n, 2)).all()


def test_deep_drive_uneven_group_counts():
    groups = RaftGroups(8, 3, log_slots=32, submit_slots=4, seed=17,
                        config=Config(monotone_tag_accept=True))
    groups.wait_for_leaders()
    driver = BulkDriver(groups)
    # ragged: group i gets i+1 ops
    g = np.concatenate([np.full(i + 1, i) for i in range(8)])
    res = driver.drive(g, ap.OP_LONG_ADD, 1)
    off = 0
    for i in range(8):
        got = res.results[off:off + i + 1]
        assert (got == np.arange(1, i + 2)).all(), (i, got)
        off += i + 1


def test_queue_managed_submit_refused_on_monotone_engine(rg):
    with pytest.raises(NotImplementedError):
        rg.submit(0, ap.OP_LONG_ADD, a=1)
    with pytest.raises(NotImplementedError):
        rg.submit_batch(np.arange(4), ap.OP_LONG_ADD, 1)


def test_query_lane_allowed_and_never_escalates_on_monotone_engine():
    """Queries don't append, so they stay allowed — and an unservable
    query must RETRY on the query lane, never escalate to the (closed)
    command path where the gate would reject its tag forever."""
    groups = RaftGroups(4, 3, log_slots=16, submit_slots=4, seed=23,
                        config=Config(monotone_tag_accept=True))
    groups.wait_for_leaders()
    driver = BulkDriver(groups)
    driver.drive(np.array([0]), ap.OP_LONG_ADD, 7)
    # atomic reads via the queued query lane: unservable slots (cold
    # lease after an election) must RETRY as queries, not escalate
    tags = [groups.submit_query(0, ap.OP_VALUE_GET, consistency="atomic")
            for _ in range(3)]
    groups.run_until(tags, max_rounds=60)
    assert all(groups.results[t] == 7 for t in tags)
    # nothing leaked onto the command queues (the wedge the round-4
    # review flagged)
    assert not any(groups._queues.values())


def _isolate(groups, g, peer):
    """Deliver mask cutting ``peer`` off group ``g`` both directions."""
    G, P = groups.num_groups, groups.num_peers
    deliver = np.ones((G, P, P), bool)
    deliver[g, peer, :] = False
    deliver[g, :, peer] = False
    return jnp.asarray(deliver)


def test_gate_exactly_once_across_leader_change_uncommitted_tail():
    """The soundness hinge: ops accepted into a leader log that NEVER
    replicated are lost with that leader; the gate must accept the
    re-dispatch at the new leader (tags > its ring max) and each op
    applies EXACTLY once."""
    groups = RaftGroups(2, 3, log_slots=16, submit_slots=2, seed=31,
                        config=Config(monotone_tag_accept=True,
                                      timer_min=2, timer_max=4,
                                      lease_gated_accept=False))
    groups.wait_for_leaders()
    out = _step_raw(groups, groups._empty_submits())
    lead = int(np.asarray(out.leader)[0])
    # isolate the leader FIRST, then submit [1,2]: the leader accepts
    # them (no lease gate) but can never replicate them
    saved = groups.deliver
    groups.deliver = _isolate(groups, 0, lead)
    for _ in range(3):
        out = _step_raw(groups, _submit_window(groups, 0, [1, 2]))
        if np.asarray(out.accepted)[0].all():
            break
    else:
        pytest.fail("doomed leader never accepted the window")
    # let a new leader rise among the connected majority
    for _ in range(20):
        out = _step_raw(groups, groups._empty_submits())
        new_lead = int(np.asarray(out.leader)[0])
        if new_lead not in (-1, lead):
            break
    else:
        pytest.fail("no new leader elected")
    # re-dispatch the lost ops at the new leader: ring max is 0 there,
    # so [1,2] must be accepted again
    for _ in range(10):
        out = _step_raw(groups, _submit_window(groups, 0, [1, 2]))
        if np.asarray(out.accepted)[0].all():
            break
    else:
        pytest.fail("re-dispatch never accepted at the new leader")
    # heal; old leader rewinds and adopts the new log
    groups.deliver = saved
    for _ in range(10):
        _step_raw(groups, groups._empty_submits())
    # exactly-once: counter == 2 on the applied state of every live lane
    val = groups.value(0, peer=int(np.asarray(_step_raw(
        groups, groups._empty_submits()).leader)[0]))
    assert val == 2, f"counter {val}: an op applied twice or never"


def test_gate_dedups_committed_ops_across_leader_change():
    """Committed entries survive elections (leader completeness), so a
    duplicate re-send after failover must be rejected."""
    groups = RaftGroups(2, 3, log_slots=16, submit_slots=2, seed=37,
                        config=Config(monotone_tag_accept=True,
                                      timer_min=2, timer_max=4))
    groups.wait_for_leaders()
    for _ in range(10):
        out = _step_raw(groups, _submit_window(groups, 0, [1, 2]))
        if np.asarray(out.accepted)[0].all():
            break
    for _ in range(4):  # commit + apply on a quorum
        out = _step_raw(groups, groups._empty_submits())
    lead = int(np.asarray(out.leader)[0])
    saved = groups.deliver
    groups.deliver = _isolate(groups, 0, lead)
    for _ in range(20):
        out = _step_raw(groups, groups._empty_submits())
        if int(np.asarray(out.leader)[0]) not in (-1, lead):
            break
    # duplicate re-send at the new leader: its log CONTAINS [1,2]
    # (committed entries survive) -> ring max 2 -> rejected
    out = _step_raw(groups, _submit_window(groups, 0, [1, 2]))
    assert not np.asarray(out.accepted)[0].any()
    groups.deliver = saved
    for _ in range(8):
        _step_raw(groups, groups._empty_submits())
    val = groups.value(0, peer=int(np.asarray(_step_raw(
        groups, groups._empty_submits()).leader)[0]))
    assert val == 2


def test_timeout_resyncs_stream_cursor_engine_not_wedged():
    """A drive that times out mid-stream must leave the engine usable:
    the device consumed tags the host never saw resolve, so the cursor
    resyncs from the device ring and the NEXT drive's tags are accepted
    (round-4 review: the stale cursor wedged every later drive)."""
    groups = RaftGroups(4, 3, log_slots=32, submit_slots=4, seed=29,
                        config=Config(monotone_tag_accept=True))
    groups.wait_for_leaders()
    driver = BulkDriver(groups)
    g = np.repeat(np.arange(4), 8)
    # max_rounds too small to even finish phase 1 + settle + harvest
    with pytest.raises(TimeoutError):
        driver.drive(g, ap.OP_LONG_ADD, 1, max_rounds=1)
    # the engine recovers: a fresh drive completes and its results account
    # for WHATEVER prefix of the abandoned drive committed (at-most-once
    # for abandoned ops — each group's counter is monotone and the new
    # ops' deltas all land exactly once)
    res = driver.drive(g, ap.OP_LONG_ADD, 1)
    vals = res.results.reshape(4, 8)
    assert (np.diff(vals, axis=1) == 1).all()  # FIFO, each delta once


def test_bulk_query_drive_all_levels():
    """Client-visible bulk READS through the no-append query lane: each
    level serves the applied value; ATOMIC additionally rides the leader
    lease (linearizable with zero log entries)."""
    groups = RaftGroups(8, 3, log_slots=32, submit_slots=4, seed=41,
                        config=Config(monotone_tag_accept=True))
    groups.wait_for_leaders()
    driver = BulkDriver(groups)
    g = np.repeat(np.arange(8), 5)
    driver.drive(g, ap.OP_LONG_ADD, 1)   # counters now 5 everywhere
    reads = np.repeat(np.arange(8), 7)
    for level in ("sequential", "atomic", "causal", "process"):
        got = driver.drive_queries(reads, ap.OP_VALUE_GET,
                                   consistency=level)
        assert (got == 5).all(), (level, got)


def test_bulk_query_drive_map_and_errors():
    groups = RaftGroups(4, 3, log_slots=32, submit_slots=4, seed=43,
                        config=Config(monotone_tag_accept=True))
    groups.wait_for_leaders()
    driver = BulkDriver(groups)
    n = 4 * 6
    g = np.repeat(np.arange(4), 6)
    driver.drive(g, ap.OP_MAP_PUT, np.tile(np.arange(6), 4),
                 100 + np.arange(n))
    got = driver.drive_queries(g, ap.OP_MAP_GET, np.tile(np.arange(6), 4))
    assert (got == 100 + np.arange(n)).all()
    with pytest.raises(ValueError):
        driver.drive_queries(g, ap.OP_LONG_ADD, 1)  # not read-only
    with pytest.raises(ValueError):
        driver.drive_queries(g, ap.OP_MAP_GET, 0, consistency="nope")


def test_deep_drive_session_events_ingested():
    """Lock grants ride the event ring; the deep drive's rare ev path
    must still deliver them to the host buffer."""
    groups = RaftGroups(4, 3, log_slots=32, submit_slots=4, seed=19,
                        config=Config(monotone_tag_accept=True))
    groups.wait_for_leaders()
    driver = BulkDriver(groups)
    # acquire(1) grants synchronously; acquire(2) queues; release(1)
    # hands the lock to 2 via an EV_LOCK_GRANT outbox event
    res = driver.drive(
        np.array([0, 0, 0]),
        np.array([ap.OP_LOCK_ACQUIRE, ap.OP_LOCK_ACQUIRE,
                  ap.OP_LOCK_RELEASE]),
        np.array([1, 2, 1]), np.array([0, -1, 0]))
    assert res.results.size == 3
    assert any(code == ap.EV_LOCK_GRANT and target == 2
               for _, code, target, _ in groups.events.get(0, []))


def test_checkpoint_restore_rebuilds_stream_cursor(tmp_path):
    """Restoring a monotone engine must rebuild _stream_count from the
    log ring, or the next drive's tags collide with consumed ones and
    the gate rejects them forever (the cursor is host-side state the
    snapshot does not carry)."""
    from copycat_tpu.models import checkpoint

    groups = RaftGroups(6, 3, log_slots=32, submit_slots=4, seed=61,
                        config=Config(monotone_tag_accept=True))
    groups.wait_for_leaders()
    driver = BulkDriver(groups)
    g = np.repeat(np.arange(6), 9)
    driver.drive(g, ap.OP_LONG_ADD, 1)

    path = tmp_path / "snap.npz"
    checkpoint.save(groups, path)
    restored = checkpoint.load(path)
    assert (restored._stream_count == 9).all(), restored._stream_count
    drv2 = BulkDriver(restored)
    res = drv2.drive(g, ap.OP_LONG_ADD, 1)
    assert (res.results.reshape(6, 9) == 9 + np.arange(1, 10)).all()
