"""Pipelined bulk driver (models/bulk.py — VERDICT r3 #4).

Correctness of the vectorized schedule + double-buffered rounds: results
must match the queue-managed path exactly (per-group FIFO order), spills
from backpressure must retry, and tags must not collide with the
queue-managed path's.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.models import BulkDriver, RaftGroups  # noqa: E402
from copycat_tpu.ops import apply as ap  # noqa: E402


@pytest.fixture(scope="module")
def rg():
    groups = RaftGroups(8, 3, log_slots=32, submit_slots=4, seed=11)
    groups.wait_for_leaders()
    return groups


def test_bulk_counter_results_match_sequential_semantics(rg):
    driver = BulkDriver(rg)
    # 5 adds per group with distinct amounts: per-group FIFO means the
    # k-th op's result is the prefix sum
    amounts = np.tile(np.arange(1, 6), 8)
    groups = np.repeat(np.arange(8), 5)
    res = driver.drive(groups, ap.OP_LONG_ADD, amounts)
    want = np.tile(np.cumsum(np.arange(1, 6)), 8)
    assert (res.results == want).all(), res.results
    assert (res.latency_rounds() >= 1).all()


def test_bulk_deep_per_group_chains_spill_and_complete(rg):
    """More ops per group than submit slots x scheduled rounds can carry
    at once — the respill path must keep FIFO and complete everything."""
    driver = BulkDriver(rg)
    per_group = 40  # 10 scheduled rounds at S=4, plus backpressure spills
    groups = np.repeat(np.arange(8), per_group)
    base = rg.value(0, peer=0)
    res = driver.drive(groups, ap.OP_LONG_ADD, 1)
    finals = res.results.reshape(8, per_group)[:, -1]
    assert (np.diff(res.results.reshape(8, per_group), axis=1) == 1).all()
    assert (finals == res.results.reshape(8, per_group)[:, 0]
            + per_group - 1).all()
    assert base >= 0  # engine still healthy


def test_bulk_and_queued_paths_interleave_without_tag_collisions(rg):
    driver = BulkDriver(rg)
    t = rg.submit(0, ap.OP_LONG_ADD, a=1000)
    res = driver.drive(np.arange(8), ap.OP_LONG_ADD, 1)
    rg.run_until([t])
    assert res.results.size == 8
    assert rg.results[t] >= 1000  # queue op resolved with its own value


def test_queue_op_applying_during_bulk_drive_still_resolves(rg):
    """A queue-managed op already IN the log when a bulk drive starts is
    reported by the device exactly once — during a bulk round. The bulk
    harvest must route it into rg.results, not drop it behind the tag
    filter."""
    driver = BulkDriver(rg)
    t = rg.submit(1, ap.OP_LONG_ADD, a=500)
    rg.step_round()           # accepted into the log, not yet resolved
    res = driver.drive(np.arange(8), ap.OP_LONG_ADD, 1)
    assert res.results.size == 8
    # resolved by the bulk rounds themselves (or the drain) — run_until
    # must find it already present without timing out
    rg.run_until([t], max_rounds=10)
    assert rg.results[t] >= 500


def test_bulk_latency_percentiles_shape(rg):
    driver = BulkDriver(rg)
    res = driver.drive(np.arange(8), ap.OP_LONG_ADD, 1)
    pct = res.latency_percentiles_ms()
    assert set(pct) == {"p50", "p99"} and pct["p99"] >= pct["p50"] > 0


def test_bulk_query_drive_on_classic_engine(rg):
    """drive_queries works on NON-monotone engines too — queries never
    append, so the tag gate is irrelevant (docstring contract)."""
    driver = BulkDriver(rg)
    driver.drive(np.arange(8), ap.OP_LONG_ADD, 5)
    got = driver.drive_queries(np.repeat(np.arange(8), 3), ap.OP_VALUE_GET,
                               consistency="sequential")
    # every group's counter is at least 5 (other tests in this module
    # share the engine); reads must be served and consistent per group
    assert (got.reshape(8, 3) == got.reshape(8, 3)[:, :1]).all()
    assert (got >= 5).all()


def test_deep_scan_mode_matches_dispatch_mode():
    """``BulkDriver(deep_scan=True)`` — the whole blind phase as ONE
    lax.scan program — produces identical results, stream cursors, and
    session events to the per-window dispatch mode (same seeds)."""
    from copycat_tpu.ops.consensus import Config

    def build():
        rg = RaftGroups(8, 3, log_slots=32, submit_slots=4, seed=9,
                        config=Config(monotone_tag_accept=True))
        rg.wait_for_leaders()
        return rg

    rg1, rg2 = build(), build()
    d1 = BulkDriver(rg1)
    d2 = BulkDriver(rg2, deep_scan=True)
    gs = np.repeat(np.arange(8), 10)
    r1 = d1.drive(gs, ap.OP_LONG_ADD, 1)
    r2 = d2.drive(gs, ap.OP_LONG_ADD, 1)
    assert list(r1.results) == list(r2.results)
    assert (rg1._stream_count == rg2._stream_count).all()

    # second drive reuses the compiled scan (same shapes) and mixed
    # per-op payloads take the non-const scatter path
    ops = np.where(np.arange(80) % 2 == 0, ap.OP_LONG_ADD,
                   ap.OP_VALUE_GET)
    r1 = d1.drive(gs, ops, 2)
    r2 = d2.drive(gs, ops, 2)
    assert list(r1.results) == list(r2.results)

    # session events (lock grant) surface identically through the
    # stacked [W, ...] event path
    for rg, d in ((rg1, d1), (rg2, d2)):
        d.drive([0, 0], ap.OP_LOCK_ACQUIRE, [7, 8], -1)
        d.drive([0], ap.OP_LOCK_RELEASE, 7)
    assert rg1.events.get(0) == rg2.events.get(0)
    assert any(code == ap.EV_LOCK_GRANT and target == 8
               for _, code, target, _a in rg2.events.get(0, []))
