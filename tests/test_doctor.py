"""The cross-member ``doctor``: root-cause attribution under the four
injected-fault scenarios the acceptance bar names (partition, slow
disk, replication-window collapse, crash-with-spill-recovery), the
assembly's incomplete semantics, and the CLI error paths."""

import argparse
import asyncio
import json

import pytest

jax = pytest.importorskip("jax")

from copycat_tpu import cli  # noqa: E402
from copycat_tpu.io.local import LocalTransport, NetworkNemesis  # noqa: E402
from copycat_tpu.server.log import NoOpEntry, Storage, StorageLevel  # noqa: E402
from copycat_tpu.server.raft import RaftServer  # noqa: E402
from copycat_tpu.server.stats import StatsListener  # noqa: E402
from copycat_tpu.testing.nemesis import SlowDiskNemesis, crash_server  # noqa: E402
from copycat_tpu.utils.health import (  # noqa: E402
    CRITICAL,
    OK,
    assemble_doctor_report,
    render_doctor_report,
)

from helpers import arun  # noqa: E402
from raft_fixtures import KVStateMachine, Put, create_cluster  # noqa: E402


async def _listeners(cluster):
    out = []
    for s in cluster.servers:
        out.append(await StatsListener(s, port=0).open())
    return out, [f"127.0.0.1:{ln.port}" for ln in out]


def _causes(report, detector):
    return [c for c in report["causes"] if detector in c["detectors"]]


# ---------------------------------------------------------------------------
# scenario 1: partition -> commit stall attributed with election churn
# ---------------------------------------------------------------------------


def test_doctor_attributes_partition(monkeypatch):
    monkeypatch.setenv("COPYCAT_INVARIANTS", "strict")
    monkeypatch.setenv("COPYCAT_HEALTH_STALL_S", "0.5")
    monkeypatch.setenv("COPYCAT_HEALTH_CHURN_WARN", "2")

    async def run():
        cluster = await create_cluster(3, election_timeout=0.15,
                                       heartbeat_interval=0.03)
        listeners = []
        try:
            client = await cluster.client()
            for i in range(5):
                await client.submit(Put(key=f"k{i}", value=i))
            leader = cluster.leader
            nemesis = cluster.registry.attach_nemesis(NetworkNemesis())
            nemesis.partition(*[[s.address] for s in cluster.servers])
            deadline = asyncio.get_running_loop().time() + 4.0
            while asyncio.get_running_loop().time() < deadline:
                leader._append(NoOpEntry())
                await asyncio.sleep(0.15)
                v = leader.health.tick()
                if v["detectors"]["commit_stall"]["status"] == CRITICAL:
                    break
            # the stats listeners ride real TCP: the fan-out works even
            # while the cluster transport is partitioned
            listeners, addrs = await _listeners(cluster)
            members, failed, traces = await cli.collect_doctor(addrs)
            assert failed == []
            report = assemble_doctor_report(members, failed, traces)
            assert report["incomplete"] is False
            assert report["verdict"] == CRITICAL
            stalls = _causes(report, "commit_stall")
            assert stalls, report["causes"]
            top = stalls[0]
            assert top["group"] == 0
            assert str(leader.address) in top["symptom"]
            assert ("election instability" in top["cause"]
                    or "quorum loss (partition)" in top["cause"])
            text = render_doctor_report(report)
            assert "cluster verdict: CRITICAL" in text
            assert "commit stalled" in text
            nemesis.heal()
        finally:
            for ln in listeners:
                await ln.close()
            await cluster.close()

    arun(run(), timeout=120)


# ---------------------------------------------------------------------------
# scenario 2: slow disk on the leader -> fsync spike names the member
# ---------------------------------------------------------------------------


def test_doctor_attributes_slow_disk(monkeypatch, tmp_path):
    monkeypatch.setenv("COPYCAT_INVARIANTS", "strict")

    async def run():
        cluster = await create_cluster(
            3, storage_factory=lambda i: Storage(
                StorageLevel.DISK, str(tmp_path / str(i)),
                max_entries_per_segment=32))
        try:
            client = await cluster.client()
            leader = cluster.leader
            for i in range(10):
                await client.submit(Put(key=f"w{i}", value=i))
            for s in cluster.servers:
                s.health.tick()
            slow = SlowDiskNemesis(
                leader, delay_s=max(
                    0.05, leader.groups[0]._fsync_ewma_ms * 10 / 1e3))
            slow.install()
            try:
                for i in range(3):
                    await client.submit(Put(key=f"s{i}", value=i))
            finally:
                slow.remove()
            members = {str(s.address): {"health": s.health.tick()}
                       for s in cluster.servers}
            report = assemble_doctor_report(members)
            spikes = _causes(report, "fsync_spike")
            assert spikes, report["causes"]
            # the slowed member is named (loop stalls from its blocking
            # fsync can plausibly trip other members too — the leader
            # must be among the attributed ones either way)
            named = {m for c in spikes for m in c["members"]}
            assert str(leader.address) in named, (named, report["causes"])
            assert all("disk" in c["cause"] for c in spikes)
        finally:
            await cluster.close()

    arun(run(), timeout=120)


# ---------------------------------------------------------------------------
# scenario 3: slow FOLLOWER -> the leader's window collapse correlated
# with the follower's own fsync findings across members
# ---------------------------------------------------------------------------


def test_doctor_correlates_window_collapse_with_follower_disk(
        monkeypatch, tmp_path):
    monkeypatch.setenv("COPYCAT_INVARIANTS", "strict")
    monkeypatch.setenv("COPYCAT_REPL_WINDOW", "8")

    async def run():
        cluster = await create_cluster(
            3, session_timeout=30.0,
            storage_factory=lambda i: Storage(
                StorageLevel.DISK, str(tmp_path / str(i)),
                max_entries_per_segment=64))
        try:
            client = await cluster.client(session_timeout=30.0)
            leader = cluster.leader
            follower = next(s for s in cluster.servers if s is not leader)
            for i in range(20):
                await client.submit(Put(key=f"w{i}", value=i))
            for s in cluster.servers:
                s.health.tick()
            ack_ewma = max((ps.ack_ewma_ms for ps in
                            leader.groups[0]._peer_streams.values()),
                           default=1.0)
            slow = SlowDiskNemesis(
                follower,
                delay_s=max(0.06, ack_ewma * 8 / 1e3,
                            follower.groups[0]._fsync_ewma_ms * 10 / 1e3))
            slow.install()
            try:
                for burst in range(3):
                    await asyncio.gather(*(
                        client.submit(Put(key=f"b{burst}.{i}", value=i))
                        for i in range(60)))
                    await asyncio.sleep(0.3)
                    v = leader.health.tick()
                    if v["detectors"]["window_collapse"]["status"] != OK:
                        break
            finally:
                slow.remove()
            members = {str(s.address): {"health": s.health.tick()
                                        if s is not leader else v}
                       for s in cluster.servers}
            report = assemble_doctor_report(members)
            correlated = [c for c in report["causes"]
                          if set(c["detectors"]) >= {"window_collapse",
                                                     "fsync_spike"}]
            assert correlated, report["causes"]
            top = correlated[0]
            # the cross-member attribution: the leader saw the collapse,
            # the slow follower's own fsync finding explains it
            assert str(leader.address) in top["members"]
            assert str(follower.address) in top["members"]
            assert "fsync spike (disk)" in top["cause"]
        finally:
            await cluster.close()

    arun(run(), timeout=120)


# ---------------------------------------------------------------------------
# scenario 4: crash with black-box spill -> recovery attributed via the
# real fan-out
# ---------------------------------------------------------------------------


def test_doctor_attributes_crash_recovery(monkeypatch, tmp_path):
    monkeypatch.setenv("COPYCAT_INVARIANTS", "strict")

    async def run():
        storage = lambda i: Storage(StorageLevel.DISK, str(tmp_path),  # noqa: E731
                                    max_entries_per_segment=16)
        cluster = await create_cluster(1, storage_factory=storage)
        listeners = []
        try:
            server = cluster.servers[0]
            client = await cluster.client()
            for i in range(5):
                await client.submit(Put(key=f"k{i}", value=i))
            server.health_note("nemesis_fault", fault="injected")
            await crash_server(server)
            reborn = RaftServer(
                server.address, [server.address],
                LocalTransport(cluster.registry,
                               local_address=server.address),
                KVStateMachine(), storage=storage(0),
                election_timeout=0.2, heartbeat_interval=0.04)
            cluster.servers[0] = reborn
            await reborn.open()
            listeners, addrs = await _listeners(cluster)
            members, failed, traces = await cli.collect_doctor(addrs)
            report = assemble_doctor_report(members, failed, traces)
            crashes = _causes(report, "blackbox")
            assert crashes, report["causes"]
            top = crashes[0]
            assert str(reborn.address) in top["members"]
            assert "black-box tail before death" in top["cause"]
            assert any(e["kind"] == "nemesis_fault"
                       for e in top["events"])
            assert report["verdict"] != OK
        finally:
            for ln in listeners:
                await ln.close()
            await cluster.close()

    arun(run(), timeout=120)


# ---------------------------------------------------------------------------
# assembly semantics + CLI error paths
# ---------------------------------------------------------------------------


def test_doctor_partial_fanout_incomplete():
    async def run():
        cluster = await create_cluster(3)
        listeners = []
        try:
            for s in cluster.servers:
                s.health.tick()
            listeners, addrs = await _listeners(cluster)
            members, failed, traces = await cli.collect_doctor(
                addrs + ["127.0.0.1:1"])
            assert failed == ["127.0.0.1:1"]
            report = assemble_doctor_report(members, failed, traces)
            assert report["incomplete"] is True
            assert any("unreachable" in why
                       for why in report["incomplete_why"])
            # the unreachable member is a symptom, not just missing data
            fanout = _causes(report, "fanout")
            assert fanout and "127.0.0.1:1" in fanout[0]["members"]
            assert report["verdict"] != OK
            assert "INCOMPLETE" in render_doctor_report(report)
        finally:
            for ln in listeners:
                await ln.close()
            await cluster.close()

    arun(run(), timeout=120)


def test_doctor_cli_all_unreachable_is_one_line_error(capsys):
    rc = cli._doctor(argparse.Namespace(
        addresses=["127.0.0.1:1", "127.0.0.1:2"], slowest=3, json=False))
    assert rc == 1
    err = capsys.readouterr().err
    assert "none of 2 member(s) reachable" in err
    assert "--stats-port" in err


def test_doctor_cli_renders_against_live_cluster(capsys):
    async def scenario():
        cluster = await create_cluster(1)
        listeners, addrs = await _listeners(cluster)
        try:
            members, failed, traces = await cli.collect_doctor(addrs)
            report = assemble_doctor_report(members, failed, traces)
            print(render_doctor_report(report))
        finally:
            for ln in listeners:
                await ln.close()
            await cluster.close()

    arun(scenario(), timeout=120)
    out = capsys.readouterr().out
    assert "cluster verdict" in out


def test_stats_cli_bad_address_is_actionable(capsys):
    rc = cli._stats(argparse.Namespace(address="localhost", what="stats",
                                       watch=None))
    assert rc == 1
    err = capsys.readouterr().err
    assert "expected host:port" in err


def test_doctor_ungraded_member_is_not_healthy():
    """A member whose health plane is off (COPYCAT_HEALTH=0 serves
    {"status": "disabled"}) ran zero checks — the doctor must degrade
    the verdict, not read it as a clean member."""
    members = {
        "m1:1": {"health": {"status": "ok", "node": "m1:1",
                            "detectors": {}}},
        "m2:2": {"health": {"status": "disabled", "node": "m2:2"}},
    }
    report = assemble_doctor_report(members)
    assert report["verdict"] == "warn"
    ungraded = _causes(report, "health_plane")
    assert ungraded and "m2:2" in ungraded[0]["members"]
    assert "'disabled'" in ungraded[0]["symptom"]
    assert report["member_status"]["m2:2"] == "disabled"


def test_doctor_json_report_shape():
    members = {
        "m1:1": {"health": {"status": "critical", "detectors": {
            "commit_stall": {"status": "critical", "groups": {
                "0": {"status": "critical",
                      "reason": "commit stalled 3.0s at index 7 with 4 "
                                "uncommitted entries (and growing)",
                      "evidence": {"commit_index": [7, 7]}}}}}}},
        "m2:2": {"health": {"status": "warn", "detectors": {
            "fsync_spike": {"status": "warn", "groups": {
                "0": {"status": "warn",
                      "reason": "fsync 40.0ms vs 0.3ms baseline (133x)",
                      "evidence": {}}}}}}},
    }
    report = assemble_doctor_report(members)
    assert report["verdict"] == "critical"
    stall = _causes(report, "commit_stall")[0]
    # the same-group fsync finding on the OTHER member is pulled in as
    # the cause — the "follower fsync p99 (disk)" decomposition
    assert "slow disk (fsync spike)" in stall["cause"]
    assert "m2:2" in stall["members"]
    assert json.loads(json.dumps(report)) == report  # JSON-able artifact

def test_doctor_cites_holding_frames_on_overlapping_stalls():
    """A commit_stall whose member also carries recent ``loop_stall``
    flight notes gets the holding frames attached as evidence — the
    doctor's bridge from "commits stalled" to "THIS code held the
    loop" — and the rendering prints the ``held by:`` rows."""
    import time as _time

    note = {"seq": 1, "t": round(_time.time(), 3), "round": 0,
            "kind": "loop_stall", "hold_ms": 180.0,
            "frame": "nemesis._nemesis_synchronous_hold",
            "callback": "Handle", "stack": "MainThread;nemesis."
            "_nemesis_synchronous_hold"}
    stale = dict(note, seq=2, t=round(_time.time() - 9_000, 3),
                 frame="ancient.hold")
    members = {
        "m1:1": {"health": {"status": "critical", "node": "m1:1",
                            "detectors": {
            "commit_stall": {"status": "critical", "groups": {
                "0": {"status": "critical",
                      "reason": "commit stalled 3.0s at index 7 with 4 "
                                "uncommitted entries (and growing)",
                      "evidence": {"commit_index": [7, 7]}}}}}},
         "flight": {"events": [note, stale]}},
    }
    report = assemble_doctor_report(members)
    stall = _causes(report, "commit_stall")[0]
    frames = stall["profile_frames"]
    assert frames == [{"member": "m1:1",
                       "frame": "nemesis._nemesis_synchronous_hold",
                       "hold_ms": 180.0}]  # the stale note aged out
    out = render_doctor_report(report)
    assert ("held by: m1:1: nemesis._nemesis_synchronous_hold "
            "(180 ms)") in out
    # no notes -> no key: the report shape without the profiling
    # plane is unchanged
    del members["m1:1"]["flight"]
    report2 = assemble_doctor_report(members)
    assert "profile_frames" not in _causes(report2, "commit_stall")[0]
