"""Raft core tests: election, replication, sessions, events, consistency.

The reference pyramid (SURVEY.md §4): real consensus over the fake transport,
3-5 servers, inline state machines.
"""

import asyncio

import pytest

from copycat_tpu.client.client import ApplicationError
from copycat_tpu.server.raft import FOLLOWER, LEADER
from helpers import async_test
from raft_fixtures import (
    BoundedGet,
    Fail,
    Get,
    KVStateMachine,
    Notify,
    Put,
    PutTtl,
    SeqGet,
    create_cluster,
)


@async_test
async def test_single_server_put_get():
    cluster = await create_cluster(1)
    try:
        client = await cluster.client()
        assert await client.submit(Put(key="a", value=1)) is None
        assert await client.submit(Put(key="a", value=2)) == 1  # returns old
        assert await client.submit(Get(key="a")) == 2
    finally:
        await cluster.close()


@async_test
async def test_three_server_replication():
    cluster = await create_cluster(3)
    try:
        client = await cluster.client()
        for i in range(20):
            await client.submit(Put(key=f"k{i}", value=i))
        assert await client.submit(Get(key="k7")) == 7
        # All machines converge to identical state.
        await asyncio.sleep(0.3)
        states = [s.state_machine.data for s in cluster.servers]
        for st in states[1:]:
            assert st == states[0]
    finally:
        await cluster.close()


@async_test
async def test_exactly_once_under_retry():
    cluster = await create_cluster(3)
    try:
        client = await cluster.client()
        # Submit the same logical command twice with the same seq by going
        # through the server-side cache: simulate a retry by re-sending the
        # request object directly.
        from copycat_tpu.protocol import messages as msg

        conn = await client._connect()
        req = msg.CommandRequest(session_id=client.session().id, seq=1,
                                 operation=Put(key="x", value="v1"))
        r1 = await conn.send(req)
        r2 = await conn.send(req)  # identical seq -> cached, applied once
        assert r1.result == r2.result
        assert r1.index == r2.index
        leader = cluster.leader
        assert leader.state_machine.applied_ops == 1
    finally:
        await cluster.close()


@async_test
async def test_out_of_order_seq_applies_in_order():
    """Concurrent submits racing over reconnects can arrive reordered; the
    leader must append (and apply) them in client seq order."""
    cluster = await create_cluster(3)
    try:
        client = await cluster.client()
        from copycat_tpu.protocol import messages as msg

        conn = await client._connect()
        sid = client.session().id
        # seq 2 arrives first and must wait for seq 1.
        t2 = asyncio.ensure_future(conn.send(msg.CommandRequest(
            session_id=sid, seq=2, operation=Put(key="o", value="second"))))
        await asyncio.sleep(0.1)
        assert not t2.done()
        r1 = await conn.send(msg.CommandRequest(
            session_id=sid, seq=1, operation=Put(key="o", value="first")))
        r2 = await t2
        assert r1.error is None and r2.error is None
        assert r1.index < r2.index  # applied in seq order
        assert r2.result == "first"  # put returns the previous value
        leader = cluster.leader
        assert leader.state_machine.data["o"] == "second"
    finally:
        await cluster.close()


@async_test
async def test_query_consistency_levels():
    cluster = await create_cluster(3)
    try:
        client = await cluster.client()
        await client.submit(Put(key="q", value=9))

        assert await client.submit(Get(key="q")) == 9  # LINEARIZABLE
        assert await client.submit(BoundedGet(key="q")) == 9
        assert await client.submit(SeqGet(key="q")) == 9
    finally:
        await cluster.close()


@async_test
async def test_application_error_propagates():
    cluster = await create_cluster(3)
    try:
        client = await cluster.client()
        with pytest.raises(ApplicationError, match="deliberate failure"):
            await client.submit(Fail())
        # The cluster stays healthy after a state machine error.
        await client.submit(Put(key="after", value=1))
        assert await client.submit(Get(key="after")) == 1
    finally:
        await cluster.close()


@async_test
async def test_session_events_push():
    cluster = await create_cluster(3)
    try:
        client = await cluster.client()
        received: list = []
        got = asyncio.Event()

        def on_poked(payload):
            received.append(payload)
            got.set()

        client.session().on_event("poked", on_poked)
        result = await client.submit(Notify(payload="hello"))
        assert result == "notified"
        await asyncio.wait_for(got.wait(), 5)
        assert received == ["hello"]
    finally:
        await cluster.close()


@async_test
async def test_linearizable_events_before_response():
    """ATOMIC rule: the event arrives before the command response completes."""
    cluster = await create_cluster(3)
    try:
        client = await cluster.client()
        received: list = []
        client.session().on_event("poked", received.append)
        await client.submit(Notify(payload="first"))
        # The event must already be here - no sleep.
        assert received == ["first"]
    finally:
        await cluster.close()


@async_test
async def test_ttl_expiry_via_log_time():
    cluster = await create_cluster(3)
    try:
        client = await cluster.client()
        await client.submit(PutTtl(key="tmp", value=1, ttl=0.3))
        assert await client.submit(Get(key="tmp")) == 1
        await asyncio.sleep(0.8)  # leader appends NoOp to advance the clock
        assert await client.submit(Get(key="tmp")) is None
        # Expiry is deterministic on all servers.
        await asyncio.sleep(0.2)
        for server in cluster.servers:
            assert "tmp" not in server.state_machine.data
    finally:
        await cluster.close()


@async_test(timeout=90)
async def test_leader_failover():
    cluster = await create_cluster(3)
    try:
        client = await cluster.client(session_timeout=5.0)
        await client.submit(Put(key="pre", value="crash"))
        old_leader = cluster.leader
        await old_leader.close()
        # Client re-routes; new leader elected; data survives.
        await client.submit(Put(key="post", value="recovered"))
        assert await client.submit(Get(key="pre")) == "crash"
        assert await client.submit(Get(key="post")) == "recovered"
        new_leader = cluster.leader
        assert new_leader is not old_leader
    finally:
        await cluster.close()


@async_test(timeout=90)
async def test_session_expiry_fans_out():
    cluster = await create_cluster(3, session_timeout=0.8)
    try:
        client = await cluster.client(session_timeout=0.8)
        session_id = client.session().id
        await client.submit(Put(key="s", value=1))
        # Kill keep-alives without a graceful unregister.
        client._keepalive.cancel()
        client._session.state = "expired"  # stop client-side submissions
        await asyncio.sleep(3.0)
        leader = cluster.leader
        assert session_id in leader.state_machine.expired_sessions
        assert session_id in leader.state_machine.closed_sessions
        assert session_id not in leader.sessions
    finally:
        await cluster.close()


@async_test
async def test_graceful_close_calls_close_not_expire():
    cluster = await create_cluster(3)
    try:
        client = await cluster.client()
        session_id = client.session().id
        await client.submit(Put(key="g", value=1))
        await client.close()
        await asyncio.sleep(0.3)
        leader = cluster.leader
        assert session_id in leader.state_machine.closed_sessions
        assert session_id not in leader.state_machine.expired_sessions
    finally:
        await cluster.close()


@async_test(timeout=120)
async def test_server_join_and_leave():
    from copycat_tpu.io.local import LocalTransport
    from copycat_tpu.server.raft import RaftServer
    from raft_fixtures import KVStateMachine, next_ports

    cluster = await create_cluster(3)
    try:
        client = await cluster.client()
        await client.submit(Put(key="j", value=1))
        # Join a 4th server not in the original member list.
        new_addr = next_ports(1)[0]
        joiner = RaftServer(
            new_addr,
            [s.address for s in cluster.servers],
            LocalTransport(cluster.registry),
            KVStateMachine(),
            election_timeout=0.2,
            heartbeat_interval=0.04,
        )
        await joiner.open()
        cluster.servers.append(joiner)
        await asyncio.sleep(0.5)
        leader = cluster.leader
        assert new_addr in leader.members
        # The joiner catches up with replicated state.
        deadline = asyncio.get_running_loop().time() + 5
        while asyncio.get_running_loop().time() < deadline:
            if joiner.state_machine.data.get("j") == 1:
                break
            await asyncio.sleep(0.05)
        assert joiner.state_machine.data.get("j") == 1
        # Leave again.
        await joiner.leave()
        await joiner.close()
        cluster.servers.remove(joiner)
        await asyncio.sleep(0.3)
        assert new_addr not in cluster.leader.members
    finally:
        await cluster.close()


@async_test
async def test_log_cleaning_and_compaction():
    cluster = await create_cluster(3)
    try:
        client = await cluster.client()
        for i in range(30):
            await client.submit(Notify(payload=i))  # notify cleans its commit
        await asyncio.sleep(0.3)
        leader = cluster.leader
        # Cleaned entries got compacted (nulled) up to the global index.
        nulled = sum(1 for i in range(leader.log.first_index, leader.log.last_index + 1)
                     if leader.log.get(i) is None)
        assert nulled > 0
    finally:
        await cluster.close()
