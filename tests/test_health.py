"""The health plane (docs/OBSERVABILITY.md "Health & diagnosis"):
detector units on synthetic evidence, nemesis-driven ground truth on
live clusters (partition -> churn + commit stall, slow disk -> fsync
spike, slow follower -> replication-window collapse, expiry storms,
snapshot failures), the durable black-box spill surviving a
SIGKILL-shaped crash, and the ``COPYCAT_HEALTH=0`` off-plane."""

import asyncio
import json
import os
from collections import deque

import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.server.log import Storage, StorageLevel  # noqa: E402
from copycat_tpu.utils import knobs  # noqa: E402
from copycat_tpu.server.log import NoOpEntry  # noqa: E402
from copycat_tpu.server.raft import RaftServer  # noqa: E402
from copycat_tpu.server.stats import StatsListener, fetch_stats  # noqa: E402
from copycat_tpu.io.local import LocalTransport, NetworkNemesis  # noqa: E402
from copycat_tpu.testing.nemesis import SlowDiskNemesis, crash_server  # noqa: E402
from copycat_tpu.utils.health import (  # noqa: E402
    CRITICAL,
    OK,
    WARN,
    BlackBox,
    CommitStallDetector,
    FsyncSpikeDetector,
    IngressBacklogDetector,
    LeaderChurnDetector,
    SessionExpiryDetector,
    SnapshotFailureDetector,
    WindowCollapseDetector,
    worst,
)

from helpers import async_test  # noqa: E402
from raft_fixtures import KVStateMachine, Put, create_cluster  # noqa: E402


def _hist(samples, dt=0.2):
    return deque((i * dt, s) for i, s in enumerate(samples))


# ---------------------------------------------------------------------------
# detector units: synthetic evidence windows
# ---------------------------------------------------------------------------


def test_worst_severity_ordering():
    assert worst([]) == OK
    assert worst([OK, WARN, OK]) == WARN
    assert worst([WARN, CRITICAL]) == CRITICAL


def test_leader_churn_grades(monkeypatch):
    monkeypatch.setenv("COPYCAT_HEALTH_CHURN_WARN", "3")
    det = LeaderChurnDetector()
    quiet = _hist([{"elections": 5, "transitions": 2}] * 4)
    assert det.evaluate(quiet, 0).severity == OK
    churny = _hist([{"elections": 5, "transitions": 2},
                    {"elections": 7, "transitions": 3}])
    assert det.evaluate(churny, 0).severity == WARN
    storm = _hist([{"elections": 5, "transitions": 2},
                   {"elections": 11, "transitions": 4}])
    f = det.evaluate(storm, 0)
    assert f.severity == CRITICAL
    assert f.evidence["elections"] == [5, 11]


def test_commit_stall_frozen_vs_growing(monkeypatch):
    monkeypatch.setenv("COPYCAT_HEALTH_STALL_S", "0.5")
    det = CommitStallDetector()
    healthy = _hist([{"commit_index": i, "log_last_index": i}
                     for i in range(5)])
    assert det.evaluate(healthy, 0).severity == OK
    frozen = _hist([{"commit_index": 10, "log_last_index": 12}] * 5)
    f = det.evaluate(frozen, 0)
    assert f.severity == WARN and "frozen" in f.reason
    growing = _hist([{"commit_index": 10, "log_last_index": 12 + i}
                     for i in range(5)])
    f = det.evaluate(growing, 0)
    assert f.severity == CRITICAL and "growing" in f.reason
    # a short freeze (below the stall bound) is not a stall
    brief = _hist([{"commit_index": 10, "log_last_index": 12}] * 2,
                  dt=0.1)
    assert det.evaluate(brief, 0).severity == OK


def test_fsync_spike_vs_pre_window_baseline(monkeypatch):
    monkeypatch.setenv("COPYCAT_HEALTH_FSYNC_FACTOR", "4")
    det = FsyncSpikeDetector()
    flat = _hist([{"fsyncs": i, "fsync_max_ms": 2.0,
                   "fsync_ewma_ms": 2.0} for i in range(4)])
    assert det.evaluate(flat, 0).severity == OK
    # the spike is judged against the baseline at the window START so a
    # sustained slow disk cannot drag the EWMA up to meet itself
    spike = _hist([{"fsyncs": 0, "fsync_max_ms": 2.0,
                    "fsync_ewma_ms": 2.0},
                   {"fsyncs": 5, "fsync_max_ms": 10.0,
                    "fsync_ewma_ms": 3.0}])
    assert det.evaluate(spike, 0).severity == WARN
    cliff = _hist([{"fsyncs": 0, "fsync_max_ms": 2.0,
                    "fsync_ewma_ms": 2.0},
                   {"fsyncs": 5, "fsync_max_ms": 80.0,
                    "fsync_ewma_ms": 10.0}])
    assert det.evaluate(cliff, 0).severity == CRITICAL
    # sub-ms baselines clamp to the 1 ms noise floor: scheduler jitter
    # on a page-cache fsync is not a disk incident
    jitter = _hist([{"fsyncs": 0, "fsync_max_ms": 0.08,
                     "fsync_ewma_ms": 0.08},
                    {"fsyncs": 5, "fsync_max_ms": 0.9,
                     "fsync_ewma_ms": 0.2}])
    assert det.evaluate(jitter, 0).severity == OK
    # no baseline yet (first fsyncs ever): never judged
    cold = _hist([{"fsyncs": 0, "fsync_max_ms": 0.0,
                   "fsync_ewma_ms": 0.0},
                  {"fsyncs": 3, "fsync_max_ms": 50.0,
                   "fsync_ewma_ms": 50.0}])
    assert det.evaluate(cold, 0).severity == OK


def test_window_collapse_floor_hits_and_rewinds():
    det = WindowCollapseDetector()
    # (window, floor, cumulative floor hits) per peer
    healthy = _hist([{"repl_windows": {"p1": (64, 8, 0)}, "rewinds": 0}]
                    * 3)
    assert det.evaluate(healthy, 0).severity == OK
    # a floor hit inside the window fires even though AIMD already
    # regrew the sampled window value — the counter is the witness
    collapsed = _hist([{"repl_windows": {"p1": (64, 8, 0)}, "rewinds": 0},
                       {"repl_windows": {"p1": (32, 8, 2)}, "rewinds": 0}])
    f = det.evaluate(collapsed, 0)
    assert f.severity == WARN and "p1" in f.evidence["peers"]
    storm = _hist([{"repl_windows": {"p1": (64, 8, 0)}, "rewinds": 0},
                   {"repl_windows": {"p1": (8, 8, 1)}, "rewinds": 4}])
    assert det.evaluate(storm, 0).severity == CRITICAL
    # hits before this window don't re-fire; pinned alone (no new hits,
    # no rewinds) stays quiet too
    old_news = _hist([{"repl_windows": {"p1": (8, 8, 3)}, "rewinds": 0}]
                     * 3)
    assert det.evaluate(old_news, 0).severity == OK


def test_expiry_storm_and_snapshot_failures(monkeypatch):
    monkeypatch.setenv("COPYCAT_HEALTH_EXPIRY_WARN", "3")
    det = SessionExpiryDetector()
    assert det.evaluate(
        _hist([{"sessions_expired": 2}, {"sessions_expired": 3}]),
        0).severity == OK
    assert det.evaluate(
        _hist([{"sessions_expired": 2}, {"sessions_expired": 6}]),
        0).severity == WARN
    assert det.evaluate(
        _hist([{"sessions_expired": 2}, {"sessions_expired": 20}]),
        0).severity == CRITICAL
    snap = SnapshotFailureDetector()
    assert snap.evaluate(
        _hist([{"snap_failures": 0}, {"snap_failures": 0}]),
        0).severity == OK
    assert snap.evaluate(
        _hist([{"snap_failures": 0}, {"snap_failures": 1}]),
        0).severity == WARN
    assert snap.evaluate(
        _hist([{"snap_failures": 0}, {"snap_failures": 5}]),
        0).severity == CRITICAL


def test_ingress_backlog_growth(monkeypatch):
    monkeypatch.setenv("COPYCAT_HEALTH_QUEUE_WARN", "10")
    det = IngressBacklogDetector()
    flat = _hist([{"proxy_inflight": 12, "event_backlog": 0}] * 3)
    assert det.evaluate(flat, None).severity == OK  # high but not growing
    growing = _hist([{"proxy_inflight": 2, "event_backlog": 0},
                     {"proxy_inflight": 14, "event_backlog": 0}])
    f = det.evaluate(growing, None)
    assert f.severity == WARN and f.group is None
    flood = _hist([{"proxy_inflight": 2, "event_backlog": 0},
                   {"proxy_inflight": 30, "event_backlog": 30}])
    assert det.evaluate(flood, None).severity == CRITICAL


# ---------------------------------------------------------------------------
# the durable black-box
# ---------------------------------------------------------------------------


def test_blackbox_roundtrip_and_recovered_tag(tmp_path):
    path = str(tmp_path / "node.blackbox")
    bb = BlackBox(path)
    bb.record("fault", fault="partition")
    bb.record("violation", check="commit_monotone")
    assert [e["kind"] for e in bb.events()] == ["fault", "violation"]
    assert not any(e.get("recovered") for e in bb.events())
    bb.close()
    # the next life reloads the previous one's events, recovered-tagged
    reborn = BlackBox(path)
    kinds = [(e["kind"], e.get("recovered")) for e in reborn.events()]
    assert kinds == [("fault", True), ("violation", True)]
    assert reborn.summary()["recovered_events"] == 2
    reborn.close()


def test_blackbox_distrusts_everything_past_a_torn_frame(tmp_path):
    path = str(tmp_path / "node.blackbox")
    bb = BlackBox(path)
    for i in range(5):
        bb.record("fault", n=i)
    bb.close()
    # tear the file mid-way through: a crash mid-append
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 13)
    reborn = BlackBox(path)
    ns = [e["n"] for e in reborn.recovered]
    assert ns == [0, 1, 2, 3]  # the torn 5th record is dropped
    assert reborn.torn == 1
    reborn.close()


def test_blackbox_truncates_torn_tail_before_appending(tmp_path):
    """A crash mid-append leaves a torn tail; the NEXT life must
    truncate it before appending or ALL of its own events land after
    garbage and the life after that (whose scan stops at the first bad
    frame) silently discards them."""
    path = str(tmp_path / "node.blackbox")
    life1 = BlackBox(path)
    for i in range(3):
        life1.record("fault", life=1, n=i)
    life1.close()
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 5)  # crash mid-append
    life2 = BlackBox(path)
    assert life2.torn == 1
    assert [e["n"] for e in life2.recovered] == [0, 1]
    life2.record("fault", life=2)
    life2.close()
    life3 = BlackBox(path)
    lives = [e.get("life") for e in life3.recovered]
    assert lives == [1, 1, 2]  # life 2's forensics survived
    life3.close()


def test_blackbox_rotation_bounds_disk(tmp_path):
    path = str(tmp_path / "node.blackbox")
    bb = BlackBox(path, max_bytes=4096)
    for i in range(400):
        bb.record("fault", n=i, pad="x" * 40)
    bb.close()
    assert os.path.getsize(path) <= 4096 + 200
    assert os.path.getsize(path + ".1") <= 4096 + 200
    # the ring still serves the most recent events after reload
    reborn = BlackBox(path, max_bytes=4096)
    assert reborn.recovered[-1]["n"] == 399
    reborn.close()


# ---------------------------------------------------------------------------
# live clusters: the monitor, the routes, the A/B knob
# ---------------------------------------------------------------------------


@async_test(timeout=120)
async def test_monitor_ok_on_healthy_cluster_and_routes():
    cluster = await create_cluster(3)
    try:
        client = await cluster.client()
        for i in range(5):
            await client.submit(Put(key=f"k{i}", value=i))
        leader = cluster.leader
        verdict = leader.health.tick()
        assert verdict["status"] == OK and verdict["reasons"] == []
        expected = {
            "leader_churn", "commit_stall", "window_collapse",
            "fsync_spike", "session_expiry", "snapshot_failure",
            "ingress_backlog", "slo_burn"}
        if knobs.get_bool("COPYCAT_PROFILE"):  # loop_stall rides the plane
            expected.add("loop_stall")
        assert set(verdict["detectors"]) == expected
        snap = leader.stats_snapshot()["raft"]
        assert snap["health.checks"] >= 1
        assert snap["health.status"] == 0
        listener = await StatsListener(leader, port=0).open()
        try:
            health = json.loads(await fetch_stats(
                f"127.0.0.1:{listener.port}", "/health"))
            assert health["status"] == OK
            assert health["node"] == str(leader.address)
            healthz = json.loads(await fetch_stats(
                f"127.0.0.1:{listener.port}", "/healthz"))
            # uptime_s/git_sha (utils/buildinfo.py) ride every role's
            # liveness payload: restart + half-rolled detection
            assert healthz.pop("uptime_s") >= 0
            assert "git_sha" in healthz  # None outside a checkout
            healthz.pop("git_sha")
            assert healthz == {"ok": True, "node": str(leader.address),
                               "role": "leader", "term": leader.term}
            unknown = json.loads(await fetch_stats(
                f"127.0.0.1:{listener.port}", "/nope"))
            assert "/health" in unknown["routes"]
            assert "/healthz" in unknown["routes"]
        finally:
            await listener.close()
    finally:
        await cluster.close()


def test_health_off_knob_removes_the_plane(monkeypatch, tmp_path):
    monkeypatch.setenv("COPYCAT_HEALTH", "0")

    async def run():
        cluster = await create_cluster(
            1, storage_factory=lambda i: Storage(
                StorageLevel.DISK, str(tmp_path),
                max_entries_per_segment=16))
        try:
            server = cluster.servers[0]
            assert server.health is None
            assert server.blackbox is None
            assert not any(f.endswith(".blackbox")
                           for f in os.listdir(tmp_path))
            snap = server.stats_snapshot()["raft"]
            assert not any(k.startswith("health.") for k in snap)
            listener = await StatsListener(server, port=0).open()
            try:
                health = json.loads(await fetch_stats(
                    f"127.0.0.1:{listener.port}", "/health"))
                assert health["status"] == "disabled"
            finally:
                await listener.close()
        finally:
            await cluster.close()

    from helpers import arun
    arun(run(), timeout=120)


# ---------------------------------------------------------------------------
# nemesis-driven ground truth (strict invariants: the faults must not
# trip a safety monitor while the health plane grades them)
# ---------------------------------------------------------------------------


def test_partition_yields_churn_and_commit_stall(monkeypatch):
    monkeypatch.setenv("COPYCAT_INVARIANTS", "strict")
    monkeypatch.setenv("COPYCAT_HEALTH_STALL_S", "0.5")
    monkeypatch.setenv("COPYCAT_HEALTH_CHURN_WARN", "2")

    async def run():
        cluster = await create_cluster(3, election_timeout=0.15,
                                       heartbeat_interval=0.03)
        try:
            client = await cluster.client()
            for i in range(5):
                await client.submit(Put(key=f"k{i}", value=i))
            leader = cluster.leader
            for s in cluster.servers:
                s.health.tick()
            # full partition: every member alone — no quorum anywhere
            nemesis = cluster.registry.attach_nemesis(NetworkNemesis())
            nemesis.partition(*[[s.address] for s in cluster.servers])
            # appends land on the old leader but can never commit: the
            # commit-stall signature, with lag growing
            for _ in range(4):
                leader._append(NoOpEntry())
            deadline = asyncio.get_running_loop().time() + 3.0
            stall = churn = OK
            while asyncio.get_running_loop().time() < deadline:
                await asyncio.sleep(0.15)
                leader._append(NoOpEntry())
                for s in cluster.servers:
                    v = s.health.tick()
                    det = v["detectors"]
                    stall = worst([stall,
                                   det["commit_stall"]["status"]])
                    churn = worst([churn,
                                   det["leader_churn"]["status"]])
                if stall == CRITICAL and churn != OK:
                    break
            assert stall == CRITICAL, "commit stall (growing) not graded"
            assert churn != OK, "leader churn not graded"
            # the verdict carries the machinery an operator needs
            v = leader.health.tick()
            assert any("commit stalled" in r for r in v["reasons"])
            assert leader.stats_snapshot()["raft"]["health.status"] >= 1
            nemesis.heal()
        finally:
            await cluster.close()

    from helpers import arun
    arun(run(), timeout=120)


def test_slow_disk_grades_fsync_spike(monkeypatch, tmp_path):
    monkeypatch.setenv("COPYCAT_INVARIANTS", "strict")

    async def run():
        cluster = await create_cluster(
            3, storage_factory=lambda i: Storage(
                StorageLevel.DISK, str(tmp_path / str(i)),
                max_entries_per_segment=32))
        try:
            client = await cluster.client()
            leader = cluster.leader
            # establish the EWMA baseline with healthy-disk commits
            for i in range(10):
                await client.submit(Put(key=f"w{i}", value=i))
            baseline_ms = leader.groups[0]._fsync_ewma_ms
            assert baseline_ms > 0.0
            leader.health.tick()
            # scale the injected delay to the MEASURED baseline: on a
            # loaded CI host healthy fsyncs can already be slow, and a
            # fixed 50ms would not read as a spike against them
            delay_s = max(0.05, baseline_ms * 10.0 / 1e3)
            slow = SlowDiskNemesis(leader, delay_s=delay_s)
            slow.install()
            try:
                for i in range(3):
                    await client.submit(Put(key=f"s{i}", value=i))
            finally:
                slow.remove()
            v = leader.health.tick()
            f = v["detectors"]["fsync_spike"]["groups"]["0"]
            assert f["status"] in (WARN, CRITICAL)
            assert "baseline" in f["reason"]
            assert max(f["evidence"]["fsync_max_ms"]) >= delay_s * 1e3
        finally:
            await cluster.close()

    from helpers import arun
    arun(run(), timeout=120)


def test_slow_follower_collapses_replication_window(monkeypatch, tmp_path):
    monkeypatch.setenv("COPYCAT_INVARIANTS", "strict")
    monkeypatch.setenv("COPYCAT_REPL_WINDOW", "8")

    async def run():
        cluster = await create_cluster(
            3, session_timeout=30.0,
            storage_factory=lambda i: Storage(
                StorageLevel.DISK, str(tmp_path / str(i)),
                max_entries_per_segment=64))
        try:
            # the blocking fsync stalls the shared loop: a short session
            # timeout would expire the client mid-burst
            client = await cluster.client(session_timeout=30.0)
            leader = cluster.leader
            followers = [s for s in cluster.servers if s is not leader]
            # healthy acks first: the AIMD EWMA must learn a fast
            # baseline for the slow follower to read as congestion
            for i in range(20):
                await client.submit(Put(key=f"w{i}", value=i))
            leader.health.tick()
            # scale the injected ack delay to the learned ack baseline:
            # AIMD shrinks on latency RATIOS, and a loaded host's
            # healthy acks may already be tens of ms
            ack_ewma = max((ps.ack_ewma_ms for ps in
                            leader.groups[0]._peer_streams.values()),
                           default=1.0)
            slow = SlowDiskNemesis(followers[0],
                                   delay_s=max(0.06, ack_ewma * 8 / 1e3))
            slow.install()
            fired = OK
            evidence_peers: list = []
            try:
                # the floor-hit counter makes the transient collapse
                # observable after the fact: the burst's consecutive
                # slow acks halve the window to its floor even though
                # AIMD regrows it once the EWMA re-baselines
                for burst in range(3):
                    await asyncio.gather(*(
                        client.submit(Put(key=f"b{burst}.{i}", value=i))
                        for i in range(60)))
                    await asyncio.sleep(0.3)
                    v = leader.health.tick()
                    g = v["detectors"]["window_collapse"]["groups"]
                    got = g["0"]["status"]
                    if got != OK:
                        fired = worst([fired, got])
                        evidence_peers = g["0"]["evidence"]["peers"]
                        break
            finally:
                slow.remove()
            assert fired != OK, \
                "window collapse never graded under a slow follower"
            assert str(followers[0].address) in evidence_peers
        finally:
            await cluster.close()

    from helpers import arun
    arun(run(), timeout=120)


def test_session_expiry_storm(monkeypatch):
    monkeypatch.setenv("COPYCAT_HEALTH_EXPIRY_WARN", "2")

    async def run():
        cluster = await create_cluster(3)
        try:
            leader = cluster.leader
            clients = [await cluster.client(session_timeout=0.4)
                       for _ in range(3)]
            leader.health.tick()
            # the clients die without closing: keep-alives stop, the
            # leader's wall-clock detector expires the sessions
            for c in clients:
                c._keepalive.cancel()
                c._keepalive = None
            deadline = asyncio.get_running_loop().time() + 5.0
            got = OK
            while asyncio.get_running_loop().time() < deadline:
                await asyncio.sleep(0.2)
                v = leader.health.tick()
                got = v["detectors"]["session_expiry"]["groups"]["0"][
                    "status"]
                if got != OK:
                    break
            assert got in (WARN, CRITICAL)
        finally:
            await cluster.close()

    from helpers import arun
    arun(run(), timeout=120)


def test_snapshot_failures_graded(monkeypatch, tmp_path):
    monkeypatch.setenv("COPYCAT_SNAPSHOT_ENTRIES", "5")

    async def run():
        cluster = await create_cluster(
            1, storage_factory=lambda i: Storage(
                StorageLevel.DISK, str(tmp_path),
                max_entries_per_segment=16))
        try:
            server = cluster.servers[0]
            client = await cluster.client()
            server.health.tick()

            def broken_save(index, payload):
                raise OSError("disk full")

            server.groups[0]._snapshots.save = broken_save
            for i in range(12):
                await client.submit(Put(key=f"k{i}", value=i))
            v = server.health.tick()
            f = v["detectors"]["snapshot_failure"]["groups"]["0"]
            assert f["status"] in (WARN, CRITICAL)
            assert server.metrics.counter("snap.capture_failures").value > 0
            # the failure also landed in the durable black-box
            kinds = [e["kind"] for e in server.blackbox.events()]
            assert "snapshot_failed" in kinds
        finally:
            await cluster.close()

    from helpers import arun
    arun(run(), timeout=120)


# ---------------------------------------------------------------------------
# the black-box survives a SIGKILL-shaped crash
# ---------------------------------------------------------------------------


def test_blackbox_survives_crash_and_flight_serves_it(monkeypatch,
                                                      tmp_path):
    monkeypatch.setenv("COPYCAT_INVARIANTS", "strict")

    async def run():
        storage = lambda i: Storage(StorageLevel.DISK, str(tmp_path),  # noqa: E731
                                    max_entries_per_segment=16)
        cluster = await create_cluster(1, storage_factory=storage)
        try:
            server = cluster.servers[0]
            client = await cluster.client()
            for i in range(5):
                await client.submit(Put(key=f"k{i}", value=i))
            server.health_note("pre_crash_fault", fault="injected")
            assert any(e["kind"] == "pre_crash_fault"
                       for e in server.blackbox.events())
            await crash_server(server)
            # the next life: same storage directory, same address
            reborn = RaftServer(
                server.address, [server.address],
                LocalTransport(cluster.registry,
                               local_address=server.address),
                KVStateMachine(), storage=storage(0),
                election_timeout=0.2, heartbeat_interval=0.04)
            cluster.servers[0] = reborn
            await reborn.open()
            recovered = reborn.blackbox.recovered
            assert any(e["kind"] == "pre_crash_fault"
                       and e.get("recovered") for e in recovered)
            listener = await StatsListener(reborn, port=0).open()
            try:
                flight = json.loads(await fetch_stats(
                    f"127.0.0.1:{listener.port}", "/flight"))
                bb = flight["blackbox"]
                assert bb["recovered_events"] >= 1
                assert any(e["kind"] == "pre_crash_fault"
                           for e in bb["recovered"])
            finally:
                await listener.close()
        finally:
            await cluster.close()

    from helpers import arun
    arun(run(), timeout=120)


# ---------------------------------------------------------------------------
# SLO burn detection (docs/OBSERVABILITY.md "Retrospective telemetry"):
# objectives judged over the RETAINED series window, not the monitor's
# short evidence deque
# ---------------------------------------------------------------------------


def _slo_rows(server, stuck, ok=0, t0=1000.0):
    """Ingest synthetic retained samples: `stuck` intervals where a
    group's commit sat frozen behind its log tail, then `ok` healthy
    ones (lag closed, commit advancing)."""
    commit = 100
    gauges = ["raft_commit_lag", "raft_commit_index"]
    for i in range(stuck):
        server.series.ingest({"raft_commit_lag": 7,
                              "raft_commit_index": commit,
                              "_gauge_keys": gauges}, t=t0 + i)
    for i in range(ok):
        commit += 3
        server.series.ingest({"raft_commit_lag": 0,
                              "raft_commit_index": commit,
                              "_gauge_keys": gauges}, t=t0 + stuck + i)


def test_slo_burn_availability_grades_and_gauges(monkeypatch):
    monkeypatch.setenv("COPYCAT_SLO_AVAIL", "0.99")

    async def run():
        cluster = await create_cluster(1)
        try:
            server = cluster.servers[0]
            assert "slo_burn" in server.health.tick()["detectors"]
            snap = server.stats_snapshot()["raft"]
            assert snap["slo.avail_objective"] == 0.99
            # ~1 stuck interval in ~21: burn ~5x the 1% budget -> WARN
            _slo_rows(server, stuck=2, ok=20)
            v = server.health.tick()
            slo = v["detectors"]["slo_burn"]["groups"]["server"]
            assert slo["status"] == WARN
            assert "availability burn" in slo["reason"]
            # a window that is mostly stuck: fast burn -> CRITICAL
            _slo_rows(server, stuck=60, t0=2000.0)
            v = server.health.tick()
            slo = v["detectors"]["slo_burn"]["groups"]["server"]
            assert slo["status"] == CRITICAL
            assert slo["evidence"]["unavailable_intervals"]
            snap = server.stats_snapshot()["raft"]
            assert snap["slo.avail_burn"] >= 10
            assert snap["slo.avail_observed"] < 1.0
        finally:
            await cluster.close()

    from helpers import arun
    arun(run(), timeout=120)


def test_slo_burn_latency_objective(monkeypatch):
    monkeypatch.setenv("COPYCAT_SLO_P99_MS", "10")

    async def run():
        cluster = await create_cluster(1)
        try:
            server = cluster.servers[0]
            snap = server.stats_snapshot()["raft"]
            assert snap["slo.p99_objective_ms"] == 10.0
            # active intervals (commit-latency count advancing) whose
            # sampled p99 breaches the objective in every interval
            count = 0
            for i in range(6):
                count += 5
                server.series.ingest(
                    {"latency.commit_ms": {"count": count, "mean": 20.0,
                                           "p50": 18.0, "p99": 25.0,
                                           "max": 30.0}}, t=1000.0 + i)
            v = server.health.tick()
            slo = v["detectors"]["slo_burn"]["groups"]["server"]
            assert slo["status"] == CRITICAL
            assert "breached the 10ms objective" in slo["reason"]
            snap = server.stats_snapshot()["raft"]
            assert snap["slo.p99_observed_ms"] == 25.0
            assert snap["slo.p99_burn"] == 1.0
            # availability gauges were never registered: no objective
            assert "slo.avail_objective" not in snap
        finally:
            await cluster.close()

    from helpers import arun
    arun(run(), timeout=120)


def test_slo_burn_without_objectives_stays_ok():
    async def run():
        cluster = await create_cluster(1)
        try:
            server = cluster.servers[0]
            _slo_rows(server, stuck=30)
            v = server.health.tick()
            slo = v["detectors"]["slo_burn"]["groups"]["server"]
            assert slo["status"] == OK  # nothing configured, no grading
            snap = server.stats_snapshot()["raft"]
            assert not any(k.startswith("slo.") for k in snap)
        finally:
            await cluster.close()

    from helpers import arun
    arun(run(), timeout=120)
