"""Native C++ transport tests (native/copycat_native.cpp via io/native.py).

Skipped when the toolchain can't build the shared library. The wire format
is shared with the asyncio TCP transport, so the interop test runs a native
server against an asyncio client.
"""

import asyncio

import pytest

from copycat_tpu.io.native import native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native library unavailable")

from copycat_tpu.io.native import NativeTcpTransport  # noqa: E402
from copycat_tpu.io.tcp import TcpTransport  # noqa: E402
from copycat_tpu.io.transport import Address, TransportError  # noqa: E402

PORT = 18431


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


def echo_handler(conn):
    async def echo(msg):
        return f"echo:{msg}"
    conn.handler(str, echo)


def test_native_request_response():
    async def main():
        transport = NativeTcpTransport()
        try:
            server = transport.server()
            await server.listen(Address("127.0.0.1", PORT), echo_handler)
            conn = await transport.client().connect(Address("127.0.0.1", PORT))
            assert await conn.send("hello") == "echo:hello"
            big = "x" * 2_000_000  # exceeds the initial 1MB poll buffer
            assert await conn.send(big) == f"echo:{big}"
            await conn.close()
            await server.close()
        finally:
            transport.shutdown()
    run(main())


def test_native_concurrent_requests():
    async def main():
        transport = NativeTcpTransport()
        try:
            server = transport.server()
            await server.listen(Address("127.0.0.1", PORT + 1), echo_handler)
            conn = await transport.client().connect(
                Address("127.0.0.1", PORT + 1))
            results = await asyncio.gather(
                *[conn.send(f"m{i}") for i in range(50)])
            assert results == [f"echo:m{i}" for i in range(50)]
            await conn.close()
            await server.close()
        finally:
            transport.shutdown()
    run(main())


def test_native_handler_error_crosses_wire():
    async def main():
        transport = NativeTcpTransport()
        try:
            server = transport.server()

            def attach(conn):
                async def boom(msg):
                    raise ValueError("nope")
                conn.handler(str, boom)

            await server.listen(Address("127.0.0.1", PORT + 2), attach)
            conn = await transport.client().connect(
                Address("127.0.0.1", PORT + 2))
            with pytest.raises(TransportError, match="ValueError: nope"):
                await conn.send("x")
            await conn.close()
            await server.close()
        finally:
            transport.shutdown()
    run(main())


def test_native_server_asyncio_client_interop():
    """Same wire format as io/tcp.py: endpoints interoperate."""
    async def main():
        native = NativeTcpTransport()
        try:
            server = native.server()
            await server.listen(Address("127.0.0.1", PORT + 3), echo_handler)
            conn = await TcpTransport().client().connect(
                Address("127.0.0.1", PORT + 3))
            assert await conn.send("across") == "echo:across"
            await conn.close()
            await server.close()
        finally:
            native.shutdown()
    run(main())


def test_asyncio_server_native_client_interop():
    async def main():
        native = NativeTcpTransport()
        try:
            server = TcpTransport().server()
            await server.listen(Address("127.0.0.1", PORT + 4), echo_handler)
            conn = await native.client().connect(Address("127.0.0.1", PORT + 4))
            assert await conn.send("back") == "echo:back"
            await conn.close()
            await server.close()
        finally:
            native.shutdown()
    run(main())


def test_native_hostname_resolution():
    """Hostnames (not just dotted quads) resolve via getaddrinfo."""
    async def main():
        transport = NativeTcpTransport()
        try:
            server = transport.server()
            await server.listen(Address("localhost", PORT + 5), echo_handler)
            conn = await transport.client().connect(
                Address("localhost", PORT + 5))
            assert await conn.send("named") == "echo:named"
            await conn.close()
            await server.close()
        finally:
            transport.shutdown()
    run(main())


def test_native_connect_refused_fails_fast():
    """The connect itself is nonblocking in C (completion via epoll), but
    the asyncio connect() awaits it — a refused connect raises there,
    matching TcpTransport so failover loops keep working."""
    async def main():
        transport = NativeTcpTransport()
        try:
            with pytest.raises(TransportError):
                await asyncio.wait_for(transport.client().connect(
                    Address("127.0.0.1", PORT + 6)), 5)  # nothing listening
        finally:
            transport.shutdown()
    run(main())
