"""Device resource kernel tests: map/set/queue/lock/election + TTL + events.

Drives the full batched consensus path (RaftGroups) so every assertion
exercises replicated, quorum-committed apply — the reference's
"real consensus, fake network" strategy (SURVEY.md §4) on device.
Reference semantics: MapState.java:32, SetState.java:32, QueueState.java:30,
LockState.java:33, LeaderElectionState.java:31.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.models import RaftGroups  # noqa: E402
from copycat_tpu.ops import apply as ap  # noqa: E402
from copycat_tpu.ops.apply import FAIL  # noqa: E402


def make(groups=1, peers=3, **kw):
    kw.setdefault("log_slots", 64)
    rg = RaftGroups(groups, peers, **kw)
    rg.wait_for_leaders()
    return rg


def run_ops(rg, ops, group=0):
    """Submit (opcode, a, b, c) tuples in order; return list of results."""
    tags = [rg.submit(group, *op) for op in ops]
    rg.run_until(tags)
    return [rg.results[t] for t in tags]


def events(rg, group=0, code=None):
    evs = rg.events.get(group, [])
    if code is None:
        return evs
    return [e for e in evs if e[1] == code]


# ---------------------------------------------------------------------------
# map
# ---------------------------------------------------------------------------

def test_map_put_get_remove_semantics():
    rg = make()
    res = run_ops(rg, [
        (ap.OP_MAP_PUT, 7, 100),          # -> 0 (no previous)
        (ap.OP_MAP_PUT, 7, 200),          # -> 100
        (ap.OP_MAP_GET, 7),               # -> 200
        (ap.OP_MAP_CONTAINS_KEY, 7),      # -> 1
        (ap.OP_MAP_CONTAINS_KEY, 8),      # -> 0
        (ap.OP_MAP_CONTAINS_VALUE, 200),  # -> 1
        (ap.OP_MAP_SIZE,),                # -> 1
        (ap.OP_MAP_REMOVE, 7),            # -> 200
        (ap.OP_MAP_GET, 7),               # -> 0
        (ap.OP_MAP_IS_EMPTY,),            # -> 1
    ])
    assert res == [0, 100, 200, 1, 0, 1, 1, 200, 0, 1]


def test_map_conditional_ops():
    rg = make()
    res = run_ops(rg, [
        (ap.OP_MAP_PUT_IF_ABSENT, 1, 10),   # -> 1 (put)
        (ap.OP_MAP_PUT_IF_ABSENT, 1, 99),   # -> 0 (present)
        (ap.OP_MAP_GET, 1),                 # -> 10
        (ap.OP_MAP_REPLACE, 1, 20),         # -> 10
        (ap.OP_MAP_REPLACE, 2, 5),          # -> FAIL (absent)
        (ap.OP_MAP_REPLACE_IF, 1, 20, 30),  # -> 1
        (ap.OP_MAP_REPLACE_IF, 1, 99, 40),  # -> 0
        (ap.OP_MAP_GET, 1),                 # -> 30
        (ap.OP_MAP_REMOVE_IF, 1, 99),       # -> 0
        (ap.OP_MAP_REMOVE_IF, 1, 30),       # -> 1
        (ap.OP_MAP_GET_OR_DEFAULT, 1, 77),  # -> 77
    ])
    assert res == [1, 0, 10, 10, FAIL, 1, 0, 30, 0, 1, 77]


def test_map_ttl_expiry_is_deterministic_log_time():
    rg = make()
    r1 = run_ops(rg, [(ap.OP_MAP_PUT, 5, 42, 3),   # ttl = 3 ticks
                      (ap.OP_MAP_GET, 5)])
    assert r1 == [0, 42]
    rg.run(10)  # advance the logical clock past the deadline
    r2 = run_ops(rg, [(ap.OP_MAP_GET, 5), (ap.OP_MAP_SIZE,),
                      (ap.OP_MAP_CONTAINS_KEY, 5)])
    assert r2 == [0, 0, 0]


def test_map_clear_and_overflow():
    rg = make()
    K = rg.config.resource.map_slots
    res = run_ops(rg, [(ap.OP_MAP_PUT, k, k * 10) for k in range(1, K + 1)])
    assert res == [0] * K
    over = run_ops(rg, [(ap.OP_MAP_PUT, 999, 1)])  # table full
    assert over == [FAIL]
    res = run_ops(rg, [(ap.OP_MAP_SIZE,), (ap.OP_MAP_CLEAR,),
                       (ap.OP_MAP_SIZE,), (ap.OP_MAP_PUT, 999, 1)])
    assert res[0] == K and res[2] == 0 and res[3] == 0


def test_map_groups_are_isolated():
    rg = make(groups=3)
    t1 = rg.submit(0, ap.OP_MAP_PUT, 1, 111)
    t2 = rg.submit(1, ap.OP_MAP_PUT, 1, 222)
    rg.run_until([t1, t2])
    g0 = run_ops(rg, [(ap.OP_MAP_GET, 1)], group=0)
    g1 = run_ops(rg, [(ap.OP_MAP_GET, 1)], group=1)
    g2 = run_ops(rg, [(ap.OP_MAP_GET, 1)], group=2)
    assert (g0, g1, g2) == ([111], [222], [0])


# ---------------------------------------------------------------------------
# set
# ---------------------------------------------------------------------------

def test_set_semantics():
    rg = make()
    res = run_ops(rg, [
        (ap.OP_SET_ADD, 5), (ap.OP_SET_ADD, 5), (ap.OP_SET_ADD, 9),
        (ap.OP_SET_CONTAINS, 5), (ap.OP_SET_CONTAINS, 6),
        (ap.OP_SET_SIZE,), (ap.OP_SET_REMOVE, 5), (ap.OP_SET_REMOVE, 5),
        (ap.OP_SET_SIZE,), (ap.OP_SET_CLEAR,), (ap.OP_SET_SIZE,),
    ])
    assert res == [1, 0, 1, 1, 0, 2, 1, 0, 1, 0, 0]


def test_set_ttl():
    rg = make()
    assert run_ops(rg, [(ap.OP_SET_ADD, 3, 0, 2)]) == [1]
    rg.run(8)
    assert run_ops(rg, [(ap.OP_SET_CONTAINS, 3), (ap.OP_SET_SIZE,)]) == [0, 0]


# ---------------------------------------------------------------------------
# queue
# ---------------------------------------------------------------------------

def test_queue_fifo():
    rg = make()
    res = run_ops(rg, [
        (ap.OP_Q_POLL,),                       # empty -> FAIL
        (ap.OP_Q_OFFER, 11), (ap.OP_Q_OFFER, 22), (ap.OP_Q_OFFER, 33),
        (ap.OP_Q_PEEK,), (ap.OP_Q_SIZE,),
        (ap.OP_Q_POLL,), (ap.OP_Q_POLL,), (ap.OP_Q_POLL,), (ap.OP_Q_POLL,),
    ])
    assert res == [FAIL, 1, 1, 1, 11, 3, 11, 22, 33, FAIL]


def test_queue_full_and_clear():
    rg = make()
    Q = rg.config.resource.queue_slots
    res = run_ops(rg, [(ap.OP_Q_OFFER, i) for i in range(Q + 2)])
    assert res == [1] * Q + [0, 0]
    res = run_ops(rg, [(ap.OP_Q_CLEAR,), (ap.OP_Q_SIZE,), (ap.OP_Q_OFFER, 7),
                       (ap.OP_Q_POLL,)])
    assert res == [0, 0, 1, 7]


# ---------------------------------------------------------------------------
# lock (grant delivered as a session event — DistributedLock.java:58)
# ---------------------------------------------------------------------------

def test_lock_grant_queue_release():
    rg = make()
    res = run_ops(rg, [
        (ap.OP_LOCK_ACQUIRE, 101, -1),  # free -> granted (1)
        (ap.OP_LOCK_ACQUIRE, 102, -1),  # held -> queued (2)
        (ap.OP_LOCK_ACQUIRE, 103, 0),   # try-lock -> fail (0)
        (ap.OP_LOCK_RELEASE, 101),      # -> 1, grants 102
        (ap.OP_LOCK_RELEASE, 102),      # -> 1, queue empty
        (ap.OP_LOCK_RELEASE, 999),      # not holder -> 0
    ])
    assert res == [1, 2, 0, 1, 1, 0]
    # only the queued waiter's grant is an event; immediate grant (101) and
    # immediate try-lock failure (103) are synchronous command results
    grants = events(rg, code=ap.EV_LOCK_GRANT)
    assert [e[2] for e in grants] == [102]
    assert events(rg, code=ap.EV_NONE) == []


def test_lock_timeout_waiter_never_granted():
    rg = make()
    res = run_ops(rg, [
        (ap.OP_LOCK_ACQUIRE, 1, -1),   # granted
        (ap.OP_LOCK_ACQUIRE, 2, 3),    # queued with 3-tick deadline
    ])
    assert res == [1, 2]
    rg.run(10)  # deadline passes in log time
    res = run_ops(rg, [(ap.OP_LOCK_RELEASE, 1)])
    assert res == [1]
    rg.run(10)  # let followers apply
    # expired waiter was dropped: lock is free, no grant event to 2
    holder = np.asarray(rg.state.resources.lk_holder)[0]
    assert (holder == -1).all()
    assert events(rg, code=ap.EV_LOCK_GRANT) == []


def test_lock_cancel_orders_with_grant():
    rg = make()
    res = run_ops(rg, [
        (ap.OP_LOCK_ACQUIRE, 1, -1),
        (ap.OP_LOCK_ACQUIRE, 2, -1),
        (ap.OP_LOCK_CANCEL, 2),        # still queued -> 1 (dequeued)
        (ap.OP_LOCK_RELEASE, 1),       # queue empty after cancel
        (ap.OP_LOCK_CANCEL, 3),        # never queued -> 0
    ])
    assert res == [1, 2, 1, 1, 0]
    rg.run(10)  # let followers apply
    holder = np.asarray(rg.state.resources.lk_holder)[0]
    assert (holder == -1).all()
    # cancel AFTER the grant already happened reports "you won" (2)
    res = run_ops(rg, [
        (ap.OP_LOCK_ACQUIRE, 5, -1),
        (ap.OP_LOCK_CANCEL, 5),
    ])
    assert res == [1, 2]


def test_lock_contention_fifo_order():
    rg = make()
    res = run_ops(rg, [(ap.OP_LOCK_ACQUIRE, 10, -1)]
                  + [(ap.OP_LOCK_ACQUIRE, 10 + i, -1) for i in range(1, 5)]
                  + [(ap.OP_LOCK_RELEASE, 10 + i) for i in range(5)])
    assert res == [1, 2, 2, 2, 2] + [1] * 5
    grants = [e[2] for e in events(rg, code=ap.EV_LOCK_GRANT)]
    assert grants == [11, 12, 13, 14]  # strict FIFO succession (10 = sync)


# ---------------------------------------------------------------------------
# leader election resource (epoch = log index fencing token)
# ---------------------------------------------------------------------------

def test_election_listen_promote_fencing():
    rg = make()
    res = run_ops(rg, [
        (ap.OP_ELECT_LISTEN, 7),  # vacant -> elected, result = epoch
        (ap.OP_ELECT_LISTEN, 8),  # queued
        (ap.OP_ELECT_LISTEN, 9),  # queued
    ])
    epoch7 = res[0]
    assert epoch7 > 0 and res[1:] == [0, 0]
    assert run_ops(rg, [(ap.OP_ELECT_IS_LEADER, 7, epoch7)]) == [1]
    assert run_ops(rg, [(ap.OP_ELECT_IS_LEADER, 8, epoch7)]) == [0]

    # resign promotes FIFO successor with a fresh epoch (7's immediate win
    # was its listen result — only the promotion is an event)
    assert run_ops(rg, [(ap.OP_ELECT_RESIGN, 7)]) == [1]
    elects = events(rg, code=ap.EV_ELECT)
    assert [e[2] for e in elects] == [8]
    epoch8 = elects[-1][3]
    assert epoch8 > epoch7
    assert run_ops(rg, [(ap.OP_ELECT_IS_LEADER, 8, epoch8)]) == [1]
    # stale fencing token from the old leadership is rejected
    assert run_ops(rg, [(ap.OP_ELECT_IS_LEADER, 7, epoch7)]) == [0]

    # a queued waiter can unlisten without affecting the leader
    assert run_ops(rg, [(ap.OP_ELECT_RESIGN, 9)]) == [0]
    assert run_ops(rg, [(ap.OP_ELECT_RESIGN, 8)]) == [1]
    rg.run(10)  # let followers apply
    leader = np.asarray(rg.state.resources.el_leader)[0]
    assert (leader == -1).all()


def test_lock_cancelled_waiters_free_capacity():
    rg = make()
    W = rg.config.resource.wait_slots
    assert run_ops(rg, [(ap.OP_LOCK_ACQUIRE, 1, -1)]) == [1]
    waiters = list(range(10, 10 + W))
    assert run_ops(rg, [(ap.OP_LOCK_ACQUIRE, w, -1) for w in waiters]) \
        == [2] * W
    # queue is full; a fresh waiter is rejected
    assert run_ops(rg, [(ap.OP_LOCK_ACQUIRE, 99, -1)]) == [0]
    # cancel every waiter: the ring must compact, reclaiming capacity
    assert run_ops(rg, [(ap.OP_LOCK_CANCEL, w) for w in waiters]) == [1] * W
    assert run_ops(rg, [(ap.OP_LOCK_ACQUIRE, 99, -1)]) == [2]
    assert run_ops(rg, [(ap.OP_LOCK_RELEASE, 1)]) == [1]
    assert [e[2] for e in events(rg, code=ap.EV_LOCK_GRANT)] == [99]


def test_lock_acquire_idempotent_and_holder_query():
    rg = make()
    res = run_ops(rg, [
        (ap.OP_LOCK_ACQUIRE, 1, -1),  # granted
        (ap.OP_LOCK_ACQUIRE, 1, -1),  # retry by holder -> still 1, no dup
        (ap.OP_LOCK_ACQUIRE, 2, -1),  # queued
        (ap.OP_LOCK_ACQUIRE, 2, -1),  # retry by waiter -> 2, no dup entry
        (ap.OP_LOCK_HOLDER,),         # -> 1
        (ap.OP_LOCK_RELEASE, 1),
        (ap.OP_LOCK_HOLDER,),         # -> 2
        (ap.OP_LOCK_RELEASE, 2),
        (ap.OP_LOCK_HOLDER,),         # -> -1 (queue held no duplicates)
    ])
    assert res == [1, 1, 2, 2, 1, 1, 2, 1, -1]


def test_election_duplicate_listen_idempotent():
    rg = make()
    res = run_ops(rg, [(ap.OP_ELECT_LISTEN, 7)])
    epoch7 = res[0]
    assert epoch7 > 0
    res = run_ops(rg, [
        (ap.OP_ELECT_LISTEN, 7),   # leader re-listen -> current epoch
        (ap.OP_ELECT_LISTEN, 8),   # queued
        (ap.OP_ELECT_LISTEN, 8),   # retry -> idempotent, no dup
        (ap.OP_ELECT_LEADER,),     # -> 7
        (ap.OP_ELECT_RESIGN, 7),   # promotes 8
        (ap.OP_ELECT_LEADER,),     # -> 8
        (ap.OP_ELECT_RESIGN, 8),
        (ap.OP_ELECT_LEADER,),     # -> -1: no stale duplicate of 8 promoted
    ])
    assert res == [epoch7, 0, 0, 7, 1, 8, 1, -1]


def test_value_ttl_survives_failed_cas():
    rg = make()
    res = run_ops(rg, [(ap.OP_VALUE_SET, 5, 0, 5),  # ttl = 5 ticks
                       (ap.OP_VALUE_CAS, 7, 9)])    # miss — must not clear TTL
    assert res == [0, 0]
    rg.run(15)
    assert run_ops(rg, [(ap.OP_VALUE_GET,)]) == [0]  # expired as scheduled


# ---------------------------------------------------------------------------
# zero-size pools: compiled out, ops fail cleanly (ResourceConfig)
# ---------------------------------------------------------------------------

def test_counters_only_config():
    from copycat_tpu.ops.apply import ResourceConfig
    from copycat_tpu.ops.consensus import Config

    rg = RaftGroups(1, 3, log_slots=32,
                    config=Config(resource=ResourceConfig.counters_only()))
    rg.wait_for_leaders()
    # counters fully work
    res = run_ops(rg, [(ap.OP_LONG_ADD, 5), (ap.OP_LONG_ADD, 5),
                       (ap.OP_VALUE_GET,)])
    assert res == [5, 10, 10]
    # disabled pools fail cleanly with the sentinel
    res = run_ops(rg, [(ap.OP_MAP_PUT, 1, 2), (ap.OP_SET_ADD, 1),
                       (ap.OP_Q_OFFER, 1)])
    assert res == [FAIL, FAIL, FAIL]
    # lock still works in try-lock-only mode (no wait ring)
    res = run_ops(rg, [
        (ap.OP_LOCK_ACQUIRE, 7, 0),   # grant
        (ap.OP_LOCK_ACQUIRE, 8, -1),  # would queue; no ring -> fail (0)
        (ap.OP_LOCK_HOLDER,),
        (ap.OP_LOCK_RELEASE, 7),
        (ap.OP_LOCK_HOLDER,),
    ])
    assert res == [1, 0, 7, 1, -1]
    # election works leader-only (no succession ring)
    res = run_ops(rg, [(ap.OP_ELECT_LISTEN, 5)])
    epoch = res[0]
    assert epoch > 0
    res = run_ops(rg, [
        (ap.OP_ELECT_LISTEN, 6),      # no ring -> FAIL
        (ap.OP_ELECT_IS_LEADER, 5, epoch),
        (ap.OP_ELECT_RESIGN, 5),
        (ap.OP_ELECT_LEADER,),
    ])
    assert res == [FAIL, 1, 1, -1]


# ---------------------------------------------------------------------------
# convergence: replicated pools stay identical across replicas
# ---------------------------------------------------------------------------

def test_all_pools_converge_under_partitions():
    G, P = 2, 3
    rg = RaftGroups(G, P, log_slots=64)
    rg.wait_for_leaders()
    rng = np.random.default_rng(3)
    import jax.numpy as jnp
    ops = [
        (ap.OP_MAP_PUT, 1, 10), (ap.OP_SET_ADD, 2), (ap.OP_Q_OFFER, 3),
        (ap.OP_LOCK_ACQUIRE, 4, -1), (ap.OP_ELECT_LISTEN, 5),
        (ap.OP_MAP_PUT, 6, 60, 4), (ap.OP_LOCK_RELEASE, 4),
        (ap.OP_VALUE_SET, 8), (ap.OP_Q_POLL,), (ap.OP_MAP_REMOVE, 1),
    ]
    for i, op in enumerate(ops):
        for g in range(G):
            rg.submit(g, *op)
        if i % 3 == 0:
            rg.deliver = jnp.asarray(rng.random((G, P, P)) > 0.3)
        rg.run(4)
    rg.deliver = jnp.ones((G, P, P), bool)
    rg.run(40)  # heal + converge

    res = rg.state.resources
    applied = np.asarray(rg.state.applied_index)
    for g in range(G):
        assert len(set(applied[g].tolist())) == 1, applied[g]
    # every linearizable pool field is bit-identical across replicas
    for name in res._fields:
        if name.startswith("ev_"):
            continue  # outbox ring drains in lockstep, not compared
        arr = np.asarray(getattr(res, name))
        for g in range(G):
            first = arr[g, 0]
            for p in range(1, P):
                assert (arr[g, p] == first).all(), (name, g, arr[g])


# ---- outbox-ring overflow: event loss + authoritative fallback -------------
#
# VERDICT weak-#4 / next-#8: the outbox is a drop-oldest ring (apply.py,
# "drop oldest" at the event push); an evicted grant/elect event is gone for
# good, and the facades' documented recovery is the authoritative replicated
# register (OP_LOCK_HOLDER / OP_ELECT_LEADER).  These tests force the loss
# deterministically — event_slots=1 and two event-producing commits applied
# in the same round, so the second push evicts the first — then assert the
# facade recovers through the fallback, not the event.

def _overflow_groups():
    from copycat_tpu.models import DeviceElection, DeviceLock
    from copycat_tpu.ops.consensus import Config
    from copycat_tpu.ops.apply import ResourceConfig
    cfg = Config(resource=ResourceConfig(
        map_slots=0, set_slots=0, queue_slots=0,
        wait_slots=4, listener_slots=4, event_slots=1))
    rg = RaftGroups(1, 3, log_slots=64, config=cfg)
    rg.wait_for_leaders()
    a, b = DeviceLock(rg, 0, 1), DeviceLock(rg, 0, 2)
    e1, e2 = DeviceElection(rg, 0, 11), DeviceElection(rg, 0, 12)
    a.lock()
    assert e1.listen() is not None      # elected immediately, no event
    assert e2.listen() is None          # queued successor
    # B queues behind A; the grant will arrive by event (or not, below)
    acquire = rg.submit(0, ap.OP_LOCK_ACQUIRE, 2, -1)
    rg.run_until([acquire])
    assert rg.results.pop(acquire) not in (0, 1)  # queued, not granted/full
    return rg, a, b, e1, e2


def _same_round_commits(rg, ops_):
    tags = [rg.submit(0, *op) for op in ops_]
    rg.run_until(tags)
    return [rg.results.pop(t) for t in tags]


def test_lost_lock_grant_recovered_via_holder_register():
    rg, a, b, e1, e2 = _overflow_groups()
    # release(A) grants B (event #1); resign(e1) elects e2 (event #2).
    # Both commit in one submit batch -> both apply in one round -> the
    # 1-slot ring drops the grant, keeps the elect.
    res = _same_round_commits(
        rg, [(ap.OP_LOCK_RELEASE, 1), (ap.OP_ELECT_RESIGN, 11)])
    assert res == [1, 1]
    rg.run(8)  # drain whatever survived in the ring
    evs = rg.events.get(0, [])
    assert any(c == ap.EV_ELECT and t == 12 for _, c, t, _a in evs)
    assert not any(c == ap.EV_LOCK_GRANT for _, c, t, _a in evs), evs
    # the facade must still converge, via the authoritative holder register
    assert b._await_grant(None) is True
    assert b._call(ap.OP_LOCK_HOLDER) == 2
    # and the election facade sees its (surviving) event the normal way
    assert e2.poll_elected() is not None
    assert e2.is_leader()


def test_lost_elect_event_recovered_via_leader_register():
    rg, a, b, e1, e2 = _overflow_groups()
    # reversed order: the elect event is pushed first and evicted by the
    # lock grant
    res = _same_round_commits(
        rg, [(ap.OP_ELECT_RESIGN, 11), (ap.OP_LOCK_RELEASE, 1)])
    assert res == [1, 1]
    rg.run(8)
    evs = rg.events.get(0, [])
    assert any(c == ap.EV_LOCK_GRANT and t == 2 for _, c, t, _a in evs)
    assert not any(c == ap.EV_ELECT for _, c, t, _a in evs), evs
    # poll_elected never sees the event; the every-20-polls fallback must
    # consult OP_ELECT_LEADER and recover the epoch + fencing token
    epoch = None
    for _ in range(25):
        epoch = e2.poll_elected()
        if epoch is not None:
            break
    assert epoch is not None
    assert e2.is_leader(epoch)
    # the lock side converges on its surviving event
    assert b._await_grant(None) is True
