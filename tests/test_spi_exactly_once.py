"""End-to-end exactly-once through the public API under leader churn.

Jepsen's counter invariant at the SPI level: with batched concurrent
increments racing a mid-storm leader kill, every acknowledged increment
applied exactly once and every failed one at most once — the final
counter value must land in [acked, acked + unknown]. Exercises the
batch RPC failover promotion, session-seq dedup across re-routes, and
the windowed device executor, all at once.
"""

import asyncio

import pytest

jax = pytest.importorskip("jax")

from copycat_tpu.atomic import DistributedAtomicLong  # noqa: E402
from copycat_tpu.io.local import LocalServerRegistry, LocalTransport  # noqa: E402
from copycat_tpu.manager.atomix import AtomixClient, AtomixServer  # noqa: E402
from copycat_tpu.manager.device_executor import DeviceEngineConfig  # noqa: E402

from helpers import async_test  # noqa: E402
from raft_fixtures import next_ports  # noqa: E402

ENGINE = DeviceEngineConfig(capacity=16, num_peers=3, log_slots=32)


@async_test(timeout=300)
async def test_acked_increments_apply_exactly_once_across_leader_kills():
    registry = LocalServerRegistry()
    addrs = next_ports(3)
    servers = [AtomixServer(a, addrs, LocalTransport(registry),
                            election_timeout=0.2, heartbeat_interval=0.04,
                            session_timeout=20.0, executor="tpu",
                            engine_config=ENGINE) for a in addrs]
    await asyncio.gather(*(s.open() for s in servers))
    client = AtomixClient(addrs, LocalTransport(registry),
                          session_timeout=20.0)
    await client.open()
    live = list(servers)
    try:
        counters = await asyncio.gather(
            *(client.get(f"x{i}", DistributedAtomicLong) for i in range(6)))

        acked = [0] * len(counters)
        unknown = [0] * len(counters)

        async def one(i) -> None:
            try:
                await asyncio.wait_for(counters[i].increment_and_get(), 30)
                acked[i] += 1
            except Exception:
                unknown[i] += 1

        async def storm(rounds: int) -> None:
            for _ in range(rounds):
                await asyncio.gather(
                    *(one(i) for i in range(len(counters))))

        # phase 1: steady state
        await storm(4)
        # phase 2: kill the leader mid-storm ONCE — on a 3-server
        # cluster a second kill would drop below quorum, so the storm
        # races exactly one failover (2 of 3 survive and re-elect)
        task = asyncio.ensure_future(storm(6))
        await asyncio.sleep(0.15)
        leader = next((s for s in live
                       if s.server.role == "leader"), None)
        if leader is not None:
            live.remove(leader)
            await asyncio.wait_for(leader.close(), 10)
        await asyncio.wait_for(task, 120)

        # settle: a final storm must fully succeed on the surviving quorum
        await storm(3)

        got = await asyncio.gather(*(c.get() for c in counters))
        for i, value in enumerate(got):
            assert acked[i] <= value <= acked[i] + unknown[i], (
                f"counter {i}: value {value} outside exactly-once window "
                f"[{acked[i]}, {acked[i] + unknown[i]}]")
        assert sum(acked) >= 6 * 7  # the storms genuinely committed work
    finally:
        try:
            await asyncio.wait_for(client.close(), 5)
        except Exception:
            pass
        for s in live:
            try:
                await asyncio.wait_for(s.close(), 5)
            except Exception:
                pass
