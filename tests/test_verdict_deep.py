"""Smoke the deep-plane verdict harness (VERDICT r4 #4) at tiny scale.

The committed LINEARIZABILITY.md block comes from the full-scale run
(``python -m copycat_tpu.testing.verdict``); this guards the harness
mechanics — fault schedules with mid-drive recovery, per-op real-time
windows from BulkResult, the abort/recover path, and the checker hookup.
"""

import pytest

pytest.importorskip("jax")


def test_deep_verdict_smoke(monkeypatch):
    import copycat_tpu.testing.verdict as V

    monkeypatch.setattr(V, "DEEP_GROUPS", 32)
    monkeypatch.setattr(V, "DEEP_SAMPLE", 8)
    monkeypatch.setattr(V, "DEEP_EPOCHS", 8)
    res = V.run_deep_verdict()
    assert res["violations"] == 0
    assert res["undecided_groups"] == 0
    assert res["linearizable"] is True
    # the harness actually checked real committed work
    assert res["checked_ops"] >= 8 * 8 * 4 // 2
    assert res["sampled_groups"] == 8
