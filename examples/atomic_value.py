"""Atomic value example (reference ``AtomicValueExample.java:29``): a client
that repeatedly sets and reads a distributed value.

    python examples/atomic_value.py 127.0.0.1:5001 [127.0.0.1:5002 ...]
"""

import asyncio
import sys

from copycat_tpu.atomic import DistributedAtomicValue
from copycat_tpu.io.tcp import TcpTransport
from copycat_tpu.io.transport import Address
from copycat_tpu.manager.atomix import AtomixClient


async def main() -> None:
    members = [Address.parse(a) for a in (sys.argv[1:] or ["127.0.0.1:5001"])]
    client = AtomixClient.builder(members).with_transport(TcpTransport()).build()
    await client.open()
    print("client connected")

    value = await client.get("value", DistributedAtomicValue)
    counter = 0
    while True:
        await value.set(f"hello-{counter}")
        print("set ->", await value.get())
        counter += 1
        await asyncio.sleep(1)


if __name__ == "__main__":
    asyncio.run(main())
