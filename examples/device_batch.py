"""Device-engine example: the reference's resource API served by the TPU
batch (no analogue in the reference — its consensus core was an external
JAR; here it is the compiled XLA step, selected at server build time per
SURVEY.md §7.1, mirroring ``withStateMachine`` at
``AtomixReplica.java:374``).

Runs a 3-server in-process cluster whose fixed-shape resources
(counters, maps, locks) execute on the batched device engine — one
group per resource instance — while staying behind the exact same
``Atomix`` facade the CPU path serves:

    python examples/device_batch.py [num_counters]

Works on CPU too (the engine is the same jitted program; JAX picks the
backend).
"""

import asyncio
import sys

from copycat_tpu.atomic import DistributedAtomicLong
from copycat_tpu.collections import DistributedMap
from copycat_tpu.coordination import DistributedLock
from copycat_tpu.io.local import LocalServerRegistry, LocalTransport
from copycat_tpu.io.transport import Address
from copycat_tpu.manager.atomix import AtomixClient, AtomixServer
from copycat_tpu.manager.device_executor import DeviceEngineConfig


async def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    registry = LocalServerRegistry()
    addrs = [Address("local", 5000 + i) for i in range(3)]
    servers = [
        AtomixServer(a, addrs, LocalTransport(registry),
                     election_timeout=0.2, heartbeat_interval=0.04,
                     session_timeout=60.0, executor="tpu",
                     engine_config=DeviceEngineConfig(
                         capacity=max(16, n + 4), num_peers=3,
                         log_slots=32))
        for a in addrs
    ]
    await asyncio.gather(*(s.open() for s in servers))
    client = AtomixClient(addrs, LocalTransport(registry),
                          session_timeout=60.0)
    await client.open()
    print(f"3-server cluster up; device engine hosts the resources")

    # n independent counters -> n device groups, one batch
    counters = [await client.get(f"counter-{i}", DistributedAtomicLong)
                for i in range(n)]
    for round_no in range(3):
        totals = await asyncio.gather(
            *(c.add_and_get(i + 1) for i, c in enumerate(counters)))
        print(f"round {round_no}: counters -> {totals}")

    table = await client.get("table", DistributedMap)
    await table.put("answer", 42)
    print("map get ->", await table.get("answer"))

    lock = await client.get("gate", DistributedLock)
    await lock.lock()
    print("lock acquired; releasing")
    await lock.unlock()

    await client.close()
    await asyncio.gather(*(s.close() for s in servers))
    print("done")


if __name__ == "__main__":
    asyncio.run(main())
