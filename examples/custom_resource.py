"""Custom resource example (docs/GUIDE.md §11): a replicated inventory.

No reference analogue as an example, but the machinery is the
reference's resource SPI (``@ResourceInfo`` + ``ResourceStateMachine``
with reflection-registered handlers, ``Resource.java:41`` /
``ResourceStateMachine.java:30``): declare an operation, a state
machine whose annotated handler is auto-registered, and a client
resource — then use it like any built-in through ``atomix.get``.

Self-contained: boots a 3-server cluster over the in-memory transport.

    python examples/custom_resource.py
"""

import asyncio

from copycat_tpu.io.local import LocalServerRegistry, LocalTransport
from copycat_tpu.io.serializer import serialize_with
from copycat_tpu.io.transport import Address
from copycat_tpu.manager.atomix import AtomixClient, AtomixServer
from copycat_tpu.protocol.messages import Message
from copycat_tpu.protocol.operations import Command
from copycat_tpu.resource.resource import AbstractResource, resource_info
from copycat_tpu.resource.state_machine import ResourceStateMachine
from copycat_tpu.server.state_machine import Commit


@serialize_with(310)
class Reserve(Message, Command):
    _fields = ("amount",)


@serialize_with(311)
class Release(Message, Command):
    _fields = ("hold",)


@serialize_with(312)          # the state-machine CLASS travels by registry id
class InventoryState(ResourceStateMachine):
    """Stock counter with holds, honoring the log-cleaning contract:
    a Reserve commit is retained while its hold is live and cleaned on
    release (so compaction can drop both entries)."""

    def __init__(self) -> None:
        super().__init__()
        self.stock = 10
        self.holds: dict[int, Commit] = {}

    def reserve(self, commit: Commit[Reserve]):        # auto-registered
        amount = commit.operation.amount
        if amount > self.stock:
            commit.clean()     # refused command: entry is dead, compactable
            return False
        self.stock -= amount
        self.holds[commit.index] = commit              # retained commit
        return commit.index                            # the hold id

    def release(self, commit: Commit[Release]):        # auto-registered
        held = self.holds.pop(commit.operation.hold, None)
        if held is not None:
            self.stock += held.operation.amount
            held.clean()                               # superseded entry
        commit.clean()                                 # tombstone itself
        return self.stock


@resource_info(state_machine=InventoryState)
class Inventory(AbstractResource):
    async def reserve(self, amount: int):
        return await self.submit(Reserve(amount=amount))

    async def release(self, hold: int) -> int:
        return await self.submit(Release(hold=hold))


async def main() -> None:
    registry = LocalServerRegistry()
    addrs = [Address.parse(f"127.0.0.1:{5600 + i}") for i in range(3)]
    servers = [
        AtomixServer.builder(a, addrs)
        .with_transport(LocalTransport(registry)).build()
        for a in addrs
    ]
    await asyncio.gather(*(s.open() for s in servers))

    client = AtomixClient.builder(addrs) \
        .with_transport(LocalTransport(registry)).build()
    await client.open()

    inv = await client.get("warehouse", Inventory)
    hold = await inv.reserve(7)
    print("reserved 7, hold id:", hold)
    print("over-reserve refused:", await inv.reserve(9))
    print("stock after release:", await inv.release(hold))

    await client.close()
    for s in servers:
        await s.close()


if __name__ == "__main__":
    asyncio.run(main())
