"""Standalone server example (reference ``StandaloneServerExample.java:27``):
a pure server node with disk storage and small segments.

    python examples/standalone_server.py 127.0.0.1:5001 [peers...]

The logic lives in :mod:`copycat_tpu.cli` (also installed as the
``copycat-server`` console script).
"""

from copycat_tpu.cli import server as run


if __name__ == "__main__":
    run()
