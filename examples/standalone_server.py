"""Standalone server example (reference ``StandaloneServerExample.java:27``):
a pure server node with disk storage and small segments.

    python examples/standalone_server.py 127.0.0.1:5001 [peers...]
"""

import asyncio
import sys
import tempfile

from copycat_tpu.io.tcp import TcpTransport
from copycat_tpu.io.transport import Address
from copycat_tpu.manager.atomix import AtomixServer
from copycat_tpu.server.log import Storage, StorageLevel


async def main() -> None:
    args = sys.argv[1:] or ["127.0.0.1:5001"]
    address = Address.parse(args[0])
    members = [Address.parse(a) for a in args]

    storage = Storage(StorageLevel.DISK,
                      directory=tempfile.mkdtemp(prefix="copycat-tpu-"),
                      max_entries_per_segment=16)
    server = (AtomixServer.builder(address, members)
              .with_transport(TcpTransport())
              .with_storage(storage)
              .build())
    await server.open()
    print(f"server listening at {address} (log: {storage.directory})")

    while True:
        await asyncio.sleep(10)


def run() -> None:
    asyncio.run(main())


if __name__ == "__main__":
    run()
