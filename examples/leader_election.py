"""Leader election example (reference ``LeaderElectionExample.java:28``).

Run one replica per terminal over real TCP:

    python examples/leader_election.py 127.0.0.1:5001 127.0.0.1:5002 127.0.0.1:5003
    python examples/leader_election.py 127.0.0.1:5002 127.0.0.1:5001 127.0.0.1:5003
    python examples/leader_election.py 127.0.0.1:5003 127.0.0.1:5001 127.0.0.1:5002

First argv is this node's address; the rest are peers.  Each node joins the
election; when elected it prints so and verifies its epoch periodically.
"""

import asyncio
import sys

from copycat_tpu.coordination import DistributedLeaderElection
from copycat_tpu.io.tcp import TcpTransport
from copycat_tpu.io.transport import Address
from copycat_tpu.manager.atomix import AtomixReplica


async def main() -> None:
    args = sys.argv[1:] or ["127.0.0.1:5001"]
    address = Address.parse(args[0])
    members = [Address.parse(a) for a in args]

    replica = (AtomixReplica.builder(address, members)
               .with_transport(TcpTransport())
               .build())
    await replica.open()
    print(f"replica at {address} open")

    election = await replica.get("election", DistributedLeaderElection)
    epoch_holder = {}

    def elected(epoch: int) -> None:
        epoch_holder["epoch"] = epoch
        print(f"{address} ELECTED leader, epoch={epoch}")

    await election.on_election(elected)
    print(f"{address} listening for leadership")

    while True:
        await asyncio.sleep(5)
        epoch = epoch_holder.get("epoch")
        if epoch is not None:
            still = await election.is_leader(epoch)
            print(f"{address} leadership check (epoch {epoch}): {still}")


if __name__ == "__main__":
    asyncio.run(main())
