"""Group membership example (reference ``GroupMembershipExample.java``): a
replica that joins a membership group and prints join/leave events.

    python examples/group_membership.py 127.0.0.1:5001 [peers...]
"""

import asyncio
import sys

from copycat_tpu.coordination import DistributedMembershipGroup
from copycat_tpu.io.tcp import TcpTransport
from copycat_tpu.io.transport import Address
from copycat_tpu.manager.atomix import AtomixReplica


async def main() -> None:
    args = sys.argv[1:] or ["127.0.0.1:5001"]
    address = Address.parse(args[0])
    members = [Address.parse(a) for a in args]

    replica = (AtomixReplica.builder(address, members)
               .with_transport(TcpTransport())
               .build())
    await replica.open()

    group = await replica.get("group", DistributedMembershipGroup)
    group.on_join(lambda m: print(f"member joined: {m.id}"))
    group.on_leave(lambda m: print(f"member left: {m}"))
    me = await group.join()
    print(f"{address} joined as member {me.id}")
    print("members:", [m.id for m in await group.members()])

    while True:
        await asyncio.sleep(10)


if __name__ == "__main__":
    asyncio.run(main())
