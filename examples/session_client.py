"""Sessioned batch client example: the reference's client contract —
sessions, exactly-once command correlation, session events, deterministic
expiry/close fan-out — riding the deep pipelined data plane
(``copycat_tpu.models.session_client``, round 5's plane unification).

Two sessions share one client runtime: one holds a lock and commits a
counter burst, the other queues on the lock and receives the GRANT as a
session event when the first closes. Every command carries
(session, seq) and its result is re-readable any number of times.

    python examples/session_client.py [groups] [ops_per_group]

Works on CPU or TPU (same jitted program; JAX picks the backend).
"""

import sys
import time

import numpy as np

from copycat_tpu.models import BulkSessionClient, RaftGroups
from copycat_tpu.ops import apply as ap
from copycat_tpu.ops.consensus import Config


def main() -> None:
    groups_n = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    per_group = int(sys.argv[2]) if len(sys.argv) > 2 else 32

    rg = RaftGroups(groups_n, 3, log_slots=32, submit_slots=4,
                    config=Config(monotone_tag_accept=True))
    rg.wait_for_leaders()
    client = BulkSessionClient(rg)

    worker = client.open_session()
    backup = client.open_session()
    grants = []
    backup.on_event(0, lambda ev: grants.append(ev)
                    if ev.code == ap.EV_LOCK_GRANT else None)

    # worker takes the lock on group 0; backup queues behind it
    t_lock = worker.lock_acquire(0)
    t_wait = backup.lock_acquire(0)
    client.flush()
    assert worker.result(t_lock) == 1, "worker should hold the lock"
    assert backup.result(t_wait) == 2, "backup should be queued"

    # a sessioned burst: per_group increments on every group, one drive
    t0 = time.perf_counter()
    seqs = worker.submit_batch(
        np.repeat(np.arange(groups_n), per_group), ap.OP_LONG_ADD, 1)
    n = client.flush()
    dt = time.perf_counter() - t0
    print(f"{n:,} committed session ops in {dt:.3f}s "
          f"({n / dt:,.0f} ops/sec client-visible)")

    # exactly-once correlation: seq -> result, re-readable
    tail = worker.results_window(int(seqs[-per_group]), per_group)
    assert list(tail) == list(range(1, per_group + 1)), tail[:4]

    # linearizable (leader-lease) reads through the query lane
    reads = worker.query_batch(np.arange(groups_n), ap.OP_VALUE_GET,
                               consistency="atomic")
    assert (reads == per_group).all()

    # graceful close releases the lock THROUGH THE LOG; the grant
    # reaches the backup session as an event on the next flush
    worker.close()
    client.flush()
    assert grants and grants[0].target == backup.id, \
        "backup should receive the grant event"
    q = backup.submit(0, ap.OP_LOCK_HOLDER)
    client.flush()
    assert backup.result(q) == backup.id
    print(f"lock handed over to backup session {backup.id} via event; "
          f"all reads = {per_group}")
    client.close()


if __name__ == "__main__":
    main()
