"""Bulk data plane example: batch-scale client workloads with zero
per-op Python (``copycat_tpu.models.bulk`` — no analogue in the
reference, whose client runtime is one RPC per command).

Drives N committed increments per group across G Raft groups through the
pipelined vectorized driver and prints client-visible throughput +
latency percentiles:

    python examples/bulk_counters.py [groups] [ops_per_group]

Works on CPU or TPU (same jitted program; JAX picks the backend).
"""

import sys

import numpy as np

from copycat_tpu.models import BulkDriver, RaftGroups
from copycat_tpu.ops.apply import OP_LONG_ADD
from copycat_tpu.ops.consensus import Config


def main() -> None:
    groups_n = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
    per_group = int(sys.argv[2]) if len(sys.argv) > 2 else 64

    # monotone_tag_accept = the DEEP pipeline: FIFO + dedup enforced on
    # device by the tag gate, so the driver dispatches with zero blocking
    # fetches and harvests one buffer per drive (the tunnel-latency
    # killer; see PERF.md round 4)
    rg = RaftGroups(groups_n, 3, log_slots=64, submit_slots=16,
                    config=Config(monotone_tag_accept=True,
                                  append_window=16, applies_per_round=16))
    print(f"electing leaders across {groups_n} groups x 3 peers ...")
    rg.wait_for_leaders()

    driver = BulkDriver(rg)
    groups = np.repeat(np.arange(groups_n), per_group)
    print(f"driving {groups.size:,} committed increments ...")
    driver.drive(groups, OP_LONG_ADD, 1)  # warm (compile + transfers)
    res = driver.drive(groups, OP_LONG_ADD, 1)

    pct = res.latency_percentiles_ms()
    print(f"{groups.size:,} ops in {res.wall_s:.3f}s over {res.rounds} "
          f"rounds -> {groups.size / res.wall_s:,.0f} client-visible "
          f"committed ops/sec")
    print(f"latency p50={pct['p50']:.1f} ms p99={pct['p99']:.1f} ms")
    # per-group FIFO: the last op of group 0 saw every earlier increment
    final = res.results.reshape(groups_n, per_group)[:, -1]
    assert (final == 2 * per_group).all(), "FIFO prefix sums violated?"
    print("per-group FIFO verified")

    # and the read lane: ATOMIC (leader-lease gated) reads of every
    # counter — linearizable, zero log entries
    import time
    from copycat_tpu.ops.apply import OP_VALUE_GET
    driver.drive_queries(groups[:groups_n], OP_VALUE_GET,
                         consistency="atomic")  # warm (query jit compile)
    t0 = time.perf_counter()
    got = driver.drive_queries(groups, OP_VALUE_GET, consistency="atomic")
    dt = time.perf_counter() - t0
    assert (got == 2 * per_group).all()
    print(f"{groups.size:,} ATOMIC lease reads in {dt:.3f}s -> "
          f"{groups.size / dt:,.0f} linearizable reads/sec")


if __name__ == "__main__":
    main()
