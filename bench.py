"""Headline benchmark: committed linearizable ops/sec over batched Raft groups.

BASELINE.md metric: "committed ops/sec over 10k Raft groups". The reference
publishes no numbers (BASELINE.md §published — absence verified), so
``vs_baseline`` is reported against the BASELINE.json north-star target of
1M linearizable ops/sec.

Prints ONE JSON line on stdout; all diagnostics go to stderr.

Shape of the run: G groups × 3 peers live on device; leaders are elected,
then R rounds of the jitted consensus step run under ``lax.scan`` with every
submit slot full (DistributedLong.addAndGet ops). Each committed entry is a
quorum-replicated, leader-applied linearizable command; the count is summed
on device and divided by wall time.
"""

from __future__ import annotations

import json
import os
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from copycat_tpu.ops.apply import OP_LONG_ADD
from copycat_tpu.ops.consensus import (
    Config,
    Submits,
    full_delivery,
    init_state,
    step,
)

GROUPS = int(os.environ.get("COPYCAT_BENCH_GROUPS", "10000"))
PEERS = int(os.environ.get("COPYCAT_BENCH_PEERS", "3"))
LOG_SLOTS = int(os.environ.get("COPYCAT_BENCH_LOG_SLOTS", "32"))
ROUNDS = int(os.environ.get("COPYCAT_BENCH_ROUNDS", "200"))
REPEATS = int(os.environ.get("COPYCAT_BENCH_REPEATS", "3"))
SUBMIT_SLOTS = 4
NORTH_STAR_OPS = 1_000_000.0


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    config = Config()
    key = jax.random.PRNGKey(0)
    key, init_key = jax.random.split(key)
    state = init_state(GROUPS, PEERS, LOG_SLOTS, init_key, config)
    deliver = full_delivery(GROUPS, PEERS)

    ones = jnp.ones((GROUPS, SUBMIT_SLOTS), jnp.int32)
    submits = Submits(opcode=ones * OP_LONG_ADD, a=ones, b=ones * 0,
                      tag=ones, valid=ones.astype(bool))
    jit_step = jax.jit(partial(step, config=config))

    log(f"bench: G={GROUPS} P={PEERS} L={LOG_SLOTS} rounds={ROUNDS} "
        f"device={jax.devices()[0].platform}")

    # Elect leaders in every group (empty submits).
    empty = Submits(opcode=ones * 0, a=ones * 0, b=ones * 0, tag=ones * 0,
                    valid=jnp.zeros((GROUPS, SUBMIT_SLOTS), bool))
    t0 = time.perf_counter()
    for r in range(100):
        key, k = jax.random.split(key)
        state, out = jit_step(state, empty, deliver, k)
        if int((np.asarray(out.leader) >= 0).sum()) == GROUPS:
            break
    else:
        raise RuntimeError("not all groups elected a leader")
    log(f"bench: all {GROUPS} leaders elected in {r + 1} rounds "
        f"({time.perf_counter() - t0:.1f}s incl. compile)")

    def run(state, key):
        def body(carry, _):
            state, key = carry
            key, k = jax.random.split(key)
            state, out = step(state, submits, deliver, k, config=config)
            return (state, key), out.out_valid.sum(dtype=jnp.int32)
        (state, key), counts = jax.lax.scan(body, (state, key), None,
                                            length=ROUNDS)
        return state, key, counts.sum()

    run_jit = jax.jit(run)

    # Warmup (compile + reach steady state).
    state, key, n = run_jit(state, key)
    jax.block_until_ready(n)
    log(f"bench: warmup committed {int(n)} ops")

    best = 0.0
    for rep in range(REPEATS):
        t0 = time.perf_counter()
        state, key, n = run_jit(state, key)
        n = int(jax.block_until_ready(n))
        dt = time.perf_counter() - t0
        ops = n / dt
        best = max(best, ops)
        log(f"bench: rep {rep}: {n} committed ops in {dt:.3f}s -> "
            f"{ops:,.0f} ops/sec ({dt / ROUNDS * 1e3:.2f} ms/round)")

    print(json.dumps({
        "metric": f"committed_linearizable_ops_per_sec_{GROUPS}_groups",
        "value": round(best, 1),
        "unit": "ops/sec",
        "vs_baseline": round(best / NORTH_STAR_OPS, 4),
    }))


if __name__ == "__main__":
    main()
