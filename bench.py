"""Driver entry point: delegates to :mod:`copycat_tpu.bench`.

Kept at the repo root because the benchmark driver runs ``python bench.py``
here; the implementation lives in the package so the installed console
script (``copycat-bench``) shares it. Prints ONE JSON line on stdout.
"""

from copycat_tpu.bench import main

if __name__ == "__main__":
    main()
