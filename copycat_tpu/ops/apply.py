"""Vectorized state-machine apply kernels for every device resource type.

The reference applies one commit at a time through per-resource executors
(``ResourceManager.operateResource``, ``ResourceManager.java:56``; resource
state machines ``AtomicValueState.java:32``, ``MapState.java:32``,
``LockState.java:33``, ``LeaderElectionState.java:31``, ``QueueState.java:30``,
``SetState.java:32``). Here the same op semantics are data — an opcode plus
three int32 arguments — applied to ALL groups' replicas at once with
``jnp.where`` masking, so XLA vectorizes the apply across the
``[num_groups, num_peers]`` batch instead of dispatching per commit.

Design rules (SURVEY.md §7.3):

- **Fixed shapes**: maps/sets are fixed-slot probe tables, queues and wait
  lists are fixed-capacity rings. Overflow returns the ``FAIL`` sentinel —
  the host falls back to the CPU oracle path for oversized resources.
- **Pay only for hosted types**: every pool size in :class:`ResourceConfig`
  may be 0, which compiles the pool *out* of the kernel entirely (its ops
  then return ``FAIL``). A deployment whose groups host only counters
  carries no map/lock/event state through the step — pool traffic is the
  step's bandwidth bill, so this is the single biggest throughput lever
  (measured 600k → 1.6M committed ops/sec at 10k groups on one chip).
- **Deterministic time** (§7.3 #3): TTLs and lock timeouts are evaluated
  lazily against the *entry's* logical timestamp (the leader's replicated
  round clock at append), never wall clock — replica state stays a pure
  function of the applied log prefix, so all replicas converge bit-exactly.
  Client-observed timeouts are driven through the log (``OP_LOCK_CANCEL``),
  which totally orders grant-vs-timeout races (the reference instead runs
  replicated ``executor().schedule`` timers, ``ResourceStateMachineExecutor``).
- **Events** (§7.3 #4): session-push events (lock grant
  ``LockState.java:publish("lock",…)``, election ``publish("elect",…)``)
  go into a per-lane replicated event ring with absolute sequence numbers;
  the step drains the leader lane into ``StepOutputs`` and the host dedups
  by sequence across leader changes (at-least-once while a leader exists,
  with the authoritative ``OP_LOCK_HOLDER``/``OP_ELECT_LEADER`` queries as
  the overflow-proof fallback).

Only fixed-width state lives on device. Arbitrary Python payloads take the
CPU oracle path (``copycat_tpu.server``); the device path covers the hot,
fixed-shape resource kernels (BASELINE.md configs #1-#5).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

INT_MIN = jnp.iinfo(jnp.int32).min
INT_MAX = jnp.iinfo(jnp.int32).max

#: Sentinel returned for failed/absent/overflow results. Device-path values
#: must avoid INT_MIN (the host facades enforce this).
FAIL = int(INT_MIN)

# --- opcodes (device-path operation catalog) -------------------------------
# Mirrors the reference's serializer-id catalogs as a dense opcode space:
# AtomicValueCommands ids 50-55, MapCommands ids 60-72, SetCommands 100-105,
# QueueCommands 90-99, LockCommands 115-116, LeaderElectionCommands 110-112.
OP_NOP = 0

# value / long (AtomicValueState.java:32, DistributedAtomicLong.java:29)
OP_VALUE_SET = 1          # a=value, c=ttl ticks (0 = none)
OP_VALUE_GET = 2
OP_VALUE_CAS = 3          # a=expect, b=update -> 1 if swapped else 0
OP_VALUE_GET_AND_SET = 4  # a=update -> previous value
OP_LONG_ADD = 5           # a=delta -> new value (addAndGet)

# map (MapState.java:32; hashed fixed keyspace per SURVEY.md §7.1)
OP_MAP_PUT = 10           # a=key, b=value, c=ttl -> previous value | 0
OP_MAP_GET = 11           # a=key -> value | 0
OP_MAP_REMOVE = 12        # a=key -> previous value | 0
OP_MAP_PUT_IF_ABSENT = 13  # a=key, b=value, c=ttl -> 1 if put else 0
OP_MAP_GET_OR_DEFAULT = 14  # a=key, b=default
OP_MAP_REMOVE_IF = 15     # a=key, b=value -> 1 if removed
OP_MAP_REPLACE = 16       # a=key, b=value -> previous | FAIL if absent
OP_MAP_REPLACE_IF = 17    # a=key, b=expect, c=update -> 1 if replaced
OP_MAP_CONTAINS_KEY = 18  # a=key -> 0/1
OP_MAP_CONTAINS_VALUE = 19  # a=value -> 0/1
OP_MAP_SIZE = 20
OP_MAP_IS_EMPTY = 21
OP_MAP_CLEAR = 22

# set (SetState.java:32)
OP_SET_ADD = 30           # a=value, c=ttl -> 1 if added else 0
OP_SET_REMOVE = 31        # a=value -> 1 if removed
OP_SET_CONTAINS = 32      # a=value -> 0/1
OP_SET_SIZE = 33
OP_SET_CLEAR = 34

# queue (QueueState.java:30; device subset — remove(v)/contains take the
# CPU path, SURVEY.md §2.1 QueueState row)
OP_Q_OFFER = 40           # a=value -> 1 | 0 when full
OP_Q_POLL = 41            # -> value | FAIL when empty
OP_Q_PEEK = 42            # -> value | FAIL when empty
OP_Q_SIZE = 43
OP_Q_CLEAR = 44

# lock (LockState.java:33; grant delivered as an event, DistributedLock.java:58)
OP_LOCK_ACQUIRE = 50      # a=holder id, b=timeout ticks (-1 forever, 0 try)
OP_LOCK_RELEASE = 51      # a=holder id -> 1 if released
OP_LOCK_CANCEL = 52       # a=holder id -> 2 already-granted | 1 dequeued | 0 gone
OP_LOCK_HOLDER = 53       # -> current holder id | -1 (authoritative grant
#                           check — the facades' fallback if a grant event
#                           is lost to outbox-ring overflow)

# leader election (LeaderElectionState.java:31; epoch = entry log index)
OP_ELECT_LISTEN = 60      # a=candidate id -> epoch if elected now else 0
OP_ELECT_RESIGN = 61      # a=candidate id (resign / unlisten)
OP_ELECT_IS_LEADER = 62   # a=candidate id, b=epoch -> 0/1 (fencing check)
OP_ELECT_LEADER = 63      # -> current leader id | -1 (authoritative)
OP_ELECT_GET_EPOCH = 64   # -> current epoch

# multimap (MultiMapState.java:30; probe table keyed on the (key, value)
# PAIR — the device variant of the reference's nested map-of-maps)
OP_MM_PUT = 70            # a=key, b=value, c=ttl -> 1 if added, 0 if dup
OP_MM_REMOVE = 71         # a=key -> count of entries removed
OP_MM_REMOVE_ENTRY = 72   # a=key, b=value -> 1 if removed
OP_MM_CONTAINS_KEY = 73   # a=key -> 0/1
OP_MM_CONTAINS_ENTRY = 74  # a=key, b=value -> 0/1
OP_MM_CONTAINS_VALUE = 75  # a=value -> 0/1
OP_MM_COUNT = 76          # a=key -> entries under key (MultiMapState.java:169)
OP_MM_SIZE = 77           # -> total entries
OP_MM_IS_EMPTY = 78
OP_MM_CLEAR = 79

# topic pub/sub (TopicState.java:31; publish fans out through the event
# ring as ONE broadcast event per publish — subscribers filter by their
# replicated membership, which this kernel tracks)
OP_TOPIC_LISTEN = 85      # a=subscriber id -> 1 if added, 0 if already
OP_TOPIC_UNLISTEN = 86    # a=subscriber id -> 1 if removed
OP_TOPIC_PUB = 87         # a=message -> subscriber count at publish
OP_TOPIC_COUNT = 88       # -> current subscriber count

# Cluster membership change (consensus-layer, not a resource pool): a
# single-server Raft configuration change rides the log like any command
# and is applied by the consensus step itself — each replica lane updates
# its OWN membership view when it applies the entry (``ops/consensus.py``
# phase 5). Routed to POOL_NONE here (no resource work, result 0).
# Reference obligation: server join/leave
# (manager/src/test/java/io/atomix/AtomixServerTest.java
# testServerJoin/testServerLeave); safety requires ONE change in flight
# at a time (adjacent single-server configs always share a quorum
# intersection), which the step enforces at append.
OP_CFG_ADD = 90           # a=peer lane -> 0 (idempotent)
OP_CFG_REMOVE = 91        # a=peer lane -> 0 (idempotent; last member kept)

# Read-only opcodes servable on the fast query lane (query_step evaluates
# and DISCARDS state, so admitting a write there would silently drop the
# mutation while acking success — the host validates against this set).
QUERY_OPCODES = frozenset({
    OP_VALUE_GET,
    OP_MAP_GET, OP_MAP_GET_OR_DEFAULT, OP_MAP_CONTAINS_KEY,
    OP_MAP_CONTAINS_VALUE, OP_MAP_SIZE, OP_MAP_IS_EMPTY,
    OP_SET_CONTAINS, OP_SET_SIZE,
    OP_Q_PEEK, OP_Q_SIZE,
    OP_LOCK_HOLDER,
    OP_ELECT_IS_LEADER, OP_ELECT_LEADER, OP_ELECT_GET_EPOCH,
    OP_MM_CONTAINS_KEY, OP_MM_CONTAINS_ENTRY, OP_MM_CONTAINS_VALUE,
    OP_MM_COUNT, OP_MM_SIZE, OP_MM_IS_EMPTY,
    OP_TOPIC_COUNT,
})

# --- event codes (session push, harvested from the leader lane) ------------
EV_NONE = 0
EV_LOCK_GRANT = 1   # target=holder id, arg=1
EV_ELECT = 3        # target=new leader id, arg=epoch (fencing token)
EV_TOPIC_MSG = 4    # target=-1 (broadcast), arg=message


class ResourceConfig(NamedTuple):
    """Fixed device pool sizes (hashable — part of the jit-static Config).

    Any size may be 0: the pool is then compiled out of the kernel and its
    ops return ``FAIL``. Size the pools to the resource types the groups
    actually host — pool state is carried through every step, so unused
    pools cost real HBM bandwidth.
    """

    map_slots: int = 16
    set_slots: int = 16
    queue_slots: int = 16
    wait_slots: int = 8       # lock wait queue (0 = try-lock only)
    listener_slots: int = 8   # election listener queue (0 = no succession)
    event_slots: int = 32     # session-event outbox ring
    multimap_slots: int = 16  # (key, value)-pair probe table
    topic_slots: int = 8      # topic subscriber table

    @classmethod
    def counters_only(cls) -> "ResourceConfig":
        """Value/long registers only — the leanest (fastest) kernel."""
        return cls(map_slots=0, set_slots=0, queue_slots=0, wait_slots=0,
                   listener_slots=0, event_slots=0, multimap_slots=0,
                   topic_slots=0)


class ResourceState(NamedTuple):
    """Per-group, per-replica device-resident resource state.

    Every field is ``[num_groups, num_peers, ...]``: each replica applies the
    same committed ops in the same order, so replica states stay identical —
    exactly the reference's replicated-state-machine discipline, kept as a
    batch dimension so divergence is *testable* (see tests). The event ring
    (``ev_*``) is outbox infrastructure, not linearizable state: lanes drain
    it in lockstep, so its heads may differ across replicas. Disabled pools
    (size 0) are zero-width arrays — present in the tree, absent from the
    compiled program.
    """

    # value register + TTL deadline (0 = none)
    value: jnp.ndarray    # [G,P] i32
    val_dl: jnp.ndarray   # [G,P] i32

    # hashed map: fixed probe table
    map_key: jnp.ndarray   # [G,P,K] i32
    map_val: jnp.ndarray   # [G,P,K] i32
    map_live: jnp.ndarray  # [G,P,K] bool
    map_dl: jnp.ndarray    # [G,P,K] i32 (0 = no TTL)

    # set: probe table without values
    set_key: jnp.ndarray   # [G,P,Ks] i32
    set_live: jnp.ndarray  # [G,P,Ks] bool
    set_dl: jnp.ndarray    # [G,P,Ks] i32

    # FIFO queue ring
    q_val: jnp.ndarray     # [G,P,Q] i32
    q_head: jnp.ndarray    # [G,P] i32 (absolute pops)
    q_size: jnp.ndarray    # [G,P] i32

    # lock: holder + wait-queue ring (id, deadline, live)
    lk_holder: jnp.ndarray   # [G,P] i32, -1 = free
    lk_wait_id: jnp.ndarray  # [G,P,W] i32
    lk_wait_dl: jnp.ndarray  # [G,P,W] i32 (INT_MAX = wait forever)
    lk_wait_live: jnp.ndarray  # [G,P,W] bool
    lk_head: jnp.ndarray     # [G,P] i32
    lk_size: jnp.ndarray     # [G,P] i32

    # leader election: leader + listener ring + epoch fencing token
    el_leader: jnp.ndarray   # [G,P] i32, -1 = none
    el_epoch: jnp.ndarray    # [G,P] i32 (log index of the winning listen)
    el_id: jnp.ndarray       # [G,P,Wl] i32
    el_live: jnp.ndarray     # [G,P,Wl] bool
    el_head: jnp.ndarray     # [G,P] i32
    el_size: jnp.ndarray     # [G,P] i32

    # session-event outbox ring (code/target/arg), absolute head/tail seqs
    ev_code: jnp.ndarray    # [G,P,E] i32
    ev_target: jnp.ndarray  # [G,P,E] i32
    ev_arg: jnp.ndarray     # [G,P,E] i32
    ev_head: jnp.ndarray    # [G,P] i32
    ev_tail: jnp.ndarray    # [G,P] i32

    # multimap: probe table keyed on the (key, value) PAIR
    mm_key: jnp.ndarray     # [G,P,M] i32
    mm_val: jnp.ndarray     # [G,P,M] i32
    mm_live: jnp.ndarray    # [G,P,M] bool
    mm_dl: jnp.ndarray      # [G,P,M] i32 (0 = no TTL)

    # topic: subscriber membership table
    tp_id: jnp.ndarray      # [G,P,T] i32
    tp_live: jnp.ndarray    # [G,P,T] bool


def init_resources(num_groups: int, num_peers: int,
                   rc: ResourceConfig = ResourceConfig()) -> ResourceState:
    G, P = num_groups, num_peers
    z2 = jnp.zeros((G, P), jnp.int32)

    def zi(n):
        return jnp.zeros((G, P, n), jnp.int32)

    def zb(n):
        return jnp.zeros((G, P, n), bool)

    return ResourceState(
        value=z2, val_dl=z2,
        map_key=zi(rc.map_slots), map_val=zi(rc.map_slots),
        map_live=zb(rc.map_slots), map_dl=zi(rc.map_slots),
        set_key=zi(rc.set_slots), set_live=zb(rc.set_slots),
        set_dl=zi(rc.set_slots),
        q_val=zi(rc.queue_slots), q_head=z2, q_size=z2,
        lk_holder=z2 - 1, lk_wait_id=zi(rc.wait_slots),
        lk_wait_dl=zi(rc.wait_slots), lk_wait_live=zb(rc.wait_slots),
        lk_head=z2, lk_size=z2,
        el_leader=z2 - 1, el_epoch=z2, el_id=zi(rc.listener_slots),
        el_live=zb(rc.listener_slots), el_head=z2, el_size=z2,
        ev_code=zi(rc.event_slots), ev_target=zi(rc.event_slots),
        ev_arg=zi(rc.event_slots), ev_head=z2, ev_tail=z2,
        mm_key=zi(rc.multimap_slots), mm_val=zi(rc.multimap_slots),
        mm_live=zb(rc.multimap_slots), mm_dl=zi(rc.multimap_slots),
        tp_id=zi(rc.topic_slots), tp_live=zb(rc.topic_slots),
    )


# ---------------------------------------------------------------------------
# small vectorized helpers over [G,P,N] pools
# ---------------------------------------------------------------------------

def _gather3(arr: jnp.ndarray, slot: jnp.ndarray) -> jnp.ndarray:
    """arr[G,P,N] selected at slot[G,P] -> [G,P].

    One-hot select-reduce: take_along_axis lowers to an element-wise DMA
    loop on TPU; the masked sum is one fused vector pass over the pool."""
    N = arr.shape[-1]
    oh = slot[..., None] == jnp.arange(N, dtype=jnp.int32)
    return jnp.where(oh, arr, 0).sum(axis=-1).astype(arr.dtype)


def _scatter3(arr: jnp.ndarray, slot: jnp.ndarray, mask: jnp.ndarray,
              value: jnp.ndarray) -> jnp.ndarray:
    """Masked write of value[G,P] into arr[G,P,N] at slot[G,P]."""
    N = arr.shape[-1]
    hit = (jnp.arange(N)[None, None, :] == slot[..., None]) & mask[..., None]
    return jnp.where(hit, value[..., None], arr)


def _first_true(mask: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(index of first True along last axis, any True) for mask[G,P,N].

    One max-reduce instead of argmax + any (two reduces): score slot i as
    N-i where mask holds, 0 otherwise — the max is N-first_index, and 0
    means no hit. Profiled in the apply scan: argmax+reduce_or were ~25%
    of the mixed round (PERF.md)."""
    N = mask.shape[-1]
    score = jnp.where(mask, N - jnp.arange(N, dtype=jnp.int32), 0)
    best = jnp.max(score, axis=-1)
    found = best > 0
    return jnp.where(found, N - best, 0).astype(jnp.int32), found


def _ring_pos(head: jnp.ndarray, n: int) -> jnp.ndarray:
    """Position-in-queue of each ring slot: [G,P,N] given head[G,P]."""
    slots = jnp.arange(n, dtype=jnp.int32)[None, None, :]
    return (slots - head[..., None]) % n


def _ring_compact(mask: jnp.ndarray, head, size, pos, live_arr, live_win,
                  *arrays):
    """Stable-compact ring slots where ``mask`` holds; returns
    (head, size, live, compacted arrays...). FIFO order of live entries is
    preserved (argsort key = pos for live, pos+N for dead). Lanes where
    ``mask`` is False keep every field untouched."""
    N = arrays[0].shape[-1]
    # Stable live-first order WITHOUT argsort: ring positions are a
    # permutation of 0..N-1, so the keys (pos for live, N+pos for dead) are
    # pairwise distinct and each slot's target rank is just how many keys
    # are smaller — O(N²) vector compares beat the sort network (PERF.md).
    key = jnp.where(live_win, pos, N + pos)
    rank = jnp.sum((key[..., None, :] < key[..., :, None]).astype(jnp.int32),
                   axis=-1)                                   # [G,P,N]
    count = jnp.sum(live_win, axis=-1).astype(jnp.int32)
    m3 = mask[..., None]
    # permutation as a one-hot [G,P,N,N] select-reduce (N is small); the
    # take_along_axis equivalent lowers to an element-wise DMA loop on TPU.
    # perm[i, j] == True iff the slot moving to position i is j, i.e.
    # rank[j] == i.
    perm = rank[..., None, :] == jnp.arange(N, dtype=jnp.int32)[:, None]
    pick = lambda arr: jnp.where(perm, arr[..., None, :], 0).sum(-1).astype(
        arr.dtype)
    out = [jnp.where(m3, pick(arr), arr) for arr in arrays]
    live = jnp.where(m3, jnp.arange(N)[None, None, :] < count[..., None],
                     live_arr)
    head = jnp.where(mask, 0, head)
    size = jnp.where(mask, count, size)
    return head, size, live, out


# ---------------------------------------------------------------------------
# pool classification (conflict partitioning)
# ---------------------------------------------------------------------------

#: Pool ids: entries in DIFFERENT pools commute (disjoint state), so the
#: step's apply phase folds each pool's entries independently, touching
#: only that pool's arrays (PERF.md "conflict-partitioned apply").
(POOL_VALUE, POOL_MAP, POOL_SET, POOL_QUEUE, POOL_LOCK, POOL_ELECT,
 POOL_MMAP, POOL_TOPIC) = range(8)
NUM_POOLS = 8
POOL_NONE = NUM_POOLS  # NoOps — applied (indices advance), no pool work


def pool_of(opcode: jnp.ndarray) -> jnp.ndarray:
    """Map opcodes to pool ids ([G,P] -> [G,P], POOL_NONE for NoOp)."""
    pool = jnp.full_like(opcode, POOL_NONE)
    pool = jnp.where((opcode >= OP_VALUE_SET) & (opcode <= OP_LONG_ADD),
                     POOL_VALUE, pool)
    pool = jnp.where((opcode >= OP_MAP_PUT) & (opcode <= OP_MAP_CLEAR),
                     POOL_MAP, pool)
    pool = jnp.where((opcode >= OP_SET_ADD) & (opcode <= OP_SET_CLEAR),
                     POOL_SET, pool)
    pool = jnp.where((opcode >= OP_Q_OFFER) & (opcode <= OP_Q_CLEAR),
                     POOL_QUEUE, pool)
    pool = jnp.where((opcode >= OP_LOCK_ACQUIRE) & (opcode <= OP_LOCK_HOLDER),
                     POOL_LOCK, pool)
    pool = jnp.where((opcode >= OP_ELECT_LISTEN) & (opcode <= OP_ELECT_GET_EPOCH),
                     POOL_ELECT, pool)
    pool = jnp.where((opcode >= OP_MM_PUT) & (opcode <= OP_MM_CLEAR),
                     POOL_MMAP, pool)
    pool = jnp.where((opcode >= OP_TOPIC_LISTEN) & (opcode <= OP_TOPIC_COUNT),
                     POOL_TOPIC, pool)
    return pool


# ---------------------------------------------------------------------------
# per-pool apply kernels
#
# Each kernel applies ONE entry per (group, replica) lane against ONLY its
# pool's arrays, so a scan over a pool's entries carries that pool's HBM
# and nothing else. ``apply_entry`` below composes all six for the
# single-entry case (query lane + CPU-oracle differential tests).
# ---------------------------------------------------------------------------

def apply_value(value, val_dl, opcode, a, b, c, now, live):
    """Value/long registers; returns ((value, val_dl), result)."""
    def op(code):
        return live & (opcode == code)

    expired = (val_dl > 0) & (val_dl <= now)
    eff = jnp.where(expired, 0, value)  # TTL'd value reads as unset

    is_set = op(OP_VALUE_SET)
    is_get = op(OP_VALUE_GET)
    is_cas = op(OP_VALUE_CAS)
    is_gas = op(OP_VALUE_GET_AND_SET)
    is_add = op(OP_LONG_ADD)
    cas_hit = is_cas & (eff == a)
    # Only ops that actually write may touch value/val_dl — a failed CAS
    # must leave an active TTL intact.
    wrote = is_set | cas_hit | is_gas | is_add
    purge = (is_get | is_cas) & expired  # observed expiry without writing

    new_value = eff
    new_value = jnp.where(is_set, a, new_value)
    new_value = jnp.where(cas_hit, b, new_value)
    new_value = jnp.where(is_gas, a, new_value)
    new_value = jnp.where(is_add, eff + a, new_value)
    out_value = jnp.where(wrote, new_value, jnp.where(purge, 0, value))
    new_dl = jnp.where(is_set & (c > 0), now + c, 0)
    out_dl = jnp.where(wrote, new_dl, jnp.where(purge, 0, val_dl))

    result = jnp.zeros_like(opcode)
    result = jnp.where(is_get, eff, result)
    result = jnp.where(is_cas, cas_hit.astype(jnp.int32), result)
    result = jnp.where(is_gas, eff, result)
    result = jnp.where(is_add, eff + a, result)
    return (out_value, out_dl), result


def apply_map(mk, mv, ml, mdl, opcode, a, b, c, now, live):
    """Hashed probe-table map; returns ((mk, mv, ml, mdl), result)."""
    def op(code):
        return live & (opcode == code)

    is_map = live & (opcode >= OP_MAP_PUT) & (opcode <= OP_MAP_CLEAR)
    result = jnp.zeros_like(opcode)
    if mk.shape[-1] == 0:
        return (mk, mv, ml, mdl), jnp.where(is_map, INT_MIN, result)

    m_alive = ml & ((mdl == 0) | (mdl > now[..., None]))
    hit = m_alive & (mk == a[..., None])
    hit_idx, hit_any = _first_true(hit)
    free_idx, free_any = _first_true(~m_alive)
    old = jnp.where(hit_any, _gather3(mv, hit_idx), 0)

    put = op(OP_MAP_PUT)
    pia = op(OP_MAP_PUT_IF_ABSENT)
    rep = op(OP_MAP_REPLACE)
    repif = op(OP_MAP_REPLACE_IF) & hit_any & (old == b)
    write_new = (put | pia) & ~hit_any           # needs a free slot
    write_over = (put & hit_any) | (rep & hit_any) | repif
    ins_ok = write_new & free_any
    w_idx = jnp.where(hit_any, hit_idx, free_idx)
    w_val = jnp.where(repif, c, b)
    w_dl = jnp.where((put | pia) & (c > 0), now + c, 0)
    do_write = ins_ok | write_over
    mk = _scatter3(mk, w_idx, do_write, a)
    mv = _scatter3(mv, w_idx, do_write, w_val)
    mdl = _scatter3(mdl, w_idx, do_write,
                    jnp.where(write_over & ~put, 0, w_dl))
    ml = _scatter3(ml, w_idx, do_write, jnp.ones_like(a, bool))

    rm = op(OP_MAP_REMOVE) | (op(OP_MAP_REMOVE_IF) & (old == b))
    ml = _scatter3(ml, hit_idx, rm & hit_any, jnp.zeros_like(a, bool))
    ml = jnp.where(op(OP_MAP_CLEAR)[..., None], False, ml)
    # drop expired slots whenever any map op touches the group (lazy
    # purge; just-written slots have dl == 0 or dl > now, so they
    # always survive)
    ml = jnp.where(is_map[..., None],
                   ml & ((mdl == 0) | (mdl > now[..., None])), ml)

    m_size = jnp.sum(m_alive, axis=-1).astype(jnp.int32)
    result = jnp.where(put, old, result)
    result = jnp.where(put & write_new & ~free_any, INT_MIN, result)
    result = jnp.where(pia, jnp.where(hit_any, 0,
                       jnp.where(free_any, 1, INT_MIN)), result)
    result = jnp.where(op(OP_MAP_GET), old, result)
    result = jnp.where(op(OP_MAP_GET_OR_DEFAULT),
                       jnp.where(hit_any, old, b), result)
    result = jnp.where(op(OP_MAP_REMOVE), old, result)
    result = jnp.where(op(OP_MAP_REMOVE_IF),
                       (hit_any & (old == b)).astype(jnp.int32), result)
    result = jnp.where(rep, jnp.where(hit_any, old, INT_MIN), result)
    result = jnp.where(op(OP_MAP_REPLACE_IF), repif.astype(jnp.int32),
                       result)
    result = jnp.where(op(OP_MAP_CONTAINS_KEY),
                       hit_any.astype(jnp.int32), result)
    result = jnp.where(op(OP_MAP_CONTAINS_VALUE),
                       jnp.any(m_alive & (mv == a[..., None]),
                               axis=-1).astype(jnp.int32), result)
    result = jnp.where(op(OP_MAP_SIZE), m_size, result)
    result = jnp.where(op(OP_MAP_IS_EMPTY),
                       (m_size == 0).astype(jnp.int32), result)
    return (mk, mv, ml, mdl), result


def apply_set(sk, sl, sdl, opcode, a, b, c, now, live):
    """Probe-table set; returns ((sk, sl, sdl), result)."""
    def op(code):
        return live & (opcode == code)

    is_setop = live & (opcode >= OP_SET_ADD) & (opcode <= OP_SET_CLEAR)
    result = jnp.zeros_like(opcode)
    if sk.shape[-1] == 0:
        return (sk, sl, sdl), jnp.where(is_setop, INT_MIN, result)

    s_alive = sl & ((sdl == 0) | (sdl > now[..., None]))
    s_hit = s_alive & (sk == a[..., None])
    s_hit_idx, s_hit_any = _first_true(s_hit)
    s_free_idx, s_free_any = _first_true(~s_alive)

    add = op(OP_SET_ADD) & ~s_hit_any & s_free_any
    sk = _scatter3(sk, s_free_idx, add, a)
    sdl = _scatter3(sdl, s_free_idx, add, jnp.where(c > 0, now + c, 0))
    sl = _scatter3(sl, s_free_idx, add, jnp.ones_like(a, bool))
    srm = op(OP_SET_REMOVE) & s_hit_any
    sl = _scatter3(sl, s_hit_idx, srm, jnp.zeros_like(a, bool))
    sl = jnp.where(op(OP_SET_CLEAR)[..., None], False, sl)
    sl = jnp.where(is_setop[..., None],
                   sl & ((sdl == 0) | (sdl > now[..., None])), sl)
    s_size = jnp.sum(s_alive, axis=-1).astype(jnp.int32)
    result = jnp.where(op(OP_SET_ADD),
                       jnp.where(s_hit_any, 0,
                                 jnp.where(s_free_any, 1, INT_MIN)),
                       result)
    result = jnp.where(op(OP_SET_REMOVE), s_hit_any.astype(jnp.int32),
                       result)
    result = jnp.where(op(OP_SET_CONTAINS), s_hit_any.astype(jnp.int32),
                       result)
    result = jnp.where(op(OP_SET_SIZE), s_size, result)
    return (sk, sl, sdl), result


def apply_queue(qv, qh, qs, opcode, a, b, c, now, live):
    """FIFO ring queue; returns ((qv, qh, qs), result)."""
    def op(code):
        return live & (opcode == code)

    is_q = live & (opcode >= OP_Q_OFFER) & (opcode <= OP_Q_CLEAR)
    result = jnp.zeros_like(opcode)
    if qv.shape[-1] == 0:
        return (qv, qh, qs), jnp.where(is_q, INT_MIN, result)

    Q = qv.shape[-1]
    offer = op(OP_Q_OFFER)
    can_push = offer & (qs < Q)
    qv = _scatter3(qv, (qh + qs) % Q, can_push, a)
    head_val = _gather3(qv, qh % Q)
    poll = op(OP_Q_POLL) & (qs > 0)
    qs = jnp.where(can_push, qs + 1, qs)
    qh = jnp.where(poll, qh + 1, qh)
    qs = jnp.where(poll, qs - 1, qs)
    qs = jnp.where(op(OP_Q_CLEAR), 0, qs)
    result = jnp.where(offer, can_push.astype(jnp.int32), result)
    result = jnp.where(op(OP_Q_POLL),
                       jnp.where(poll, head_val, INT_MIN), result)
    result = jnp.where(op(OP_Q_PEEK),
                       jnp.where(qs > 0, head_val, INT_MIN), result)
    result = jnp.where(op(OP_Q_SIZE), qs, result)
    return (qv, qh, qs), result


def apply_lock(holder, wid, wdl, wlv, lh, ls, opcode, a, b, now, live):
    """Lock kernel; returns ((holder, wid, wdl, wlv, lh, ls), result,
    (ev_mask, ev_code, ev_target, ev_arg))."""
    def op(code):
        return live & (opcode == code)

    is_lock = live & (opcode >= OP_LOCK_ACQUIRE) & (opcode <= OP_LOCK_HOLDER)
    result = jnp.zeros_like(opcode)
    ev_mask = jnp.zeros_like(live)
    ev_code = jnp.zeros_like(opcode)
    ev_target = jnp.zeros_like(opcode)
    ev_arg = jnp.zeros_like(opcode)

    acq = op(OP_LOCK_ACQUIRE)
    rel = op(OP_LOCK_RELEASE)
    cxl = op(OP_LOCK_CANCEL)
    held_by_me = holder == a
    grant_now = acq & (holder == -1)
    holder = jnp.where(grant_now, a, holder)
    idem = acq & held_by_me          # retried acquire we already won
    do_rel = rel & held_by_me
    W = wid.shape[-1]
    if W > 0:
        # Lazily expire timed-out waiters, then compact the ring: dead
        # slots (cancelled or expired anywhere in the window) must never
        # wedge capacity. Stable compaction keeps FIFO order.
        pos = _ring_pos(lh, W)
        in_win = pos < ls[..., None]
        wlv = wlv & ~(is_lock[..., None] & in_win & (wdl <= now[..., None]))
        live_win = wlv & in_win
        any_dead = is_lock & jnp.any(in_win & ~wlv, axis=-1)
        lh, ls, wlv, (wid, wdl) = _ring_compact(
            any_dead, lh, ls, pos, wlv, live_win, wid, wdl)

        pos2 = _ring_pos(lh, W)
        in_win2 = pos2 < ls[..., None]
        queued_me = jnp.any(wlv & in_win2 & (wid == a[..., None]), axis=-1)

        want_q = acq & ~grant_now & ~idem & ~queued_me & (b != 0)
        q_ok = want_q & (ls < W)
        q_dl = jnp.where(b < 0, INT_MAX, now + b)
        wid = _scatter3(wid, (lh + ls) % W, q_ok, a)
        wdl = _scatter3(wdl, (lh + ls) % W, q_ok, q_dl)
        wlv = _scatter3(wlv, (lh + ls) % W, q_ok, jnp.ones_like(a, bool))
        ls = jnp.where(q_ok, ls + 1, ls)

        # release: hand to the first waiter (ring is compacted: head live)
        next_id = _gather3(wid, lh % W)
        has_next = do_rel & (ls > 0)
        holder = jnp.where(do_rel,
                           jnp.where(has_next, next_id, -1), holder)
        lh = jnp.where(has_next, lh + 1, lh)
        ls = jnp.where(has_next, ls - 1, ls)

        # cancel: totally ordered with grants through the log, so the
        # client's timeout decision is race-free (2 = won before cancel)
        already = cxl & held_by_me
        cxl_hit = wlv & in_win2 & (wid == a[..., None])
        cxl_idx, cxl_found = _first_true(cxl_hit)
        wlv = _scatter3(wlv, cxl_idx, cxl & ~already & cxl_found,
                        jnp.zeros_like(a, bool))

        result = jnp.where(acq, jnp.where(
            grant_now | idem, 1,
            jnp.where(q_ok | queued_me, 2, 0)), result)
        result = jnp.where(cxl, jnp.where(already, 2,
                           jnp.where(cxl_found, 1, 0)), result)
        # Only queued-waiter grants are asynchronous; an immediate grant
        # or failure reaches the client as the command's own result
        ev_mask = ev_mask | has_next
        ev_code = jnp.where(has_next, EV_LOCK_GRANT, ev_code)
        ev_target = jnp.where(has_next, next_id, ev_target)
        ev_arg = jnp.where(has_next, 1, ev_arg)
    else:
        holder = jnp.where(do_rel, -1, holder)
        result = jnp.where(acq,
                           jnp.where(grant_now | idem, 1, 0), result)
        result = jnp.where(cxl, jnp.where(held_by_me, 2, 0), result)
    result = jnp.where(rel, do_rel.astype(jnp.int32), result)
    result = jnp.where(op(OP_LOCK_HOLDER), holder, result)
    return (holder, wid, wdl, wlv, lh, ls), result, \
        (ev_mask, ev_code, ev_target, ev_arg)


def apply_elect(el, ep, eid, elv, eh, es, opcode, a, b, index, live):
    """Leader-election kernel; returns ((el, ep, eid, elv, eh, es),
    result, (ev_mask, ev_code, ev_target, ev_arg))."""
    def op(code):
        return live & (opcode == code)

    is_el = live & (opcode >= OP_ELECT_LISTEN) & (opcode <= OP_ELECT_GET_EPOCH)
    result = jnp.zeros_like(opcode)
    ev_mask = jnp.zeros_like(live)
    ev_code = jnp.zeros_like(opcode)
    ev_target = jnp.zeros_like(opcode)
    ev_arg = jnp.zeros_like(opcode)

    listen = op(OP_ELECT_LISTEN)
    resign = op(OP_ELECT_RESIGN)
    am_leader = el == a
    vacant = el == -1
    win_now = listen & vacant
    el = jnp.where(win_now, a, el)
    ep = jnp.where(win_now, index, ep)
    do_res = resign & am_leader
    Wl = eid.shape[-1]
    if Wl > 0:
        # compact out unlisted waiters (same discipline as the lock ring)
        e_pos = _ring_pos(eh, Wl)
        e_in = e_pos < es[..., None]
        e_live_win = elv & e_in
        e_dead = is_el & jnp.any(e_in & ~elv, axis=-1)
        eh, es, elv, (eid,) = _ring_compact(
            e_dead, eh, es, e_pos, elv, e_live_win, eid)

        e_pos2 = _ring_pos(eh, Wl)
        e_in2 = e_pos2 < es[..., None]
        listed = jnp.any(elv & e_in2 & (eid == a[..., None]), axis=-1)

        # a retried listen by the sitting leader or a queued waiter is
        # idempotent — no duplicate ring entry
        el_q = listen & ~vacant & ~am_leader & ~listed & (es < Wl)
        eid = _scatter3(eid, (eh + es) % Wl, el_q, a)
        elv = _scatter3(elv, (eh + es) % Wl, el_q, jnp.ones_like(a, bool))
        es = jnp.where(el_q, es + 1, es)
        el_full = listen & ~vacant & ~am_leader & ~listed & ~el_q

        # resign by the leader promotes the next listener (FIFO
        # succession, LeaderElectionState.close:36-49); by a waiter unlists
        succ_id = _gather3(eid, eh % Wl)
        has_succ = do_res & (es > 0)
        el = jnp.where(do_res, jnp.where(has_succ, succ_id, -1), el)
        ep = jnp.where(has_succ, index, ep)
        eh = jnp.where(has_succ, eh + 1, eh)
        es = jnp.where(has_succ, es - 1, es)
        e_hit = elv & e_in2 & (eid == a[..., None])
        e_idx, e_found = _first_true(e_hit)
        elv = _scatter3(elv, e_idx, resign & ~do_res & e_found,
                        jnp.zeros_like(a, bool))

        result = jnp.where(listen, jnp.where(win_now, index,
                           jnp.where(am_leader, ep,
                           jnp.where(el_full, INT_MIN, 0))), result)
        ev_mask = ev_mask | has_succ
        ev_code = jnp.where(has_succ, EV_ELECT, ev_code)
        ev_target = jnp.where(has_succ, succ_id, ev_target)
        ev_arg = jnp.where(has_succ, index, ev_arg)
    else:
        el = jnp.where(do_res, -1, el)
        result = jnp.where(listen, jnp.where(win_now, index,
                           jnp.where(am_leader, ep, INT_MIN)), result)
    result = jnp.where(resign, do_res.astype(jnp.int32), result)
    result = jnp.where(op(OP_ELECT_IS_LEADER),
                       (am_leader & (ep == b)).astype(jnp.int32), result)
    result = jnp.where(op(OP_ELECT_LEADER), el, result)
    result = jnp.where(op(OP_ELECT_GET_EPOCH), ep, result)
    return (el, ep, eid, elv, eh, es), result, \
        (ev_mask, ev_code, ev_target, ev_arg)


def apply_multimap(mk, mv, ml, mdl, opcode, a, b, c, now, live):
    """(key, value)-pair probe table; returns ((mk, mv, ml, mdl), result).

    The reference's nested ``Map<Object, Map<Object, Commit>>``
    (``MultiMapState.java:30``) flattened to pairs: membership is per
    (key, value), removal by key drops every pair under it.
    """
    def op(code):
        return live & (opcode == code)

    is_mm = live & (opcode >= OP_MM_PUT) & (opcode <= OP_MM_CLEAR)
    result = jnp.zeros_like(opcode)
    if mk.shape[-1] == 0:
        return (mk, mv, ml, mdl), jnp.where(is_mm, INT_MIN, result)

    alive = ml & ((mdl == 0) | (mdl > now[..., None]))
    key_hit = alive & (mk == a[..., None])
    pair_hit = key_hit & (mv == b[..., None])
    pair_idx, pair_any = _first_true(pair_hit)
    free_idx, free_any = _first_true(~alive)
    key_count = jnp.sum(key_hit, axis=-1).astype(jnp.int32)
    total = jnp.sum(alive, axis=-1).astype(jnp.int32)

    put = op(OP_MM_PUT) & ~pair_any & free_any
    mk = _scatter3(mk, free_idx, put, a)
    mv = _scatter3(mv, free_idx, put, b)
    mdl = _scatter3(mdl, free_idx, put, jnp.where(c > 0, now + c, 0))
    ml = _scatter3(ml, free_idx, put, jnp.ones_like(a, bool))

    # remove-by-key drops EVERY live pair under the key in one pass
    rm_key = op(OP_MM_REMOVE)
    ml = jnp.where(rm_key[..., None] & key_hit, False, ml)
    rm_pair = op(OP_MM_REMOVE_ENTRY) & pair_any
    ml = _scatter3(ml, pair_idx, rm_pair, jnp.zeros_like(a, bool))
    ml = jnp.where(op(OP_MM_CLEAR)[..., None], False, ml)
    # lazy TTL purge on any touch, like the map kernel
    ml = jnp.where(is_mm[..., None],
                   ml & ((mdl == 0) | (mdl > now[..., None])), ml)

    result = jnp.where(op(OP_MM_PUT),
                       jnp.where(pair_any, 0,
                                 jnp.where(free_any, 1, INT_MIN)), result)
    result = jnp.where(rm_key, key_count, result)
    result = jnp.where(op(OP_MM_REMOVE_ENTRY), pair_any.astype(jnp.int32),
                       result)
    result = jnp.where(op(OP_MM_CONTAINS_KEY),
                       (key_count > 0).astype(jnp.int32), result)
    result = jnp.where(op(OP_MM_CONTAINS_ENTRY), pair_any.astype(jnp.int32),
                       result)
    result = jnp.where(op(OP_MM_CONTAINS_VALUE),
                       jnp.any(alive & (mv == a[..., None]),
                               axis=-1).astype(jnp.int32), result)
    result = jnp.where(op(OP_MM_COUNT), key_count, result)
    result = jnp.where(op(OP_MM_SIZE), total, result)
    result = jnp.where(op(OP_MM_IS_EMPTY), (total == 0).astype(jnp.int32),
                       result)
    return (mk, mv, ml, mdl), result


def apply_topic(tid, tlive, opcode, a, b, now, live):
    """Topic subscriber table + publish fan-out; returns
    ((tid, tlive), result, (ev_mask, ev_code, ev_target, ev_arg)).

    Publish emits ONE broadcast event carrying the message
    (``EV_TOPIC_MSG``, target = -1); subscribers consume the group's
    event stream and filter client-side — the reference instead pushes a
    per-session event from ``TopicState.publish`` (``TopicState.java:31``);
    the SPI path preserves that exact semantic via the CPU machine, this
    kernel is the batch-scale fan-out.
    """
    def op(code):
        return live & (opcode == code)

    is_tp = live & (opcode >= OP_TOPIC_LISTEN) & (opcode <= OP_TOPIC_COUNT)
    result = jnp.zeros_like(opcode)
    ev_mask = jnp.zeros_like(live)
    ev_code = jnp.zeros_like(opcode)
    ev_target = jnp.zeros_like(opcode)
    ev_arg = jnp.zeros_like(opcode)
    if tid.shape[-1] == 0:
        return (tid, tlive), jnp.where(is_tp, INT_MIN, result), \
            (ev_mask, ev_code, ev_target, ev_arg)

    hit = tlive & (tid == a[..., None])
    hit_idx, hit_any = _first_true(hit)
    free_idx, free_any = _first_true(~tlive)
    count = jnp.sum(tlive, axis=-1).astype(jnp.int32)

    sub = op(OP_TOPIC_LISTEN) & ~hit_any & free_any
    tid = _scatter3(tid, free_idx, sub, a)
    tlive = _scatter3(tlive, free_idx, sub, jnp.ones_like(a, bool))
    unsub = op(OP_TOPIC_UNLISTEN) & hit_any
    tlive = _scatter3(tlive, hit_idx, unsub, jnp.zeros_like(a, bool))

    pub = op(OP_TOPIC_PUB)
    result = jnp.where(op(OP_TOPIC_LISTEN),
                       jnp.where(hit_any, 0,
                                 jnp.where(free_any, 1, INT_MIN)), result)
    result = jnp.where(op(OP_TOPIC_UNLISTEN), hit_any.astype(jnp.int32),
                       result)
    result = jnp.where(pub, count, result)
    result = jnp.where(op(OP_TOPIC_COUNT), count, result)

    fan = pub & (count > 0)
    ev_mask = ev_mask | fan
    ev_code = jnp.where(fan, EV_TOPIC_MSG, ev_code)
    ev_target = jnp.where(fan, -1, ev_target)
    ev_arg = jnp.where(fan, a, ev_arg)
    return (tid, tlive), result, (ev_mask, ev_code, ev_target, ev_arg)


def push_events(res: ResourceState, ev_mask, ev_code, ev_target, ev_arg,
                ) -> ResourceState:
    """Push one event per lane (where ``ev_mask``) into the outbox ring,
    dropping the oldest on overflow."""
    E = res.ev_code.shape[-1]
    if E == 0:
        return res
    evc, evt, eva = res.ev_code, res.ev_target, res.ev_arg
    evh, evtl = res.ev_head, res.ev_tail
    overflow = ev_mask & ((evtl - evh) >= E)
    evh = jnp.where(overflow, evh + 1, evh)  # drop oldest
    slot = evtl % E
    evc = _scatter3(evc, slot, ev_mask, ev_code)
    evt = _scatter3(evt, slot, ev_mask, ev_target)
    eva = _scatter3(eva, slot, ev_mask, ev_arg)
    evtl = jnp.where(ev_mask, evtl + 1, evtl)
    return res._replace(ev_code=evc, ev_target=evt, ev_arg=eva,
                        ev_head=evh, ev_tail=evtl)


# ---------------------------------------------------------------------------
# the apply kernel
# ---------------------------------------------------------------------------

def apply_entry(
    res: ResourceState,
    opcode: jnp.ndarray,  # [G,P] i32
    a: jnp.ndarray,       # [G,P] i32
    b: jnp.ndarray,       # [G,P] i32
    c: jnp.ndarray,       # [G,P] i32
    index: jnp.ndarray,   # [G,P] i32 — absolute log index of this entry
    now: jnp.ndarray,     # [G,P] i32 — entry's logical timestamp
    live: jnp.ndarray,    # [G,P] bool — entry exists and is being applied
) -> tuple[ResourceState, jnp.ndarray]:
    """Apply one committed entry per (group, replica) lane.

    Composition of the six per-pool kernels (an entry belongs to exactly
    one pool, so the untouched pools pass through unchanged — XLA elides
    them). The step's hot path instead folds each pool separately
    (:func:`apply_window`); this composed form serves the query lane,
    single-entry callers and the differential tests.

    Returns ``(new_state, result)`` where ``result`` is the int32 command
    response for the lane (meaningful only where ``live``). Session events
    are pushed into the state's event ring.
    """
    (value, val_dl), r_val = apply_value(
        res.value, res.val_dl, opcode, a, b, c, now, live)
    (mk, mv, ml, mdl), r_map = apply_map(
        res.map_key, res.map_val, res.map_live, res.map_dl,
        opcode, a, b, c, now, live)
    (sk, sl, sdl), r_set = apply_set(
        res.set_key, res.set_live, res.set_dl, opcode, a, b, c, now, live)
    (qv, qh, qs), r_q = apply_queue(
        res.q_val, res.q_head, res.q_size, opcode, a, b, c, now, live)
    (holder, wid, wdl, wlv, lh, ls), r_lock, ev_lock = apply_lock(
        res.lk_holder, res.lk_wait_id, res.lk_wait_dl, res.lk_wait_live,
        res.lk_head, res.lk_size, opcode, a, b, now, live)
    (el, ep, eid, elv, eh, es), r_el, ev_el = apply_elect(
        res.el_leader, res.el_epoch, res.el_id, res.el_live,
        res.el_head, res.el_size, opcode, a, b, index, live)
    (mmk, mmv, mml, mmdl), r_mm = apply_multimap(
        res.mm_key, res.mm_val, res.mm_live, res.mm_dl,
        opcode, a, b, c, now, live)
    (tid, tlv), r_tp, ev_tp = apply_topic(
        res.tp_id, res.tp_live, opcode, a, b, now, live)

    # exactly one pool claims each opcode, so results merge by sum of the
    # disjoint contributions
    result = r_val + r_map + r_set + r_q + r_lock + r_el + r_mm + r_tp

    res = res._replace(
        value=value, val_dl=val_dl,
        map_key=mk, map_val=mv, map_live=ml, map_dl=mdl,
        set_key=sk, set_live=sl, set_dl=sdl,
        q_val=qv, q_head=qh, q_size=qs,
        lk_holder=holder, lk_wait_id=wid, lk_wait_dl=wdl, lk_wait_live=wlv,
        lk_head=lh, lk_size=ls,
        el_leader=el, el_epoch=ep, el_id=eid, el_live=elv, el_head=eh,
        el_size=es,
        mm_key=mmk, mm_val=mmv, mm_live=mml, mm_dl=mmdl,
        tp_id=tid, tp_live=tlv)

    # grant/elect/topic are mutually exclusive across opcodes: ≤1 event
    ev_mask = ev_lock[0] | ev_el[0] | ev_tp[0]
    pick = lambda i: jnp.where(ev_lock[0], ev_lock[i],
                               jnp.where(ev_el[0], ev_el[i], ev_tp[i]))
    return push_events(res, ev_mask, pick(1), pick(2), pick(3)), result


def push_events_window(res: ResourceState, mask: jnp.ndarray,
                       code: jnp.ndarray, target: jnp.ndarray,
                       arg: jnp.ndarray) -> ResourceState:
    """Push a window of per-lane event candidates (``[G,P,A]``, ≤1 event
    per window position, ordered by position = log order) into the outbox
    ring in ONE fused pass per ring array, dropping the oldest entries on
    overflow — bit-identical ring evolution to pushing the events one
    entry at a time in log order."""
    E = res.ev_code.shape[-1]
    if E == 0 or mask.shape[-1] == 0:
        return res
    evh, evtl = res.ev_head, res.ev_tail
    count = mask.sum(axis=-1, dtype=jnp.int32)             # [G,P]
    off = jnp.cumsum(mask, axis=-1, dtype=jnp.int32) - mask  # exclusive
    # If the window somehow carries more events than the ring holds, only
    # the LAST E survive (same drop-oldest outcome as sequential pushes)
    # — also guarantees distinct slots below, so the one-hot sum is exact.
    mask = mask & (off >= count[..., None] - E)
    slot = (evtl[..., None] + off) % E                     # [G,P,A]
    hit = (slot[..., None] == jnp.arange(E, dtype=jnp.int32)) \
        & mask[..., None]                                  # [G,P,A,E]
    any_hit = hit.any(axis=2)                              # [G,P,E]

    def write(ring, vals):
        filled = jnp.where(hit, vals[..., None], 0).sum(axis=2)
        return jnp.where(any_hit, filled.astype(ring.dtype), ring)

    new_tail = evtl + count
    new_head = jnp.maximum(evh, new_tail - E)              # drop-oldest
    return res._replace(
        ev_code=write(res.ev_code, code),
        ev_target=write(res.ev_target, target),
        ev_arg=write(res.ev_arg, arg),
        ev_head=new_head, ev_tail=new_tail)


def apply_window(
    res: ResourceState,
    opcode: jnp.ndarray,  # [G,P,A] window-position-major entry fields
    a: jnp.ndarray,
    b: jnp.ndarray,
    c: jnp.ndarray,
    index: jnp.ndarray,   # [G,P,A] absolute log indexes (contiguous)
    now: jnp.ndarray,     # [G,P,A] entry timestamps
    do: jnp.ndarray,      # [G,P,A] bool — within this round's commit budget
    budgets: tuple,       # per-pool applies admitted per round (len 6, ≥1)
) -> tuple[ResourceState, jnp.ndarray, jnp.ndarray]:
    """Conflict-partitioned apply of a contiguous window of ≤A entries.

    The legacy formulation scanned ``apply_entry`` A times, dragging EVERY
    pool's state through HBM per iteration — ~95% of the mixed-scenario
    round (PERF.md "Known next bottleneck"). Entries in different pools
    commute (disjoint state), so here each pool folds only ITS entries —
    compacted to ``budgets[k]`` scan iterations over only that pool's
    arrays. Log order is preserved within each pool (the only order that
    matters); the admitted window is the longest prefix in which no pool
    exceeds its budget, so a lane never applies entry j before j-1.

    Returns ``(new_res, result [G,P,A], admitted [G,P,A])`` — results are
    positioned at their window slots; non-admitted entries stay pending
    for the next round (exactly like the existing per-round A budget).

    Events are scattered back to their window positions and pushed in log
    order (``push_events_window``), so the outbox ring evolves
    bit-identically to the sequential formulation.
    """
    A = opcode.shape[-1]
    pool = pool_of(jnp.where(do, opcode, -1))  # !do → POOL_NONE (opcode -1)
    is_pool = [(pool == k) for k in range(NUM_POOLS)]

    # Longest prefix in which every pool stays within budget.
    admitted = do
    rank = []
    for k in range(NUM_POOLS):
        cum = jnp.cumsum(is_pool[k].astype(jnp.int32), axis=-1)
        rank.append(jnp.where(is_pool[k], cum - 1, A))
        if budgets[k] < A:
            admitted = admitted & jnp.where(is_pool[k],
                                            cum <= budgets[k], True)
    admitted = jnp.cumprod(admitted.astype(jnp.int32), axis=-1).astype(bool)

    result = jnp.zeros_like(opcode)

    def fold(kernel, state_arrays, k, n_out):
        """Scan ``kernel`` over pool k's ≤budgets[k] compacted entries,
        carrying only ``state_arrays``. Returns (state, result
        contribution [G,P,A], events scattered to window positions —
        (mask, code, target, arg) each [G,P,A], or None).

        When the budget covers the whole window (B >= A), compaction
        would be the identity up to padding — skip it and iterate the
        window positions directly (zero overhead vs the legacy scan)."""
        B = min(budgets[k], A)
        sel = admitted & is_pool[k]
        if B >= A:
            oh = None
            live_b = sel
            fields = (opcode, a, b, c, index, now)
        else:
            oh = (rank[k][..., None] == jnp.arange(B, dtype=jnp.int32)) \
                & sel[..., None]                              # [G,P,A,B]
            pick = lambda arr: jnp.where(oh, arr[..., None], 0).sum(axis=2)
            live_b = jnp.any(oh, axis=2)                      # [G,P,B]
            fields = tuple(pick(f) for f in (opcode, a, b, c, index, now))
        xs = jax.tree.map(lambda x: jnp.moveaxis(x, -1, 0),   # [B,G,P]
                          fields + (live_b,))

        def body(st, x):
            op_i, a_i, b_i, c_i, idx_i, now_i, live_i = x
            out = kernel(*st, op_i, a_i, b_i, c_i, idx_i, now_i, live_i)
            return out[0], out[1:]
        # Full unroll: lax.scan blocks cross-iteration fusion, and with
        # only ONE pool's arrays in the carry, XLA fuses the unrolled
        # iterations into far fewer passes over that pool's HBM.
        state, outs = jax.lax.scan(body, state_arrays, xs, unroll=True)

        def unpick(stacked):  # [B,G,P] -> [G,P,A] at window positions
            by_slot = jnp.moveaxis(stacked, 0, -1)            # [G,P,B]
            if oh is None:
                return by_slot
            return jnp.where(oh, by_slot[..., None, :], 0).sum(axis=-1)

        contribution = unpick(outs[0])
        events = None
        if n_out > 1:
            events = tuple(unpick(x) for x in outs[1])
        return state, contribution, events

    # adapters: uniform (state..., op, a, b, c, index, now, live) signature
    k_val = lambda v, dl, op_, a_, b_, c_, i_, n_, lv: \
        apply_value(v, dl, op_, a_, b_, c_, n_, lv)
    k_map = lambda mk, mv, ml, mdl, op_, a_, b_, c_, i_, n_, lv: \
        apply_map(mk, mv, ml, mdl, op_, a_, b_, c_, n_, lv)
    k_set = lambda sk, sl, sdl, op_, a_, b_, c_, i_, n_, lv: \
        apply_set(sk, sl, sdl, op_, a_, b_, c_, n_, lv)
    k_q = lambda qv, qh, qs, op_, a_, b_, c_, i_, n_, lv: \
        apply_queue(qv, qh, qs, op_, a_, b_, c_, n_, lv)
    k_lock = lambda h, wi, wd, wl, lh, ls, op_, a_, b_, c_, i_, n_, lv: \
        apply_lock(h, wi, wd, wl, lh, ls, op_, a_, b_, n_, lv)
    k_el = lambda el, ep, ei, el_, eh, es, op_, a_, b_, c_, i_, n_, lv: \
        apply_elect(el, ep, ei, el_, eh, es, op_, a_, b_, i_, lv)
    k_mm = lambda mk_, mv_, ml_, md_, op_, a_, b_, c_, i_, n_, lv: \
        apply_multimap(mk_, mv_, ml_, md_, op_, a_, b_, c_, n_, lv)
    k_tp = lambda ti, tl, op_, a_, b_, c_, i_, n_, lv: \
        apply_topic(ti, tl, op_, a_, b_, n_, lv)

    (value, val_dl), r, _ = fold(
        k_val, (res.value, res.val_dl), POOL_VALUE, 1)
    result = result + r
    (mk, mv, ml, mdl), r, _ = fold(
        k_map, (res.map_key, res.map_val, res.map_live, res.map_dl),
        POOL_MAP, 1)
    result = result + r
    (sk, sl, sdl), r, _ = fold(
        k_set, (res.set_key, res.set_live, res.set_dl), POOL_SET, 1)
    result = result + r
    (qv, qh, qs), r, _ = fold(
        k_q, (res.q_val, res.q_head, res.q_size), POOL_QUEUE, 1)
    result = result + r
    (holder, wid, wdl, wlv, lh, ls), r, ev_lock = fold(
        k_lock, (res.lk_holder, res.lk_wait_id, res.lk_wait_dl,
                 res.lk_wait_live, res.lk_head, res.lk_size),
        POOL_LOCK, 2)
    result = result + r
    (el, ep, eid, elv, eh, es), r, ev_el = fold(
        k_el, (res.el_leader, res.el_epoch, res.el_id, res.el_live,
               res.el_head, res.el_size), POOL_ELECT, 2)
    result = result + r
    (mmk, mmv, mml, mmdl), r, _ = fold(
        k_mm, (res.mm_key, res.mm_val, res.mm_live, res.mm_dl),
        POOL_MMAP, 1)
    result = result + r
    (tid, tlv), r, ev_tp = fold(
        k_tp, (res.tp_id, res.tp_live), POOL_TOPIC, 2)
    result = result + r

    res = res._replace(
        value=value, val_dl=val_dl,
        map_key=mk, map_val=mv, map_live=ml, map_dl=mdl,
        set_key=sk, set_live=sl, set_dl=sdl,
        q_val=qv, q_head=qh, q_size=qs,
        lk_holder=holder, lk_wait_id=wid, lk_wait_dl=wdl, lk_wait_live=wlv,
        lk_head=lh, lk_size=ls,
        el_leader=el, el_epoch=ep, el_id=eid, el_live=elv, el_head=eh,
        el_size=es,
        mm_key=mmk, mm_val=mmv, mm_live=mml, mm_dl=mmdl,
        tp_id=tid, tp_live=tlv)
    # Merge the event-producing pools by window position (disjoint — an
    # entry belongs to one pool) and push in log order.
    ev_mask = ev_lock[0].astype(bool) | ev_el[0].astype(bool) \
        | ev_tp[0].astype(bool)
    res = push_events_window(res, ev_mask,
                             ev_lock[1] + ev_el[1] + ev_tp[1],
                             ev_lock[2] + ev_el[2] + ev_tp[2],
                             ev_lock[3] + ev_el[3] + ev_tp[3])
    return res, result, admitted


def drain_events(res: ResourceState, n: int, mask: jnp.ndarray
                 ) -> tuple[ResourceState, tuple[jnp.ndarray, ...]]:
    """Pop up to ``n`` oldest events from each lane's outbox ring where
    ``mask`` ([G] bool — group has an active leader) holds.

    Returns ``(new_state, (seq, code, target, arg, valid))``, each
    ``[G,P,n]``. Lanes of a group pop in lockstep (deterministic); the
    caller harvests the leader lane and dedups by absolute ``seq``. Gating
    on an active leader means events emitted during leaderless rounds stay
    queued until someone can deliver them (at-least-once).
    """
    E = res.ev_code.shape[-1]
    G, P = res.ev_head.shape
    if E == 0 or n == 0:
        z = jnp.zeros((G, P, n), jnp.int32)
        return res, (z, z, z, z, jnp.zeros((G, P, n), bool))
    evh, evtl = res.ev_head, res.ev_tail
    lane_mask = mask[:, None]
    seqs, codes, targets, args, valids = [], [], [], [], []
    for i in range(n):
        seq = evh + i
        ok = lane_mask & (seq < evtl)
        slot = seq % E
        seqs.append(seq)
        codes.append(jnp.where(ok, _gather3(res.ev_code, slot), 0))
        targets.append(jnp.where(ok, _gather3(res.ev_target, slot), 0))
        args.append(jnp.where(ok, _gather3(res.ev_arg, slot), 0))
        valids.append(ok)
    new_head = jnp.where(lane_mask, jnp.minimum(evh + n, evtl), evh)
    out = tuple(jnp.stack(x, axis=-1) for x in
                (seqs, codes, targets, args, valids))
    return res._replace(ev_head=new_head), out
