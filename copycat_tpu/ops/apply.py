"""Vectorized state-machine apply kernels.

The reference applies one commit at a time through per-resource executors
(``ResourceManager.operateResource``, ``ResourceManager.java:56``;
``AtomicValueState.java:32``). Here the same op semantics are data — an
opcode plus two int32 arguments — applied to ALL groups' replicas at once
with ``jnp.where`` masking, so XLA vectorizes the apply across the
``[num_groups, num_peers]`` batch instead of dispatching per commit.

Only fixed-width state lives on device. Arbitrary Python payloads take the
CPU oracle path (``copycat_tpu.server``); the device path covers the hot,
fixed-shape resource kernels (BASELINE.md configs).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

# --- opcodes (device-path operation catalog) -------------------------------
# Mirrors the reference's serializer-id catalogs (AtomicValueCommands ids
# 50-55 etc.) as a dense opcode space.
OP_NOP = 0
OP_VALUE_SET = 1
OP_VALUE_GET = 2
OP_VALUE_CAS = 3          # a=expect, b=update -> result: 1 if swapped else 0
OP_VALUE_GET_AND_SET = 4  # a=update -> result: previous value
OP_LONG_ADD = 5           # a=delta -> result: new value (addAndGet)


class ResourceState(NamedTuple):
    """Per-group, per-replica device-resident resource state.

    Every field is ``[num_groups, num_peers, ...]``: each replica applies the
    same committed ops in the same order, so replica states stay identical —
    exactly the reference's replicated-state-machine discipline, kept as a
    batch dimension so divergence is *testable* (see tests).
    """

    value: jnp.ndarray  # [G, P] int32 — AtomicValue/AtomicLong register


def init_resources(num_groups: int, num_peers: int) -> ResourceState:
    return ResourceState(
        value=jnp.zeros((num_groups, num_peers), jnp.int32),
    )


def apply_entry(
    res: ResourceState,
    opcode: jnp.ndarray,  # [G, P] int32
    a: jnp.ndarray,       # [G, P] int32
    b: jnp.ndarray,       # [G, P] int32
    live: jnp.ndarray,    # [G, P] bool — entry exists and is being applied
) -> tuple[ResourceState, jnp.ndarray]:
    """Apply one committed entry per (group, replica) lane.

    Returns ``(new_state, result)`` where ``result`` is the int32 command
    response for the lane (meaningful only where ``live``).
    """
    value = res.value

    is_set = live & (opcode == OP_VALUE_SET)
    is_get = live & (opcode == OP_VALUE_GET)
    is_cas = live & (opcode == OP_VALUE_CAS)
    is_gas = live & (opcode == OP_VALUE_GET_AND_SET)
    is_add = live & (opcode == OP_LONG_ADD)

    cas_hit = is_cas & (value == a)

    new_value = value
    new_value = jnp.where(is_set, a, new_value)
    new_value = jnp.where(cas_hit, b, new_value)
    new_value = jnp.where(is_gas, a, new_value)
    new_value = jnp.where(is_add, value + a, new_value)

    result = jnp.zeros_like(value)
    result = jnp.where(is_get, value, result)
    result = jnp.where(is_cas, cas_hit.astype(jnp.int32), result)
    result = jnp.where(is_gas, value, result)
    result = jnp.where(is_add, new_value, result)

    return res._replace(value=new_value), result
