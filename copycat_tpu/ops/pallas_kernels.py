"""Pallas TPU kernels for the consensus hot path.

The per-round tally that advances ``commitIndex`` — the k-th largest
``matchIndex`` across the peer axis (Raft's quorum median; BASELINE.json's
"quorum-vote tally / commitIndex advance" lift) — is computed here as a
blocked Pallas kernel instead of ``jnp.sort``:

- layout is ``[P, G]`` so the huge group axis rides the 128-wide vector
  lanes and the tiny peer axis (3/5/7) sits in sublanes;
- selection is ``k-1`` rounds of masked max-extraction (P and k are
  static), all in VMEM registers — no general sort network;
- the same closed-form selection is also provided as a pure-jnp reference
  (``kth_largest``), the default path and the differential-test oracle.

On CPU the kernel runs in interpreter mode (tests); on TPU it compiles to
Mosaic. Gate via ``Config.use_pallas`` (``ops.consensus``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

INT_MIN = jnp.iinfo(jnp.int32).min


def kth_largest(x: jnp.ndarray, k: int) -> jnp.ndarray:
    """k-th largest along axis 1 of ``x [G, P]`` (k is 1-based), in jnp.

    Masked max-extraction — O(k·P) elementwise ops, no sort. The oracle
    for the Pallas kernel and the default consensus path.
    """
    m = x
    for _ in range(k - 1):
        mx = jnp.max(m, axis=1, keepdims=True)
        is_mx = m == mx
        first = (jnp.cumsum(is_mx.astype(jnp.int32), axis=1) == 1) & is_mx
        m = jnp.where(first, INT_MIN, m)
    return jnp.max(m, axis=1)


def kth_largest_masked(x: jnp.ndarray, mask: jnp.ndarray,
                       k: jnp.ndarray) -> jnp.ndarray:
    """k-th largest of ``x [G, P]`` among ``mask [G, P]`` lanes, with a
    PER-GROUP dynamic ``k [G]`` (1-based).

    The dynamic-membership quorum tally: masked-out (non-member) lanes are
    excluded, and k varies per group (``count//2 + 1`` of each group's
    member count). Static-k masked max-extraction can't express a traced
    k, so this uses the same O(P²) pairwise rank-select as the Pallas
    kernel — each element's tie-broken descending rank is unique, and
    exactly one element matches rank k-1 (provided k ≤ member count,
    which quorum-of-members guarantees).
    """
    P = x.shape[1]
    xm = jnp.where(mask, x, INT_MIN)
    r_val = xm[:, :, None]                    # element r   [G,P,1]
    s_val = xm[:, None, :]                    # vs s        [G,1,P]
    r_idx = jnp.arange(P, dtype=jnp.int32)[None, :, None]
    s_idx = jnp.arange(P, dtype=jnp.int32)[None, None, :]
    beats = (s_val > r_val) | ((s_val == r_val) & (s_idx < r_idx))
    rank = jnp.sum(beats.astype(jnp.int32), axis=2)          # [G,P]
    sel = rank == (k - 1)[:, None]
    return jnp.sum(jnp.where(sel, xm, 0), axis=1)


def _kth_kernel(x_ref, out_ref, *, k: int):
    """Block kernel: x [P, BG] -> out [1, BG] (k-th largest over axis 0).

    Rank-select instead of sort or masked max-extraction: Mosaic has no
    cumsum lowering, so each row's tie-broken descending rank is computed
    with O(P²) pairwise compares (P is 3-7) and exactly one row matches
    rank k-1.
    """
    m = x_ref[...]
    P = m.shape[0]
    r_val = m[:, None, :]                     # row r        [P,1,BG]
    s_val = m[None, :, :]                     # vs row s     [1,P,BG]
    r_idx = jax.lax.broadcasted_iota(jnp.int32, (P, P, 1), 0)
    s_idx = jax.lax.broadcasted_iota(jnp.int32, (P, P, 1), 1)
    beats = (s_val > r_val) | ((s_val == r_val) & (s_idx < r_idx))
    rank = jnp.sum(beats.astype(jnp.int32), axis=1)  # [P,BG]
    sel = rank == (k - 1)
    out_ref[...] = jnp.sum(jnp.where(sel, m, 0), axis=0, keepdims=True)


@functools.partial(jax.jit, static_argnames=("k", "block", "interpret"))
def kth_largest_pallas(x: jnp.ndarray, k: int, block: int = 512,
                       interpret: bool | None = None) -> jnp.ndarray:
    """k-th largest along axis 1 of ``x [G, P]`` via a Pallas TPU kernel."""
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    G, P = x.shape
    Gp = (G + block - 1) // block * block
    xt = jnp.transpose(x)  # [P, G] — groups on the lane axis in the kernel
    if Gp != G:
        xt = jnp.pad(xt, ((0, 0), (0, Gp - G)), constant_values=INT_MIN)

    out = pl.pallas_call(
        functools.partial(_kth_kernel, k=k),
        grid=(Gp // block,),
        in_specs=[pl.BlockSpec((P, block), lambda i: (0, i))],
        out_specs=pl.BlockSpec((1, block), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, Gp), x.dtype),
        interpret=interpret,
    )(xt)
    return out[0, :G]
