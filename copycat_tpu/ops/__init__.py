"""TPU-native consensus kernels.

All Raft groups are batched into fixed-shape ``[num_groups, num_peers]``
tensors and stepped as ONE jitted XLA program per synchronous round:
election vote tallies, AppendEntries log-matching, quorum commit advance,
and vectorized state-machine apply (SURVEY.md §7.1).
"""

from .consensus import (  # noqa: F401
    CANDIDATE,
    FOLLOWER,
    LEADER,
    DeviceTelemetry,
    RaftState,
    StepOutputs,
    Submits,
    init_state,
    make_submits,
    step,
)
from . import apply  # noqa: F401
