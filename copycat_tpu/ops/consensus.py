"""Batched Raft consensus as one jitted XLA step.

The reference's consensus core (external Copycat, consumed per SURVEY.md §2.3)
runs one Raft group per server over asyncio-style RPC. Here ALL groups run at
once: state is ``[num_groups, num_peers]`` tensors and one ``step()`` call
advances every group by one synchronous message round —

1. client ops are injected into leader logs,
2. leaders send AppendEntries (log-matching check, ring-buffer entry copy),
3. acks update matchIndex, quorum sort advances commitIndex,
4. election timers fire, RequestVote tallies elect leaders,
5. committed entries are applied through the vectorized resource kernels.

Quorum tallies are sums over the peer axis; when the peer axis is sharded
over a ``jax.sharding.Mesh`` those sums become ICI collectives (XLA inserts
them from the sharding annotations — see ``copycat_tpu.parallel``).

Message loss is first-class: ``deliver[g, from, to]`` masks every exchange,
so partitions/nemesis run *inside* the compiled step (SURVEY.md §4's
"real consensus, fake network" strategy, on device).

Safety properties preserved (tested in tests/test_tpu_consensus.py):
 - election safety: ≤1 leader per (group, term) — single ``voted_for`` per
   voter per term, deterministic lowest-index tie-break among candidates;
 - log matching: AppendEntries carries (prevIndex, prevTerm); mismatch
   rejects and rewinds nextIndex;
 - leader completeness: vote granted only to candidates with up-to-date
   logs (last term, last index) ≥ voter's;
 - commit safety: commitIndex advances only onto entries of the leader's
   current term (Raft §5.4.2 — a fresh leader appends a NoOp to unlock).

The log is a fixed-capacity ring per replica (SURVEY.md §5.7): slot(i) =
(i-1) mod L. Followers lagging beyond the ring window are flagged ``stale``
and stop receiving (snapshot install catches them up — see
``models/raft_groups.py``).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from .apply import (
    NUM_POOLS,
    OP_CFG_ADD,
    OP_CFG_REMOVE,
    ResourceConfig,
    ResourceState,
    _gather3,
    apply_entry,
    apply_window,
    drain_events,
    init_resources,
    pool_of,
)

FOLLOWER, CANDIDATE, LEADER = 0, 1, 2


class DeviceTelemetry(NamedTuple):
    """Per-group on-device telemetry deltas for ONE consensus round.

    Every leaf is ``[G]`` i32 (``applies`` is ``[G, NUM_POOLS+1]``) —
    deliberately group-leading and group-local: on a group-sharded mesh
    each value reduces only over the peer/slot axes of its own shard, so
    the telemetry block compiles to ZERO cross-device collectives (the
    same rule the deep accumulators follow — a scalar total here would
    be the one all-reduce in the program). The host sums over G.

    Derived entirely from values the step already computes — no extra
    RNG, no state writes — so the telemetry-off step is bit-identical
    to a tree without the block (``Config.telemetry`` is static; off
    compiles it out entirely and ``StepOutputs.telemetry`` is None).
    """

    elections_started: jnp.ndarray  # lanes whose timer fired this round
    leader_changes: jnp.ndarray     # election won by a lane != round-start
    #                                 leader (or the group was leaderless)
    term_bumps: jnp.ndarray         # delta of the group-max term
    leaderless: jnp.ndarray         # 1 iff no leader at round start
    commit_advance: jnp.ndarray     # delta of the group-max commit index
    commit_max: jnp.ndarray         # post-round max commit index (monotone
    #                                 — the invariant monitor's witness)
    term_max: jnp.ndarray           # post-round max term over lanes
    leader_lane: jnp.ndarray        # post-round leader lane (-1 none) —
    leader_term: jnp.ndarray        # paired with its term (-1 none): the
    #                                 watch-list's ≤1-leader-per-term feed
    applies: jnp.ndarray            # [G, NUM_POOLS+1] entries applied by
    #                                 the reporting lane, by resource pool
    #                                 (last column = NoOp/config entries)
    ring_occ_max: jnp.ndarray       # max over lanes of last-applied
    submit_rejections: jnp.ndarray  # valid slots rejected (backpressure,
    #                                 lease/tag gate) — requeued, not lost
    vote_splits: jnp.ndarray        # 1 iff candidates existed and nobody won
    events_drained: jnp.ndarray     # leader-lane outbox events popped
    events_dropped: jnp.ndarray     # outbox ring drop-oldest overwrites


class RaftState(NamedTuple):
    """Device-resident replicated state for G groups × P peers."""

    term: jnp.ndarray          # [G,P] i32
    voted_for: jnp.ndarray     # [G,P] i32, -1 = none
    role: jnp.ndarray          # [G,P] i32 ∈ {FOLLOWER, CANDIDATE, LEADER}
    leader_hint: jnp.ndarray   # [G,P] i32 peer index, -1 = unknown
    timer: jnp.ndarray         # [G,P] i32 rounds until election timeout
    clock: jnp.ndarray         # [G,P] i32 logical round clock (replicated —
    #                            identical in every lane; stamps log entries
    #                            so TTL/timeout evaluation is deterministic)
    last_index: jnp.ndarray    # [G,P] i32
    commit_index: jnp.ndarray  # [G,P] i32
    applied_index: jnp.ndarray  # [G,P] i32
    next_index: jnp.ndarray    # [G,P,P] i32 (axis1 = owner-as-leader, axis2 = target)
    match_index: jnp.ndarray   # [G,P,P] i32
    log_term: jnp.ndarray      # [G,P,L] i32 ring
    log_op: jnp.ndarray        # [G,P,L] i32 opcode
    log_a: jnp.ndarray         # [G,P,L] i32 arg
    log_b: jnp.ndarray         # [G,P,L] i32 arg
    log_c: jnp.ndarray         # [G,P,L] i32 arg
    log_time: jnp.ndarray      # [G,P,L] i32 logical timestamp at append
    log_tag: jnp.ndarray       # [G,P,L] i32 host correlation tag
    resources: ResourceState
    # Leader lease (appended last — checkpoint leaf padding relies on new
    # fields being strictly trailing): True iff the current leader
    # received same-term acks from a QUORUM in the latest round. Sound in
    # the synchronous round model: a competing leader elected by round R
    # needs a majority of voters at a higher term, any quorum of
    # same-term acks must intersect that majority, and the intersecting
    # node's higher-term reject would have cleared the lease — so a held
    # lease proves no other leader could have committed anything yet,
    # which is exactly the freshness BOUNDED_LINEARIZABLE reads need
    # (reference Consistency.java:157-176) without a log append.
    lease: jnp.ndarray         # [G,P] bool (replicated per lane)
    # Voting membership as of each lane's APPLIED prefix, a bitmask over
    # peer lanes (bit p = lane p votes). Config entries carry the FULL
    # new config (the leader composes the bitmask at append from its
    # current view — Raft §4.1's C_new entries), and a lane's ACTIVE view
    # is derived per round as the latest config entry in its log —
    # adopted at append, reverted on truncation — falling back to this
    # applied mask (Raft's "latest configuration in the log" rule; the
    # applied prefix is immutable, so the fallback is always available).
    # Single-server changes at a time (step-enforced at append) keep any
    # two adjacent configs quorum-intersecting. All-ones unless
    # ``Config.dynamic_membership`` — the static path never reads it.
    member: jnp.ndarray        # [G,P] i32 bitmask


class Submits(NamedTuple):
    """Client ops to inject this round, S slots per group."""

    opcode: jnp.ndarray  # [G,S] i32
    a: jnp.ndarray       # [G,S] i32
    b: jnp.ndarray       # [G,S] i32
    c: jnp.ndarray       # [G,S] i32
    tag: jnp.ndarray     # [G,S] i32
    valid: jnp.ndarray   # [G,S] bool


class StepOutputs(NamedTuple):
    accepted: jnp.ndarray    # [G,S] bool — submit made it into the leader log
    # Results are reported from the MOST-ADVANCED lane (argmax post-apply
    # applied_index), not the leader lane: an entry applied during a
    # leaderless round would otherwise never be reported (its result is
    # not re-derivable later). Every entry is applied by that lane in the
    # first round the global max applied_index passes it; re-reports from
    # lanes catching up later are possible (at-least-once) — consumers
    # dedup by tag (models/raft_groups.py _harvest pops _inflight).
    out_valid: jnp.ndarray   # [G,A] bool — a command applied this round
    out_tag: jnp.ndarray     # [G,A] i32
    out_result: jnp.ndarray  # [G,A] i32
    out_latency: jnp.ndarray  # [G,A] i32 rounds from log append to apply
    #                           (commit latency in logical rounds —
    #                           BASELINE.md p99 metric)
    leader: jnp.ndarray      # [G] i32 leader peer at round start (-1 none)
    commit_index: jnp.ndarray  # [G] i32 leader commit after the round
    stale: jnp.ndarray       # [G,P] bool — lagging beyond ring window
    clock: jnp.ndarray       # [G] i32 post-step logical clock
    # session events drained from the leader lane's outbox ring; host dedups
    # by seq (at-least-once across leader changes)
    ev_seq: jnp.ndarray      # [G,D] i32
    ev_code: jnp.ndarray     # [G,D] i32
    ev_target: jnp.ndarray   # [G,D] i32
    ev_arg: jnp.ndarray      # [G,D] i32
    ev_valid: jnp.ndarray    # [G,D] bool
    # (index, term) each accepted submit landed at / each applied entry
    # came from. Together these give the host PROVABLE loss detection for
    # exactly-once retry without any kernel dedup state (the device-path
    # analogue of the reference's session-sequenced resubmit, Copycat
    # client runtime per SURVEY §2.3): a pending entry (idx, term_e) is
    # certainly lost once an entry with term T > term_e is applied at any
    # index j ≤ idx — log terms are monotone within a log, so the log that
    # held the pending entry had term ≤ term_e < T at j and can never be
    # the committed log; re-submitting cannot double-apply. (idx == j with
    # a different tag is the special case T != term_e of the same rule.)
    assigned: jnp.ndarray       # [G,S] i32 (0 where not accepted)
    assigned_term: jnp.ndarray  # [G,S] i32
    out_index: jnp.ndarray      # [G,A] i32 (0 where not out_valid)
    out_term: jnp.ndarray       # [G,A] i32
    # POST-round leader term (-1 when leaderless): the host gates new
    # submissions for a group while any accepted op's append term is
    # older than this (the op's fate is uncertain across the leader
    # change) — preserving per-group FIFO completion, the reference's
    # session program-order guarantee. Post-round (not round-start) so
    # the gate engages before anything can be drained into a fresh
    # leader's log.
    leader_term: jnp.ndarray    # [G] i32
    # Submit slots rejected PERMANENTLY (a config change that would
    # empty the group): the host fails them to the client immediately
    # instead of requeueing — a forever-retrying config op would block
    # its group's whole queue behind the FIFO suffix-reject.
    refused: jnp.ndarray        # [G,S] bool
    # Per-group telemetry deltas (:class:`DeviceTelemetry`) when
    # ``Config.telemetry`` — None otherwise (a None pytree subtree costs
    # nothing to carry, stack, or fetch). Trailing with a default so
    # every existing positional constructor stays valid.
    telemetry: Any = None


class Config(NamedTuple):
    """Static step configuration (hashable → usable as a jit static arg)."""

    append_window: int = 4    # entries per AppendEntries per round
    applies_per_round: int = 4
    # Per-pool apply budgets (value, map, set, queue, lock, election):
    # the apply phase folds each pool's entries separately, carrying only
    # that pool's arrays — entries in different pools commute — and admits
    # the longest window prefix in which no pool exceeds its budget
    # (apply.py apply_window; PERF.md "conflict-partitioned apply").
    # None = every pool gets the full applies_per_round budget. For mixed
    # workloads where each round touches each pool once or twice, small
    # budgets for the big pools (map/set/queue/lock/election) cut the
    # apply phase's HBM traffic by ~budget/A.
    pool_budgets: tuple | None = None
    timer_min: int = 4        # election timeout in rounds (randomized range)
    timer_max: int = 9
    events_per_round: int = 4  # outbox events drained per step
    resource: ResourceConfig = ResourceConfig()
    use_pallas: bool = False  # Pallas quorum-tally kernel (TPU hot path)
    # Per-group dynamic voter membership (server join/leave — reference
    # AtomixServerTest.testServerJoin/testServerLeave). When True, quorum
    # tallies count only each lane's ``RaftState.member`` view (dynamic
    # per-group quorum via rank-select), non-member lanes neither
    # campaign nor receive AppendEntries, and OP_CFG_ADD/REMOVE entries
    # change membership at apply time. When False (default) the step
    # compiles exactly as before — static P-lane quorum, member unread.
    dynamic_membership: bool = False
    # Refuse submit acceptance at a leader that did not hold the lease
    # (quorum-acked latest round) LAST round. An entry appended to a
    # partitioned leader's log otherwise rots until heal/supersession —
    # the round-3 mixed-bench p99 of 459 ms was exactly one op waiting
    # out a whole isolation window. Refused slots requeue host-side and
    # land on a live leader within ~an election of the fault, pulling
    # the tail to the election timescale at unchanged throughput.
    lease_gated_accept: bool = True
    # Device-enforced per-group FIFO + dedup for the bulk data plane
    # (models/bulk.py deep pipeline): a submit is accepted only when its
    # tag is EXACTLY (max live-ring tag of the leader log) + 1 + (its
    # rank among this window's valid slots) — i.e. tags must arrive as a
    # dense monotone per-group sequence (1, 2, 3, ...). Duplicates
    # (tag <= ring max) and out-of-order futures are rejected, so the
    # host may re-send ANY unresolved op at ANY time without risking
    # double-apply — the device-side analogue of the reference client's
    # session command sequencing (Copycat client, SURVEY §2.3), derived
    # entirely from the replicated log (election no-ops carry tag 0 and
    # never disturb the max; no new replicated state). Safety
    # (exactly-once) is UNCONDITIONAL: a duplicate whose original still
    # sits in any electable log is rejected, because either the original
    # is inside the ring window (max >= tag) or >= L newer higher-tag
    # stream entries scrolled past it (max > tag); acceptance therefore
    # implies the original can never commit. Liveness under leader
    # churn can wedge on truncated-slot tag inflation — engines with
    # this flag are bulk-plane engines (fault-free delivery), and the
    # driver surfaces a TimeoutError rather than stalling silently.
    # Queue-managed submits (retries of old tags) are incompatible;
    # RaftGroups refuses them on monotone engines.
    monotone_tag_accept: bool = False
    # Device-plane flight-recorder telemetry (docs/OBSERVABILITY.md §
    # device plane): compile a :class:`DeviceTelemetry` block of per-
    # group reductions into the step, returned as
    # ``StepOutputs.telemetry`` and fetched with the existing output
    # transfer (amortized — the hot loop stays one transfer per drive).
    # Derived purely from values the step already computes: no extra
    # randomness, no state writes — OFF compiles the exact pre-telemetry
    # program and the step's state evolution is bit-identical either
    # way (tested in tests/test_device_telemetry.py; A/B in PERF.md
    # round 8). The host side (device.* metrics, flight recorder,
    # invariant monitors) lives in models/telemetry.py.
    telemetry: bool = False


def pin_partitionable_rng() -> None:
    """Pin ``jax_threefry_partitionable`` ON before the step's RNG is
    traced. The legacy lowering materializes GLOBAL random bits and
    slices each shard's block, which on a group-sharded mesh compiles to
    collective-permutes + all-reduces per ``random.randint`` — the
    election-timer draws alone put 22 all-reduces into the step and
    broke the zero-collective contract (MULTICHIP_SCALING.md) on jax
    builds that default the flag off; the partitionable form derives
    every shard's bits locally from the key.

    Invoked at THIS module's import (below), before any repo path can
    touch ``jax.random``: the flag changes ``PRNGKey``/``split`` values
    too, so a lazier pin (e.g. inside ``init_state`` alone) would make
    two same-seed engines built sequentially in one process diverge —
    the first one's key splits run pre-flag, the second's post-flag —
    and break every same-seed differential. The scope is already
    confined: neither the package root nor the client imports ``ops``,
    so host applications that merely import the client never see the
    flag; only engine users (who need it for the zero-collective
    contract) do. Random STREAMS differ from unflagged runs (timer
    draws change), but all in-repo determinism is
    same-process/same-flag — multihost lockstep holds because every
    process imports this module."""
    jax.config.update("jax_threefry_partitionable", True)


pin_partitionable_rng()


def init_state(num_groups: int, num_peers: int, log_slots: int,
               key: jax.Array, config: Config = Config(),
               members=None) -> RaftState:
    """``members`` (optional, needs ``config.dynamic_membership``): initial
    voter set as a ``[P]`` or ``[G,P]`` bool mask — every lane starts with
    the same view. Non-member lanes are cold standbys until an
    ``OP_CFG_ADD`` entry brings them in (e.g. 3 voters in a P=5 tensor)."""
    G, P, L = num_groups, num_peers, log_slots
    z2 = jnp.zeros((G, P), jnp.int32)
    z3 = jnp.zeros((G, P, P), jnp.int32)
    zl = jnp.zeros((G, P, L), jnp.int32)
    if members is None:
        mem = jnp.full((G, P), (1 << P) - 1, jnp.int32)
    else:
        m = jnp.broadcast_to(jnp.asarray(members, bool), (G, P))
        bits = jnp.sum(m * (1 << jnp.arange(P, dtype=jnp.int32))[None, :],
                       axis=1, dtype=jnp.int32)
        mem = jnp.broadcast_to(bits[:, None], (G, P))
    return RaftState(
        term=z2, voted_for=z2 - 1, role=z2 + FOLLOWER, leader_hint=z2 - 1,
        timer=jax.random.randint(key, (G, P), config.timer_min, config.timer_max),
        clock=z2,
        last_index=z2, commit_index=z2, applied_index=z2,
        next_index=z3 + 1, match_index=z3,
        log_term=zl, log_op=zl, log_a=zl, log_b=zl, log_c=zl,
        log_time=zl, log_tag=zl,
        resources=init_resources(G, P, config.resource),
        lease=jnp.zeros((G, P), bool),
        member=mem,
    )


def make_submits(num_groups: int, submit_slots: int) -> Submits:
    G, S = num_groups, submit_slots
    z = jnp.zeros((G, S), jnp.int32)
    return Submits(opcode=z, a=z, b=z, c=z, tag=z,
                   valid=jnp.zeros((G, S), bool))


def full_delivery(num_groups: int, num_peers: int) -> jnp.ndarray:
    return jnp.ones((num_groups, num_peers, num_peers), bool)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _peer_view(x: jnp.ndarray, lead: jnp.ndarray) -> jnp.ndarray:
    """Select x[g, lead[g], ...] → [G, ...] (lead clipped; mask separately).

    One-hot select-reduce over the tiny peer axis instead of
    ``take_along_axis``: XLA lowers these per-row gathers to element-wise
    DMA loops on TPU (measured ~70ns/element — it dominated the step),
    while the masked sum stays a fused VPU pass over x."""
    P = x.shape[1]
    oh = jnp.arange(P, dtype=jnp.int32)[None, :] == jnp.clip(lead, 0)[:, None]
    oh = oh.reshape(oh.shape + (1,) * (x.ndim - 2))
    return jnp.where(oh, x, 0).sum(axis=1).astype(x.dtype)


def _term_at_2d(log_term: jnp.ndarray, last: jnp.ndarray,
                idx: jnp.ndarray) -> jnp.ndarray:
    """Term lookup on a [G,L] ring at idx [G,P] (0 outside the live window)."""
    L = log_term.shape[-1]
    slot = (idx - 1) % L
    t = _gather3(jnp.broadcast_to(log_term[:, None, :],
                                  idx.shape + (L,)), slot)
    valid = (idx >= 1) & (idx <= last[:, None]) & (idx > last[:, None] - L)
    return jnp.where(valid, t, 0)


def _term_at_own(log_term: jnp.ndarray, last: jnp.ndarray,
                 idx: jnp.ndarray) -> jnp.ndarray:
    """Term lookup on each replica's own [G,P,L] ring at idx [G,P]."""
    L = log_term.shape[-1]
    t = _gather3(log_term, (idx - 1) % L)
    valid = (idx >= 1) & (idx <= last) & (idx > last - L)
    return jnp.where(valid, t, 0)


def _scatter_lane(x: jnp.ndarray, lead: jnp.ndarray, active: jnp.ndarray,
                  new: jnp.ndarray) -> jnp.ndarray:
    """Write new[G,...] into x[G,P,...] at lane (g, lead[g]) where active."""
    P = x.shape[1]
    lane = (jnp.arange(P)[None, :] == lead[:, None]) & active[:, None]
    lane = lane.reshape(lane.shape + (1,) * (x.ndim - 2))
    return jnp.where(lane, jnp.expand_dims(new, 1), x)


def _slot_write(log: jnp.ndarray, slot: jnp.ndarray, mask: jnp.ndarray,
                value: jnp.ndarray) -> jnp.ndarray:
    """Masked scatter value[G,P] into log[G,P,L] at slot[G,P]."""
    L = log.shape[-1]
    hit = (jnp.arange(L)[None, None, :] == slot[..., None]) & mask[..., None]
    return jnp.where(hit, value[..., None], log)


def install_snapshots(state: RaftState, stale: jnp.ndarray,
                      leader: jnp.ndarray,
                      config: Config = Config()) -> RaftState:
    """Catch up followers flagged ``stale`` by copying the leader's lane.

    A follower lagging beyond the ring window can never be served by
    AppendEntries (``can_serve`` in :func:`step`); the reference would ship a
    compacted log segment here. Since live state = applied state + the ring
    (SURVEY.md §5.4), installing a snapshot is: copy the leader's log ring,
    indices and resource state into the stale lane and re-follow the leader.
    Vectorized over all flagged ``[G, P]`` lanes; jit-safe.
    """
    has = stale & (leader >= 0)[:, None]

    def cp(x: jnp.ndarray) -> jnp.ndarray:
        lv = _peer_view(x, leader)
        mask = has.reshape(has.shape + (1,) * (x.ndim - 2))
        return jnp.where(mask, jnp.expand_dims(lv, 1), x)

    return state._replace(
        term=cp(state.term),
        voted_for=jnp.where(has, leader[:, None], state.voted_for),
        role=jnp.where(has, FOLLOWER, state.role),
        leader_hint=jnp.where(has, leader[:, None], state.leader_hint),
        # Fresh full timeout so the caught-up follower doesn't immediately
        # depose the leader it just synced from.
        timer=jnp.where(has, config.timer_max, state.timer),
        last_index=cp(state.last_index), commit_index=cp(state.commit_index),
        applied_index=cp(state.applied_index),
        # next/match are as-owner state: unused until this lane wins an
        # election, which reinitializes them — leave untouched.
        log_term=cp(state.log_term), log_op=cp(state.log_op),
        log_a=cp(state.log_a), log_b=cp(state.log_b), log_c=cp(state.log_c),
        log_time=cp(state.log_time), log_tag=cp(state.log_tag),
        resources=jax.tree.map(cp, state.resources),
        # the applied-config mask is applied state like the pools: the
        # stale lane adopts the leader's (its applied_index jumps with
        # the snapshot; the log ring is copied too, so the derived
        # latest-in-log view matches as well)
        member=cp(state.member),
    )


def current_leader(state: RaftState) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-group leader lane and whether one exists: ``(lead [G], active
    [G])``. The highest-term LEADER lane wins; a stale lower-term leader
    stays silent until it learns the higher term."""
    lead_term = jnp.where(state.role == LEADER, state.term, -1)
    lead = jnp.argmax(lead_term, axis=1).astype(jnp.int32)
    active = jnp.max(lead_term, axis=1) >= 0
    return jnp.where(active, lead, -1), active


def query_step(state: RaftState, queries: Submits,
               atomic: jnp.ndarray | None = None,
               config: Config = Config()) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Serve read-only ops from the leader's applied state — no log append.

    The reference serves CAUSAL/SEQUENTIAL queries without consensus
    (``Consistency.java:45-126``); this is the device equivalent: a
    separate tiny program (no state output — nothing is written back)
    that evaluates query opcodes against the leader lane's resource
    pools. Serving is gated on the lane being a current leader that (a)
    has applied everything it committed AND (b) has committed an entry of
    its OWN term — a freshly elected leader's commit index can trail its
    predecessor's served state until its election no-op commits (Raft
    §8), and serving before that could hand a client state older than a
    read it already observed. With the gate, reads are sequential:
    leader-local and monotone per group.

    ``atomic`` ([G,S] bool, optional) marks slots needing
    BOUNDED_LINEARIZABLE freshness (the reference's ATOMIC read level,
    ``Consistency.java:157-176``): those are additionally gated on the
    leader LEASE (quorum-acked in the latest round — ``RaftState.lease``),
    which certifies no other leader could have committed anything, so the
    read linearizes at the lease round without a log append.

    Returns ``(results [G,S], served [G,S] bool)`` — unserved slots (no
    leader, fresh leader, applied < commit, or no lease for an atomic
    slot) must be retried or escalated to the command path by the caller
    (models/raft_groups.py does the latter).
    """
    G = state.term.shape[0]
    S = queries.valid.shape[1]
    lead, active = current_leader(state)
    l_applied = _peer_view(state.applied_index, lead)
    l_commit = _peer_view(state.commit_index, lead)
    l_term = _peer_view(state.term, lead)
    l_last = _peer_view(state.last_index, lead)
    l_log_term = _peer_view(state.log_term, lead)
    commit_term = _term_at_2d(l_log_term, l_last, l_commit[:, None])[:, 0]
    current = active & (l_applied >= l_commit) & (commit_term == l_term)
    served = queries.valid & current[:, None]
    if atomic is not None:
        leased = jnp.any(state.lease, axis=1)
        served = served & (~atomic | leased[:, None])

    # Leader-lane view of every pool, broadcast over the S query slots so
    # the shape-generic apply kernel evaluates ALL slots in one fused pass
    # (the broadcast is a view — reads never materialize [G,S,...] pools).
    lres = jax.tree.map(
        lambda x: jnp.broadcast_to(
            _peer_view(x, lead)[:, None], (G, S) + x.shape[2:]),
        state.resources)
    now = jnp.broadcast_to(_peer_view(state.clock, lead)[:, None], (G, S))

    # Read-only evaluation: the returned (possibly TTL-purged) state is
    # discarded, so the replicated pools are never perturbed.
    _, results = apply_entry(
        lres, queries.opcode, queries.a, queries.b, queries.c,
        jnp.zeros_like(queries.opcode), now, served)
    return jnp.where(served, results, 0), served


# ---------------------------------------------------------------------------
# the step
# ---------------------------------------------------------------------------

def step(state: RaftState, submits: Submits, deliver: jnp.ndarray,
         key: jax.Array, config: Config) -> tuple[RaftState, StepOutputs]:
    """Advance every group by one synchronous consensus round."""
    G, P = state.term.shape
    L = state.log_term.shape[-1]
    E = config.append_window
    A = config.applies_per_round
    quorum = P // 2 + 1
    peer_ids = jnp.arange(P)
    g_ids = jnp.arange(G)

    # Submit-leaf normalization: hosts behind a high-latency transport
    # (the tunneled TPU) shrink H2D bytes by passing COMPACT leaves —
    # a Python/numpy scalar for a burst-uniform opcode/payload (zero
    # transfer), or for ``tag`` a [G,1] column meaning "this base tag at
    # slot 0, consecutive at later slots" (the deep bulk plane's dense
    # per-group streams, models/bulk.py — 16x fewer tag bytes). ``valid``
    # is always a full [G,S] bool array and defines S. Full [G,S] arrays
    # pass through untouched, so every existing caller is unchanged.
    S_sub = submits.valid.shape[-1]

    def _norm(x):
        x = jnp.asarray(x, jnp.int32)
        return x if x.shape == (G, S_sub) \
            else jnp.broadcast_to(x, (G, S_sub))

    tag_n = jnp.asarray(submits.tag, jnp.int32)
    if tag_n.ndim == 2 and tag_n.shape == (G, 1) and S_sub != 1:
        tag_n = tag_n + jnp.arange(S_sub, dtype=jnp.int32)[None, :]
    else:
        tag_n = _norm(tag_n)
    submits = submits._replace(
        opcode=_norm(submits.opcode), a=_norm(submits.a),
        b=_norm(submits.b), c=_norm(submits.c), tag=tag_n)

    # Replicated logical clock: +1 per step in every lane, so entry
    # timestamps (and thus TTL/timeout evaluation) are identical on every
    # replica (SURVEY.md §7.3 #3 — never wall clock inside the kernel).
    clock1 = state.clock + 1

    # Self-delivery is always on (a node talks to itself).
    deliver = deliver | jnp.eye(P, dtype=bool)[None]

    lead, active = current_leader(state)

    # Dynamic membership views (compiled in only when configured; the
    # static path keeps the P-lane quorum and never reads state.member).
    dyn = config.dynamic_membership
    if dyn:
        from .pallas_kernels import kth_largest_masked
        # Each lane's ACTIVE config = the latest config entry in its log
        # — adopted at APPEND, reverted by truncation (Raft §4.1) — else
        # the applied-prefix mask. Entries in (applied, last] live at
        # ring slot (idx-1) % L, so slot s holds index
        # applied + 1 + ((s - applied) % L) when inside the window.
        s_ids_m = jnp.arange(L, dtype=jnp.int32)[None, None, :]
        off_m = (s_ids_m - state.applied_index[..., None]) % L
        win_m = off_m < (state.last_index - state.applied_index)[..., None]
        cfg_m = win_m & ((state.log_op == OP_CFG_ADD)
                         | (state.log_op == OP_CFG_REMOVE))     # [G,P,L]
        key_m = jnp.where(cfg_m, state.applied_index[..., None] + 1 + off_m,
                          0)
        best_m = jnp.max(key_m, axis=-1)                        # [G,P]
        latest_mask = jnp.sum(
            jnp.where(cfg_m & (key_m == best_m[..., None]), state.log_a, 0),
            axis=-1)
        view = jnp.where(best_m > 0, latest_mask, state.member)  # [G,P] i32
        self_member = ((view >> peer_ids[None, :]) & 1).astype(bool)
        view_quorum = jax.lax.population_count(view) // 2 + 1    # [G,P]
        cfg_inflight = _peer_view(best_m > 0, lead)              # [G]
        l_view = _peer_view(view, lead)                          # [G]
        l_quorum = _peer_view(view_quorum, lead)                 # [G]
        # which lanes the leader's active config counts
        l_member = ((l_view[:, None] >> peer_ids[None, :]) & 1) \
            .astype(bool)                                        # [G,P]

    l_term = _peer_view(state.term, lead)          # [G]
    l_last = _peer_view(state.last_index, lead)    # [G]
    l_commit = _peer_view(state.commit_index, lead)
    l_applied = _peer_view(state.applied_index, lead)
    l_next = _peer_view(state.next_index, lead)    # [G,P]
    l_match = _peer_view(state.match_index, lead)  # [G,P]
    l_log_term = _peer_view(state.log_term, lead)  # [G,L]
    l_log_op = _peer_view(state.log_op, lead)
    l_log_a = _peer_view(state.log_a, lead)
    l_log_b = _peer_view(state.log_b, lead)
    l_log_c = _peer_view(state.log_c, lead)
    l_log_time = _peer_view(state.log_time, lead)
    l_log_tag = _peer_view(state.log_tag, lead)
    l_clock = jnp.max(clock1, axis=1)              # [G] (identical per lane)

    # Quorum tallies = k-th largest over the peer axis; Pallas kernel on
    # the TPU hot path, closed-form jnp selection otherwise.
    if config.use_pallas:
        from .pallas_kernels import kth_largest_pallas as _kth
    else:
        from .pallas_kernels import kth_largest as _kth

    # ---- phase 1: inject client submits into the leader log ----
    # Backpressure: never let the ring overwrite entries the leader itself or
    # a quorum-th replica still has to apply (laggards beyond the window go
    # stale and are snapshot-installed by the host).
    # Under dynamic membership, quorum tallies count only the leader's
    # member view — non-member lanes never receive entries, so an
    # unmasked tally would wedge backpressure/commit at their floor.
    if dyn:
        q_applied = kth_largest_masked(state.applied_index, l_member,
                                       l_quorum)
    else:
        q_applied = _kth(state.applied_index, quorum)
    allowed_last = jnp.minimum(l_applied, q_applied) + L

    accept_ok = active
    if config.lease_gated_accept:
        # last round's quorum-ack witness at the leader lane: no lease →
        # no new appends (host requeues; see Config.lease_gated_accept)
        accept_ok = active & (_peer_view(state.lease, lead) != 0)
    valid = submits.valid & accept_ok[:, None]
    if dyn:
        # Config-change append guard + full-config composition: ONE
        # change in flight at a time (adjacent single-server configs
        # always quorum-intersect; two concurrent ones need not — Raft
        # §4.2), so a config submit is rejected (the host requeues it)
        # while a config entry sits un-applied in the leader's log or
        # another rides earlier in the same window, and removing the
        # last member is refused outright. The leader composes the FULL
        # new config bitmask from its active view (Raft's C_new entries)
        # — that mask, not the submitted lane, is what the entry's ``a``
        # carries, so any lane can adopt a config from one entry.
        is_cfg = (submits.opcode == OP_CFG_ADD) \
            | (submits.opcode == OP_CFG_REMOVE)
        in_range = (submits.a >= 0) & (submits.a < P)
        bit = jnp.where(in_range, 1 << jnp.clip(submits.a, 0, P - 1), 0)
        new_mask = jnp.where(submits.opcode == OP_CFG_ADD,
                             l_view[:, None] | bit,
                             l_view[:, None] & ~bit)            # [G,S]
        first_cfg = (jnp.cumsum((is_cfg & valid).astype(jnp.int32),
                                axis=1) == 1) & is_cfg
        # Permanently impossible (would empty the group): FAIL fast via
        # the refused output — requeueing would livelock the whole queue
        # behind it (suffix rejects below keep FIFO hole-free).
        refused = is_cfg & valid & first_cfg & ~cfg_inflight[:, None] \
            & (new_mask == 0)
        cfg_rejected = is_cfg & valid & ~(first_cfg & ~cfg_inflight[:, None]
                                          & (new_mask != 0))
        # Reject the whole window SUFFIX from a rejected config submit:
        # rejections must stay hole-free (like backpressure's), or a
        # later op in the same window would append — and commit — ahead
        # of the requeued config change, breaking per-group FIFO
        # completion (the session program order _harvest preserves).
        valid = valid & (jnp.cumsum(cfg_rejected.astype(jnp.int32),
                                    axis=1) == 0)
    if config.monotone_tag_accept:
        # Max stream tag in the leader log's LIVE ring window. Slot j's
        # resident index is the unique idx in (last-L, last] with
        # (idx-1) % L == j; slots outside the window (never appended, or
        # beyond a truncated last) are masked out. Election no-ops carry
        # tag 0 and stream tags start at 1, so max==0 means "no stream
        # entry yet".
        j_ids = jnp.arange(L, dtype=jnp.int32)[None, :]
        idx_at = l_last[:, None] - ((l_last[:, None] - (j_ids + 1)) % L)
        in_log = (idx_at >= 1) & (idx_at <= l_last[:, None])
        last_stream = jnp.max(jnp.where(in_log, l_log_tag, 0), axis=1)
        vi = valid.astype(jnp.int32)
        rank = jnp.cumsum(vi, axis=1) - vi       # rank among valid slots
        gate_ok = submits.tag == last_stream[:, None] + 1 + rank
        # suffix-reject from the first gate failure keeps acceptance
        # hole-free (same discipline as backpressure/config rejects)
        gate_fail = valid & ~gate_ok
        valid = valid & gate_ok & (jnp.cumsum(
            gate_fail.astype(jnp.int32), axis=1) == 0)
    pos = l_last[:, None] + jnp.cumsum(valid.astype(jnp.int32), axis=1)
    accepted = valid & (pos <= allowed_last[:, None])
    # One-hot scatter per log array: accepted slots are distinct within a
    # group (cumsum positions), so at most one submit hits each ring slot —
    # a masked sum over the S axis writes all slots in a single fused VPU
    # pass (XLA's scatter lowers to an element-wise DMA loop on TPU).
    slot_s = jnp.where(accepted, (pos - 1) % L, L)         # [G,S]; L = drop
    inj_hit = slot_s[:, :, None] == jnp.arange(L, dtype=jnp.int32)  # [G,S,L]
    inj_any = inj_hit.any(axis=1)                           # [G,L]

    def _inject(log: jnp.ndarray, vals: jnp.ndarray) -> jnp.ndarray:
        filled = jnp.where(inj_hit, vals[:, :, None], 0).sum(axis=1)
        return jnp.where(inj_any, filled, log)

    l_log_term = _inject(l_log_term,
                         jnp.broadcast_to(l_term[:, None], slot_s.shape))
    l_log_op = _inject(l_log_op, submits.opcode)
    l_log_a = _inject(l_log_a,
                      jnp.where(is_cfg, new_mask, submits.a) if dyn
                      else submits.a)
    l_log_b = _inject(l_log_b, submits.b)
    l_log_c = _inject(l_log_c, submits.c)
    l_log_time = _inject(l_log_time,
                         jnp.broadcast_to(l_clock[:, None], slot_s.shape))
    l_log_tag = _inject(l_log_tag, submits.tag)
    l_last = l_last + accepted.sum(axis=1, dtype=jnp.int32)

    # ---- phase 2: AppendEntries leader → followers ----
    del_fwd = _peer_view(deliver, lead)                       # deliver[g,lead,f]
    del_back = _peer_view(jnp.swapaxes(deliver, 1, 2), lead)  # deliver[g,f,lead]
    recv = active[:, None] & (peer_ids[None, :] != lead[:, None]) & del_fwd
    if dyn:
        # leaders replicate only to members of their current config; a
        # re-added lane is behind and reconverges via rewind or the
        # stale→snapshot-install path
        recv = recv & l_member

    prev = l_next - 1                                         # [G,P]
    # The leader can only serve entries still in its ring: prev must sit
    # inside the window (prev == 0 qualifies only while the log hasn't
    # wrapped — a wrapped leader must snapshot-install a fresh follower,
    # never serve overwritten slots relabeled as old indices).
    can_serve = prev > l_last[:, None] - L
    stale = recv & ~can_serve
    recv = recv & can_serve
    prev_term = _term_at_2d(l_log_term, l_last, prev)
    upto = jnp.minimum(prev + E, l_last[:, None])

    msg_term = l_term[:, None]
    ok_term = recv & (msg_term >= state.term)
    reject_term = recv & (msg_term < state.term)

    term1 = jnp.where(ok_term, msg_term, state.term)
    voted1 = jnp.where(ok_term & (msg_term > state.term), -1, state.voted_for)
    role1 = jnp.where(ok_term, FOLLOWER, state.role)
    hint1 = jnp.where(ok_term, lead[:, None], state.leader_hint)
    heartbeat = ok_term

    f_prev_term = _term_at_own(state.log_term, state.last_index, prev)
    in_window = prev > state.last_index - L
    match = ok_term & (
        (prev == 0)
        | (prev <= state.commit_index)  # committed prefix always matches
        | ((prev <= state.last_index) & in_window & (f_prev_term == prev_term)))

    # Entry copy as ONE masked cyclic-window select per log array: the same
    # absolute index lives in the same ring slot on every replica, so
    # copying indices (prev+1 .. upto) is a broadcast of the leader's ring
    # masked to the window of slots {prev%L .. (upto-1)%L} (length ≤ E ≤ L,
    # so the window never self-overlaps). Replaces an E-unrolled
    # gather+scatter chain — the step's former bandwidth hog.
    count = jnp.where(match, jnp.clip(upto - prev, 0, E), 0)  # [G,P]
    s_ids = jnp.arange(L, dtype=jnp.int32)[None, None, :]
    win = ((s_ids - prev[..., None]) % L) < count[..., None]  # [G,P,L]

    def _win_copy(follower: jnp.ndarray, leader_view: jnp.ndarray
                  ) -> jnp.ndarray:
        return jnp.where(win, leader_view[:, None, :], follower)

    log_term2 = _win_copy(state.log_term, l_log_term)
    log_op2 = _win_copy(state.log_op, l_log_op)
    log_a2 = _win_copy(state.log_a, l_log_a)
    log_b2 = _win_copy(state.log_b, l_log_b)
    log_c2 = _win_copy(state.log_c, l_log_c)
    log_time2 = _win_copy(state.log_time, l_log_time)
    log_tag2 = _win_copy(state.log_tag, l_log_tag)

    entries_sent = match & (upto >= prev + 1)
    last2 = jnp.where(entries_sent, upto, state.last_index)
    # Commit advance only after the consistency check passed, capped at the
    # last VERIFIED entry (prev + entries appended) — a follower's unverified
    # tail must never be committed by a leaderCommit heartbeat (Raft §5.3).
    verified = jnp.where(entries_sent, upto, prev)
    commit2 = jnp.where(
        match,
        jnp.maximum(state.commit_index,
                    jnp.minimum(l_commit[:, None], verified)),
        state.commit_index)

    # ---- phase 3: acks → matchIndex/nextIndex, quorum commit advance ----
    ack_seen = (recv | reject_term) & del_back
    leader_stale = active & jnp.any(ack_seen & (term1 > l_term[:, None]), axis=1)
    max_ack_term = jnp.max(jnp.where(ack_seen, term1, 0), axis=1)

    ack_success = match & del_back
    ack_match = jnp.where(entries_sent, upto, prev)
    l_match = jnp.where(ack_success, jnp.maximum(l_match, ack_match), l_match)
    l_next = jnp.where(ack_success, l_match + 1, l_next)
    ack_fail = ok_term & ~match & del_back
    hint = jnp.where(prev <= state.last_index, prev - 1, state.last_index)
    l_next = jnp.where(ack_fail,
                       jnp.clip(jnp.minimum(prev, hint + 1), 1, None), l_next)

    self_lane = peer_ids[None, :] == lead[:, None]
    # Leader lease: a quorum of same-term acks THIS round (self included)
    # with no higher term observed — see RaftState.lease for why this
    # certifies exclusive leadership through this round.
    match_full = jnp.where(self_lane, l_last[:, None], l_match)
    if dyn:
        acked = jnp.sum((ack_success | self_lane) & l_member, axis=1)
        lease_g = active & ~leader_stale & (acked >= l_quorum)
        cand_commit = kth_largest_masked(match_full, l_member, l_quorum)
    else:
        acked = jnp.sum(ack_success | self_lane, axis=1)
        lease_g = active & ~leader_stale & (acked >= quorum)
        cand_commit = _kth(match_full, quorum)
    cand_commit_term = _term_at_2d(l_log_term, l_last, cand_commit[:, None])[:, 0]
    advance = active & ~leader_stale & (cand_commit > l_commit) \
        & (cand_commit_term == l_term)
    l_commit = jnp.where(advance, cand_commit, l_commit)

    # Scatter the leader view back into replica lanes.
    sc = ~leader_stale & active
    term1 = jnp.where(self_lane & leader_stale[:, None],
                      jnp.maximum(l_term[:, None], max_ack_term[:, None]), term1)
    role1 = jnp.where(self_lane & leader_stale[:, None], FOLLOWER, role1)
    voted1 = jnp.where(self_lane & leader_stale[:, None], -1, voted1)
    last2 = _scatter_lane(last2, lead, active, l_last)
    commit2 = _scatter_lane(commit2, lead, sc, l_commit)
    next2 = _scatter_lane(state.next_index, lead, sc, l_next)
    match2 = _scatter_lane(state.match_index, lead, sc, l_match)
    log_term2 = _scatter_lane(log_term2, lead, active, l_log_term)
    log_op2 = _scatter_lane(log_op2, lead, active, l_log_op)
    log_a2 = _scatter_lane(log_a2, lead, active, l_log_a)
    log_b2 = _scatter_lane(log_b2, lead, active, l_log_b)
    log_c2 = _scatter_lane(log_c2, lead, active, l_log_c)
    log_time2 = _scatter_lane(log_time2, lead, active, l_log_time)
    log_tag2 = _scatter_lane(log_tag2, lead, active, l_log_tag)

    # ---- phase 4: election timers + RequestVote tally ----
    key_t, key_c = jax.random.split(key)
    fresh = jax.random.randint(key_t, (G, P), config.timer_min, config.timer_max)
    is_ldr = role1 == LEADER
    # CheckQuorum (Raft thesis §6.2, the standard companion to leader
    # stickiness below): a leader's timer is renewed only by an ack
    # QUORUM this round (lease_g; stale lower-term leaders never renew).
    # Without it, stickiness could wedge a group forever under a stable
    # asymmetric partition — a leader reaching some-but-not-quorum
    # followers keeps them sticky while never committing; here it steps
    # down after an election timeout and its followers become electable.
    renewed = self_lane & lease_g[:, None]
    timer1 = jnp.where(heartbeat | (is_ldr & renewed), fresh,
                       state.timer - 1)
    ldr_down = is_ldr & (timer1 <= 0)
    role1 = jnp.where(ldr_down, FOLLOWER, role1)
    is_ldr = is_ldr & ~ldr_down
    timer1 = jnp.where(ldr_down, fresh, timer1)
    timeout = ~is_ldr & ~heartbeat & ~ldr_down & (timer1 <= 0)
    if dyn:
        # lanes outside their own config view never campaign (a removed
        # server must not disrupt the cluster it left; a standby lane
        # must not elect itself before an ADD brings it in)
        timeout = timeout & self_member

    term_e = jnp.where(timeout, term1 + 1, term1)
    voted_e = jnp.where(timeout, peer_ids[None, :], voted1)
    role_e = jnp.where(timeout, CANDIDATE, role1)
    timer1 = jnp.where(
        timeout, jax.random.randint(key_c, (G, P), config.timer_min,
                                    config.timer_max), timer1)

    cand_mask = role_e == CANDIDATE
    # A vote needs request AND response delivery. Lanes that believe a
    # current leader exists — they received its AppendEntries THIS round,
    # or they ARE it — ignore RequestVote entirely (no term adoption, no
    # grant): Raft's leader-stickiness rule (thesis §4.2.3), which is
    # what stops a server that was removed from the config (and so
    # receives no appends, is never deposed via the ack path, and cannot
    # be caught up) from depose-looping a healthy group with ever-growing
    # terms. A genuinely partitioned MEMBER still deposes a stale leader
    # through its AppendEntries reject (leader_stale above), so real
    # failovers are unaffected.
    reach = cand_mask[:, :, None] & deliver & jnp.swapaxes(deliver, 1, 2) \
        & ~(heartbeat | is_ldr)[:, None, :]
    c_term_b = jnp.where(reach, term_e[:, :, None], 0)
    v_seen = c_term_b.max(axis=1)                                 # [G,V]
    higher = v_seen > term_e
    term_v = jnp.maximum(term_e, v_seen)
    voted_v = jnp.where(higher, -1, voted_e)
    role_v = jnp.where(higher, FOLLOWER, role_e)

    own_last_term = _term_at_own(log_term2, last2, last2)         # [G,P]
    c_pair = (own_last_term[:, :, None], last2[:, :, None])
    v_pair = (own_last_term[:, None, :], last2[:, None, :])
    up_to_date = (c_pair[0] > v_pair[0]) | (
        (c_pair[0] == v_pair[0]) & (c_pair[1] >= v_pair[1]))

    elig = reach & (term_e[:, :, None] == term_v[:, None, :]) & up_to_date \
        & ((voted_v[:, None, :] == -1) | (voted_v[:, None, :] == peer_ids[None, :, None]))
    choice = jnp.where(elig, peer_ids[None, :, None], P).min(axis=1)  # [G,V]
    voted_v = jnp.where(choice < P, choice, voted_v)
    grant = elig & (peer_ids[None, :, None] == choice[:, None, :])
    # role_v is the post-vote role on the candidate's own lane (it may have
    # stepped down to a higher-term candidate).
    if dyn:
        # a candidate counts only votes from lanes in ITS active config
        # view, against that view's quorum (any lane may still GRANT a
        # vote — standard Raft: servers answer RequestVote from/for
        # non-members for liveness during config changes)
        mem_cv = ((view[:, :, None] >> peer_ids[None, None, :]) & 1) \
            .astype(bool)                                         # [G,C,V]
        votes = jnp.sum(grant & mem_cv, axis=2)                   # [G,C]
        won = (role_v == CANDIDATE) & cand_mask & self_member \
            & (votes >= view_quorum)
    else:
        votes = grant.sum(axis=2)                                 # [G,C]
        won = (role_v == CANDIDATE) & cand_mask & (votes >= quorum)

    role_f = jnp.where(won, LEADER, role_v)
    hint_f = jnp.where(won, peer_ids[None, :], hint1)
    # Winner initializes nextIndex/matchIndex and appends a NoOp of its term.
    win_lane = won[:, :, None]
    next2 = jnp.where(win_lane, last2[:, :, None] + 2, next2)  # +1 entry +NoOp
    match2 = jnp.where(win_lane, 0, match2)
    noop_idx = last2 + 1
    noop_slot = (noop_idx - 1) % L
    log_term2 = _slot_write(log_term2, noop_slot, won, term_v)
    log_op2 = _slot_write(log_op2, noop_slot, won, jnp.zeros_like(term_v))
    log_time2 = _slot_write(log_time2, noop_slot, won, clock1)
    log_tag2 = _slot_write(log_tag2, noop_slot, won, jnp.zeros_like(term_v))
    last_f = jnp.where(won, noop_idx, last2)

    # ---- phase 5: apply committed entries (all replicas, A per round) ----
    # All A candidate entries (contiguous indices applied+1 .. applied+A,
    # capped at commit) are gathered in ONE fused one-hot select-reduce
    # per log array (take_along_axis lowers to an element-wise DMA loop on
    # TPU; the masked sum is a vector pass), then applied by the
    # conflict-partitioned window kernel: each resource pool folds only
    # ITS entries, carrying only its own arrays (apply.py apply_window).
    idx_all = state.applied_index[..., None] + 1 \
        + jnp.arange(A, dtype=jnp.int32)[None, None, :]       # [G,P,A]
    slot_all = (idx_all - 1) % L
    do_all = idx_all <= commit2[..., None]
    win_oh = slot_all[..., None] == jnp.arange(L, dtype=jnp.int32)  # [G,P,A,L]
    ga = lambda log: jnp.where(win_oh, log[:, :, None, :], 0).sum(axis=-1)
    time_w = ga(log_time2)
    op_w = ga(log_op2)
    a_w = ga(log_a2)
    b_w = ga(log_b2)
    c_w = ga(log_c2)
    if config.pool_budgets is not None:
        if len(config.pool_budgets) != NUM_POOLS:
            raise ValueError(
                f"pool_budgets needs {NUM_POOLS} entries "
                f"(value,map,set,queue,lock,election), got "
                f"{config.pool_budgets!r}")
        budgets = tuple(max(1, min(int(x), A))
                        for x in config.pool_budgets)
        resources, res_w, admitted = apply_window(
            state.resources, op_w, a_w, b_w, c_w, idx_all, time_w,
            do_all, budgets)
    else:
        # No budgets → every entry in the window applies; the single
        # sequential scan over the composed kernel has fewer fusions than
        # six per-pool folds, which wins when the step is dispatch-bound
        # (small G / single-pool workloads). The partitioned path wins
        # when budgets shrink a heavy pool's HBM traffic (mixed configs).
        xs = jax.tree.map(
            lambda x: jnp.moveaxis(x, 2, 0),                  # [A,G,P]
            (op_w, a_w, b_w, c_w, time_w, idx_all, do_all))

        def _apply_one(resources, x):
            op_i, a_i, b_i, c_i, time_i, idx, do = x
            return apply_entry(resources, op_i, a_i, b_i, c_i, idx,
                               time_i, do)

        resources, res_all = jax.lax.scan(_apply_one, state.resources, xs)
        res_w = jnp.moveaxis(res_all, 0, 2)                   # [G,P,A]
        admitted = do_all
    applied = state.applied_index \
        + admitted.sum(axis=-1, dtype=jnp.int32)

    # Config-change entries take effect on each lane AS IT APPLIES them:
    # an unrolled in-order fold over the ≤A window positions (config
    # changes are rare, so A tiny [G,P] selects per round are noise; the
    # one-in-flight append guard means ≥2 hits per window only when a
    # lane catches up on two serialized changes at once — the fold order
    # keeps even that correct).
    member2 = state.member
    if dyn:
        # config entries carry the full bitmask, so the applied config is
        # just the mask of the latest admitted config entry in the window
        cfg_w = (op_w == OP_CFG_ADD) | (op_w == OP_CFG_REMOVE)
        for i in range(A):
            hit = admitted[:, :, i] & cfg_w[:, :, i]              # [G,P]
            member2 = jnp.where(hit, a_w[:, :, i], member2)

    # Reporting lane: the lane with the highest applied_index AFTER this
    # round. In the first round the global max passes an entry, the argmax
    # lane applies it (all lanes started below it), so every result is
    # reported at least once — even when the group is leaderless (see
    # StepOutputs docstring). One fused pass each over [G,P,A].
    rep = jnp.argmax(applied, axis=1).astype(jnp.int32)       # [G]
    rep_oh = peer_ids[None, :] == rep[:, None]                # [G,P]
    rep3 = lambda x: jnp.where(rep_oh[:, :, None], x, 0).sum(axis=1)
    out_valid = rep3(admitted).astype(bool)                   # [G,A]
    out_tag = jnp.where(out_valid, rep3(ga(log_tag2)), 0)
    out_result = jnp.where(out_valid, rep3(res_w), 0)
    time_rep = rep3(time_w)
    out_latency = jnp.where(out_valid, l_clock[:, None] - time_rep, 0)

    # ---- phase 6: drain session events (leader lane → host) --------------
    # Gated on an active leader so events emitted during leaderless rounds
    # are not popped unseen.
    resources, (ev_seq, ev_code, ev_target, ev_arg, ev_ok) = drain_events(
        resources, config.events_per_round, active)
    lead_ev = active[:, None] & _peer_view(ev_ok, lead)

    if dyn:
        # A leader whose removal has been committed+applied steps down
        # (Raft thesis §4.2.2: it keeps leading while C_new-without-self
        # replicates, under the old config, then stops). Candidates are
        # judged by the ACTIVE view instead — a re-added lane may
        # campaign on its appended-but-uncommitted config, a removed
        # lane's view reverts to the applied mask and it stands down.
        self_m2 = ((member2 >> peer_ids[None, :]) & 1).astype(bool)
        # Step down only when BOTH the applied config and the active
        # view exclude the lane: a lane that won its election on an
        # appended-but-uncommitted re-ADD (view includes it, applied
        # does not) must keep leading until that entry applies, or it
        # would be demoted every round and churn terms forever.
        role_f = jnp.where((role_f == LEADER) & ~self_m2 & ~self_member,
                           FOLLOWER, role_f)
        role_f = jnp.where((role_f == CANDIDATE) & ~self_member, FOLLOWER,
                           role_f)

    new_state = RaftState(
        term=jnp.maximum(term_v, term_e), voted_for=voted_v, role=role_f,
        leader_hint=hint_f, timer=timer1, clock=clock1,
        last_index=last_f, commit_index=commit2, applied_index=applied,
        next_index=next2, match_index=match2,
        log_term=log_term2, log_op=log_op2, log_a=log_a2, log_b=log_b2,
        log_c=log_c2, log_time=log_time2,
        log_tag=log_tag2, resources=resources,
        lease=jnp.broadcast_to(lease_g[:, None], (G, P)),
        member=member2)

    # ---- telemetry block (compiled in only under Config.telemetry) -------
    # Pure reductions over values already computed above: no new RNG, no
    # state writes — the off path is the exact pre-telemetry program.
    # Every reduction stays per-group ([G]-leading) so a group-sharded
    # mesh compiles it without cross-device collectives.
    tel = None
    if config.telemetry:
        i32 = jnp.int32
        term_max = jnp.max(new_state.term, axis=1)
        commit_max = jnp.max(commit2, axis=1)
        post_lead_term = jnp.where(role_f == LEADER, new_state.term, -1)
        post_lead = jnp.argmax(post_lead_term, axis=1).astype(i32)
        post_term = jnp.max(post_lead_term, axis=1)
        rejected = submits.valid & ~accepted
        if dyn:
            rejected = rejected & ~refused
        # entries applied by the reporting lane, bucketed by pool (the
        # commit-stream view — counting all P lanes would overstate by P)
        pool_w = pool_of(op_w)                               # [G,P,A]
        pool_oh = pool_w[..., None] == jnp.arange(NUM_POOLS + 1,
                                                  dtype=i32)  # [G,P,A,K]
        rep_adm = (rep_oh[:, :, None] & admitted)[..., None]
        applies_by_pool = jnp.sum(pool_oh & rep_adm, axis=(1, 2),
                                  dtype=i32)                 # [G,K]
        # outbox accounting: heads advance by drain pops or drop-oldest
        # overwrites; lanes evolve in lockstep, so the max lane is the
        # group's truth
        pops = ev_ok.sum(axis=-1, dtype=i32)                 # [G,P]
        head_adv = resources.ev_head - state.resources.ev_head
        tel = DeviceTelemetry(
            elections_started=timeout.sum(axis=1, dtype=i32),
            leader_changes=jnp.sum(
                won & ((peer_ids[None, :] != lead[:, None])
                       | ~active[:, None]), axis=1, dtype=i32),
            term_bumps=term_max - jnp.max(state.term, axis=1),
            leaderless=(~active).astype(i32),
            commit_advance=commit_max
            - jnp.max(state.commit_index, axis=1),
            commit_max=commit_max,
            term_max=term_max,
            leader_lane=jnp.where(post_term >= 0, post_lead, -1),
            leader_term=post_term,
            applies=applies_by_pool,
            ring_occ_max=jnp.max(last_f - applied, axis=1),
            submit_rejections=rejected.sum(axis=1, dtype=i32),
            vote_splits=(jnp.any(cand_mask, axis=1)
                         & ~jnp.any(won, axis=1)).astype(i32),
            events_drained=lead_ev.sum(axis=1, dtype=i32),
            events_dropped=jnp.max(
                jnp.maximum(head_adv - pops, 0), axis=1),
        )

    outputs = StepOutputs(
        accepted=accepted, out_valid=out_valid, out_tag=out_tag,
        out_result=out_result, out_latency=out_latency, leader=lead,
        commit_index=jnp.where(active, l_commit, jnp.max(commit2, axis=1)),
        stale=stale, clock=l_clock,
        ev_seq=_peer_view(ev_seq, lead), ev_code=_peer_view(ev_code, lead),
        ev_target=_peer_view(ev_target, lead),
        ev_arg=_peer_view(ev_arg, lead), ev_valid=lead_ev,
        assigned=jnp.where(accepted, pos, 0),
        assigned_term=jnp.where(accepted, l_term[:, None], 0),
        out_index=jnp.where(out_valid, rep3(idx_all), 0),
        out_term=jnp.where(out_valid, rep3(ga(log_term2)), 0),
        leader_term=jnp.max(
            jnp.where(role_f == LEADER, new_state.term, -1), axis=1),
        refused=refused if dyn else jnp.zeros_like(submits.valid),
        telemetry=tel)
    return new_state, outputs


def deep_step(state: RaftState, resbuf: jnp.ndarray, valbuf: jnp.ndarray,
              rndbuf: jnp.ndarray, evflag: jnp.ndarray, base: jnp.ndarray,
              rnd: jnp.ndarray, submits: Submits, deliver: jnp.ndarray,
              key: jax.Array, config: Config, onehot: bool = False
              ) -> tuple[RaftState, jnp.ndarray, jnp.ndarray, jnp.ndarray,
                         jnp.ndarray, StepOutputs]:
    """One consensus round + ON-DEVICE result accumulation (deep bulk plane).

    The deep pipelined driver (``models/bulk.py``) commits dense
    per-group tag streams (``Config.monotone_tag_accept``), so an applied
    result's stream rank is ``out_tag - 1 - base[g]`` — this wrapper
    scatters each round's applied results/resolve-rounds into carried
    ``[G, B]`` buffers keyed by that rank. The host then fetches ONE
    buffer set per drive instead of per-round out arrays: through a
    tunneled accelerator (~tens of ms per blocking D2H) that is the
    difference between per-round and per-drive transfer cost (round-4
    host-scenario profile: transfers were ~90% of wall time).

    ``rndbuf`` keeps the EARLIEST resolve round per op (``.min`` scatter)
    so at-least-once re-reports never inflate client latency. ``evflag``
    carries "any session event drained so far" — the host checks one
    scalar and fetches per-round event leaves only on the rare path.
    Reports for tags outside [base+1, base+B] (earlier drives, election
    no-ops) fall on the ``mode="drop"`` sentinel column.
    """
    state, out = step(state, submits, deliver, key, config=config)
    G = out.out_tag.shape[0]
    B = resbuf.shape[1]
    return _deep_accumulate(state, resbuf, valbuf, rndbuf, evflag, base,
                            rnd, out, G, B, onehot)


def _deep_accumulate(state, resbuf, valbuf, rndbuf, evflag, base, rnd,
                     out, G, B, onehot):
    """Scatter one round's applied results into the deep accumulators
    (the body shared by :func:`deep_step` and :func:`deep_scan`)."""
    k = out.out_tag - 1 - base[:, None]
    ok = out.out_valid & (k >= 0) & (k < B)
    rnd_i = jnp.asarray(rnd, jnp.int32)
    if onehot:
        # One-hot select-reduce: ranks are distinct within a group-round,
        # so a masked sum over the A axis writes every hit in one fused
        # pass — and, unlike scatter, it stays SHARD-LOCAL on a
        # group-sharded mesh (the round-4 collective census caught the
        # scatter form compiling to all-gathers of the [G,B] buffers).
        # Cost is O(G*A*B) per round, so the unsharded path below keeps
        # the O(G*A) scatter instead.
        hit = jnp.where(ok, k, -1)[:, :, None] \
            == jnp.arange(B, dtype=jnp.int32)[None, None, :]   # [G,A,B]
        any_hit = hit.any(axis=1)                               # [G,B]
        resbuf = jnp.where(
            any_hit,
            jnp.where(hit, out.out_result[:, :, None], 0).sum(axis=1),
            resbuf)
        rndbuf = jnp.where(
            any_hit,
            jnp.minimum(rndbuf,
                        jnp.where(hit, rnd_i, jnp.int32(2**30)).min(axis=1)),
            rndbuf)
        valbuf = valbuf | any_hit
    else:
        kk = jnp.where(ok, k, B)  # B = drop sentinel (out of range)
        g_ids = jnp.arange(G, dtype=jnp.int32)[:, None]
        resbuf = resbuf.at[g_ids, kk].set(out.out_result, mode="drop")
        rndbuf = rndbuf.at[g_ids, kk].min(
            jnp.broadcast_to(rnd_i, kk.shape), mode="drop")
        valbuf = valbuf.at[g_ids, kk].set(True, mode="drop")
    # per-GROUP event flag (host ors it after the fetch): a scalar
    # .any() here would be the one cross-shard all-reduce in the whole
    # program on a group-sharded mesh (census-verified)
    evflag = evflag | out.ev_valid.any(axis=1)
    return state, resbuf, valbuf, rndbuf, evflag, out


def deep_scan(state: RaftState, resbuf: jnp.ndarray, valbuf: jnp.ndarray,
              rndbuf: jnp.ndarray, evflag: jnp.ndarray, base: jnp.ndarray,
              submits_w: Submits, deliver: jnp.ndarray, key: jax.Array,
              config: Config, onehot: bool = False):
    """The deep drive's ENTIRE blind phase as one compiled program.

    ``submits_w`` stacks W rounds of submit windows ([W, ...] leaves —
    the trailing windows are the empty settle rounds); a ``lax.scan``
    runs :func:`deep_step`'s round W times with the accumulators
    carried on device. The host uploads one stacked payload and
    dispatches ONCE instead of once per window — the per-drive
    host↔device interaction count drops from ~W to 1, on top of the
    round-4 design's zero blocking fetches (``models/bulk.py`` scan
    mode; events come back stacked [W, ...] for the rare
    session-event path).
    """
    W = submits_w.valid.shape[0]
    keys = jax.random.split(key, W)
    rnds = jnp.arange(W, dtype=jnp.int32)

    def body(carry, xs):
        st, rb, vb, nb, ev = carry
        sub, rnd, k = xs
        st, out = step(st, sub, deliver, k, config=config)
        st, rb, vb, nb, ev, out = _deep_accumulate(
            st, rb, vb, nb, ev, base, rnd, out,
            out.out_tag.shape[0], rb.shape[1], onehot)
        return (st, rb, vb, nb, ev), ((out.ev_seq, out.ev_code,
                                       out.ev_target, out.ev_arg,
                                       out.ev_valid), out.telemetry)

    (state, resbuf, valbuf, rndbuf, evflag), (evs, tels) = jax.lax.scan(
        body, (state, resbuf, valbuf, rndbuf, evflag),
        (submits_w, rnds, keys))
    # ``tels`` is the stacked [W, G] telemetry of the whole blind phase
    # (None when Config.telemetry is off) — fetched with the drive's one
    # accumulator harvest, never per round.
    return state, resbuf, valbuf, rndbuf, evflag, evs, tels
